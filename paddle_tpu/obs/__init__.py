"""paddle_tpu.obs — end-to-end observability (ISSUE 6 + 7 tentpoles).

One layer, four surfaces:

* **Span tracing** (`obs.span` / flow ids / `obs.export_trace`): causal
  wall-time spans across every thread of the stack — Executor dispatch,
  compile-cache misses (transform -> verify -> XLA compile), the feed
  pipeline's producer/ring, and the serving engine's admission ->
  coalesce -> dispatch -> complete pipeline, linked across threads by
  flow ids.  Export is Chrome-trace/Perfetto JSON: ONE file shows a
  train step or a serving request end to end.

* **Cost attribution** (`obs.cost`): per-executable FLOPs/bytes from
  XLA `cost_analysis`, cached with the CompileCache entry at compile
  time and combined with measured dispatch intervals into live
  `mfu_pct` / `hbm_bw_pct` gauges; plus the `collective_bytes_<type>`
  bytes-on-wire counters the quantized-collectives ROADMAP item will
  assert against.

* **Per-op attribution** (`obs.opprof` / `obs.op_profile(program)`):
  every op lowers inside `jax.named_scope` with its greppable
  `program#<id>/block<idx>/op<id>:<type>[pass=...]` provenance, and
  each compile-cache miss walks the AOT executable's HLO to fold
  per-instruction FLOPs/bytes/fusions/relayouts back onto source
  Program ops — through the transform pipeline's rewrites — so the
  whole-program MFU number decomposes into named ops
  (`tools/tracetool.py top-ops`, BENCH `detail.op_profile`).

* **Snapshot** (`obs.snapshot()`): one structured export — span
  summary + every profiler timer/counter + the cost gauges + the
  per-op profiles — tagged with this host's process index
  (`all_hosts=True` gathers every host's tables into one merged
  view), embedded by bench.py in BENCH JSON `detail.obs` and by
  `obs.export_trace` in the trace file's otherData (so
  `tools/tracetool.py` can attribute stalls and report MFU from the
  trace alone).

Enable/disable at runtime (`obs.enable()` / `obs.disable()`); disabled
tracing is a single attribute check per site — the async hot path's
zero-sync, zero-transfer contract is untouched either way
(docs/observability.md).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import json as _json
import os as _os
import threading as _threading

from . import cost
from . import devprof
from . import memprof
from . import numerics
from . import opprof
from . import telemetry
from .tracing import NULL_SPAN, TRACER, Tracer  # noqa: F401

__all__ = ["span", "add_span", "new_flow", "attach_flow", "current_span",
           "enable", "disable", "enabled", "reset", "snapshot",
           "export_trace", "op_profile", "profile_window", "roofline",
           "mem_profile", "memory_ledger", "publish_mem_oom",
           "bisect_nonfinite", "numerics_report",
           "cost", "devprof", "memprof", "numerics", "opprof",
           "telemetry",
           "start_telemetry", "stop_telemetry", "maybe_start_telemetry",
           "telemetry_epoch_refresh", "telemetry_handle", "TRACER",
           "NULL_SPAN", "Tracer"]


def enable(reset: bool = False) -> None:
    """Turn span recording on (optionally clearing the buffer along
    with any completed devprof captures — see reset())."""
    TRACER.enable(reset=reset)
    if reset:
        devprof.reset()


def disable() -> None:
    TRACER.disable()


def enabled() -> bool:
    return TRACER.enabled


def reset() -> None:
    """Clear the span buffer and drop counter (enabled state kept).
    Completed devprof captures are cleared too — a fresh trace must
    not merge device tracks from a window profiled before the
    reset."""
    TRACER.reset()
    devprof.reset()


def span(name: str, flow=None, attrs: Optional[dict] = None):
    """Context manager recording one span on this thread's track; the
    shared no-op singleton while tracing is disabled."""
    return TRACER.span(name, flow=flow, attrs=attrs)


def add_span(name: str, t0: float, dur: float, flow=None,
             attrs: Optional[dict] = None) -> None:
    """Record a span retroactively (perf_counter seconds)."""
    TRACER.add_span(name, t0, dur, flow=flow, attrs=attrs)


def new_flow() -> int:
    """Mint a process-unique flow id linking spans across threads."""
    return TRACER.new_flow()


def attach_flow(flow) -> None:
    TRACER.attach_flow(flow)


def current_span():
    return TRACER.current_span()


def op_profile(program=None, label: Optional[str] = None) \
        -> Optional[Dict[str, Any]]:
    """The per-op cost-attribution table for `program` (matched by the
    SOURCE prog_id its rows attribute to), for an exact executable
    `label`, or the most recently compiled executable when neither is
    given.  None until a compile-cache miss has captured one.  Rows
    carry `program#<id>/block<idx>/op<id>:<type>[pass=...]` provenance
    plus flops/bytes shares, fusion membership, transpose/relayout
    counts and collective payload bytes (docs/observability.md)."""
    prog_id = getattr(program, "prog_id", None) \
        if program is not None else None
    return opprof.profile_for(prog_id=prog_id, label=label)


def profile_window(steps: Optional[int] = None,
                   label: Optional[str] = None):
    """Arm a bounded *measured* device-time capture window
    (obs/devprof.py): `jax.profiler` trace around the next dispatches,
    xplane parse, and the join back onto source Program ops.  Use as a
    context manager, or pass `steps=N` and let the Executor training
    loop auto-stop it.  `PADDLE_OBS_DEVPROF=1` arms the same window
    from the environment."""
    return devprof.profile_window(steps=steps, label=label)


def roofline(program=None, label: Optional[str] = None) \
        -> Optional[Dict[str, Any]]:
    """The measured roofline for `program` (matched by the SOURCE
    prog_id the window's join attributed time to), for an exact window
    `label`, or the most recent window when neither is given: per-op
    measured time vs opprof FLOPs/bytes -> achieved-FLOPs/achieved-BW
    and a compute-/memory-/relayout-bound verdict.  None until a
    profile_window has finished."""
    prog_id = getattr(program, "prog_id", None) \
        if program is not None else None
    return devprof.roofline_for(prog_id=prog_id, label=label)


def mem_profile(program=None, label: Optional[str] = None) \
        -> Optional[Dict[str, Any]]:
    """The static memory-attribution table for `program` (matched by
    the SOURCE prog_id its rows attribute to), for an exact executable
    `label`, or the most recently compiled executable when neither is
    given.  None until a compile-cache miss has captured one.  Rows
    attribute the executable's temp-buffer peak (`memory_analysis()`)
    to `program#<id>/block<idx>/op<id>:<type>` provenance, with the
    remainder in an explicit `unattributed` bin
    (docs/observability.md)."""
    prog_id = getattr(program, "prog_id", None) \
        if program is not None else None
    return memprof.profile_for(prog_id=prog_id, label=label)


def memory_ledger() -> Dict[str, Any]:
    """The live device-memory ledger: every byte the framework
    intentionally holds on device (scope vars, compile-cache
    const/feed caches, feed-ring staged batches, KV pages, in-flight
    ckpt snapshots), reconciled against `device.memory_stats()` —
    `bytes_in_use = ledger total + executable temp + unattributed`,
    with the residual explicit.  Device fields are None on backends
    without memory_stats (CPU)."""
    return memprof.memory_ledger()


def publish_mem_oom(label: str = "", error: Any = "") -> Dict[str, Any]:
    """RESOURCE_EXHAUSTED forensics: assemble the mem_oom report
    (ledger at failure time + the failing executable's top static temp
    buffers) and publish it as a flight bundle.  With a live telemetry
    session the watchdog writes a full bundle (series + memory.json);
    otherwise a minimal bundle lands in the PADDLE_OBS_FLIGHT_DIR (if
    set).  Always returns the report; never raises — this runs on the
    dispatch except-path."""
    doc = memprof.oom_report(label=label, error=error)
    handle = _TELEMETRY
    try:
        if handle is not None and handle.watchdog is not None:
            handle.watchdog.trigger(
                "mem_oom",
                f"RESOURCE_EXHAUSTED dispatching {label or '<program>'}"
                f": {str(error)[:200]}")
        else:
            flight_dir = _obs_flag("obs_flight_dir",
                                   "PADDLE_OBS_FLIGHT_DIR", "", str)
            if flight_dir:
                telemetry.write_standalone_bundle(
                    flight_dir, "mem_oom",
                    f"RESOURCE_EXHAUSTED dispatching "
                    f"{label or '<program>'}",
                    {"memory.json": doc})
    except Exception:  # noqa: BLE001 - forensics must not mask the OOM
        pass
    return doc


def bisect_nonfinite(program, feed=None, scope=None, fetch_list=None,
                     transform: bool = True) -> Dict[str, Any]:
    """First-NaN bisection (obs/numerics.py): transform `program`
    exactly as the executor would, replay it op-by-op eagerly over
    `scope` + `feed`, and name the FIRST op in program order whose
    output goes non-finite — provenance with [pass=...] tags,
    construction stack (`op_callstack`), and input stats.  Offline
    forensics; under `PADDLE_OBS_NUMERICS=bisect` the executor runs
    the same replay automatically when the async NaN monitor fires."""
    return numerics.bisect_nonfinite(program, feed=feed, scope=scope,
                                     fetch_list=fetch_list,
                                     transform=transform)


def numerics_report() -> Dict[str, Any]:
    """The full numeric-health document (`numerics.json` in flight
    bundles): per-op nan/inf/absmax/l2 aggregate keyed by provenance,
    training-health gauges, the AMP loss scale, and the last hit +
    bisection report.  Drains pending stats first."""
    return numerics.numerics_doc()


def _process_index() -> int:
    try:
        from ..distributed.parallel import _safe_process_index

        return int(_safe_process_index())
    except Exception:  # noqa: BLE001 - no jax/dist: single host
        return 0


def _local_tables() -> Dict[str, Any]:
    from .. import profiler

    stats = profiler.get_int_stats()
    times = profiler.get_time_stats()
    return {
        "counters": dict(stats),
        "timers_ms": {k: round(float(v), 3) for k, v in times.items()},
    }


def _gather_host_tables(local: Dict[str, Any]) -> Dict[str, Any]:
    """All-gather each host's counter/timer tables (the shard_skew_ms
    epoch-boundary idiom from dataset.feed_pipeline: fine OFF the hot
    path, degrades to the local view when gathering is unavailable).
    Tables are variable-length, so the JSON payload is length-gathered
    first, then gathered as padded byte arrays."""
    import json as _json

    from ..dataset.feed_pipeline import host_topology

    index, count = host_topology()
    if count <= 1:
        return {str(index): local}
    try:
        import numpy as np
        from jax.experimental import multihost_utils

        data = _json.dumps(local).encode()
        lens = np.asarray(multihost_utils.process_allgather(
            np.int32(len(data)))).ravel()
        buf = np.zeros(int(lens.max()), np.uint8)
        buf[:len(data)] = np.frombuffer(data, np.uint8)
        bufs = np.asarray(multihost_utils.process_allgather(buf))
        out = {}
        for i, n in enumerate(lens):
            out[str(i)] = _json.loads(
                bytes(bufs[i, :int(n)]).decode())  # sync-ok: snapshot boundary
        return out
    except Exception:  # noqa: BLE001 - observability, not control flow
        return {str(index): local}


def snapshot(all_hosts: bool = False) -> Dict[str, Any]:
    """One structured observability export: span summary, every
    profiler counter/timer, cost gauges, bytes-on-wire counters, and
    the per-op cost-attribution tables.  Tagged with this host's
    `jax.process_index()`; `all_hosts=True` additionally all-gathers
    every host's counter/timer tables into `hosts` (a collective —
    every process of a pod run must call it, e.g. at an epoch/export
    boundary) so the pod exports ONE merged view."""
    local = _local_tables()
    snap = {
        "host": _process_index(),
        "spans": TRACER.summary(),
        "cost": cost.snapshot(),
        "op_profile": opprof.snapshot(),
        "devprof": devprof.snapshot(),
        "memory": memprof.snapshot(),
        "numerics": numerics.snapshot(),
        **local,
    }
    if all_hosts:
        snap["hosts"] = _gather_host_tables(local)
    return snap


# ---------------------------------------------------------------------------
# Live telemetry session (ISSUE 10 tentpole wiring).  The stdlib-only
# machinery lives in obs/telemetry.py; this is the in-process glue:
# flag/env resolution, the profiler/cost source bundle, the watchdog's
# export callbacks, and a refcounted singleton so a training loop and a
# serving engine in one process share a sampler + endpoint.
# ---------------------------------------------------------------------------

class _TelemetryHandle:
    """One live telemetry session: sampler thread + optional HTTP
    endpoint + watchdog.  `port` is the bound port (None without
    HTTP); close() is refcount-aware via stop_telemetry()."""

    def __init__(self, collector, server, watchdog):
        self.collector = collector
        self.server = server
        self.watchdog = watchdog
        self.port = server.port if server is not None else None

    def close(self) -> None:
        stop_telemetry()


_TELEMETRY: Optional[_TelemetryHandle] = None
_TELEMETRY_REFS = 0
_TELEMETRY_LOCK = _threading.Lock()


def _obs_flag(name: str, env_var: str, default, typ):
    """Resolve a PADDLE_OBS_* knob: fluid flag first (which itself was
    env-seeded at import), then a late env read for processes that set
    the variable after paddle_tpu import, then the default."""
    try:
        from ..fluid import flags as _flags

        entry = _flags._REGISTRY.get(name)
        if entry is not None and entry["value"] != entry["default"]:
            return typ(entry["value"])
    except Exception:  # noqa: BLE001 - flags registry unavailable
        pass
    env = _os.environ.get(env_var)
    if env is not None:
        try:
            return typ(env)
        except ValueError:
            pass
    return default


def start_telemetry(port: Optional[int] = None,
                    sample_s: Optional[float] = None,
                    flight_dir: Optional[str] = None,
                    flight_keep: Optional[int] = None,
                    flight_min_interval_s: Optional[float] = None,
                    thresholds: Optional[dict] = None) -> _TelemetryHandle:
    """Start (or join) the process-wide telemetry session: background
    sampler over the profiler/cost tables, anomaly watchdog + flight
    recorder, and — when `port` >= 0 (0 = ephemeral) — the /metrics +
    /healthz + /snapshot + /debug/trace HTTP endpoint.  Refcounted:
    every start_telemetry() must be paired with a stop_telemetry() (or
    handle.close()); the session tears down on the last one."""
    global _TELEMETRY, _TELEMETRY_REFS
    with _TELEMETRY_LOCK:
        if _TELEMETRY is not None:
            _TELEMETRY_REFS += 1
            return _TELEMETRY
        if port is None:
            port = _obs_flag("obs_http_port", "PADDLE_OBS_HTTP_PORT",
                             -1, int)
        if sample_s is None:
            sample_s = _obs_flag("obs_sample_s", "PADDLE_OBS_SAMPLE_S",
                                 telemetry.DEFAULT_SAMPLE_S, float)
        if flight_dir is None:
            flight_dir = _obs_flag("obs_flight_dir",
                                   "PADDLE_OBS_FLIGHT_DIR",
                                   "artifacts/flight", str)
        if flight_keep is None:
            flight_keep = _obs_flag("obs_flight_keep",
                                    "PADDLE_OBS_FLIGHT_KEEP", 5, int)
        if flight_min_interval_s is None:
            flight_min_interval_s = _obs_flag(
                "obs_flight_min_interval_s",
                "PADDLE_OBS_FLIGHT_MIN_INTERVAL_S", 60.0, float)
        def _bundle_meta() -> dict:
            # run-config stamp for the bundle manifest: a diff between
            # two bundles can tell a deliberate quant_collectives flip
            # (expected ~4x collective_bytes shift) from real drift
            from ..parallel import quant_collectives as _qc

            meta = {"quant_collectives": _qc.mode()}
            try:
                # which tenants shared the device at dump time
                # (multi-tenant fleet, serving/registry.py) — an
                # incident bundle without the co-tenant list cannot
                # distinguish noisy-neighbour from self-inflicted
                from ..serving.registry import active_tenants

                tenants = active_tenants()
                if tenants:
                    meta["tenants"] = tenants
            except Exception:  # noqa: BLE001 - meta only
                pass
            return meta

        watchdog = telemetry.Watchdog(
            thresholds=thresholds,
            artifacts_dir=flight_dir or None,
            keep=flight_keep,
            min_interval_s=flight_min_interval_s,
            trace_cb=export_trace,
            snapshot_cb=snapshot,
            op_profile_cb=opprof.snapshot,
            mem_cb=memprof.memory_doc,
            numerics_cb=numerics.numerics_doc,
            meta_cb=_bundle_meta)
        collector = telemetry.Collector(
            sources=telemetry.default_sources(),
            sample_s=sample_s, watchdog=watchdog)

        def _overhead(ms: float) -> None:
            from .. import profiler

            profiler.time_add("telemetry_sample_ms", ms)

        collector.overhead_cb = _overhead
        collector.snapshot_cb = snapshot
        collector.trace_json_cb = TRACER.chrome_trace
        server = None
        if port is not None and port >= 0:
            server = telemetry.TelemetryServer(collector,
                                               port=port).start()
        collector.start()
        _TELEMETRY = _TelemetryHandle(collector, server, watchdog)
        _TELEMETRY_REFS = 1
        return _TELEMETRY


def stop_telemetry() -> None:
    """Release one reference on the telemetry session; the sampler and
    endpoint shut down when the last holder releases."""
    global _TELEMETRY, _TELEMETRY_REFS
    with _TELEMETRY_LOCK:
        if _TELEMETRY is None:
            return
        _TELEMETRY_REFS -= 1
        if _TELEMETRY_REFS > 0:
            return
        handle, _TELEMETRY, _TELEMETRY_REFS = _TELEMETRY, None, 0
    handle.collector.stop()
    if handle.server is not None:
        handle.server.stop()


def maybe_start_telemetry() -> Optional[_TelemetryHandle]:
    """The PADDLE_OBS_HTTP_PORT auto-attach seam used by
    Executor.train_from_dataset and serving.Engine: starts (or joins)
    the telemetry session when the port knob is set (>= 0), returns
    None — no thread, no endpoint, no overhead — when it is not."""
    port = _obs_flag("obs_http_port", "PADDLE_OBS_HTTP_PORT", -1, int)
    if port is None or port < 0:
        return None
    return start_telemetry(port=port)


def telemetry_handle() -> Optional[_TelemetryHandle]:
    return _TELEMETRY


def telemetry_epoch_refresh() -> None:
    """Refresh the telemetry endpoint's pod-merged `/snapshot` view.
    Rides the existing epoch-boundary collective (the shard_skew_ms
    gather in dataset.feed_pipeline._finish_epoch) so the all-gather
    happens where every host already participates; a no-op without a
    live session."""
    handle = _TELEMETRY
    if handle is None:
        return
    try:
        handle.collector.refresh_merged(
            lambda: snapshot(all_hosts=True))
    except Exception:  # noqa: BLE001 - observability, not control flow
        pass


def export_trace(path: str, include_snapshot: bool = True) -> int:
    """Write the recorded spans as Chrome-trace/Perfetto JSON.  The
    snapshot rides in otherData so tracetool can summarize MFU and
    stall attribution from the one file; when a devprof window has
    captured measured device time, its device op events merge in as
    their own tracks, flow-linked from the `executor.dispatch` spans
    that launched them.  Returns the span ("X") event count."""
    other = None
    if include_snapshot:
        snap = snapshot()
        snap.pop("spans", None)  # the events ARE the span detail
        other = {"snapshot": snap}
    doc = TRACER.chrome_trace(other_data=other)
    try:
        devprof.merge_chrome_trace(doc)
    except Exception:  # noqa: BLE001 - the host trace must still export
        pass
    try:
        # ledger samples as a Chrome "C" counter track, aligned with
        # the span timeline (both perf_counter-clocked)
        doc["traceEvents"].extend(memprof.chrome_counter_events())
    except Exception:  # noqa: BLE001 - the host trace must still export
        pass
    with open(path, "w") as f:
        _json.dump(doc, f)
    return sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
