"""Whole-program shape/dtype verification over the transformed graph
(ISSUE 11 tentpole).

Build-time inference (`Block._infer_shapes`) runs once per op at
construction and never again — yet the transform pipeline
(`layout_optimize`, `fold_bn`, `dead_op_elim`) rewrites the graph
AFTER it, and a bad rewrite (an NHWC adapter attr on the wrong slot, a
synthesized fold chain that drops the dtype, DCE removing a writer
something still reads) surfaces as an unreadable JAX trace error with
no op-level provenance.  This module replays shape/dtype inference
op-by-op over the FINAL (post-transform) Program:

* a per-block **abstract env** of `(shape, dtype)` keyed by var name,
  where `-1` dims are symbolic (the batch dimension and anything
  derived from it) — block envs chain to their parent like
  `Block._var_recursive`;
* inference is driven by `registry.eval_op_shape` (two-probe dynamic
  dim detection, layout-adapter aware) with a **declarative fallback
  table** for ops whose lowering cannot be abstractly evaluated — the
  case `_infer_shapes` silently skipped before this PR;
* `while` / `conditional_block` sub-blocks are flowed through with
  **loop-carried-var widening**: a loop body that changes a carried
  var's shape widens the differing dims to symbolic and re-runs once;
  a carried dtype change is an ERROR.

The same engine now backs `Block._infer_shapes` (framework.py), so
build-time inference and post-transform verification cannot drift.

Registered as the ERROR-tier verifier pass `shape-consistency`
(analysis/verifier.py), which `Executor._prepare` /
`CompiledProgram._compile` run once per compile-cache miss, AFTER
`apply_transforms` — findings carry `program#<id> block<idx> op<id>`
provenance plus the rewriting pass's `[pass=...]` tag from the op's
`op_provenance` attr.

This module imports ONLY the stdlib at module scope (jax/registry are
imported lazily inside the eval path), so `tools/shapecheck.py` can
load it by file path on a box without jax and still check the
fallback-table subset — the tpulint loading idiom.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Tuple

from .verifier import ERROR, Finding, VerifyContext, register_pass

_EMPTY = "@EMPTY@"  # framework.EMPTY_VAR_NAME (kept import-free)
_GRAD_SUFFIX = "@GRAD"

logger = logging.getLogger("paddle_tpu.shape_check")

# (shape tuple with -1 = symbolic dim, canonical dtype string)
AbstractVal = Tuple[tuple, str]

# x32 policy twin of ops/registry.jdt, stdlib-only: 64-bit narrows to
# 32-bit so declared "int64" compares equal to an int32 eval result
_NARROW_64 = {"int64": "int32", "uint64": "uint32", "float64": "float32",
              "complex128": "complex64"}


def canon_dtype(name) -> str:
    s = str(name)
    return _NARROW_64.get(s, s)


class ShapeInferBail(Exception):
    """The op could not be abstractly evaluated (value-dependent
    lowering, jax unavailable, ...) and has no fallback rule; declared
    shapes stay authoritative for its outputs."""

    def __init__(self, op_type: str, reason: str):
        self.op_type = op_type
        self.reason = reason
        super().__init__(f"{op_type}: {reason}")


class ShapeInferSkip(ShapeInferBail):
    """No lowering rule is registered for the op type at all — the
    caller owns the shapes by contract (not counted as a bailout)."""


# ---------------------------------------------------------------------------
# Declarative fallback shape rules
# ---------------------------------------------------------------------------
#
# rule(op, ins) -> {slot: [(shape, dtype) | None, ...]}, where `ins`
# maps input slots to abstract values (None = unknown/empty input).
# Rules are pure stdlib — they are the subset tools/shapecheck.py can
# evaluate without jax — and cover ops whose lowering is either
# mesh-dependent (collectives: under `jax.eval_shape` there are no mesh
# axes, so the lowering's shape behavior does not reflect a real pod
# run) or value-dependent (recv_v2's payload pairing).

def _first_in(ins, slot="X") -> Optional[AbstractVal]:
    vals = ins.get(slot) or []
    return vals[0] if vals else None


def _identity_rule(op, ins):
    return {"Out": list(ins.get("X") or [])}


def _no_output_rule(op, ins):
    return {}


def _unknown_rule(op, ins):
    # mesh-dependent result shape (the factor is the mesh axis size,
    # which does not exist statically) and data-parallel programs mix
    # global-shaped feeds with per-shard-declared interiors — the only
    # honest abstract answer is "unknown"
    return {"Out": [None]}


def _allgather_rule(op, ins):
    x = _first_in(ins)
    n = int(op.attr("nranks", 0) or 0)
    if x is None or n <= 1:
        return {"Out": [None]}  # no static nranks: mesh decides
    shape, dt = x
    d0 = shape[0] if shape else 1
    out = ((-1 if d0 == -1 else d0 * n),) + tuple(shape[1:])
    return {"Out": [(out, dt)]}


def _reducescatter_rule(op, ins):
    x = _first_in(ins)
    n = int(op.attr("nranks", 0) or 0)
    if x is None or n <= 1:
        return {"Out": [None]}  # no static nranks: mesh decides
    shape, dt = x
    d0 = shape[0] if shape else 1
    out = ((-1 if d0 == -1 else d0 // n),) + tuple(shape[1:])
    return {"Out": [(out, dt)]}


def _recv_v2_rule(op, ins):
    x = _first_in(ins)
    if x is not None:
        return {"Out": [x]}
    shape = op.attr("out_shape")
    dtype = op.attr("dtype", "float32")
    if not shape:
        return {"Out": [None]}
    return {"Out": [(tuple(int(d) for d in shape), canon_dtype(dtype))]}


FALLBACK_SHAPE_RULES: Dict[str, Callable] = {
    # ring collectives: elementwise across replicas, shape-preserving
    "c_allreduce_sum": _identity_rule,
    "c_allreduce_max": _identity_rule,
    "c_allreduce_min": _identity_rule,
    "c_allreduce_prod": _identity_rule,
    "mp_allreduce_sum": _identity_rule,
    "c_reduce_sum": _identity_rule,
    "c_broadcast": _identity_rule,
    "c_identity": _identity_rule,
    "barrier": _identity_rule,
    "c_sync_calc_stream": _identity_rule,
    "c_sync_comm_stream": _identity_rule,
    # shape-changing collectives: a static nranks attr decides the
    # factor; without one the mesh does, and the abstract answer is
    # "unknown"
    "c_allgather": _allgather_rule,
    "c_reducescatter": _reducescatter_rule,
    # shard-convention-changing collectives: their declared outputs are
    # per-shard while feeds are global — never statically comparable
    "alltoall": _unknown_rule,
    "c_split": _unknown_rule,
    "c_concat": _unknown_rule,
    # p2p: send produces nothing; recv's shape is its out_shape attr
    "send_v2": _no_output_rule,
    "recv_v2": _recv_v2_rule,
    # comm bootstrap no-ops
    "c_comm_init": _no_output_rule,
    "c_comm_init_all": _no_output_rule,
    "c_gen_nccl_id": _no_output_rule,
    "c_wait_calc_stream": _no_output_rule,
    "c_wait_comm_stream": _no_output_rule,
}

# Ops whose declared output metadata is authoritative by contract: the
# checker seeds their outputs from declared shapes and never compares.
# Control-flow owners are handled structurally (the checker descends
# into the sub-block instead of evaluating the op), the rest have
# host-side / value-dependent semantics no abstract eval can see.
OPAQUE_OPS = {
    "while", "conditional_block", "run_program", "py_func", "print",
    "assert", "save", "load", "feed", "fetch",
}


def _grad_fallback(op, lookup) -> Dict[str, AbstractVal]:
    """Generic grad-op rule: a cotangent has exactly the shape/dtype of
    the forward value it differentiates — `X@GRAD` (and the
    `X@GRAD@RENAME@i` accumulation temps) mirror `X`.  Exact for every
    vjp-derived grad op, which is all of them (ops/registry.py)."""
    out: Dict[str, AbstractVal] = {}
    for name in op.output_arg_names():
        if name == _EMPTY or _GRAD_SUFFIX not in name:
            continue
        base = name.split(_GRAD_SUFFIX, 1)[0]
        val = lookup(base)
        if val is not None:
            out[name] = val
    return out


# ---------------------------------------------------------------------------
# The shared inference engine (Block._infer_shapes rides this too)
# ---------------------------------------------------------------------------

def _declared_lookup(block) -> Callable[[str], Optional[AbstractVal]]:
    def lookup(name: str) -> Optional[AbstractVal]:
        blk = block
        while blk is not None:
            v = blk.vars.get(name)
            if v is not None:
                if v.shape is None:
                    return None
                return tuple(v.shape), canon_dtype(v.dtype)
            blk = blk.parent_block
        return None

    return lookup


def _gather_abstract_ins(op, lookup) -> Dict[str, list]:
    ins: Dict[str, list] = {}
    for slot, names in op.inputs.items():
        ins[slot] = [lookup(n) if n != _EMPTY else None for n in names]
    return ins


def _bind_rule_outs(op, outs) -> Dict[str, AbstractVal]:
    bound: Dict[str, AbstractVal] = {}
    for slot, names in op.outputs.items():
        vals = outs.get(slot, [])
        for i, name in enumerate(names):
            if name == _EMPTY or i >= len(vals) or vals[i] is None:
                continue
            shape, dt = vals[i]
            bound[name] = (tuple(shape), canon_dtype(dt))
    return bound


def _two_probe_eval(op, block, lookup) -> Dict[str, AbstractVal]:
    """registry.eval_op_shape under two batch probes; dims that track
    the probe are marked symbolic (-1).  Static inputs (no -1 dims)
    need only one probe — nothing can vary."""
    try:
        from ..ops import registry
    except Exception as e:  # noqa: BLE001 - jax-free standalone load
        raise ShapeInferBail(op.type, f"jax unavailable ({e})")
    if not registry.has_op(op.type):
        raise ShapeInferSkip(op.type, "no lowering rule registered")

    dynamic = any(
        -1 in val[0]
        for slot, names in op.inputs.items()
        for n in names if n != _EMPTY
        for val in (lookup(n),) if val is not None)
    probes = (3, 5) if dynamic else (3,)
    results = []
    for probe in probes:
        try:
            results.append(
                registry.eval_op_shape(op, block, probe, lookup=lookup))
        except Exception as e:  # noqa: BLE001 - value-dependent lowering
            raise ShapeInferBail(op.type, f"{type(e).__name__}: {e}")
    first = results[0]
    second = results[-1]
    out: Dict[str, AbstractVal] = {}
    for slot, names in op.outputs.items():
        shapes1 = first.get(slot, [])
        shapes2 = second.get(slot, [])
        for i, name in enumerate(names):
            if name == _EMPTY or i >= len(shapes1):
                continue
            s1 = shapes1[i]
            if not hasattr(s1, "shape"):
                continue  # composite values (TensorArrayVal): no one shape
            s2 = shapes2[i] if i < len(shapes2) else s1
            shape = tuple(
                -1 if a != b else a for a, b in zip(s1.shape, s2.shape))
            out[name] = (shape, canon_dtype(s1.dtype))
    return out


def infer_op_outputs(op, block, lookup=None) -> Dict[str, AbstractVal]:
    """Infer `{output var name: (shape, dtype)}` for one op.

    `lookup(name) -> (shape, dtype) | None` resolves input vars; it
    defaults to the declared shapes walked through the block chain
    (build-time inference), and the shape-consistency pass passes its
    abstract env instead (replay).  Raises ShapeInferBail when the op
    cannot be evaluated (ShapeInferSkip for unregistered types)."""
    if lookup is None:
        lookup = _declared_lookup(block)
    if op.attr("fwd_op_id", None) is not None:
        return _grad_fallback(op, lookup)
    rule = FALLBACK_SHAPE_RULES.get(op.type)
    if rule is not None:
        outs = rule(op, _gather_abstract_ins(op, lookup))
        return _bind_rule_outs(op, outs)
    return _two_probe_eval(op, block, lookup)


_LOGGED_BAIL_TYPES: set = set()


def log_bailout_once(op_type: str, reason: str) -> None:
    """Satellite: un-inferable ops are visible — one log line per op
    type per process instead of a silent `return`."""
    if op_type in _LOGGED_BAIL_TYPES:
        return
    _LOGGED_BAIL_TYPES.add(op_type)
    logger.info("shape inference bailed out for op type %r (%s); "
                "declared shapes stay authoritative", op_type, reason)


# ---------------------------------------------------------------------------
# The abstract interpreter (the shape-consistency pass body)
# ---------------------------------------------------------------------------

_MAX_FINDINGS = 25  # per program: a bad rewrite cascades; cap the noise

_LOOP_OWNERS = {"while"}
_COND_OWNERS = {"conditional_block"}


class _Env:
    """One block's abstract env; chains to the parent block's env the
    way `Block._var_recursive` chains declarations."""

    __slots__ = ("block", "vals", "parent")

    def __init__(self, block, parent: Optional["_Env"] = None):
        self.block = block
        self.vals: Dict[str, AbstractVal] = {}
        self.parent = parent

    def lookup(self, name: str) -> Optional[AbstractVal]:
        e = self
        while e is not None:
            v = e.vals.get(name)
            if v is not None:
                return v
            e = e.parent
        return None

    def bind(self, name: str, val: AbstractVal) -> None:
        # write lands on the env whose block DECLARES the var (loop
        # bodies assign to parent-declared loop-carried vars)
        e = self
        while e is not None:
            if name in getattr(e.block, "vars", {}):
                e.vals[name] = val
                return
            e = e.parent
        self.vals[name] = val

    def forget(self, name: str) -> None:
        e = self
        while e is not None:
            e.vals.pop(name, None)
            e = e.parent

    def chain(self) -> List["_Env"]:
        out, e = [], self
        while e is not None:
            out.append(e)
            e = e.parent
        return out


def _op_prov_tag(op) -> str:
    """The transform-provenance suffix for finding messages: carries
    the source-op identity plus any `[pass=...]` rewrite tags."""
    prov = op.attrs.get("op_provenance")
    return f" (provenance: {prov})" if prov else ""


def _shapes_conflict(inferred: tuple, declared: tuple) -> bool:
    if len(inferred) != len(declared):
        return True
    return any(a != -1 and b != -1 and a != b
               for a, b in zip(inferred, declared))


class _Checker:
    def __init__(self, ctx: VerifyContext):
        self.ctx = ctx
        self.prog = ctx.program
        self.findings: List[Finding] = []
        self.external = ctx.external_names()
        self.all_written = {
            n for blk in self.prog.blocks for op in blk.ops
            for n in op.output_arg_names() if n != _EMPTY}
        self.reported_vars: set = set()
        self.bailed = 0

    # -- findings ----------------------------------------------------------
    def _err(self, message, op=None, var=None) -> None:
        if len(self.findings) >= _MAX_FINDINGS:
            return
        self.findings.append(self.ctx.finding(
            ERROR, "shape-consistency", message, op=op, var=var))

    # -- env seeding -------------------------------------------------------
    def _declared(self, block, name) -> Optional[AbstractVal]:
        blk = block
        seen = set()
        while blk is not None and id(blk) not in seen:
            seen.add(id(blk))
            v = blk.vars.get(name)
            if v is not None:
                if v.shape is None:
                    return None
                return tuple(v.shape), canon_dtype(v.dtype)
            blk = getattr(blk, "parent_block", None)
        return None

    def _declared_var(self, block, name):
        blk = block
        seen = set()
        while blk is not None and id(blk) not in seen:
            seen.add(id(blk))
            v = blk.vars.get(name)
            if v is not None:
                return v
            blk = getattr(blk, "parent_block", None)
        return None

    def _seed_entry(self, env: _Env) -> None:
        """Externally-materialized vars enter the env with their
        declared shapes: feeds (`is_data`), scope state (persistable),
        and anything the caller names in feed/scope sets."""
        for v in env.block.vars.values():
            if v.shape is None:
                continue
            if getattr(v, "is_data", False) or v.persistable \
                    or v.name in self.external:
                env.vals.setdefault(
                    v.name, (tuple(v.shape), canon_dtype(v.dtype)))

    # -- input resolution --------------------------------------------------
    def _resolve_input(self, env: _Env, block, op, name: str,
                       owner_type) -> Optional[AbstractVal]:
        val = env.lookup(name)
        if val is not None:
            return val
        var = self._declared_var(block, name)
        if var is None:
            if name in self.all_written:
                return None  # produced in a block we did not walk: unknown
            if name not in self.reported_vars:
                self.reported_vars.add(name)
                self._err(
                    f"op reads {name!r}, which is neither declared in any "
                    f"reachable block scope nor written by any op — a "
                    f"rewrite renamed or removed it{_op_prov_tag(op)}",
                    op=op, var=name)
            return None
        if var.shape is None:
            return None
        declared = (tuple(var.shape), canon_dtype(var.dtype))
        if name in self.all_written or owner_type in _LOOP_OWNERS:
            # written later (loop-carried / forward ref): trust declared
            return declared
        if getattr(var, "is_data", False) or var.persistable \
                or name in self.external:
            return declared
        if self.ctx.feed_names is not None:
            # feed set is known and the var is neither fed, in scope,
            # data, persistable, nor produced by ANY op: nothing can
            # materialize it — the DCE-removed-writer signature
            if name not in self.reported_vars:
                self.reported_vars.add(name)
                self._err(
                    f"op reads {name!r}, which no op produces and which "
                    f"is not fed, persistable, or data — was its writer "
                    f"removed by a rewrite?{_op_prov_tag(op)}",
                    op=op, var=name)
            return None
        return declared  # feed unknown: the var may be fed — degrade

    # -- per-op ------------------------------------------------------------
    def _check_op(self, env: _Env, block, op, owner_type) -> None:
        inputs_known = True
        for name in op.input_arg_names():
            if name == _EMPTY:
                continue
            if self._resolve_input(env, block, op, name, owner_type) is None:
                inputs_known = False
        if op.type in OPAQUE_OPS:
            for name in op.output_arg_names():
                if name == _EMPTY or env.lookup(name) is not None:
                    continue
                d = self._declared(block, name)
                if d is not None:
                    env.bind(name, d)
            return
        if not inputs_known:
            for name in op.output_arg_names():
                if name != _EMPTY:
                    env.forget(name)
            return

        def lookup(name):
            v = env.lookup(name)
            if v is not None:
                return v
            return self._declared(block, name)

        try:
            inferred = infer_op_outputs(op, block, lookup=lookup)
        except ShapeInferBail as bail:
            if not isinstance(bail, ShapeInferSkip):
                self.bailed += 1
                log_bailout_once(bail.op_type, bail.reason)
            for name in op.output_arg_names():
                if name != _EMPTY:
                    env.forget(name)
            return
        except Exception:  # noqa: BLE001 - a checker bug must not kill compile
            for name in op.output_arg_names():
                if name != _EMPTY:
                    env.forget(name)
            return

        for name in op.output_arg_names():
            if name == _EMPTY:
                continue
            val = inferred.get(name)
            if val is None:
                env.forget(name)
                continue
            var = self._declared_var(block, name)
            # shape None = type inference was skipped at build time; the
            # declared metadata is untrusted and not compared
            if var is not None and var.shape is not None:
                decl_shape = tuple(var.shape)
                decl_dt = canon_dtype(var.dtype)
                if _shapes_conflict(val[0], decl_shape):
                    self._err(
                        f"var {name!r}: inferred shape {list(val[0])} "
                        f"conflicts with declared shape {list(decl_shape)}"
                        f"{_op_prov_tag(op)}", op=op, var=name)
                elif val[1] != decl_dt:
                    self._err(
                        f"var {name!r}: inferred dtype {val[1]} conflicts "
                        f"with declared dtype {decl_dt}"
                        f"{_op_prov_tag(op)}", op=op, var=name)
            env.bind(name, val)

    # -- block / sub-block walk -------------------------------------------
    def _walk(self, block, env: _Env, owner_type, visited) -> None:
        for op in block.ops:
            sb = op.attr("sub_block")
            if isinstance(sb, int) and 0 < sb < len(self.prog.blocks) \
                    and sb not in visited:
                self._descend(env, block, op, sb, visited)
                # outputs the body did not bind fall back to declared
                for name in op.output_arg_names():
                    if name == _EMPTY or env.lookup(name) is not None:
                        continue
                    d = self._declared(block, name)
                    if d is not None:
                        env.bind(name, d)
                continue
            if len(self.findings) >= _MAX_FINDINGS:
                return
            self._check_op(env, block, op, owner_type)

    def _descend(self, env: _Env, block, op, sb: int, visited) -> None:
        sub = self.prog.blocks[sb]
        if op.type in _LOOP_OWNERS:
            # pass 1: run the body with findings suppressed, diff the
            # loop-carried writes, widen changed dims to symbolic
            saved = [(e, dict(e.vals)) for e in env.chain()]
            kept, self.findings = self.findings, []
            # per-var dedup must not "use up" findings in the muted
            # pass, or pass 2 would silently skip them
            kept_reported = set(self.reported_vars)
            child = _Env(sub, parent=env)
            self._seed_entry(child)
            self._walk(sub, child, op.type, visited | {sb})
            self.findings = kept
            self.reported_vars = kept_reported
            for e, before in saved:
                for name, new in list(e.vals.items()):
                    old = before.get(name)
                    if old is None or old == new:
                        continue
                    if old[1] != new[1]:
                        self._err(
                            f"loop-carried var {name!r} changes dtype "
                            f"across the `while` body ({old[1]} -> "
                            f"{new[1]})" + _op_prov_tag(op), op=op, var=name)
                        e.vals[name] = old
                    elif len(old[0]) != len(new[0]):
                        self._err(
                            f"loop-carried var {name!r} changes rank "
                            f"across the `while` body ({list(old[0])} -> "
                            f"{list(new[0])})" + _op_prov_tag(op),
                            op=op, var=name)
                        e.vals[name] = old
                    else:
                        widened = tuple(
                            a if a == b else -1
                            for a, b in zip(old[0], new[0]))
                        e.vals[name] = (widened, old[1])
            # pass 2: re-run with widened carried vars, findings live
            child = _Env(sub, parent=env)
            self._seed_entry(child)
            self._walk(sub, child, op.type, visited | {sb})
        else:
            saved = [(e, dict(e.vals)) for e in env.chain()]
            child = _Env(sub, parent=env)
            self._seed_entry(child)
            self._walk(sub, child, op.type, visited | {sb})
            # a conditional body may or may not run: widen its writes
            for e, before in saved:
                for name, new in list(e.vals.items()):
                    old = before.get(name)
                    if old is None or old == new:
                        continue
                    if old[1] != new[1] or len(old[0]) != len(new[0]):
                        e.vals.pop(name, None)  # unknown across paths
                    else:
                        e.vals[name] = (tuple(
                            a if a == b else -1
                            for a, b in zip(old[0], new[0])), old[1])

    def run(self) -> List[Finding]:
        if not self.prog.blocks:
            return []
        root = _Env(self.prog.blocks[0])
        self._seed_entry(root)
        self._walk(self.prog.blocks[0], root, None, {0})
        if self.bailed:
            try:
                from ..profiler import stat_add

                stat_add("shape_check_bailouts", self.bailed)
            except Exception:  # noqa: BLE001 - stdlib-only standalone load
                pass
        return self.findings


def check_program(program, feed=None, fetch_list=None,
                  scope_names=None) -> List[Finding]:
    """Standalone entry: replay shape/dtype inference over `program`
    and return the ERROR findings (empty = consistent).  Used by
    tools/shapecheck.py and the transform bisection hook."""
    feed_names = None
    if feed is not None:
        feed_names = set(feed.keys() if hasattr(feed, "keys") else feed)
    fetch_names = None
    if fetch_list is not None:
        fetch_names = [v.name if hasattr(v, "name") else str(v)
                       for v in fetch_list]
    ctx = VerifyContext(program, feed_names=feed_names,
                        fetch_names=fetch_names, scope_names=scope_names)
    return _Checker(ctx).run()


@register_pass("shape-consistency")
def shape_consistency_pass(ctx: VerifyContext) -> List[Finding]:
    """ERROR-tier verifier pass: whole-program shape/dtype replay over
    the final (post-transform) graph."""
    return _Checker(ctx).run()


# ---------------------------------------------------------------------------
# Program-dict view (tools/shapecheck.py, jax-free)
# ---------------------------------------------------------------------------
#
# Program.to_dict() round-trips through JSON; these shims rebuild just
# enough of the Block/Operator/Variable surface for _Checker to walk a
# serialized program on a box without jax (fallback-table subset only:
# everything else degrades to unknown).

class _VarView:
    __slots__ = ("name", "shape", "dtype", "persistable", "is_data")

    def __init__(self, d):
        self.name = d["name"]
        self.shape = tuple(d["shape"]) if d.get("shape") is not None else None
        self.dtype = d.get("dtype", "float32")
        self.persistable = bool(d.get("persistable", False))
        self.is_data = bool(d.get("is_data", False))


class _OpView:
    __slots__ = ("id", "type", "inputs", "outputs", "attrs", "block")

    def __init__(self, d, block):
        self.id = d.get("id", 0)
        self.type = d["type"]
        self.inputs = {s: list(ns) for s, ns in d.get("inputs", {}).items()}
        self.outputs = {s: list(ns) for s, ns in d.get("outputs", {}).items()}
        self.attrs = dict(d.get("attrs", {}))
        self.block = block

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def input_arg_names(self):
        return [n for ns in self.inputs.values() for n in ns]

    def output_arg_names(self):
        return [n for ns in self.outputs.values() for n in ns]


class _BlockView:
    __slots__ = ("idx", "parent_idx", "vars", "ops", "program")

    def __init__(self, d, program):
        self.idx = d.get("idx", 0)
        self.parent_idx = d.get("parent_idx", -1)
        self.program = program
        self.vars = {v["name"]: _VarView(v) for v in d.get("vars", [])}
        self.ops = [_OpView(o, self) for o in d.get("ops", [])]

    @property
    def parent_block(self):
        if 0 <= self.parent_idx < len(self.program.blocks) \
                and self.parent_idx != self.idx:
            return self.program.blocks[self.parent_idx]
        return None


class ProgramView:
    """Read-only duck type of fluid.framework.Program over to_dict()
    output — what _Checker walks when loaded standalone."""

    def __init__(self, d, prog_id=0):
        self.prog_id = d.get("prog_id", prog_id)
        self.version = d.get("version", 0)
        self.blocks = [_BlockView(b, self) for b in d.get("blocks", [])]


def check_program_dict(d, feed=None, fetch_list=None) -> List[Finding]:
    """Check a serialized Program (Program.to_dict() / its JSON)."""
    return check_program(ProgramView(d), feed=feed, fetch_list=fetch_list)
