"""Static analysis for the Program IR and the codebase itself (ISSUE 3).

Two halves:

* `analysis.verifier` — the Program verifier: a pass pipeline checking
  structural invariants (op registry, def-before-use, block linkage)
  and dataflow properties (donation/aliasing safety, cross-replica
  collective order, dead code) over `fluid.framework.Program`, run by
  the Executor/CompiledProgram once per compile-cache miss under
  `FLAGS_verify_program`.
* `analysis.lint` — tpulint, the multi-rule source lint framework
  (hot-path sync discipline, serving lock order, untraced jit side
  effects), driven by `tools/tpulint.py` / `tools/run_lints.py` and
  kept stdlib-only so it runs without importing paddle_tpu.

See docs/static_analysis.md.
"""

from .verifier import (ERROR, INFO, WARNING, Finding,  # noqa: F401
                       ProgramVerificationError, VerifyContext,
                       maybe_verify_program, register_pass,
                       registered_passes, verify_program)

__all__ = [
    "ERROR", "WARNING", "INFO", "Finding", "ProgramVerificationError",
    "VerifyContext", "maybe_verify_program", "register_pass",
    "registered_passes", "verify_program",
]
