"""Static analysis for the Program IR and the codebase itself (ISSUE 3,
ISSUE 11).

Three halves:

* `analysis.verifier` — the Program verifier: a pass pipeline checking
  structural invariants (op registry, def-before-use, block linkage)
  and dataflow properties (donation/aliasing safety, cross-replica
  collective order, dead code) over `fluid.framework.Program`, run by
  the Executor/CompiledProgram once per compile-cache miss under
  `FLAGS_verify_program`.
* `analysis.shape_check` + `analysis.collective_order` — the
  post-transform passes (ISSUE 11): `shape-consistency` replays
  shape/dtype inference op-by-op over the FINAL (transformed) graph
  via an abstract interpreter with loop-carried-var widening, and
  `cross-program-collective-order` diffs collective issue-order
  signatures across programs in one clone family (train step vs eval
  clone on the same mesh).  `analysis.shard_check` (ISSUE 18) adds
  `shard-consistency`: GSPMD-style PartitionSpec propagation under the
  current mesh with predicted collective cost (`comm_report`) and the
  elastic re-shard precheck (`feasibility`).  Importing this package
  registers all of them in the verifier pipeline.
* `analysis.lint` — tpulint, the multi-rule source lint framework
  (hot-path sync discipline, serving lock order, untraced jit side
  effects), driven by `tools/tpulint.py` / `tools/run_lints.py` and
  kept stdlib-only so it runs without importing paddle_tpu.

See docs/static_analysis.md.
"""

from .verifier import (ERROR, INFO, WARNING, Finding,  # noqa: F401
                       ProgramVerificationError, VerifyContext,
                       maybe_verify_program, register_pass,
                       registered_passes, reset_finding_dedup,
                       verify_program)
from .shape_check import (FALLBACK_SHAPE_RULES, ShapeInferBail,  # noqa: F401
                          ShapeInferSkip, check_program,
                          check_program_dict, infer_op_outputs,
                          log_bailout_once)
from .collective_order import (collective_signature,  # noqa: F401
                               reset_ring_registry,
                               ring_registry_snapshot)
from . import shard_check  # noqa: F401  (registers shard-consistency)
from .shard_check import (ShardAnalysis, comm_report,  # noqa: F401
                          feasibility, propagated_shapes)

__all__ = [
    "ERROR", "WARNING", "INFO", "Finding", "ProgramVerificationError",
    "VerifyContext", "maybe_verify_program", "register_pass",
    "registered_passes", "reset_finding_dedup", "verify_program",
    "FALLBACK_SHAPE_RULES", "ShapeInferBail", "ShapeInferSkip",
    "check_program", "check_program_dict", "infer_op_outputs",
    "log_bailout_once",
    "collective_signature", "reset_ring_registry",
    "ring_registry_snapshot",
    "ShardAnalysis", "comm_report", "feasibility",
    "propagated_shapes", "shard_check",
]
