"""Lock-order rule: static lock-acquisition graph over the serving
threads (ISSUE 3, part 2).

The serving engine runs four-plus threads (dispatch, compiler,
completer, decode loop) over shared state guarded by half a dozen locks
spread across Engine / DynamicBatcher / AdmissionController / PageTable
/ CompileCache.  A lock-order inversion between any two of them is a
deadlock that only fires under production interleavings; a lock held
across `jax.device_put` or an XLA compile stalls every sibling thread
for seconds.  Both are statically visible, so this rule catches them at
lint time:

1. **Graph construction.**  A lock is any `threading.Lock / RLock /
   Condition` assigned to a `self.<attr>` (or class-level) slot; its
   node id is `Class.attr`.  Within a `with <lock>:` body, a direct
   nested acquisition adds edge A->B, and a call into a method whose
   transitive lock set (fixpoint over the intra-fileset call graph)
   contains B adds A->B.  Receivers resolve through constructor
   assignments (`self._batcher = DynamicBatcher(...)`), module-level
   constructor assignments in the same file (`_ENGINE = Engine()`),
   and **plain locals**: `b = self._batcher` / `b = DynamicBatcher()`
   / `b = _ENGINE` type the local, and `lk = self._lock` aliases the
   lock itself — so `with b._lock:` and `with lk:` are real
   acquisitions, not blind spots.
2. **Cycles** in the edge graph are reported as errors (potential
   deadlock), as is re-acquiring a non-reentrant `Lock` already held.
3. **Device work under a lock**: `device_put`, `jax.jit`, `.lower(...)`
   (with args — `str.lower()` takes none) or `.compile()` (without args
   — `re.compile(pat)` takes one) reached while holding a lock is an
   error, UNLESS the lock's id contains "compile" — a dedicated
   `*compile*` lock exists precisely to serialize compiles
   (BucketedRunner._compile_lock, CompileCache) and is exempt by that
   naming convention.

Suppress a line with `# lock-ok: <why>` or
`# tpulint: disable=lock-order`.  Static limits: local typing is
flow-insensitive (the last compatible assignment in the method wins)
and receivers flowing through function parameters or containers are
not resolved.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from . import LintContext, LintFinding, register_rule, suppressed

RULE = "lock-order"
LOCK_OK = "# lock-ok"

# files whose threads share locks: the serving subsystem plus the
# shared compile-cache machinery it leans on
SCAN = ("paddle_tpu/serving", "paddle_tpu/fluid/compile_cache.py")

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_REENTRANT_CTORS = {"RLock", "Condition"}  # Condition wraps an RLock
# methods ON a lock object itself (not acquisitions of another lock)
_LOCK_METHODS = {"wait", "wait_for", "notify", "notify_all", "acquire",
                 "release"}


def _attr_chain(node) -> Optional[List[str]]:
    """Name/Attribute chain as ["self", "kv", "table"], or None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def _ctor_name(call: ast.Call) -> Optional[str]:
    """Class name for `X(...)` / `mod.X(...)` calls."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


class _ClassInfo:
    def __init__(self, name: str, rel: str):
        self.name = name
        self.rel = rel
        self.locks: Dict[str, str] = {}  # attr -> ctor name
        self.attr_types: Dict[str, str] = {}  # attr -> class name
        self.methods: Dict[str, ast.FunctionDef] = {}


def _is_device_call(call: ast.Call) -> Optional[str]:
    """Name of the device-work construct this call is, or None."""
    fn = call.func
    chain = _attr_chain(fn) or []
    last = chain[-1] if chain else None
    if last == "device_put":
        return "device_put"
    if last == "jit" and len(chain) >= 2 and chain[-2] in ("jax", "pjit"):
        return "jax.jit"
    if last == "pjit":
        return "pjit"
    if isinstance(fn, ast.Attribute):
        if fn.attr == "lower" and (call.args or call.keywords):
            return ".lower(...)"
        if fn.attr == "compile" and not call.args and not call.keywords:
            return ".compile()"
    return None


class _MethodScan(ast.NodeVisitor):
    """One method's direct acquisitions, calls-under-locks, nested
    acquisitions, and direct device work."""

    def __init__(self, analyzer: "_Analyzer", cls: Optional[_ClassInfo],
                 rel: str):
        self.an = analyzer
        self.cls = cls
        self.rel = rel
        self.local_types: Dict[str, str] = {}  # local name -> class
        self.local_locks: Dict[str, str] = {}  # local name -> lock id
        self.stack: List[str] = []  # lock ids currently held
        self.direct: Set[str] = set()
        # (held-lock, acquired-lock, line)
        self.edges: List[Tuple[str, str, int]] = []
        # (callee key, held locks snapshot, line)
        self.calls: List[Tuple[Tuple[str, str], Tuple[str, ...], int]] = []
        # (construct, held locks snapshot, line)
        self.device: List[Tuple[str, Tuple[str, ...], int]] = []
        self.reacquires: List[Tuple[str, int]] = []

    # -- plain-local receiver typing ---------------------------------------
    def prime(self, fn_node) -> None:
        """Pre-pass over the method body typing plain locals so they
        resolve as receivers: `b = DynamicBatcher(...)` /
        `b = self._batcher` / `b = _MODULE_SINGLETON` type `b`, and
        `lk = self._lock` makes `lk` a lock alias.  Flow-insensitive
        (lint-grade): later assignments win."""
        for node in ast.walk(fn_node):
            if not isinstance(node, ast.Assign) \
                    or len(node.targets) != 1 \
                    or not isinstance(node.targets[0], ast.Name):
                continue
            name = node.targets[0].id
            val = node.value
            if isinstance(val, ast.Call):
                ctor = _ctor_name(val)
                if ctor in self.an.classes:
                    self.local_types[name] = ctor
                continue
            chain = _attr_chain(val)
            if not chain:
                continue
            if len(chain) == 1:
                src = chain[0]
                t = (self.local_types.get(src)
                     or self.an.module_types.get(src))
                if t is not None:
                    self.local_types[name] = t
                elif src in self.local_locks:
                    self.local_locks[name] = self.local_locks[src]
                continue
            lock = self._lock_id(val)
            if lock is not None:
                self.local_locks[name] = lock
                continue
            owner = self.an.resolve_owner(self.cls, chain[:-1],
                                          self.local_types)
            info = self.an.classes.get(owner) if owner else None
            t = info.attr_types.get(chain[-1]) if info else None
            if t is not None:
                self.local_types[name] = t

    # -- lock identity -----------------------------------------------------
    def _lock_id(self, expr) -> Optional[str]:
        chain = _attr_chain(expr)
        if not chain:
            return None
        if len(chain) == 1:
            return self.local_locks.get(chain[0])
        owner = self.an.resolve_owner(self.cls, chain[:-1],
                                      self.local_types)
        if owner is None:
            return None
        info = self.an.classes.get(owner)
        if info is not None and chain[-1] in info.locks:
            return f"{owner}.{chain[-1]}"
        return None

    def _enter_lock(self, lock: str, node) -> None:
        line = getattr(node, "lineno", 0)
        if lock in self.stack:
            info = self.an.lock_kinds.get(lock)
            if info not in _REENTRANT_CTORS:
                self.reacquires.append((lock, line))
        elif self.stack:
            self.edges.append((self.stack[-1], lock, line))
        self.direct.add(lock)
        self.stack.append(lock)

    # -- visitors ----------------------------------------------------------
    def visit_With(self, node: ast.With):
        entered = []
        for item in node.items:
            lock = self._lock_id(item.context_expr)
            if lock is not None:
                self._enter_lock(lock, node)
                entered.append(lock)
            else:
                self.generic_visit(item)
        for stmt in node.body:
            self.visit(stmt)
        for _ in entered:
            self.stack.pop()

    def visit_Call(self, node: ast.Call):
        line = getattr(node, "lineno", 0)
        dev = _is_device_call(node)
        if dev is not None:
            self.device.append((dev, tuple(self.stack), line))
        chain = _attr_chain(node.func)
        if chain is not None:
            # explicit .acquire() is an acquisition too
            if (chain[-1] == "acquire"
                    and isinstance(node.func, ast.Attribute)):
                lock = self._lock_id(node.func.value)
                if lock is not None:
                    self._enter_lock(lock, node)
                    self.stack.pop()  # conservative: treat as scoped
            elif not (len(chain) >= 2
                      and chain[-1] in _LOCK_METHODS
                      and isinstance(node.func, ast.Attribute)
                      and self._lock_id(node.func.value) is not None):
                callee = self.an.resolve_call(self.cls, chain,
                                              self.local_types)
                if callee is not None:
                    self.calls.append((callee, tuple(self.stack), line))
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef):
        # nested defs are scanned as their own pseudo-methods by the
        # analyzer; don't double-count their bodies under our lock stack
        # unless they are immediately called (rare; ignored)
        return

    visit_AsyncFunctionDef = visit_FunctionDef


class _Analyzer:
    def __init__(self, sources: Dict[str, str]):
        self.sources = sources
        self.classes: Dict[str, _ClassInfo] = {}
        self.lock_kinds: Dict[str, str] = {}  # lock id -> ctor name
        self.module_types: Dict[str, str] = {}  # module var -> class
        self.scans: Dict[Tuple[str, str], _MethodScan] = {}
        self._trees: Dict[str, ast.Module] = {
            rel: ast.parse(src) for rel, src in sources.items()}
        self._collect()
        self._scan_methods()
        self._fixpoint()

    # -- pass 1: classes, locks, attr types, methods ----------------------
    def _collect(self):
        for rel, tree in self._trees.items():
            for node in ast.walk(tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                info = self.classes.setdefault(node.name,
                                               _ClassInfo(node.name, rel))
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        info.methods[item.name] = item
                    elif isinstance(item, ast.Assign):
                        self._record_assign(info, item, class_level=True)
        # attr assignments inside methods
        for info in list(self.classes.values()):
            for meth in info.methods.values():
                for node in ast.walk(meth):
                    if isinstance(node, ast.Assign):
                        self._record_assign(info, node, class_level=False)
        # module-level singletons: `_ENGINE = Engine()` at top level
        # types the module var, so plain locals assigned from it (and
        # lock accesses through it) resolve
        for rel, tree in self._trees.items():
            for node in tree.body:
                if not isinstance(node, ast.Assign) \
                        or not isinstance(node.value, ast.Call):
                    continue
                ctor = _ctor_name(node.value)
                if ctor not in self.classes:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.module_types[tgt.id] = ctor
        for cname, info in self.classes.items():
            for attr, ctor in info.locks.items():
                self.lock_kinds[f"{cname}.{attr}"] = ctor

    def _record_assign(self, info: _ClassInfo, node: ast.Assign,
                       class_level: bool):
        if not isinstance(node.value, ast.Call):
            return
        ctor = _ctor_name(node.value)
        for tgt in node.targets:
            attr = None
            if class_level and isinstance(tgt, ast.Name):
                attr = tgt.id
            elif (isinstance(tgt, ast.Attribute)
                  and isinstance(tgt.value, ast.Name)
                  and tgt.value.id == "self"):
                attr = tgt.attr
            if attr is None:
                continue
            if ctor in _LOCK_CTORS:
                info.locks[attr] = ctor
            elif ctor is not None:
                info.attr_types[attr] = ctor

    # -- receiver resolution ----------------------------------------------
    def resolve_owner(self, cls: Optional[_ClassInfo],
                      chain: List[str],
                      local_types: Optional[Dict[str, str]] = None) \
            -> Optional[str]:
        """Class name owning the object named by `chain` (e.g.
        ["self","kv","table"] -> "PageTable"), or None.  `local_types`
        maps plain-local receiver names to class names (from
        `_MethodScan.prime`); module-level singletons resolve through
        `module_types`."""
        if not chain:
            return None
        if chain[0] == "self":
            if cls is None:
                return None
            cur = cls.name
            for attr in chain[1:]:
                info = self.classes.get(cur)
                if info is None:
                    return None
                nxt = info.attr_types.get(attr)
                if nxt is None:
                    return None
                cur = nxt
            return cur
        cur = None
        rest: List[str] = []
        if local_types and chain[0] in local_types:
            cur, rest = local_types[chain[0]], chain[1:]
        elif chain[0] in self.module_types:
            cur, rest = self.module_types[chain[0]], chain[1:]
        elif chain[0] in self.classes:
            # ClassName.attr class-level locks
            cur = chain[0]
            rest = chain[1:-1] if len(chain) > 2 else []
        if cur is None:
            return None
        for attr in rest:
            info = self.classes.get(cur)
            nxt = info.attr_types.get(attr) if info else None
            if nxt is None:
                return None
            cur = nxt
        return cur

    def resolve_call(self, cls: Optional[_ClassInfo],
                     chain: List[str],
                     local_types: Optional[Dict[str, str]] = None) \
            -> Optional[Tuple[str, str]]:
        """(class, method) for a call chain, or None."""
        if len(chain) == 1:
            # bare Name: a constructor of a known class counts as a call
            # into its __init__
            if chain[0] in self.classes \
                    and "__init__" in self.classes[chain[0]].methods:
                return (chain[0], "__init__")
            return None
        owner = self.resolve_owner(cls, chain[:-1], local_types)
        if owner is None:
            return None
        info = self.classes.get(owner)
        if info is not None and chain[-1] in info.methods:
            return (owner, chain[-1])
        return None

    # -- pass 2: per-method scans -----------------------------------------
    def _scan_methods(self):
        for cname, info in self.classes.items():
            for mname, meth in info.methods.items():
                scan = _MethodScan(self, info, info.rel)
                scan.prime(meth)
                for stmt in meth.body:
                    scan.visit(stmt)
                self.scans[(cname, mname)] = scan

    # -- pass 3: transitive lock / device sets -----------------------------
    def _fixpoint(self):
        self.locks_star: Dict[Tuple[str, str], Set[str]] = {
            k: set(s.direct) for k, s in self.scans.items()}
        self.device_star: Dict[Tuple[str, str],
                               Optional[Tuple[str, int, str]]] = {}
        for k, s in self.scans.items():
            hit = next((d for d in s.device), None)
            self.device_star[k] = (hit[0], hit[2], s.rel) if hit else None
        changed = True
        while changed:
            changed = False
            for k, s in self.scans.items():
                for callee, _held, line in s.calls:
                    if callee not in self.scans:
                        continue
                    extra = self.locks_star[callee] - self.locks_star[k]
                    if extra:
                        self.locks_star[k] |= extra
                        changed = True
                    if (self.device_star[k] is None
                            and self.device_star[callee] is not None):
                        dev = self.device_star[callee]
                        self.device_star[k] = dev
                        changed = True

    # -- findings ----------------------------------------------------------
    def edges(self) -> Dict[Tuple[str, str], Tuple[str, int]]:
        """lock-order edges (A held -> B acquired) -> (rel, line)."""
        out: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for (cname, mname), scan in self.scans.items():
            for a, b, line in scan.edges:
                out.setdefault((a, b), (scan.rel, line))
            for callee, held, line in scan.calls:
                if callee not in self.scans or not held:
                    continue
                for b in self.locks_star[callee]:
                    for a in held:
                        if a != b:
                            out.setdefault((a, b), (scan.rel, line))
        return out


def _cycles(edges: Dict[Tuple[str, str], Tuple[str, int]]) \
        -> List[List[str]]:
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    seen_cycles = set()
    cycles = []

    def dfs(node, path, on_path):
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_path:
                cyc = path[path.index(nxt):] + [nxt]
                key = frozenset(cyc)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycles.append(cyc)
                continue
            dfs(nxt, path + [nxt], on_path | {nxt})

    for start in sorted(graph):
        dfs(start, [start], {start})
    return cycles


def check_sources(sources: Dict[str, str]) -> List[LintFinding]:
    """Run the lock-order analysis over {relpath: source}."""
    an = _Analyzer(sources)
    findings: List[LintFinding] = []
    edges = an.edges()

    for cyc in _cycles(edges):
        locs = "; ".join(
            f"{a}->{b} at {edges[(a, b)][0]}:{edges[(a, b)][1]}"
            for a, b in zip(cyc, cyc[1:]))
        rel, line = edges[(cyc[0], cyc[1])]
        findings.append(LintFinding(
            RULE, rel, line,
            f"lock-order cycle {' -> '.join(cyc)} (potential deadlock "
            f"across serving threads): {locs}"))

    for (cname, mname), scan in an.scans.items():
        for lock, line in scan.reacquires:
            findings.append(LintFinding(
                RULE, scan.rel, line,
                f"non-reentrant lock {lock} re-acquired while already "
                f"held in {cname}.{mname} (self-deadlock)"))
        # direct device work under a held lock
        for dev, held, line in scan.device:
            for lock in held:
                if "compile" in lock.lower():
                    continue
                findings.append(LintFinding(
                    RULE, scan.rel, line,
                    f"{dev} while holding {lock} in {cname}.{mname}: "
                    f"device transfers/compiles under a shared lock "
                    f"stall every sibling thread — move it outside the "
                    f"critical section or use a dedicated *compile* "
                    f"lock"))
        # calls that transitively reach device work or re-acquire a
        # held non-reentrant lock
        for callee, held, line in scan.calls:
            if not held or callee not in an.scans:
                continue
            for lock in held:
                if (lock in an.locks_star[callee]
                        and an.lock_kinds.get(lock)
                        not in _REENTRANT_CTORS):
                    findings.append(LintFinding(
                        RULE, scan.rel, line,
                        f"call to {callee[0]}.{callee[1]} re-acquires "
                        f"non-reentrant lock {lock} already held in "
                        f"{cname}.{mname} (self-deadlock)"))
            if an.device_star.get(callee) is None:
                continue
            dev, _dline, _drel = an.device_star[callee]
            for lock in held:
                if "compile" in lock.lower():
                    continue
                findings.append(LintFinding(
                    RULE, scan.rel, line,
                    f"call to {callee[0]}.{callee[1]} (which performs "
                    f"{dev}) while holding {lock} in {cname}.{mname}"))
    return findings


@register_rule(RULE,
               help_str="lock-acquisition cycles and locks held across "
                        "device_put/compile in paddle_tpu/serving "
                        "(suppress with '# lock-ok: <why>')",
               marker=LOCK_OK)
def rule(ctx: LintContext) -> List[LintFinding]:
    sources = {}
    for rel in ctx.iter_py(*SCAN):
        sources[rel] = ctx.source(rel)
    out = []
    for f in check_sources(sources):
        if not ctx.suppressed(f.path, f.line, RULE, LOCK_OK):
            out.append(f)
    return out
