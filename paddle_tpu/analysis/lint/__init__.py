"""tpulint: multi-rule AST lint framework (ISSUE 3, part 2).

Generalizes the single-purpose tools/check_hot_path_sync.py into a rule
registry: each rule is a pure text+AST check over a set of repo files,
producing `LintFinding`s with file:line provenance.  Rules ship in this
package (hot_path_sync, lock_order, side_effects) and register
themselves on import via `@register_rule`.

Design constraints:

* stdlib-only.  Rules parse source; they never import the modules they
  check, so the framework runs in any environment — including ones
  without jax.  `tools/tpulint.py` loads this package by file path
  (importlib) precisely so the CLI works without importing paddle_tpu.
* per-line suppression.  The generic marker is
  `# tpulint: disable=<rule>[,<rule>...]`; rules may additionally honor
  a domain marker (hot-path-sync keeps the historical `# sync-ok: <why>`,
  lock-order honors `# lock-ok: <why>`, side_effects
  `# side-effect-ok: <why>`).  A marker should always say WHY — it
  declares a reviewed, intentional exception, not a mute button
  (docs/static_analysis.md covers the etiquette).
* watchlist manifests.  Rules that check a closed set of functions
  (hot-path-sync) keep that set as module-level data (`WATCHLIST`) so
  tools and tests can extend or assert over it.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Callable, Dict, List, Optional

# this file lives at paddle_tpu/analysis/lint/__init__.py — four levels
# below the repo root
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

_SUPPRESS_RE = re.compile(r"#\s*tpulint:\s*disable=([\w,\-]+)")


class LintFinding:
    """One lint hit: rule + file:line + message."""

    __slots__ = ("rule", "path", "line", "message", "severity")

    def __init__(self, rule: str, path: str, line: int, message: str,
                 severity: str = "error"):
        self.rule = rule
        self.path = path
        self.line = int(line)
        self.message = message
        self.severity = severity

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    __repr__ = __str__


def suppressed(line_text: str, rule: str, marker: Optional[str] = None) \
        -> bool:
    """True when `line_text` carries a suppression for `rule` — the
    generic `# tpulint: disable=...` form or the rule's own marker."""
    if marker is not None and marker in line_text:
        return True
    m = _SUPPRESS_RE.search(line_text)
    if m is None:
        return False
    names = {n.strip() for n in m.group(1).split(",")}
    return rule in names or "all" in names


class LintContext:
    """Shared file/AST cache handed to every rule."""

    def __init__(self, root: Optional[str] = None):
        self.root = os.path.abspath(root or REPO_ROOT)
        self._src: Dict[str, str] = {}
        self._tree: Dict[str, ast.Module] = {}

    def exists(self, rel: str) -> bool:
        return os.path.isfile(os.path.join(self.root, rel))

    def source(self, rel: str) -> str:
        if rel not in self._src:
            with open(os.path.join(self.root, rel), encoding="utf-8") as f:
                self._src[rel] = f.read()
        return self._src[rel]

    def lines(self, rel: str) -> List[str]:
        return self.source(rel).splitlines()

    def tree(self, rel: str) -> ast.Module:
        if rel not in self._tree:
            self._tree[rel] = ast.parse(self.source(rel))
        return self._tree[rel]

    def iter_py(self, *subdirs: str) -> List[str]:
        """Sorted relpaths of every .py file under the given subdirs."""
        out = []
        for sub in subdirs:
            base = os.path.join(self.root, sub)
            if os.path.isfile(base) and base.endswith(".py"):
                out.append(os.path.relpath(base, self.root))
                continue
            for dirpath, _dirnames, filenames in os.walk(base):
                for fn in filenames:
                    if fn.endswith(".py"):
                        out.append(os.path.relpath(
                            os.path.join(dirpath, fn), self.root))
        return sorted(set(out))

    def suppressed(self, rel: str, lineno: int, rule: str,
                   marker: Optional[str] = None) -> bool:
        lines = self.lines(rel)
        if not (1 <= lineno <= len(lines)):
            return False
        return suppressed(lines[lineno - 1], rule, marker)


# ---------------------------------------------------------------------------
# Rule registry
# ---------------------------------------------------------------------------

_RULES: "Dict[str, dict]" = {}


def register_rule(name: str, help_str: str = "",
                  marker: Optional[str] = None):
    """Register `fn(ctx: LintContext) -> List[LintFinding]` as a rule."""

    def deco(fn: Callable):
        _RULES[name] = {"fn": fn, "help": help_str, "marker": marker}
        return fn

    return deco


def registered_rules() -> List[str]:
    return sorted(_RULES)


def rule_info(name: str) -> dict:
    return dict(_RULES[name])


def run_rules(root: Optional[str] = None,
              rules: Optional[List[str]] = None) -> List[LintFinding]:
    """Run the named rules (default: all) over the repo at `root`."""
    ctx = LintContext(root)
    findings: List[LintFinding] = []
    for name in (rules or registered_rules()):
        if name not in _RULES:
            raise ValueError(
                f"unknown lint rule {name!r}; known: {registered_rules()}")
        findings.extend(_RULES[name]["fn"](ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# rule modules register themselves on import
from . import hot_path_sync  # noqa: E402,F401
from . import lock_order  # noqa: E402,F401
from . import side_effects  # noqa: E402,F401
from . import span_leak  # noqa: E402,F401
