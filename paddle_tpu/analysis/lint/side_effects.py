"""Untraced-side-effect rule: Python mutation of `self`/globals inside
functions handed to `jax.jit` / `pjit` (ISSUE 3, part 2).

A jitted function's Python body runs ONCE, at trace time; after that
XLA replays the compiled computation and the Python statements never
execute again.  An assignment to `self.<attr>` or to a `global` name
inside such a function therefore happens exactly once per compile-cache
entry — a classic silent-staleness bug (a step counter that stops
counting, a debug flag that never updates, metrics that freeze after
warmup).  Closure-cell mutation is deliberately exempt: the executor
uses a closure box (`check_names_box[:] = names`) precisely as a
trace-time side channel, which is a sanctioned idiom.

Detection is purely syntactic over each module:

* jit targets: `jax.jit(f)` / `jit(f)` / `pjit(f)` call sites (with
  `functools.partial(f, ...)` unwrapped), and functions decorated with
  `@jax.jit` / `@jit` / `@pjit` / `@functools.partial(jax.jit, ...)`.
  A Name argument resolves to a `def` in the same module; a
  `self.<meth>` argument resolves to a method of a class in the same
  module.
* flagged constructs inside the target's body (nested defs included —
  they run at trace time too if called): assignment / augmented
  assignment to `self.<attr>` or `self.<attr>[...]`, and assignment to
  a name declared `global` in that function.

Suppress a line with `# side-effect-ok: <why>` or
`# tpulint: disable=untraced-side-effect`.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from . import LintContext, LintFinding, register_rule, suppressed

RULE = "untraced-side-effect"
SIDE_EFFECT_OK = "# side-effect-ok"

SCAN = ("paddle_tpu",)

_JIT_NAMES = {"jit", "pjit"}


def _is_jit_ref(node) -> bool:
    """True for `jit` / `pjit` / `jax.jit` / `x.pjit` references."""
    if isinstance(node, ast.Name):
        return node.id in _JIT_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr in _JIT_NAMES
    return False


def _unwrap_partial(node):
    """functools.partial(F, ...) -> F (recursively)."""
    while (isinstance(node, ast.Call)
           and isinstance(node.func, (ast.Name, ast.Attribute))
           and (node.func.id if isinstance(node.func, ast.Name)
                else node.func.attr) == "partial"
           and node.args):
        node = node.args[0]
    return node


def _jit_target(call: ast.Call):
    """The function expression handed to jit, or None."""
    if not _is_jit_ref(call.func) or not call.args:
        return None
    return _unwrap_partial(call.args[0])


def _decorated_jit(fn) -> bool:
    for dec in fn.decorator_list:
        if _is_jit_ref(dec):
            return True
        if isinstance(dec, ast.Call):
            if _is_jit_ref(dec.func):
                return True
            inner = _unwrap_partial(dec)
            if inner is not dec and _is_jit_ref(inner):
                return True
    return False


def _self_mutation_target(node) -> Optional[str]:
    """Attr name if `node` is self.<attr> or self.<attr>[...], else
    None."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _flag_body(fn, rel: str, owner: str) -> List[LintFinding]:
    findings = []
    global_names: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            global_names.update(node.names)
    for node in ast.walk(fn):
        targets = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for tgt in targets:
            subs = [tgt]
            if isinstance(tgt, (ast.Tuple, ast.List)):
                subs = list(tgt.elts)
            for t in subs:
                attr = _self_mutation_target(t)
                if attr is not None:
                    findings.append(LintFinding(
                        RULE, rel, getattr(node, "lineno", fn.lineno),
                        f"{owner} is handed to jax.jit but mutates "
                        f"self.{attr}: the write runs once at trace "
                        f"time, then never again — return the value "
                        f"or move the mutation outside the traced "
                        f"function"))
                elif (isinstance(t, ast.Name)
                      and t.id in global_names):
                    findings.append(LintFinding(
                        RULE, rel, getattr(node, "lineno", fn.lineno),
                        f"{owner} is handed to jax.jit but assigns "
                        f"global {t.id!r}: the write runs once at "
                        f"trace time, then never again"))
    return findings


def check_source(rel: str, source: str) -> List[LintFinding]:
    tree = ast.parse(source)
    # module-wide def/method tables for resolving jit(F) references
    defs_by_name: Dict[str, ast.FunctionDef] = {}
    methods_by_name: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, node)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    methods_by_name.setdefault(item.name, item)

    findings: List[LintFinding] = []
    seen: Set[int] = set()

    def flag(fn, owner):
        if id(fn) in seen:
            return
        seen.add(id(fn))
        findings.extend(_flag_body(fn, rel, owner))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and _decorated_jit(node):
            flag(node, f"{node.name}()")
        if not isinstance(node, ast.Call):
            continue
        target = _jit_target(node)
        if target is None:
            continue
        if isinstance(target, ast.Lambda):
            # lambdas cannot contain assignments; nothing to flag
            continue
        if isinstance(target, ast.Name):
            fn = defs_by_name.get(target.id)
            if fn is not None:
                flag(fn, f"{target.id}()")
        elif (isinstance(target, ast.Attribute)
              and isinstance(target.value, ast.Name)
              and target.value.id == "self"):
            fn = methods_by_name.get(target.attr)
            if fn is not None:
                flag(fn, f"self.{target.attr}()")
    return findings


def check_sources(sources: Dict[str, str]) -> List[LintFinding]:
    out: List[LintFinding] = []
    for rel, src in sorted(sources.items()):
        out.extend(check_source(rel, src))
    return out


@register_rule(RULE,
               help_str="self/global mutation inside functions handed "
                        "to jax.jit/pjit (runs once at trace time; "
                        "suppress with '# side-effect-ok: <why>')",
               marker=SIDE_EFFECT_OK)
def rule(ctx: LintContext) -> List[LintFinding]:
    out = []
    for rel in ctx.iter_py(*SCAN):
        try:
            src = ctx.source(rel)
        except (OSError, UnicodeDecodeError):
            continue
        if "jit" not in src and "pjit" not in src:
            continue
        for f in check_source(rel, src):
            if not ctx.suppressed(f.path, f.line, RULE, SIDE_EFFECT_OK):
                out.append(f)
    return out
