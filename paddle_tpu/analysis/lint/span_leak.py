"""span-leak rule (ISSUE 6 satellite): `obs.span(...)` must be closed.

A span begun without a guaranteed close corrupts nothing (the tracer
pops leaked children when the enclosing span exits) but silently loses
the interval it was supposed to measure — and on the serving/executor
hot paths a leak means the one trace the ROADMAP perf items depend on
lies about where time went.  The rule enforces the two closed shapes:

* `with obs.span(...):` / `with obs.span(...) as s:` — the context
  manager is the canonical form; `__exit__` records even when the body
  raises.
* `return obs.span(...)` — delegation (a factory handing the span to
  its caller, e.g. `obs.span()` itself wrapping `TRACER.span()`); the
  CALLER is then in rule scope and must use a `with`.

Anything else — `s = obs.span(...)` then manual `__enter__`, a span
passed as an argument, a bare expression statement — is flagged.
Retroactive recording (`obs.add_span`) needs no closure and is the
escape hatch for call sites that only know a span existed after the
fact.  Suppress a reviewed exception with `# span-ok: <why>` or the
generic `# tpulint: disable=span-leak`.

Watched modules: the obs package itself plus every subsystem the
tentpole instrumented — the shipped tree must stay clean
(tests/test_obs.py asserts it).
"""

from __future__ import annotations

import ast
import os
from typing import List

from . import LintContext, LintFinding, register_rule

RULE = "span-leak"
MARKER = "# span-ok"

# files/dirs whose span() call sites the rule enforces
WATCHED = [
    "paddle_tpu/obs",
    "paddle_tpu/obs/telemetry.py",  # explicit: the live-telemetry layer
    # stays covered even if the obs dir entry is ever narrowed
    "paddle_tpu/obs/devprof.py",  # explicit: same reasoning for the
    # measured device-time profiler (ISSUE 12)
    "paddle_tpu/obs/memprof.py",  # explicit: same reasoning for the
    # HBM memory ledger (ISSUE 14)
    "paddle_tpu/obs/numerics.py",  # explicit: same reasoning for the
    # numeric-health layer (ISSUE 15)
    "paddle_tpu/ckpt",
    "paddle_tpu/profiler",
    "paddle_tpu/fluid/executor.py",
    "paddle_tpu/parallel/compiler.py",
    "paddle_tpu/parallel/quant_collectives.py",  # explicit: the int8
    # codec traces inside the jitted step (ISSUE 16) — span misuse
    # there would wrap device-side code in host timers
    "paddle_tpu/dataset/feed_pipeline.py",
    "paddle_tpu/fluid/aot_cache.py",  # explicit: the persistent AOT
    # cache times its own load/store (ISSUE 17) — a leaked span there
    # would misattribute disk I/O to whichever compile wrapped it
    "paddle_tpu/serving",  # covers registry.py (multi-tenant fleet)
    "paddle_tpu/ops/pallas/attention.py",  # explicit: the ragged
    # paged-attention dispatch seam (ISSUE 20) traces inside the
    # decode jit — a leaked span there would wrap device-side kernel
    # work in a host timer on every decoded token
    "paddle_tpu/tune",  # autotuner (ISSUE 19): search/trial spans wrap
    # measured executor dispatches — a leaked span would fold a whole
    # search into whatever profile runs next
    "paddle_tpu/transforms/__init__.py",
    "paddle_tpu/analysis/verifier.py",
    "bench.py",
]


def _is_span_call(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id in ("span", "obs_span")
    if isinstance(fn, ast.Attribute):
        return fn.attr == "span"
    return False


def _closed_call_ids(tree: ast.Module) -> set:
    """ids of span() Call nodes in a sanctioned position: a with-item
    context expression, or the value of a return (delegation)."""
    ok = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    ok.add(id(item.context_expr))
        elif isinstance(node, ast.Return) and isinstance(node.value,
                                                         ast.Call):
            ok.add(id(node.value))
    return ok


def check_source(rel: str, ctx: LintContext) -> List[LintFinding]:
    tree = ctx.tree(rel)
    closed = _closed_call_ids(tree)
    findings = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_span_call(node)):
            continue
        if id(node) in closed:
            continue
        if ctx.suppressed(rel, node.lineno, RULE, MARKER):
            continue
        findings.append(LintFinding(
            RULE, rel, node.lineno,
            "span begun outside a `with` (or `return` delegation): the "
            "interval is lost if this path raises — use "
            "`with obs.span(...):`, record retroactively with "
            f"obs.add_span, or mark a reviewed exception "
            f"'{MARKER}: <why>'"))
    return findings


@register_rule(RULE,
               help_str="obs.span(...) begun without context-manager/"
                        "return closure in the instrumented modules "
                        f"(suppress with '{MARKER}: <why>')",
               marker=MARKER)
def rule(ctx: LintContext) -> List[LintFinding]:
    findings = []
    for target in WATCHED:
        full = os.path.join(ctx.root, target)
        if not os.path.exists(full):
            findings.append(LintFinding(
                RULE, target, 0, "watched path missing — update "
                                 "span_leak.WATCHED if it moved"))
            continue
        for rel in ctx.iter_py(target):
            findings.extend(check_source(rel, ctx))
    return findings
