"""Hot-path sync rule (migrated unchanged from tools/check_hot_path_sync.py,
which is now a thin shim over this module).

The async hot path's contract is that `Executor.run(...,
return_numpy=False)`, the dataset/dataloader step loops, and the serving
dispatch loop perform ZERO device->host transfers per step; every
materialization must happen at a sanctioned sync point.  This rule walks
the functions that form those loops and flags `np.asarray` / `np.array`
/ `block_until_ready` / `.numpy()` / `device_get` calls on lines NOT
annotated with a `# sync-ok` marker (the marker declares a sanctioned
sync point and should say why, e.g. `# sync-ok: print_period boundary`).

Pure text+AST: no imports of the checked modules, so it runs in any
environment.  Wired into tier-1 via tests/test_async_executor.py and
tests/test_serving.py, and standalone via
`python tools/check_hot_path_sync.py` or `python tools/tpulint.py`.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Tuple

from . import (LintContext, LintFinding, REPO_ROOT, register_rule,
               suppressed)

RULE = "hot-path-sync"

# (relative file, dotted qualname) pairs forming the executor hot path —
# the rule's watchlist manifest.  A qualname that no longer resolves is
# itself an error — the lint must not silently stop covering a renamed
# loop.
WATCHLIST: List[Tuple[str, str]] = [
    ("paddle_tpu/fluid/executor.py", "Executor.run"),
    ("paddle_tpu/fluid/executor.py", "Executor._dispatch"),
    # SPMD state seat (ISSUE 13): runs at the top of EVERY dispatch —
    # re-seating host arrays under their NamedSharding must stay an
    # async device_put, never a transfer
    ("paddle_tpu/fluid/executor.py", "Executor._seat_state"),
    ("paddle_tpu/fluid/executor.py", "Executor._finish"),
    ("paddle_tpu/fluid/executor.py", "Executor._const_state"),
    ("paddle_tpu/fluid/executor.py", "Executor._normalize_feed_inner"),
    ("paddle_tpu/fluid/executor.py", "Executor._feed_cached_put"),
    ("paddle_tpu/fluid/executor.py", "Executor.train_from_dataset"),
    ("paddle_tpu/fluid/executor.py", "_FeedPrefetcher"),
    ("paddle_tpu/fluid/executor.py", "LazyFetch.numpy"),
    # pod-scale feed pipeline (ISSUE 4): the per-host sharded producer
    # and the device ring ARE the feed hot path — staging must stay
    # async (device_put only); materialization belongs to the consumer
    # at sanctioned boundaries
    ("paddle_tpu/dataset/feed_pipeline.py", "FeedPipeline.__iter__"),
    ("paddle_tpu/dataset/feed_pipeline.py", "FeedPipeline._produce"),
    # SPMD batch placement (ISSUE 13): runs inside _produce for every
    # staged batch — placement under NamedSharding(P("data",…)) is an
    # async device op, not a transfer
    ("paddle_tpu/dataset/feed_pipeline.py", "FeedPipeline._place_sharded"),
    ("paddle_tpu/dataset/feed_pipeline.py", "DeviceRing.put"),
    ("paddle_tpu/dataset/feed_pipeline.py", "DeviceRing.get"),
    ("paddle_tpu/parallel/compiler.py", "CompiledProgram._run"),
    # quantized collectives (ISSUE 16): the codec entry points trace
    # INSIDE the jitted step — a host sync or numpy materialization
    # here would stall every quantized gradient reduction
    ("paddle_tpu/parallel/quant_collectives.py", "pack"),
    ("paddle_tpu/parallel/quant_collectives.py", "quantize_blockwise"),
    ("paddle_tpu/parallel/quant_collectives.py", "dequantize_blockwise"),
    ("paddle_tpu/parallel/quant_collectives.py", "quant_allreduce_sum"),
    # graph-transform pipeline (ISSUE 5): runs ONLY on the compile-
    # cache-miss path and manipulates Program metadata — it must never
    # touch device arrays, so the zero-sync contract applies verbatim
    ("paddle_tpu/transforms/__init__.py", "maybe_transform_program"),
    ("paddle_tpu/transforms/__init__.py", "apply_transforms"),
    ("paddle_tpu/io/__init__.py", "DataLoader.__iter__"),
    # serving dispatch loop (ISSUE 2): the engine's hot path has the
    # same zero-transfer contract — the completer/retire boundaries are
    # the only sanctioned device->host materializations
    ("paddle_tpu/serving/engine.py", "Engine._dispatch_loop"),
    ("paddle_tpu/serving/engine.py", "Engine._dispatch_batch"),
    ("paddle_tpu/serving/engine.py", "Engine._completer_loop"),
    ("paddle_tpu/serving/engine.py", "AutoregressiveEngine._admit"),
    ("paddle_tpu/serving/engine.py", "AutoregressiveEngine._decode"),
    ("paddle_tpu/serving/engine.py", "AutoregressiveEngine._retire"),
    # fast decode (ISSUE 20): the chunk scheduler and the lazy-growth /
    # extend-backpressure path run every engine step between decode
    # dispatches — host-side bookkeeping plus async device calls only;
    # the ragged-kernel dispatch seam traces INSIDE the decode jit, so
    # a sync there would stall every decoded token
    ("paddle_tpu/serving/engine.py",
     "AutoregressiveEngine._prefill_tick"),
    ("paddle_tpu/serving/engine.py",
     "AutoregressiveEngine._ensure_pages"),
    ("paddle_tpu/serving/engine.py", "AutoregressiveEngine._grow_to"),
    ("paddle_tpu/ops/pallas/attention.py", "paged_attention"),
    ("paddle_tpu/serving/batcher.py", "DynamicBatcher.next_batch"),
    # multi-tenant fleet (ISSUE 17): admission (submit -> quota check)
    # and the registry request surface run on CLIENT threads racing the
    # dispatch loop; the registry's cache-eviction accounting runs
    # inside the compiler thread's put() — all of it is host-side
    # bookkeeping, never a device materialization
    ("paddle_tpu/serving/batcher.py", "DynamicBatcher.submit"),
    ("paddle_tpu/serving/batcher.py", "DynamicBatcher._pop_best"),
    ("paddle_tpu/serving/registry.py", "ModelRegistry.submit"),
    ("paddle_tpu/serving/registry.py", "_TenantCache.put"),
    ("paddle_tpu/serving/registry.py", "_TenantCache._evicted"),
    ("paddle_tpu/serving/bucketing.py", "BucketedRunner.run"),
    # persistent AOT cache (ISSUE 17): load/store run on compile-miss
    # paths (executor first dispatch, serving compiler thread) — disk
    # I/O is their job, but they handle DEVICE executables and must
    # never materialize arrays or block on the device
    ("paddle_tpu/fluid/aot_cache.py", "try_load"),
    ("paddle_tpu/fluid/aot_cache.py", "try_store"),
    ("paddle_tpu/fluid/aot_cache.py", "compile_entry_with_cache"),
    # autotuner (ISSUE 19): trials dispatch through the REAL executor
    # hot path — the only sanctioned sync is the per-trial
    # block_until_ready in tuner._sync ('# sync-ok: trial measurement
    # boundary'); the record store/load path is compile-miss disk I/O
    # with the same never-touch-device contract as the AOT cache
    ("paddle_tpu/tune/tuner.py", "_sync"),
    ("paddle_tpu/tune/tuner.py", "_measure_program"),
    ("paddle_tpu/tune/tuner.py", "search_program"),
    ("paddle_tpu/tune/record.py", "try_load"),
    ("paddle_tpu/tune/record.py", "try_store"),
    ("paddle_tpu/inference/c_bridge.py", "run_f32"),
    # obs span/cost layer (ISSUE 6): these run INSIDE every watched loop
    # above — a sync creeping into the tracer or the live-MFU gauge
    # would hide in every profile it produces
    # checkpoint writer entry points (ISSUE 8): save_async/_snapshot
    # run ON the training thread at step boundaries — the only stall
    # they may add is the device-side snapshot copy and bounded
    # backpressure; the device->host transfer belongs to the writer
    # thread (WriterPool._loop / CheckpointManager._write_job)
    ("paddle_tpu/ckpt/manager.py", "CheckpointManager.save_async"),
    ("paddle_tpu/ckpt/manager.py", "CheckpointManager._snapshot"),
    ("paddle_tpu/ckpt/writer.py", "WriterPool.submit"),
    ("paddle_tpu/obs/tracing.py", "Tracer.span"),
    ("paddle_tpu/obs/tracing.py", "Tracer.add_span"),
    ("paddle_tpu/obs/tracing.py", "Tracer._record"),
    ("paddle_tpu/obs/tracing.py", "Span.__exit__"),
    ("paddle_tpu/obs/cost.py", "ProgramCost.observe_dispatch"),
    # live telemetry (ISSUE 10): the sampler thread, the watchdog
    # evaluator and the HTTP handler all run CONCURRENTLY with every
    # watched loop above — they read host-side ring buffers and counter
    # tables only; a sync here would stall training/serving from the
    # monitoring plane
    ("paddle_tpu/obs/telemetry.py", "Collector.sample_once"),
    ("paddle_tpu/obs/telemetry.py", "Collector._loop"),
    ("paddle_tpu/obs/telemetry.py", "Watchdog.evaluate"),
    ("paddle_tpu/obs/telemetry.py", "Watchdog.observe"),
    ("paddle_tpu/obs/telemetry.py", "_Handler.do_GET"),
    # measured device-time profiling (ISSUE 12): note_dispatch and the
    # autostop check run INSIDE the dispatch/step loop; window
    # start/finish and the xplane parse run at window boundaries but on
    # the training thread — capture must never smuggle a sync into the
    # hot path it is measuring
    ("paddle_tpu/obs/devprof.py", "note_dispatch"),
    ("paddle_tpu/obs/devprof.py", "maybe_autostop"),
    ("paddle_tpu/obs/devprof.py", "DevprofWindow.start"),
    ("paddle_tpu/obs/devprof.py", "DevprofWindow.finish"),
    ("paddle_tpu/obs/devprof.py", "parse_xplane_bytes"),
    # HBM memory observability (ISSUE 14): set/add run on the dispatch /
    # ring / ckpt hot paths; ledger_gauges runs on the telemetry
    # sampler thread and oom_report on the dispatch except-path — all
    # must stay host-registry reads, never device materializations
    ("paddle_tpu/obs/memprof.py", "set_entry"),
    ("paddle_tpu/obs/memprof.py", "add_entry"),
    ("paddle_tpu/obs/memprof.py", "ledger_gauges"),
    ("paddle_tpu/obs/memprof.py", "oom_report"),
    # numeric-health observability (ISSUE 15): note_dispatch_stats /
    # note_loss_scale run ON the dispatch hot path (bounded host deque
    # appends of device references — never a transfer); drain /
    # health_gauges run on the telemetry sampler thread where the
    # LazyFetch-style materialization is the sanctioned boundary;
    # bisect_nonfinite is offline forensics whose materializations ARE
    # the point — all marked sync-ok where they materialize
    ("paddle_tpu/obs/numerics.py", "note_dispatch_stats"),
    ("paddle_tpu/obs/numerics.py", "note_loss_scale"),
    ("paddle_tpu/obs/numerics.py", "drain"),
    ("paddle_tpu/obs/numerics.py", "health_gauges"),
    ("paddle_tpu/obs/numerics.py", "bisect_nonfinite"),
    # static sharding analyzer (ISSUE 18): the shard-consistency pass
    # runs on the compile path (once per cache miss) and comm_report /
    # the checker walk are pure host-side graph interpretation — a
    # device materialization here would charge every compile a sync
    ("paddle_tpu/analysis/shard_check.py", "shard_consistency_pass"),
    ("paddle_tpu/analysis/shard_check.py", "_ShardChecker.run"),
    ("paddle_tpu/analysis/shard_check.py", "comm_report"),
    ("paddle_tpu/analysis/shard_check.py", "feasibility"),
]

# blocking / transferring constructs that must not appear unsanctioned
FORBIDDEN = [
    re.compile(r"\bnp\.asarray\s*\("),
    re.compile(r"\bnp\.array\s*\("),
    re.compile(r"\bnumpy\.asarray\s*\("),
    re.compile(r"block_until_ready\s*\("),
    re.compile(r"\bdevice_get\s*\("),
    re.compile(r"\.numpy\s*\(\s*\)"),
    re.compile(r"\bjax\.device_get\b"),
]

SYNC_OK = "# sync-ok"


def _function_spans(tree: ast.Module) -> Dict[str, Tuple[int, int]]:
    """qualname -> (first_line, last_line) for every def/class."""
    spans: Dict[str, Tuple[int, int]] = {}

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qual = f"{prefix}{child.name}"
                spans[qual] = (child.lineno, child.end_lineno)
                visit(child, qual + ".")
            else:
                visit(child, prefix)

    visit(tree, "")
    return spans


def _violations(path: str, qualnames: List[str],
                root: Optional[str] = None) \
        -> List[Tuple[str, int, str]]:
    """(relpath, line, message) triples for one file's watched spans."""
    root = root or REPO_ROOT
    with open(path) as f:
        source = f.read()
    lines = source.splitlines()
    spans = _function_spans(ast.parse(source))
    rel = os.path.relpath(path, root)
    out = []
    for qual in qualnames:
        if qual not in spans:
            out.append((rel, 0,
                        f"hot-path function {qual!r} not found — update "
                        f"the WATCHLIST "
                        f"(paddle_tpu/analysis/lint/hot_path_sync.py) "
                        f"if it moved"))
            continue
        lo, hi = spans[qual]
        for i in range(lo, hi + 1):
            line = lines[i - 1]
            if suppressed(line, RULE, SYNC_OK):
                continue
            for pat in FORBIDDEN:
                if pat.search(line):
                    out.append((rel, i,
                                f"unsanctioned sync in {qual}: "
                                f"{line.strip()!r} (add "
                                f"'{SYNC_OK}: <why>' only if this is a "
                                f"designed sync boundary)"))
    return out


def check_file(path: str, qualnames: List[str],
               root: Optional[str] = None) -> List[str]:
    """Historical string API (kept for the tools/ shim and tier-1
    hooks): one formatted message per violation."""
    out = []
    for rel, line, msg in _violations(path, qualnames, root):
        out.append(f"{rel}:{line}: {msg}" if line else f"{rel}: {msg}")
    return out


def check_repo(root: Optional[str] = None) -> List[str]:
    root = root or REPO_ROOT
    by_file: Dict[str, List[str]] = {}
    for rel, qual in WATCHLIST:
        by_file.setdefault(rel, []).append(qual)
    violations = []
    for rel, quals in by_file.items():
        violations.extend(check_file(os.path.join(root, rel), quals,
                                     root))
    return violations


@register_rule(RULE,
               help_str="blocking device->host constructs in the async "
                        "executor / serving hot path (watchlist in "
                        "hot_path_sync.WATCHLIST; suppress with "
                        "'# sync-ok: <why>')",
               marker=SYNC_OK)
def rule(ctx: LintContext) -> List[LintFinding]:
    by_file: Dict[str, List[str]] = {}
    for rel, qual in WATCHLIST:
        by_file.setdefault(rel, []).append(qual)
    findings = []
    for rel, quals in sorted(by_file.items()):
        path = os.path.join(ctx.root, rel)
        if not os.path.isfile(path):
            findings.append(LintFinding(
                RULE, rel, 0, "watched file missing — update the "
                              "WATCHLIST if it moved"))
            continue
        for vrel, line, msg in _violations(path, quals, ctx.root):
            findings.append(LintFinding(RULE, vrel, line, msg))
    return findings
