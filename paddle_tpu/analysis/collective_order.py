"""Cross-program collective-order verification (ISSUE 11, pass 2).

The per-program `collective-order` pass (verifier.py) proves one
program issues ring collectives in a replica-uniform order.  It cannot
see ACROSS programs: a train step and its eval clone run on the same
mesh and the same rings, and if host A is in the train step while
host B is already in eval (or the two programs simply interleave
collectives differently after a transform rewrote one of them), the
ring pairing deadlocks or silently mixes tensors.  TensorFlow's
placement-time graph checks (arxiv 1605.08695) catch this class before
launch; we do the same at the compile-cache-miss seam.

Mechanism: a process-wide **ring registry**.  Every time
`Executor._prepare` / `CompiledProgram._compile` verifies a program
(once per compile-cache miss, via `maybe_verify_program`), this pass

1. computes the program's **collective signature** — the issue-order
   sequence of `(ring_id, op_type)` over every block, p2p send/recv
   excluded (the pairing queue owns those);
2. diffs it against the signatures of other programs in the same
   **clone family** (`Program.clone_root` — a program and its
   `clone()`s, i.e. exactly the train-step/eval-clone pairs that share
   a mesh; unrelated programs that merely default to ring 0 are not
   compared, so independent models in one process stay independent);
3. errors on an **interleave mismatch**: after projecting both
   signatures onto their shared rings, the shorter must be an ordered
   subsequence of the longer (an eval clone that pruned its backward
   collectives is fine; a reordering is not).

Only programs that verify clean are recorded, so one bad rewrite does
not poison every later comparison.  The registry is bounded and
resettable (`reset_ring_registry`, used by tests and program zoo
sweeps).

Stdlib-only at module scope — loadable by tools/shapecheck.py without
jax, like shape_check.py.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .verifier import (ERROR, Finding, VerifyContext, _P2P,
                       _is_collective, register_pass)

# one signature entry: (ring_id, op_type, block_idx, op_id)
SigEntry = Tuple[int, str, int, int]

# clone_root -> {prog_id: (version, signature)}; only clean programs
_RING_REGISTRY: Dict[int, Dict[int, Tuple[int, List[SigEntry]]]] = {}

_MAX_FAMILIES = 256  # long-running multi-tenant process backstop


def collective_signature(program) -> List[SigEntry]:
    """The issue-order ring-collective sequence over every block,
    sub-blocks inlined at their owner op's position (that IS the issue
    order under the lowering), p2p ops excluded."""
    sig: List[SigEntry] = []

    def walk(blk, visited):
        for op in blk.ops:
            if _is_collective(op.type) and op.type not in _P2P:
                ring = op.attr("ring_id", 0)
                sig.append((int(ring or 0), op.type, blk.idx, op.id))
            sb = op.attr("sub_block")
            if isinstance(sb, int) and 0 < sb < len(program.blocks) \
                    and sb not in visited:
                walk(program.blocks[sb], visited | {sb})

    if getattr(program, "blocks", None):
        walk(program.blocks[0], {0})
    return sig


def _project(sig: List[SigEntry], rings) -> List[SigEntry]:
    return [e for e in sig if e[0] in rings]


def _embed_mismatch(short: List[SigEntry],
                    long: List[SigEntry]) -> Optional[int]:
    """Greedy subsequence embedding of `short` into `long`; returns the
    index of the first `short` entry that cannot be matched in order,
    or None when `short` embeds completely."""
    j = 0
    for i, e in enumerate(short):
        key = (e[0], e[1])
        while j < len(long) and (long[j][0], long[j][1]) != key:
            j += 1
        if j >= len(long):
            return i
        j += 1
    return None


def _diff_signatures(cur: List[SigEntry], other: List[SigEntry]):
    """Interleave-compatibility of two signatures over their shared
    rings.  Returns None when compatible, else
    (mismatch_entry_in_cur, cur_proj, other_proj)."""
    shared = {e[0] for e in cur} & {e[0] for e in other}
    if not shared:
        return None
    pc, po = _project(cur, shared), _project(other, shared)
    if len(pc) <= len(po):
        i = _embed_mismatch(pc, po)
        if i is None:
            return None
        return pc[i], pc, po
    i = _embed_mismatch(po, pc)
    if i is None:
        return None
    # `other` (the shorter) fails to embed into the current program:
    # anchor provenance on the current op where matching got stuck —
    # the first current entry the other sequence's unmatched op
    # should have aligned with
    key = (po[i][0], po[i][1])
    for e in pc:
        if (e[0], e[1]) == key:
            return e, pc, po
    return pc[-1] if pc else po[i], pc, po


def _fmt(sig: List[SigEntry], limit: int = 8) -> str:
    s = ", ".join(f"{t}@ring{r}" for r, t, _b, _o in sig[:limit])
    if len(sig) > limit:
        s += f", ... ({len(sig)} total)"
    return s or "<empty>"


def _op_by_id(program, block_idx: int, op_id: int):
    try:
        for op in program.blocks[block_idx].ops:
            if op.id == op_id:
                return op
    except Exception:  # noqa: BLE001 - provenance lookup must not raise
        pass
    return None


@register_pass("cross-program-collective-order")
def cross_program_collective_order(ctx: VerifyContext) -> List[Finding]:
    """ERROR-tier pass: diff this program's collective signature against
    every previously-verified program in its clone family."""
    prog = ctx.program
    family = getattr(prog, "clone_root", None)
    if family is None:
        return []
    sig = collective_signature(prog)
    if not sig:
        return []  # no collectives: trivially compatible, not recorded
    prog_id = getattr(prog, "prog_id", id(prog))
    version = getattr(prog, "version", 0)

    findings: List[Finding] = []
    fam = _RING_REGISTRY.get(family, {})
    for other_id, (other_ver, other_sig) in fam.items():
        if other_id == prog_id:
            continue
        diff = _diff_signatures(sig, other_sig)
        if diff is None:
            continue
        entry, pc, po = diff
        ring, op_type, block_idx, op_id = entry
        op = _op_by_id(prog, block_idx, op_id)
        findings.append(ctx.finding(
            ERROR, "cross-program-collective-order",
            f"collective issue order diverges from program#{other_id} "
            f"(v{other_ver}, same clone family — e.g. a train step vs "
            f"its eval clone on one mesh): this program issues "
            f"[{_fmt(pc)}] where the other issues [{_fmt(po)}] on the "
            f"shared ring(s); replicas running different programs "
            f"would pair mismatched collectives and deadlock — make "
            f"the shorter sequence an ordered subsequence of the "
            f"longer", op=op,
            var=f"ring{ring}" if op is None else None))
        break  # one diff per verify call is enough signal

    if not findings:
        if len(_RING_REGISTRY) >= _MAX_FAMILIES \
                and family not in _RING_REGISTRY:
            _RING_REGISTRY.clear()
        _RING_REGISTRY.setdefault(family, {})[prog_id] = (version, sig)
    return findings


def ring_registry_snapshot() -> Dict[int, Dict[int, Tuple[int, list]]]:
    """Debug/tooling view of the recorded signatures."""
    return {fam: dict(progs) for fam, progs in _RING_REGISTRY.items()}


def reset_ring_registry() -> None:
    """Forget all recorded signatures (tests, program-zoo sweeps)."""
    _RING_REGISTRY.clear()
