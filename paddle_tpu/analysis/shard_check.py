"""Static sharding analyzer: PartitionSpec propagation, predicted
collective cost, and re-shard feasibility prechecks (ISSUE 18
tentpole).

Today the only sharding feedback is `collective_bytes_spmd_*` counters
AFTER first dispatch, and `spec_layout._fit` silently clamps misfit
specs to replicated.  This module runs GSPMD-style spec propagation
(arXiv 2105.04663) as an abstract interpreter over the FINAL
(post-transform) Program under a plain `{axis: size}` mesh dict —
the shape_check.py idiom, riding the same `_Env` block chaining,
`while`-body widening, and `infer_op_outputs` shape replay:

* every var carries `(shape, dtype, entries)` where `entries` is a
  `spec_rules` tuple (`None | axis | (axes,)` per dim; `None` for the
  whole triple slot = unknown layout);
* params/optimizer state seed from the `parallel/spec_rules` registry
  resolution (the same table `spec_layout.spec_for` applies at
  compile), feeds from the `mesh.batch_spec` twin;
* op rules: elementwise preserve, broadcast-aware meet, matmul/conv
  contract-dim handling, reshape factor-group carry, transpose
  permute, collectives per their declared semantics, `@GRAD`
  mirroring at the first strip, loop-carried widening to replicated;
* a layout conflict never fails propagation — the meet resolves it
  and *records the resharding event* XLA SPMD would insert.

Three consumers:

1. the `shard-consistency` verifier pass (ERROR tier, once per
   compile-cache miss when a mesh is current): ERRORs for
   axis-used-twice-in-one-spec, sharded-dim-not-divisible after
   propagation, and collectives whose ring axis is not on the mesh;
   WARNINGs for large tensors forced replicated (byte floor
   `PADDLE_SHARDCHECK_REPLICATED_FLOOR`, default 1 MiB), every
   explicit-spec clamp, and every predicted resharding event — all
   with `program#<id> block<idx> op<id>` provenance;
2. `comm_report(program, mesh_axes)`: static per-collective predicted
   wire bytes, quant-collectives-aware (`signature_token()`), which
   bench.py stamps as `detail.sharding.predicted_collective_bytes`
   and tests hold within ±25% of measured `collective_bytes_spmd_*`;
3. `feasibility(program, old_mesh, new_mesh)`: the elastic-resharding
   precheck — re-solves the spec registry over a candidate mesh and
   reports fits/clamps/bytes-per-device delta without compiling.

Module scope imports ONLY the stdlib (spec_rules/quant config load
lazily, with a by-path fallback), so `tools/shardcheck.py` can load it
on a box without jax — the tpulint loading idiom.
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Dict, List, Optional, Set, Tuple

from .shape_check import (OPAQUE_OPS, ProgramView, ShapeInferBail,
                          _Env, canon_dtype, infer_op_outputs)
from .verifier import (ERROR, WARNING, Finding, VerifyContext,
                       register_pass)

_EMPTY = "@EMPTY@"  # framework.EMPTY_VAR_NAME (kept import-free)
_GRAD_SUFFIX = "@GRAD"

logger = logging.getLogger("paddle_tpu.shard_check")

_MAX_FINDINGS = 25  # per program: one bad spec cascades; cap the noise

_LOOP_OWNERS = {"while"}

# canonical dtype -> bytes per element (x32 policy: 64-bit already
# narrowed by canon_dtype)
_DTYPE_SIZE = {
    "float32": 4, "int32": 4, "uint32": 4, "complex64": 8,
    "float16": 2, "bfloat16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1,
}

# comm bootstrap / sync ops: no payload, exempt from the ring-axis check
_COMM_NOOPS = {
    "c_comm_init", "c_comm_init_all", "c_gen_nccl_id",
    "c_wait_calc_stream", "c_wait_comm_stream", "c_sync_calc_stream",
    "c_sync_comm_stream", "barrier",
}

_MATMUL_OPS = {"mul", "matmul", "matmul_v2"}
_EMBEDDING_OPS = {"lookup_table", "lookup_table_v2"}
_RESHAPE_OPS = {"reshape", "reshape2"}
_TRANSPOSE_OPS = {"transpose", "transpose2"}

# ops that materialize fresh (host-fed constants / RNG) values: outputs
# are replicated until something reshards them
_FRESH_REPLICATED_OPS = {
    "fill_constant", "fill_zeros_like", "gaussian_random",
    "uniform_random", "truncated_gaussian_random", "range",
    "assign_value", "eye", "one_hot", "one_hot_v2",
}

_BLOCK = 256  # quant_collectives.BLOCK twin (stdlib-only)


# ---------------------------------------------------------------------------
# Lazy config: spec registry rules + quant-collectives signature
# ---------------------------------------------------------------------------

_SPEC_RULES = None


def _spec_rules():
    """parallel.spec_rules, tolerant of the by-path package load that
    tools/shardcheck.py uses (where relative imports cannot escape the
    loaded `analysis` package)."""
    global _SPEC_RULES
    if _SPEC_RULES is not None:
        return _SPEC_RULES
    try:
        from ..parallel import spec_rules as sr
        _SPEC_RULES = sr
        return sr
    except Exception:  # noqa: BLE001 - standalone by-path load
        pass
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "parallel", "spec_rules.py")
    spec = importlib.util.spec_from_file_location(
        "paddle_tpu_spec_rules", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    _SPEC_RULES = mod
    return mod


def _registered_overrides() -> Dict[str, tuple]:
    """`register_spec` overrides as plain entry tuples; empty when
    spec_layout (jax) is unavailable (the CLI path)."""
    try:
        from ..parallel import spec_layout
        return {k: tuple(v) for k, v in
                spec_layout.registered_specs().items()}
    except Exception:  # noqa: BLE001 - jax-free load
        return {}


def quant_config() -> Tuple[Optional[str], int, Optional[str]]:
    """(mode, min_bytes, signature_token) for the quantized-collective
    wire model, via parallel.quant_collectives when importable, else
    the env twin (same defaults)."""
    try:
        from ..parallel import quant_collectives as qc
        return qc.mode(), qc.min_bytes(), qc.signature_token()
    except Exception:  # noqa: BLE001 - standalone by-path load
        mode = os.environ.get("PADDLE_QUANT_COLLECTIVES", "").strip().lower()
        mode = mode if mode in ("int8",) else None
        try:
            floor = int(os.environ.get(
                "PADDLE_QUANT_COLLECTIVES_MIN_BYTES", "1024"))
        except ValueError:
            floor = 1024
        token = f"quant_collectives={mode},min={floor}" if mode else None
        return mode, floor, token


def replicated_floor() -> int:
    """Byte floor above which a fully-replicated tensor draws a
    WARNING (`PADDLE_SHARDCHECK_REPLICATED_FLOOR`, default 1 MiB)."""
    try:
        return int(os.environ.get(
            "PADDLE_SHARDCHECK_REPLICATED_FLOOR", str(1 << 20)))
    except ValueError:
        return 1 << 20


def _dtype_bytes(dtype: Optional[str]) -> int:
    return _DTYPE_SIZE.get(canon_dtype(dtype or "float32"), 4)


def _static_nbytes(shape, dtype) -> Optional[int]:
    """Total bytes for a static shape; None when any dim is symbolic."""
    if shape is None:
        return None
    n = 1
    for d in shape:
        if d is None or int(d) < 0:
            return None
        n *= int(d)
    return n * _dtype_bytes(dtype)


def _quant_phase_bytes(nelems: int, axis_size: int) -> int:
    """Wire bytes of ONE phase (all_to_all or all_gather) of the
    two-phase quantized gradient reduction — the stdlib twin of
    `quant_collectives.wire_bytes(x, axis_size=n)`: int8 codes + one
    fp32 scale per block, over ceil(nelems/axis_size) chunks."""
    chunk = max(1, -(-int(nelems) // int(axis_size)) if nelems else 1)
    be = min(_BLOCK, chunk)
    cb = -(-chunk // be)
    return axis_size * cb * be + axis_size * cb * 4


def _quant_plain_bytes(nelems: int) -> int:
    """`quant_collectives.wire_bytes(x)` twin (no axis split): int8
    codes + fp32 scale sidecar over the whole payload."""
    size = max(1, int(nelems))
    be = min(_BLOCK, size)
    nblocks = -(-size // be)
    return nblocks * be + nblocks * 4


# ---------------------------------------------------------------------------
# Entries algebra
# ---------------------------------------------------------------------------
#
# The abstract value is (shape, dtype, entries):
#   shape   tuple with -1 symbolic dims, or None (unknown)
#   dtype   canonical dtype string, or None
#   entries spec_rules entries tuple (trimmed, per-dim None|axis|tuple),
#           or None = layout unknown (propagation degraded)

AbstractShard = Tuple[Optional[tuple], Optional[str], Optional[tuple]]

REPLICATED: tuple = ()


def _ent(entries: Optional[tuple], dim: int):
    if entries is None:
        return None
    return entries[dim] if 0 <= dim < len(entries) else None


def _trim(entries) -> tuple:
    out = list(entries)
    while out and out[-1] is None:
        out.pop()
    return tuple(out)


def _entries_equal(a, b) -> bool:
    return _trim(a or ()) == _trim(b or ())


class _ShardChecker:
    """One analysis run: findings + predicted communication events."""

    def __init__(self, ctx: VerifyContext, mesh_axes: Dict[str, int],
                 ring_axes: Optional[Dict[str, str]] = None,
                 batch_rows: Optional[int] = None,
                 floor: Optional[int] = None):
        self.ctx = ctx
        self.prog = ctx.program
        self.mesh_axes = {str(k): int(v) for k, v in
                          (mesh_axes or {}).items()}
        self.ring_axes = dict(ring_axes or {})
        self.batch_rows = batch_rows
        self.floor = replicated_floor() if floor is None else int(floor)
        self.rules = _spec_rules()
        self.overrides = _registered_overrides()
        self.findings: List[Finding] = []
        self.events: List[dict] = []
        self.clamps: List[dict] = []
        # last known (shape, dtype) per var name across the walk —
        # the post-propagation shapes the partition-spec pass consults
        self.var_shapes: Dict[str, Tuple[tuple, str]] = {}
        self.bailed = 0
        self._reported: Set[tuple] = set()
        self._quant = quant_config()
        self._muted = False  # pass-1 while-body replay: no findings/events

    # -- findings ----------------------------------------------------------
    def _find(self, severity, message, op=None, block=None, var=None,
              dedup_key=None) -> None:
        if self._muted or len(self.findings) >= _MAX_FINDINGS:
            return
        if dedup_key is not None:
            if dedup_key in self._reported:
                return
            self._reported.add(dedup_key)
        self.findings.append(self.ctx.finding(
            severity, "shard-consistency", message, op=op, block=block,
            var=var))

    def _emit(self, kind: str, var: str, nbytes: Optional[int],
              axes, reason: str, op=None, warn: bool = False) -> None:
        """Record one predicted communication event; `warn=True` marks
        it a resharding event (layout conflict) and surfaces a WARNING
        finding on top of the event record."""
        if self._muted:
            return
        f = self.ctx.finding(WARNING, "shard-consistency", reason, op=op,
                             var=var)
        self.events.append({
            "kind": kind, "var": var,
            "bytes": int(nbytes) if nbytes else 0,
            "axes": sorted(set(axes or ())), "reason": reason,
            "location": f.location,
        })
        if warn:
            self._find(WARNING, f"predicted resharding: {reason}",
                       op=op, var=var,
                       dedup_key=("reshard", var, kind, reason))

    # -- spec seeding ------------------------------------------------------
    def _resolve_seed(self, name: str, shape, var, op=None,
                      block=None) -> Optional[tuple]:
        """Registry resolution for one persistable/external var, with
        the duplicate-axis ERROR on the RAW spec and a WARNING per
        clamp (satellite: a typo'd register_spec is no longer silent)."""
        rules = self.rules
        override = self.overrides.get(name)
        annotation = getattr(var, "_sharding_axes", None) \
            if var is not None else None
        raw = override if override is not None else None
        if raw is not None:
            for p in rules.duplicate_axis_problems(raw):
                self._find(ERROR,
                           f"partition spec {raw!r} for {name!r}: {p}",
                           op=op, block=block, var=name,
                           dedup_key=("dup", name, p))
        if shape is None:
            return None
        entries, clamps = rules.resolve_entries(
            name, [0 if d == -1 else d for d in shape], self.mesh_axes,
            override=override,
            annotation=tuple(annotation) if annotation else None)
        for c in clamps:
            self.clamps.append({"var": name, "reason": c,
                                "mesh_axes": dict(self.mesh_axes)})
            self._find(WARNING,
                       f"partition spec for {name!r} clamped on mesh "
                       f"{self.mesh_axes}: {c}", op=op, block=block,
                       var=name, dedup_key=("clamp", name, c))
        return entries

    def _first_touch(self, block, name):
        for o in block.ops:
            if name in o.output_arg_names() or name in o.input_arg_names():
                return o
        return None

    def _seed_entry(self, env: _Env) -> None:
        rules = self.rules
        external = self.ctx.external_names()
        total_devices = 1
        for v in self.mesh_axes.values():
            total_devices *= int(v)
        for v in env.block.vars.values():
            if v.shape is None or v.name in env.vals:
                continue
            shape = tuple(v.shape)
            dt = canon_dtype(v.dtype)
            if getattr(v, "is_data", False):
                nrows = self.batch_rows
                if nrows is None and shape and shape[0] not in (-1, None):
                    nrows = int(shape[0])
                entries = rules.batch_entries(self.mesh_axes, nrows)
                env.vals[v.name] = (shape, dt, entries)
            elif v.persistable or v.name in external:
                op = self._first_touch(env.block, v.name)
                entries = self._resolve_seed(v.name, shape, v, op=op,
                                             block=env.block)
                env.vals[v.name] = (shape, dt, entries)
                nbytes = _static_nbytes(shape, dt)
                if (entries is not None and not _trim(entries)
                        and v.persistable and total_devices > 1
                        and dt.startswith("float")
                        and nbytes is not None and nbytes >= self.floor):
                    self._find(
                        WARNING,
                        f"large tensor {v.name!r} ({nbytes} bytes) is "
                        f"fully replicated on mesh {self.mesh_axes} — "
                        f"every device holds a full copy (floor "
                        f"{self.floor})", op=op, block=env.block,
                        var=v.name, dedup_key=("repl", v.name))

    # -- input/output plumbing --------------------------------------------
    def _declared(self, block, name):
        blk = block
        seen = set()
        while blk is not None and id(blk) not in seen:
            seen.add(id(blk))
            v = blk.vars.get(name)
            if v is not None:
                if v.shape is None:
                    return None
                return tuple(v.shape), canon_dtype(v.dtype)
            blk = getattr(blk, "parent_block", None)
        return None

    def _val(self, env: _Env, block, name) -> AbstractShard:
        v = env.lookup(name)
        if v is not None:
            return v
        d = self._declared(block, name)
        if d is None:
            return (None, None, None)
        return (d[0], d[1], None)

    def _bind(self, env: _Env, block, op, name: str,
              val: AbstractShard) -> None:
        shape, dt, entries = val
        if shape is not None and dt is not None:
            self.var_shapes[name] = (shape, dt)
        if shape is not None and entries:
            for dim, entry in enumerate(entries):
                if entry is None or dim >= len(shape):
                    continue
                size = shape[dim]
                if size is None or size < 0:
                    continue
                extent = self.rules.axis_extent(self.mesh_axes, entry)
                if extent > 1 and size % extent != 0:
                    self._find(
                        ERROR,
                        f"var {name!r}: sharded dim {dim} of size "
                        f"{size} not divisible by {entry!r} extent "
                        f"{extent} after propagation", op=op,
                        var=name, dedup_key=("div", name, dim))
                    entries = _trim(tuple(
                        e if i != dim else None
                        for i, e in enumerate(entries)))
        env.bind(name, (shape, dt, entries))

    # -- meets -------------------------------------------------------------
    def _meet(self, vals: List[AbstractShard], out_shape, var, op) \
            -> Optional[tuple]:
        """Broadcast-aware elementwise meet, right-aligned on the
        output rank.  Two different concrete layouts on one dim is the
        conflict GSPMD resolves with a reshard — recorded as an event,
        first layout wins.  Unknown (None) absorbs."""
        if out_shape is None:
            known = [v for v in vals if v[2] is not None]
            if len(known) == 1:
                return known[0][2]
            return None
        rank = len(out_shape)
        out: List[object] = [None] * rank
        unknown = False
        for shape, dt, entries in vals:
            if entries is None:
                if shape is not None and len(shape) == rank:
                    unknown = True
                continue
            if shape is None:
                unknown = True
                continue
            off = rank - len(shape)
            for i in range(len(shape)):
                e = _ent(entries, i)
                if e is None:
                    continue
                j = off + i
                if j < 0 or j >= rank:
                    continue
                if out[j] is None:
                    out[j] = e
                elif out[j] != e:
                    nbytes = _static_nbytes(out_shape, dt)
                    self._emit(
                        "all_to_all", var, nbytes,
                        self.rules.entry_names(e),
                        f"operands of {op.type!r} disagree on dim {j} "
                        f"layout ({out[j]!r} vs {e!r}); SPMD reshards "
                        f"one operand", op=op, warn=True)
        if unknown and not any(e is not None for e in out):
            return None
        return _trim(out)

    # -- op spec rules -----------------------------------------------------
    def _grad_entries(self, op, env, block) -> Dict[str, Optional[tuple]]:
        out: Dict[str, Optional[tuple]] = {}
        for name in op.output_arg_names():
            if name == _EMPTY or _GRAD_SUFFIX not in name:
                continue
            base = name.split(_GRAD_SUFFIX, 1)[0]
            out[name] = self._val(env, block, base)[2]
        return out

    def _ring_axis(self, op) -> str:
        ring = op.attr("ring_id", 0) or 0
        key = f"ring_{ring}"
        if key in self.ring_axes:
            return str(self.ring_axes[key])
        return str(self.ring_axes.get("data", "data"))

    def _collective_entries(self, op, env, block, ins) \
            -> Dict[str, Optional[tuple]]:
        t = op.type
        axis = self._ring_axis(op)
        if t not in _COMM_NOOPS and self.mesh_axes \
                and axis not in self.mesh_axes:
            self._find(
                ERROR,
                f"collective {t!r} (ring {op.attr('ring_id', 0) or 0}) "
                f"resolves to mesh axis {axis!r}, which is absent from "
                f"mesh axes {tuple(self.mesh_axes)}", op=op,
                dedup_key=("ring", t, axis))
        x = ins[0] if ins else (None, None, None)
        shape, dt, entries = x
        nelems = None
        if shape is not None and all(d is not None and d >= 0
                                     for d in shape):
            nelems = 1
            for d in shape:
                nelems *= int(d)
        payload = (nelems * _dtype_bytes(dt)) if nelems is not None \
            else None
        mode, floor, _token = self._quant
        n = int(self.mesh_axes.get(axis, 1))
        outs: Dict[str, Optional[tuple]] = {}
        out_names = [nm for nm in op.output_arg_names() if nm != _EMPTY]
        primary = out_names[0] if out_names else None

        def wire_default():
            return payload

        if t.startswith("c_allreduce") or t == "mp_allreduce_sum":
            wire = payload
            if (t == "c_allreduce_sum" and mode == "int8"
                    and dt == "float32" and payload is not None
                    and payload >= floor and n > 1 and nelems):
                wire = _quant_phase_bytes(nelems, n) \
                    + _quant_phase_bytes(nelems, n)
            if primary:
                self._emit(t, primary, wire, (axis,),
                           f"explicit {t} on ring axis {axis!r}", op=op)
                outs[primary] = entries
        elif t == "c_allgather":
            wire = payload
            if (mode == "int8" and dt == "float32" and payload is not None
                    and payload >= floor and nelems):
                wire = _quant_plain_bytes(nelems)
            if primary:
                self._emit(t, primary, wire, (axis,),
                           f"explicit {t} on ring axis {axis!r}", op=op)
                # gathered output: dim 0 de-sharded
                outs[primary] = _trim((None,) + tuple(
                    (entries or ())[1:])) if entries is not None else None
        elif t == "c_reducescatter":
            wire = payload
            if (mode == "int8" and dt == "float32" and payload is not None
                    and payload >= floor and n > 1 and nelems):
                wire = _quant_phase_bytes(nelems, n)
            if primary:
                self._emit(t, primary, wire, (axis,),
                           f"explicit {t} on ring axis {axis!r}", op=op)
                # explicit-collective programs declare PER-SHARD
                # shapes, so the scatter is already materialized in the
                # declared metadata: layout unknown, not (axis,)
                outs[primary] = None
        elif t in ("send_v2", "recv_v2"):
            if t == "send_v2":
                self._emit(t, op.input_arg_names()[0] if
                           op.input_arg_names() else "?", payload,
                           (axis,), f"explicit {t} on ring axis "
                           f"{axis!r}", op=op)
            if primary:
                outs[primary] = None
        elif t in ("alltoall", "c_split", "c_concat"):
            if primary:
                self._emit(t, primary, wire_default(), (axis,),
                           f"explicit {t} on ring axis {axis!r}", op=op)
                outs[primary] = None
        else:
            # broadcast / identity / sync family: layout-preserving
            for nm in out_names:
                outs[nm] = entries
        return outs

    def _matmul_entries(self, op, env, block) -> Dict[str, Optional[tuple]]:
        x_names = op.inputs.get("X") or []
        y_names = op.inputs.get("Y") or []
        x = self._val(env, block, x_names[0]) if x_names \
            else (None, None, None)
        y = self._val(env, block, y_names[0]) if y_names \
            else (None, None, None)
        xs, _xd, xe = x
        ys, yd, ye = y
        out_names = [nm for nm in op.output_arg_names() if nm != _EMPTY]
        if not out_names:
            return {}
        out_name = out_names[0]
        # weight contract/width sharded -> XLA gathers the weight (or
        # equivalently reduce-scatters partials); the calibrated cost
        # model charges the FULL weight bytes once per use
        if ye is not None and _trim(ye) and ys is not None:
            wb = _static_nbytes(ys, yd)
            if wb is not None and y_names:
                axes = [n for e in ye for n in
                        self.rules.entry_names(e)]
                self._emit("weight_gather", y_names[0], wb, axes,
                           f"sharded weight {y_names[0]!r} consumed by "
                           f"{op.type!r}: SPMD gathers/rescatters it "
                           f"around the matmul", op=op)
        # activation contract dim sharded -> partial sums all-reduced
        if xe is not None and xs is not None and len(xs) >= 1:
            ce = _ent(xe, len(xs) - 1)
            if ce is not None:
                d = self._declared(block, out_name)
                ob = _static_nbytes(d[0], d[1]) if d else None
                self._emit("partial_allreduce", out_name,
                           (2 * ob) if ob else 0,
                           self.rules.entry_names(ce),
                           f"contract dim of {op.type!r} input is "
                           f"sharded over {ce!r}: partial sums are "
                           f"all-reduced", op=op)
        # out[row from x dim 0, col from y last dim], dropping a col
        # entry whose axes the row entry already uses
        row = _ent(xe, 0) if xe is not None else None
        col = _ent(ye, len(ys) - 1) if (ye is not None and ys) else None
        if col is not None and row is not None:
            used = set(self.rules.entry_names(row))
            if used & set(self.rules.entry_names(col)):
                col = None
        if xe is None and ye is None:
            return {out_name: None}
        return {out_name: _trim((row, col))}

    def _embedding_entries(self, op, env, block) \
            -> Dict[str, Optional[tuple]]:
        w_names = op.inputs.get("W") or []
        id_names = op.inputs.get("Ids") or []
        w = self._val(env, block, w_names[0]) if w_names \
            else (None, None, None)
        ids = self._val(env, block, id_names[0]) if id_names \
            else (None, None, None)
        ws, wd, we = w
        out_names = [nm for nm in op.output_arg_names() if nm != _EMPTY]
        if not out_names:
            return {}
        if we is not None and _trim(we) and ws is not None and w_names:
            wb = _static_nbytes(ws, wd)
            if wb is not None:
                axes = [n for e in we for n in self.rules.entry_names(e)]
                self._emit("weight_gather", w_names[0], wb, axes,
                           f"sharded embedding table {w_names[0]!r}: "
                           f"SPMD gathers rows across the vocab shards",
                           op=op)
        # out = ids layout + replicated embedding dim
        ide = ids[2]
        if ide is None and we is None:
            return {out_names[0]: None}
        base = tuple(ide or ())
        return {out_names[0]: _trim(base)}

    def _reshape_entries(self, op, env, block) \
            -> Dict[str, Optional[tuple]]:
        in_names = op.inputs.get("X") or []
        x = self._val(env, block, in_names[0]) if in_names \
            else (None, None, None)
        xs, xd, xe = x
        out_names = [nm for nm in op.output_arg_names() if nm != _EMPTY]
        data_outs = [nm for nm in out_names if "XShape" not in nm
                     and not nm.endswith("@XSHAPE")]
        if not data_outs:
            return {}
        out_name = data_outs[0]
        d = self._declared(block, out_name)
        os_ = d[0] if d else None
        res: Dict[str, Optional[tuple]] = {
            nm: REPLICATED for nm in out_names if nm != out_name}
        if xe is None or xs is None or os_ is None:
            res[out_name] = None if xe is None else (
                xe if xs is None else None)
            return res
        if not _trim(xe):
            res[out_name] = REPLICATED
            return res
        out_entries = self._reshape_carry(op, xs, os_, xe, xd,
                                          in_names[0])
        res[out_name] = out_entries
        return res

    def _reshape_carry(self, op, in_shape, out_shape, entries, dtype,
                       var) -> Optional[tuple]:
        """Factor-group walk: map sharded input dims onto output dims.
        A sharded dim that leads its factor group carries its entry to
        the group's leading output dim; a sharded INTERIOR dim cannot
        keep its layout — SPMD reshuffles the tensor (all_to_all),
        recorded as a resharding event."""
        ins = [int(d) for d in in_shape]
        outs = [int(d) for d in out_shape]
        ents: List[object] = [None] * len(outs)

        # symbolic shapes: carry dim 0 <-> dim 0 when both lead with
        # the symbolic batch dim; other sharded dims carry only on an
        # exact right-aligned suffix match
        if any(d < 0 for d in ins) or any(d < 0 for d in outs):
            if ins and outs and ins[0] < 0 and outs[0] < 0:
                ents[0] = _ent(entries, 0)
            k = 0
            while (k < len(ins) - 1 and k < len(outs) - 1
                   and ins[-1 - k] == outs[-1 - k] and ins[-1 - k] >= 0):
                e = _ent(entries, len(ins) - 1 - k)
                if e is not None:
                    ents[len(outs) - 1 - k] = e
                k += 1
            for i in range(1, len(ins) - k):
                e = _ent(entries, i)
                if e is not None:
                    nb = _static_nbytes(tuple(in_shape), dtype)
                    self._emit(
                        "all_to_all", var, nb,
                        self.rules.entry_names(e),
                        f"reshape moves sharded dim {i} across factor "
                        f"groups; SPMD redistributes the tensor", op=op,
                        warn=True)
            return _trim(ents)

        i = j = 0
        while i < len(ins) and j < len(outs):
            gi, gj = [i], [j]
            pi, pj = ins[i], outs[j]
            while pi != pj:
                if pi < pj:
                    i += 1
                    if i >= len(ins):
                        break
                    gi.append(i)
                    pi *= ins[i]
                else:
                    j += 1
                    if j >= len(outs):
                        break
                    gj.append(j)
                    pj *= outs[j]
            if pi != pj:
                return None  # ragged factorization: give up, unknown
            lead_in = gi[0]
            for k, dim in enumerate(gi):
                e = _ent(entries, dim)
                if e is None:
                    continue
                if dim == lead_in:
                    ents[gj[0]] = e
                else:
                    nb = _static_nbytes(tuple(in_shape), dtype)
                    self._emit(
                        "all_to_all", var, nb,
                        self.rules.entry_names(e),
                        f"reshape folds sharded interior dim {dim} "
                        f"(group {tuple(gi)} -> {tuple(gj)}); SPMD "
                        f"redistributes the tensor", op=op, warn=True)
            i += 1
            j += 1
        return _trim(ents)

    def _reduce_entries(self, op, env, block) \
            -> Dict[str, Optional[tuple]]:
        in_names = op.inputs.get("X") or []
        x = self._val(env, block, in_names[0]) if in_names \
            else (None, None, None)
        xs, xd, xe = x
        out_names = [nm for nm in op.output_arg_names() if nm != _EMPTY]
        if not out_names:
            return {}
        out_name = out_names[0]
        if xe is None:
            return {out_name: None}
        if xs is None:
            return {out_name: None}
        rank = len(xs)
        dims = op.attr("dim", None)
        if op.attr("reduce_all", False) or dims is None or dims == []:
            reduced = set(range(rank))
        else:
            if isinstance(dims, int):
                dims = [dims]
            reduced = {(d + rank) % rank for d in dims}
        keep = bool(op.attr("keep_dim", False))
        out: List[object] = []
        for i in range(rank):
            e = _ent(xe, i)
            if i in reduced:
                if e is not None:
                    d = self._declared(block, out_name)
                    ob = _static_nbytes(d[0], d[1]) if d else None
                    self._emit("partial_allreduce", out_name,
                               (2 * ob) if ob else 0,
                               self.rules.entry_names(e),
                               f"{op.type!r} reduces sharded dim {i}: "
                               f"partial results are all-reduced",
                               op=op)
                if keep:
                    out.append(None)
            else:
                out.append(e)
        return {out_name: _trim(out)}

    def _default_entries(self, op, env, block, out_shapes) \
            -> Dict[str, Optional[tuple]]:
        """In-place name match first; then single-primary preserve when
        shapes agree; n-ary elementwise meet for same-rank operands;
        all-replicated-in => replicated out; else unknown."""
        in_vals: List[Tuple[str, AbstractShard]] = []
        for nm in op.input_arg_names():
            if nm != _EMPTY:
                in_vals.append((nm, self._val(env, block, nm)))
        out: Dict[str, Optional[tuple]] = {}
        in_names = {nm for nm, _v in in_vals}
        for name in op.output_arg_names():
            if name == _EMPTY:
                continue
            if name in in_names:  # in-place update (optimizer ops)
                out[name] = self._val(env, block, name)[2]
                continue
            oshape = out_shapes.get(name)
            if oshape is None:
                d = self._declared(block, name)
                oshape = d[0] if d else None
            cands = [v for _nm, v in in_vals
                     if v[0] is not None and oshape is not None
                     and len(v[0]) == len(oshape)]
            if not in_vals:
                out[name] = REPLICATED
            elif cands:
                met = self._meet(cands, oshape, name, op)
                # this is a heuristic carry (the op has no dedicated
                # rule): an entry that does not divide its output dim
                # is a bad guess, not a layout contract — drop it
                # rather than let _bind report a phantom ERROR
                if met and oshape is not None:
                    met = _trim(tuple(
                        None if (e is not None and i < len(oshape)
                                 and oshape[i] is not None
                                 and oshape[i] >= 0
                                 and self.rules.axis_extent(
                                     self.mesh_axes, e) > 1
                                 and oshape[i] % self.rules.axis_extent(
                                     self.mesh_axes, e) != 0)
                        else e
                        for i, e in enumerate(met)))
                out[name] = met
            elif all(v[2] is not None and not _trim(v[2])
                     for _nm, v in in_vals):
                out[name] = REPLICATED
            else:
                # rank-changing op with no dedicated rule: unknown
                out[name] = None
                self.bailed += 1
        return out

    def _entries_for_op(self, op, env, block, out_shapes) \
            -> Dict[str, Optional[tuple]]:
        t = op.type
        if op.attr("fwd_op_id", None) is not None:
            return self._grad_entries(op, env, block)
        from .verifier import _is_collective
        if _is_collective(t):
            ins = [self._val(env, block, nm)
                   for nm in op.input_arg_names() if nm != _EMPTY]
            return self._collective_entries(op, env, block, ins)
        if t in _MATMUL_OPS:
            return self._matmul_entries(op, env, block)
        if t in _EMBEDDING_OPS:
            return self._embedding_entries(op, env, block)
        if t in _RESHAPE_OPS:
            return self._reshape_entries(op, env, block)
        if t in _TRANSPOSE_OPS:
            in_names = op.inputs.get("X") or []
            x = self._val(env, block, in_names[0]) if in_names \
                else (None, None, None)
            xs, _xd, xe = x
            perm = op.attr("axis", None)
            out_names = [nm for nm in op.output_arg_names()
                         if nm != _EMPTY]
            data_outs = [nm for nm in out_names if "XShape" not in nm]
            res: Dict[str, Optional[tuple]] = {
                nm: REPLICATED for nm in out_names
                if nm not in data_outs[:1]}
            if data_outs:
                if xe is None or not perm:
                    res[data_outs[0]] = None if xe is None else xe
                else:
                    res[data_outs[0]] = _trim(
                        [_ent(xe, int(p)) for p in perm])
            return res
        if t.startswith("reduce_") or t == "mean":
            return self._reduce_entries(op, env, block)
        if t == "softmax_with_cross_entropy":
            logits = (op.inputs.get("Logits") or [None])[0]
            lv = self._val(env, block, logits) if logits \
                else (None, None, None)
            out: Dict[str, Optional[tuple]] = {}
            for nm in op.output_arg_names():
                if nm == _EMPTY:
                    continue
                if "Softmax" in [s for s, ns in op.outputs.items()
                                 if nm in ns]:
                    out[nm] = lv[2]
                else:  # Loss: [batch, 1] keeps the batch entry
                    out[nm] = _trim((_ent(lv[2], 0),)) \
                        if lv[2] is not None else None
            return out
        if t == "layer_norm":
            xn = (op.inputs.get("X") or [None])[0]
            xv = self._val(env, block, xn) if xn else (None, None, None)
            out: Dict[str, Optional[tuple]] = {}
            for slot, names in op.outputs.items():
                for nm in names:
                    if nm == _EMPTY:
                        continue
                    if slot == "Y":
                        out[nm] = xv[2]
                    else:  # Mean/Variance: flattened rows keep dim 0
                        out[nm] = _trim((_ent(xv[2], 0),)) \
                            if xv[2] is not None else None
            return out
        if t in _FRESH_REPLICATED_OPS:
            return {nm: REPLICATED for nm in op.output_arg_names()
                    if nm != _EMPTY}
        return self._default_entries(op, env, block, out_shapes)

    # -- per-op ------------------------------------------------------------
    def _check_op(self, env: _Env, block, op, owner_type) -> None:
        if op.type in OPAQUE_OPS:
            for name in op.output_arg_names():
                if name == _EMPTY or env.lookup(name) is not None:
                    continue
                d = self._declared(block, name)
                if d is not None:
                    env.bind(name, (d[0], d[1], None))
            return

        def shape_lookup(name):
            v = env.lookup(name)
            if v is not None and v[0] is not None:
                return (v[0], v[1] or "float32")
            return self._declared(block, name)

        out_shapes: Dict[str, tuple] = {}
        try:
            inferred = infer_op_outputs(op, block, lookup=shape_lookup)
            out_shapes = {k: v[0] for k, v in inferred.items()}
            out_dtypes = {k: v[1] for k, v in inferred.items()}
        except ShapeInferBail:
            out_dtypes = {}
        except Exception:  # noqa: BLE001 - checker bug must not kill compile
            out_dtypes = {}

        try:
            out_entries = self._entries_for_op(op, env, block, out_shapes)
        except Exception:  # noqa: BLE001 - checker bug must not kill compile
            logger.debug("shard rule failed for op %r", op.type,
                         exc_info=True)
            out_entries = {}
            self.bailed += 1

        for name in op.output_arg_names():
            if name == _EMPTY:
                continue
            shape_dt = out_shapes.get(name), out_dtypes.get(name)
            if shape_dt[0] is None:
                d = self._declared(block, name)
                shape_dt = (d[0], d[1]) if d is not None else (None, None)
            self._bind(env, block, op, name,
                       (shape_dt[0], shape_dt[1],
                        out_entries.get(name)))

    # -- walk --------------------------------------------------------------
    def _walk(self, block, env: _Env, owner_type, visited) -> None:
        for op in block.ops:
            sb = op.attr("sub_block")
            if isinstance(sb, int) and 0 < sb < len(self.prog.blocks) \
                    and sb not in visited:
                self._descend(env, block, op, sb, visited)
                for name in op.output_arg_names():
                    if name == _EMPTY or env.lookup(name) is not None:
                        continue
                    d = self._declared(block, name)
                    if d is not None:
                        env.bind(name, (d[0], d[1], None))
                continue
            self._check_op(env, block, op, owner_type)

    def _descend(self, env: _Env, block, op, sb: int, visited) -> None:
        sub = self.prog.blocks[sb]
        if op.type in _LOOP_OWNERS:
            # pass 1 muted: diff the loop-carried writes, widen shape
            # changes to symbolic and layout changes to replicated
            saved = [(e, dict(e.vals)) for e in env.chain()]
            muted, self._muted = self._muted, True
            child = _Env(sub, parent=env)
            self._seed_entry(child)
            self._walk(sub, child, op.type, visited | {sb})
            self._muted = muted
            for e, before in saved:
                for name, new in list(e.vals.items()):
                    old = before.get(name)
                    if old is None or old == new:
                        continue
                    oshape, odt, oent = old
                    nshape, _ndt, nent = new
                    if oshape is not None and nshape is not None \
                            and len(oshape) == len(nshape):
                        wshape = tuple(a if a == b else -1
                                       for a, b in zip(oshape, nshape))
                    else:
                        wshape = oshape
                    went = oent if _entries_equal(oent, nent) \
                        else REPLICATED  # loop-carried layout widens
                    e.vals[name] = (wshape, odt, went)
            child = _Env(sub, parent=env)
            self._seed_entry(child)
            self._walk(sub, child, op.type, visited | {sb})
        else:
            saved = [(e, dict(e.vals)) for e in env.chain()]
            child = _Env(sub, parent=env)
            self._seed_entry(child)
            self._walk(sub, child, op.type, visited | {sb})
            for e, before in saved:
                for name, new in list(e.vals.items()):
                    old = before.get(name)
                    if old is None or old == new:
                        continue
                    oshape, odt, oent = old
                    nshape, _ndt, nent = new
                    if oshape is not None and nshape is not None \
                            and len(oshape) == len(nshape):
                        wshape = tuple(a if a == b else -1
                                       for a, b in zip(oshape, nshape))
                        went = oent if _entries_equal(oent, nent) \
                            else REPLICATED
                        e.vals[name] = (wshape, odt, went)
                    else:
                        e.vals.pop(name, None)

    def run(self) -> "ShardAnalysis":
        if self.prog.blocks:
            root = _Env(self.prog.blocks[0])
            self._seed_entry(root)
            self._walk(self.prog.blocks[0], root, None, {0})
        if self.bailed:
            try:
                from ..profiler import stat_add
                stat_add("shard_check_bailouts", self.bailed)
            except Exception:  # noqa: BLE001 - stdlib-only standalone load
                pass
        return ShardAnalysis(
            findings=self.findings, events=self.events,
            clamps=self.clamps, var_shapes=dict(self.var_shapes),
            mesh_axes=dict(self.mesh_axes), bailed=self.bailed)


class ShardAnalysis:
    """Result of one propagation run."""

    __slots__ = ("findings", "events", "clamps", "var_shapes",
                 "mesh_axes", "bailed")

    def __init__(self, findings, events, clamps, var_shapes, mesh_axes,
                 bailed):
        self.findings = findings
        self.events = events
        self.clamps = clamps
        self.var_shapes = var_shapes
        self.mesh_axes = mesh_axes
        self.bailed = bailed

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == WARNING]


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def analyze(program, mesh_axes, *, ring_axes=None, batch_rows=None,
            feed=None, fetch_list=None, scope_names=None,
            floor=None) -> ShardAnalysis:
    """Propagate PartitionSpecs through `program` under a plain
    `{axis: size}` mesh dict; returns findings + predicted events."""
    feed_names = None
    if feed is not None:
        feed_names = set(feed.keys() if hasattr(feed, "keys") else feed)
    fetch_names = None
    if fetch_list is not None:
        fetch_names = [v.name if hasattr(v, "name") else str(v)
                       for v in fetch_list]
    ctx = VerifyContext(program, feed_names=feed_names,
                        fetch_names=fetch_names, scope_names=scope_names)
    return _ShardChecker(ctx, mesh_axes, ring_axes=ring_axes,
                         batch_rows=batch_rows, floor=floor).run()


def check_program(program, mesh_axes, *, ring_axes=None,
                  batch_rows=None, feed=None, fetch_list=None,
                  scope_names=None, floor=None) -> List[Finding]:
    """Standalone entry: findings only (tools/shardcheck.py, tests)."""
    return analyze(program, mesh_axes, ring_axes=ring_axes,
                   batch_rows=batch_rows, feed=feed,
                   fetch_list=fetch_list, scope_names=scope_names,
                   floor=floor).findings


def check_program_dict(d, mesh_axes, **kw) -> List[Finding]:
    """Check a serialized Program (Program.to_dict() / its JSON)."""
    return check_program(ProgramView(d), mesh_axes, **kw)


def propagated_shapes(program, feed=None, fetch_list=None,
                      scope_names=None) -> Dict[str, Tuple[tuple, str]]:
    """`{var: (shape, dtype)}` after replaying inference over the
    final graph (mesh-independent) — what the partition-spec pass
    consults instead of declared metadata alone."""
    return analyze(program, {}, feed=feed, fetch_list=fetch_list,
                   scope_names=scope_names).var_shapes


# calibration constants for the SPMD wire model, fitted against
# measured `collective_bytes_spmd_*` on the PR-13 acceptance
# transformer over {data:2,fsdp:2,tp:2} (tests/test_shard_check.py
# holds both quant modes within ±25%):
#  - a sharded weight consumed in the forward pass is gathered for
#    fwd AND re-gathered for the bwd remat -> 2x its bytes per use;
#    under the quantized two-jit gradient split the bwd re-gather is
#    partially shared -> 1.5x
_GATHER_FACTOR_FULL = 2.0
_GATHER_FACTOR_QUANT_SPLIT = 1.5


def comm_report(program, mesh_axes, *, ring_axes=None, batch_rows=None,
                feed=None, fetch_list=None, scope_names=None) -> dict:
    """Static predicted collective wire bytes for one compiled step of
    `program` under `mesh_axes` — BEFORE any compile.

    Two regimes:
    * programs containing explicit collective ops predict per-op-type
      bytes matching the `collective_bytes_<op_type>` counters;
    * SPMD programs (no explicit collectives) predict the
      `collective_bytes_spmd_*` counters XLA SPMD materializes:
      weight gathers from propagation events, gradient reduction per
      trainable param (quantized two-phase all_to_all+all_gather when
      the EQuARX path engages, full-width 2x all_reduce otherwise).
    """
    analysis = analyze(program, mesh_axes, ring_axes=ring_axes,
                       batch_rows=batch_rows, feed=feed,
                       fetch_list=fetch_list, scope_names=scope_names)
    rules = _spec_rules()
    mode, floor, token = quant_config()
    mesh = analysis.mesh_axes

    explicit = [e for e in analysis.events
                if e["kind"].startswith("c_")
                or e["kind"] in ("alltoall", "send_v2", "recv_v2",
                                 "mp_allreduce_sum")]
    if explicit:
        predicted: Dict[str, int] = {}
        for e in explicit:
            predicted[e["kind"]] = predicted.get(e["kind"], 0) \
                + int(e["bytes"])
        return {"mode": "explicit", "mesh_axes": dict(mesh),
                "quant": token, "predicted": predicted,
                "predicted_total": sum(predicted.values()),
                "events": analysis.events, "params": []}

    # ---- SPMD regime ----
    # trainable params: persistable float vars whose @GRAD is written
    grads_written = {
        n for blk in program.blocks for op in blk.ops
        for n in op.output_arg_names()
        if n != _EMPTY and _GRAD_SUFFIX in n}
    params: List[dict] = []
    seen: Set[str] = set()
    for blk in program.blocks:
        for name, v in blk.vars.items():
            if name in seen or not getattr(v, "persistable", False):
                continue
            if (name + _GRAD_SUFFIX) not in grads_written:
                continue
            dt = canon_dtype(getattr(v, "dtype", "float32"))
            if not dt.startswith("float"):
                continue
            nbytes = _static_nbytes(tuple(v.shape or ()), dt)
            if nbytes is None:
                continue
            seen.add(name)
            nelems = nbytes // _dtype_bytes(dt)
            params.append({"name": name, "nbytes": nbytes,
                           "nelems": nelems, "dtype": dt})

    # does gradient reduction happen at all? Only when the batch is
    # actually sharded (data/fsdp extents on the mesh)
    batch = rules.batch_entries(mesh, batch_rows)
    n_batch = rules.sharded_extent(batch, mesh)
    quant_split = (mode == "int8" and n_batch > 1)

    gather = 0.0
    all_to_all = 0.0
    all_reduce = 0.0
    factor = _GATHER_FACTOR_QUANT_SPLIT if quant_split \
        else _GATHER_FACTOR_FULL
    for e in analysis.events:
        if e["kind"] == "weight_gather":
            gather += factor * e["bytes"]
        elif e["kind"] == "partial_allreduce":
            all_reduce += e["bytes"]
        elif e["kind"] == "all_to_all":
            all_to_all += e["bytes"]

    for p in params:
        if n_batch <= 1:
            continue
        if quant_split and p["dtype"] == "float32" \
                and p["nbytes"] >= floor:
            q = _quant_phase_bytes(p["nelems"], n_batch)
            all_to_all += q
            gather += q
            p["quantized"] = True
        else:
            # opprof convention: all-reduce wire = 2x payload
            all_reduce += 2 * p["nbytes"]
            p["quantized"] = False

    predicted = {"all_gather": int(gather),
                 "all_reduce": int(all_reduce),
                 "all_to_all": int(all_to_all)}
    return {"mode": "spmd", "mesh_axes": dict(mesh), "quant": token,
            "predicted": predicted,
            "predicted_total": sum(predicted.values()),
            "events": analysis.events, "params": params,
            "n_batch": n_batch, "quant_split": quant_split}


def _axes_of(mesh) -> Dict[str, int]:
    """Accept a jax Mesh or a plain `{axis: size}` dict."""
    if hasattr(mesh, "axis_names"):
        return {str(n): int(mesh.shape[n]) for n in mesh.axis_names}
    return {str(k): int(v) for k, v in dict(mesh).items()}


def feasibility(program, old_mesh, new_mesh, *, batch_rows=None) -> dict:
    """Elastic-resharding precheck (ROADMAP elastic item): re-solve the
    spec registry over a candidate mesh and report fits/clamps and the
    per-device bytes delta WITHOUT compiling.  `feasible: False` means
    the restore path must refuse the candidate (today's behavior) —
    with the problems named instead of a bare mismatch error."""
    rules = _spec_rules()
    old_axes = _axes_of(old_mesh)
    new_axes = _axes_of(new_mesh)
    overrides = _registered_overrides()
    problems: List[str] = []
    clamps: List[str] = []

    def devcount(axes):
        n = 1
        for v in axes.values():
            n *= int(v)
        return n

    old_n, new_n = devcount(old_axes), devcount(new_axes)

    # the batch must still divide over the surviving mesh's batch axes
    if batch_rows is not None:
        old_batch = rules.sharded_extent(
            rules.batch_entries(old_axes, batch_rows), old_axes)
        new_batch = rules.sharded_extent(
            rules.batch_entries(new_axes, batch_rows), new_axes)
        want = 1
        for ax in ("data", "fsdp"):
            if ax in new_axes:
                want *= int(new_axes[ax])
        if want > 1 and batch_rows % want != 0:
            problems.append(
                f"batch of {batch_rows} rows does not divide over the "
                f"new mesh batch extent {want} "
                f"(axes {dict(new_axes)}) — old extent was {old_batch}")
        elif old_batch > 1 and new_batch <= 1:
            problems.append(
                f"batch parallelism collapses on the new mesh "
                f"{dict(new_axes)} (batch extent {new_batch}, was "
                f"{old_batch})")

    vars_out: List[dict] = []
    old_total = 0
    new_total = 0
    seen: Set[str] = set()
    for blk in program.blocks:
        for name, v in blk.vars.items():
            if name in seen or not getattr(v, "persistable", False):
                continue
            if v.shape is None:
                continue
            shape = tuple(int(s) for s in v.shape)
            if any(d < 0 for d in shape):
                continue
            seen.add(name)
            dt = canon_dtype(getattr(v, "dtype", "float32"))
            nbytes = _static_nbytes(shape, dt) or 0
            annotation = getattr(v, "_sharding_axes", None)
            override = overrides.get(name)
            old_e, _c0 = rules.resolve_entries(
                name, shape, old_axes, override=override,
                annotation=tuple(annotation) if annotation else None)
            new_e, c1 = rules.resolve_entries(
                name, shape, new_axes, override=override,
                annotation=tuple(annotation) if annotation else None)
            for c in c1:
                clamps.append(f"{name}: {c}")
            old_pd = nbytes // max(1, rules.sharded_extent(old_e,
                                                          old_axes))
            new_pd = nbytes // max(1, rules.sharded_extent(new_e,
                                                          new_axes))
            old_total += old_pd
            new_total += new_pd
            vars_out.append({
                "name": name, "nbytes": nbytes,
                "old_entries": list(old_e), "new_entries": list(new_e),
                "old_bytes_per_device": old_pd,
                "new_bytes_per_device": new_pd,
            })

    return {
        "feasible": not problems,
        "problems": problems,
        "clamps": clamps,
        "old_mesh_axes": old_axes, "new_mesh_axes": new_axes,
        "old_devices": old_n, "new_devices": new_n,
        "old_bytes_per_device": old_total,
        "new_bytes_per_device": new_total,
        "delta_bytes_per_device": new_total - old_total,
        "vars": vars_out,
    }


# ---------------------------------------------------------------------------
# The verifier pass (ERROR tier: runs once per compile-cache miss)
# ---------------------------------------------------------------------------

@register_pass("shard-consistency")
def shard_consistency_pass(ctx: VerifyContext) -> List[Finding]:
    """PartitionSpec propagation over the final graph under the CURRENT
    mesh: spec misfits are ERRORs before the compile instead of silent
    replication after it.  Skipped outside any mesh context."""
    try:
        from ..parallel import mesh as mesh_lib
    except Exception:  # noqa: BLE001 - jax-less tooling environments
        return []
    mesh = mesh_lib.current_mesh()
    if mesh is None:
        return []
    mesh_axes = {str(n): int(mesh.shape[n]) for n in mesh.axis_names}
    try:
        return _ShardChecker(ctx, mesh_axes).run().findings
    except Exception:  # noqa: BLE001 - analyzer bug must not kill compile
        logger.warning("shard-consistency pass failed", exc_info=True)
        return []
