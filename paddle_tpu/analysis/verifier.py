"""Program verifier: a pass pipeline over the Program IR (ISSUE 3
tentpole, part 1).

The reference framework validates ProgramDesc invariants in C++ (op
registry checks, var def-use, block linkage) before execution; our
pure-Python IR previously lowered unchecked, so a malformed graph
surfaced as an opaque JAX/XLA trace error with no op-level provenance.
This module restores that validation layer, TPU-native:

* **Structural passes** (ERROR tier): every op type resolves in
  `ops/registry`, inputs are defined before use under block scoping
  rules, control-flow `sub_block` references resolve, and block parent
  links are acyclic and in range.
* **Dataflow passes**: donation/aliasing safety (a var that is both
  fetched and donated is an error — the donated buffer can be
  invalidated while a LazyFetch handle still references it) and
  cross-replica collective-order consistency (every program path must
  issue `c_allreduce`/`c_broadcast`/... in the same ring-id order, so
  collectives under a conditional sub-block are an error — replicas
  whose condition differs would issue them in different order and the
  pjit lowering deadlocks/diverges across hosts).  WARNING-tier passes
  flag dead ops, vars written-never-read, and unreachable blocks.

Findings carry `program#<id> block<idx> op<id> (<type>)` provenance —
greppable — plus the nearest Python construction stack when the
Program recorded one (`FLAGS_op_callstack`).

Integration: `Executor._prepare` and `CompiledProgram._compile` call
`maybe_verify_program` once per compile-cache miss (the hot path pays
nothing on a cache hit), gated by `FLAGS_verify_program`
("on" raises on ERROR findings, "warn" is the warn-only escape hatch,
"off" disables).  Verification wall time accumulates on the
`verify_ms` profiler timer so tests can assert zero verifier time on
cache-hit steps.
"""

from __future__ import annotations

import warnings
from typing import Any, Callable, Dict, Iterable, List, Optional, Set

ERROR = "error"
WARNING = "warning"
INFO = "info"

_EMPTY = "@EMPTY@"  # framework.EMPTY_VAR_NAME (kept import-free)

# collective op families that must be issued in identical order on every
# replica (matches CompiledProgram._has_collective_ops)
_COLLECTIVE_EXTRA = {"barrier", "alltoall", "send_v2", "recv_v2",
                     "mp_allreduce_sum"}

# point-to-point ops are NOT order-checked: they are pairwise-matched at
# lowering by the p2p queue (ops/collective_ops.py raises "no data
# source" on a mis-pairing), and a send/recv pair inside one
# conditional sub-block is a supported pattern — only ring collectives
# require every replica to issue them on every path
_P2P = {"send_v2", "recv_v2"}

# op types whose value is their side effect — never "dead"
_EFFECT_OPS = {"print", "assert", "py_func", "while",
               "conditional_block", "run_program", "save", "load"}

_CONDITIONAL_OWNERS = {"conditional_block"}
_LOOP_OWNERS = {"while"}


def _is_collective(op_type: str) -> bool:
    return op_type.startswith("c_") or op_type in _COLLECTIVE_EXTRA


class Finding:
    """One verifier finding with op-level provenance."""

    __slots__ = ("severity", "pass_name", "message", "prog_id",
                 "block_idx", "op_id", "op_type", "var", "callstack")

    def __init__(self, severity: str, pass_name: str, message: str,
                 prog_id: int, block_idx: Optional[int] = None,
                 op_id: Optional[int] = None,
                 op_type: Optional[str] = None,
                 var: Optional[str] = None,
                 callstack: Optional[List[str]] = None):
        self.severity = severity
        self.pass_name = pass_name
        self.message = message
        self.prog_id = prog_id
        self.block_idx = block_idx
        self.op_id = op_id
        self.op_type = op_type
        self.var = var
        self.callstack = callstack

    @property
    def location(self) -> str:
        loc = f"program#{self.prog_id}"
        if self.block_idx is not None:
            loc += f" block{self.block_idx}"
        if self.op_id is not None:
            loc += f" op{self.op_id}"
        if self.op_type:
            loc += f" ({self.op_type})"
        if self.var:
            loc += f" var {self.var!r}"
        return loc

    def __str__(self):
        s = (f"{self.location}: [{self.pass_name}/{self.severity}] "
             f"{self.message}")
        if self.callstack:
            s += "".join(f"\n    at {fr}" for fr in self.callstack)
        return s

    __repr__ = __str__


class ProgramVerificationError(RuntimeError):
    """Raised by maybe_verify_program when ERROR findings exist and
    FLAGS_verify_program is 'on'."""

    def __init__(self, findings: List[Finding]):
        self.findings = findings
        lines = "\n".join(f"  {f}" for f in findings)
        super().__init__(
            f"program verifier found {len(findings)} error(s) "
            f"(set FLAGS_verify_program=warn to continue anyway, "
            f"FLAGS_op_callstack=1 for construction stacks):\n{lines}")


class VerifyContext:
    """Everything a pass may consult.  `feed_names` / `scope_names` /
    `fetch_names` / `donated` are None when unknown (standalone
    verification) — passes must degrade gracefully rather than
    false-positive."""

    def __init__(self, program, feed_names=None, fetch_names=None,
                 scope_names=None, donated=None):
        self.program = program
        self.feed_names = set(feed_names) if feed_names is not None \
            else None
        self.fetch_names = list(fetch_names) if fetch_names is not None \
            else None
        self.scope_names = set(scope_names) if scope_names is not None \
            else None
        self.donated = set(donated) if donated is not None else set()

    @property
    def prog_id(self) -> int:
        return getattr(self.program, "prog_id", id(self.program))

    def external_names(self) -> Set[str]:
        out: Set[str] = set()
        if self.feed_names:
            out |= self.feed_names
        if self.scope_names:
            out |= self.scope_names
        return out

    def finding(self, severity, pass_name, message, block=None, op=None,
                var=None) -> Finding:
        callstack = None
        if op is not None and isinstance(op.attrs.get("op_callstack"),
                                         (list, tuple)):
            callstack = list(op.attrs["op_callstack"])
        return Finding(
            severity, pass_name, message, self.prog_id,
            block_idx=(block.idx if block is not None
                       else (op.block.idx if op is not None else None)),
            op_id=op.id if op is not None else None,
            op_type=op.type if op is not None else None,
            var=var, callstack=callstack)


# ---------------------------------------------------------------------------
# Pass registry
# ---------------------------------------------------------------------------

# name -> (tier, fn); insertion order is execution order
_PASSES: "Dict[str, tuple]" = {}


def register_pass(name: str, tier: str = ERROR):
    """Register `fn(ctx: VerifyContext) -> List[Finding]` under `name`.
    ERROR-tier passes run on every compile-cache miss; WARNING-tier
    passes only run through explicit `verify_program` calls (tpulint,
    tests, tooling)."""

    def deco(fn: Callable):
        _PASSES[name] = (tier, fn)
        return fn

    return deco


def registered_passes(tier: Optional[str] = None) -> List[str]:
    return [n for n, (t, _f) in _PASSES.items()
            if tier is None or t == tier]


# ---------------------------------------------------------------------------
# Structural passes (ERROR tier)
# ---------------------------------------------------------------------------

@register_pass("op-registry")
def check_op_registry(ctx: VerifyContext) -> List[Finding]:
    """Every op type must resolve to a lowering rule in ops/registry
    (grad ops resolve through their forward type)."""
    from ..ops import registry

    out = []
    for blk in ctx.program.blocks:
        for op in blk.ops:
            if op.attr("fwd_op_id") is not None:
                ft = op.attr("fwd_op_type") or (
                    op.type[:-5] if op.type.endswith("_grad")
                    else op.type)
                if registry.has_op(ft) or registry.has_grad(ft):
                    continue
                out.append(ctx.finding(
                    ERROR, "op-registry",
                    f"grad op references forward type {ft!r} which has "
                    f"no registered lowering", op=op))
            elif not registry.has_op(op.type):
                out.append(ctx.finding(
                    ERROR, "op-registry",
                    f"op type {op.type!r} has no lowering rule in "
                    f"ops/registry — lowering this block would fail",
                    op=op))
    return out


def _safe_parent(program, blk):
    p = blk.parent_idx
    if isinstance(p, int) and 0 <= p < len(program.blocks) \
            and p != blk.idx:
        return program.blocks[p]
    return None


def _resolvable(program, blk, name: str) -> bool:
    """Whether `name` resolves in the block-scoped symbol table
    (corruption-tolerant: never raises on bad parent links)."""
    seen = set()
    b = blk
    while b is not None and b.idx not in seen:
        if name in b.vars:
            return True
        seen.add(b.idx)
        b = _safe_parent(program, b)
    return False


@register_pass("def-before-use")
def check_def_before_use(ctx: VerifyContext) -> List[Finding]:
    """Inputs must be defined before use under block scoping rules:
    produced by an earlier op (this block or an ancestor at the
    sub-block's call site), declared as data/persistable (fed or
    scope-resident at run time), or — inside a `while` body — a
    loop-carried var that resolves outside the loop."""
    prog = ctx.program
    findings: List[Finding] = []
    ext = ctx.external_names()
    all_written = {n for blk in prog.blocks for op in blk.ops
                   for n in op.output_arg_names() if n != _EMPTY}

    def block_entry(blk) -> Set[str]:
        return {v.name for v in blk.vars.values()
                if getattr(v, "is_data", False) or v.persistable}

    def walk(blk, avail: Set[str], owner_type: Optional[str],
             visited: Set[int]):
        avail = set(avail) | block_entry(blk) | ext
        entry_avail = set(avail)
        first_write: Dict[str, int] = {}
        for i, op in enumerate(blk.ops):
            for n in op.output_arg_names():
                if n != _EMPTY and n not in first_write:
                    first_write[n] = i
        for i, op in enumerate(blk.ops):
            for n in op.input_arg_names():
                if n == _EMPTY or n in avail:
                    continue
                fw = first_write.get(n)
                if fw is not None:
                    # written in this block, but only at op index >= i
                    loop_carried = (owner_type in _LOOP_OWNERS
                                    and (n in entry_avail
                                         or _resolvable(prog, blk, n)))
                    if not loop_carried:
                        findings.append(ctx.finding(
                            ERROR, "def-before-use",
                            f"input {n!r} is read before it is written "
                            f"(first write is op{blk.ops[fw].id} "
                            f"{blk.ops[fw].type!r} at position {fw})",
                            op=op))
                        avail.add(n)  # report once per name
                elif _resolvable(prog, blk, n) or n in all_written:
                    # declared somewhere: the value must arrive via
                    # feed or scope at run time — the executor's own
                    # "neither fed nor initialized" check owns that
                    # diagnosis when feed/scope info says otherwise
                    pass
                else:
                    findings.append(ctx.finding(
                        ERROR, "def-before-use",
                        f"input {n!r} is not defined in any reachable "
                        f"block scope and no op ever writes it",
                        op=op))
                    avail.add(n)
            sb = op.attr("sub_block")
            if isinstance(sb, int) and 0 < sb < len(prog.blocks) \
                    and sb not in visited:
                walk(prog.blocks[sb], avail, op.type, visited | {sb})
            for n in op.output_arg_names():
                if n != _EMPTY:
                    avail.add(n)

    if prog.blocks:
        walk(prog.blocks[0], set(), None, {0})
    return findings


@register_pass("block-linkage")
def check_block_linkage(ctx: VerifyContext) -> List[Finding]:
    """Control-flow sub-block references resolve; parent links are in
    range and acyclic; unreferenced non-root blocks are flagged."""
    prog = ctx.program
    n = len(prog.blocks)
    out: List[Finding] = []
    for pos, blk in enumerate(prog.blocks):
        if blk.idx != pos:
            out.append(ctx.finding(
                ERROR, "block-linkage",
                f"block at position {pos} carries idx {blk.idx}",
                block=blk))
        p = blk.parent_idx
        if blk.idx == 0:
            if p != -1:
                out.append(ctx.finding(
                    ERROR, "block-linkage",
                    f"global block has parent_idx {p} (must be -1)",
                    block=blk))
            continue
        if not isinstance(p, int) or not (-1 <= p < n) or p == blk.idx:
            out.append(ctx.finding(
                ERROR, "block-linkage",
                f"dangling parent link: parent_idx {p} does not "
                f"resolve", block=blk))
            continue
        seen: Set[int] = set()
        b = blk
        while b is not None:
            if b.idx in seen:
                out.append(ctx.finding(
                    ERROR, "block-linkage",
                    f"parent chain of block {blk.idx} is cyclic",
                    block=blk))
                break
            seen.add(b.idx)
            b = _safe_parent(prog, b)

    referenced: Set[int] = set()
    for blk in prog.blocks:
        for op in blk.ops:
            if not op.has_attr("sub_block"):
                continue
            sb = op.attr("sub_block")
            if not isinstance(sb, int) or not (0 < sb < n):
                out.append(ctx.finding(
                    ERROR, "block-linkage",
                    f"sub_block attr {sb!r} does not resolve to a "
                    f"block (program has {n})", op=op))
                continue
            referenced.add(sb)
            if prog.blocks[sb].parent_idx != blk.idx:
                out.append(ctx.finding(
                    WARNING, "block-linkage",
                    f"sub-block {sb} has parent {prog.blocks[sb].parent_idx}, "
                    f"not the owning block {blk.idx}", op=op))
    for blk in prog.blocks[1:]:
        if blk.idx not in referenced:
            out.append(ctx.finding(
                WARNING, "block-linkage",
                f"block {blk.idx} is referenced by no sub_block attr "
                f"(unreachable)", block=blk))
    return out


# ---------------------------------------------------------------------------
# Dataflow passes
# ---------------------------------------------------------------------------

@register_pass("donation-safety")
def check_donation_safety(ctx: VerifyContext) -> List[Finding]:
    """A var that is both fetched and donated is an error: the donated
    buffer may be reused by XLA while a LazyFetch handle still
    references it (the Executor shields its own state donation with a
    device-side copy; explicitly donated feeds have no such shield)."""
    if not ctx.donated or not ctx.fetch_names:
        return []
    out = []
    for name in sorted(set(ctx.donated) & set(ctx.fetch_names)):
        out.append(ctx.finding(
            ERROR, "donation-safety",
            f"variable {name!r} is both fetched and donated — the "
            f"LazyFetch handle would reference a buffer XLA is free to "
            f"reuse; fetch a copy or drop the donation", var=name))
    return out


@register_pass("collective-order")
def check_collective_order(ctx: VerifyContext) -> List[Finding]:
    """Cross-replica collective-order consistency: every program path
    must issue collectives in the same ring-id order.  A collective
    under a conditional sub-block executes on some paths and not
    others, so replicas whose condition differs deadlock (or silently
    mismatch rings); a collective in a `while` body is order-consistent
    only if the trip count is replica-uniform, which cannot be proven
    statically — flagged as a warning.  Point-to-point send/recv are
    exempt: the p2p pairing queue at lowering owns their diagnosis."""
    prog = ctx.program
    out: List[Finding] = []

    def walk(blk, in_cond: bool, in_loop: bool, visited: Set[int]):
        for op in blk.ops:
            if _is_collective(op.type) and op.type not in _P2P:
                ring = op.attr("ring_id", 0)
                if in_cond:
                    out.append(ctx.finding(
                        ERROR, "collective-order",
                        f"collective issued under a conditional "
                        f"sub-block (ring {ring}): replicas whose "
                        f"condition differs issue collectives in "
                        f"different order and the lowering is "
                        f"nondeterministic across hosts — hoist it out "
                        f"of the branch", op=op))
                elif in_loop:
                    out.append(ctx.finding(
                        WARNING, "collective-order",
                        f"collective inside a while body (ring {ring}): "
                        f"the trip count must be identical on every "
                        f"replica or collective order diverges", op=op))
            sb = op.attr("sub_block")
            if isinstance(sb, int) and 0 < sb < len(prog.blocks) \
                    and sb not in visited:
                walk(prog.blocks[sb],
                     in_cond or op.type in _CONDITIONAL_OWNERS,
                     in_loop or op.type in _LOOP_OWNERS,
                     visited | {sb})

    if prog.blocks:
        walk(prog.blocks[0], False, False, {0})
    return out


def _global_reads(prog) -> Set[str]:
    return {n for blk in prog.blocks for op in blk.ops
            for n in op.input_arg_names() if n != _EMPTY}


def _var_of(prog, blk, name: str):
    seen = set()
    b = blk
    while b is not None and b.idx not in seen:
        if name in b.vars:
            return b.vars[name]
        seen.add(b.idx)
        b = _safe_parent(prog, b)
    return None


@register_pass("dead-op", tier=WARNING)
def check_dead_ops(ctx: VerifyContext) -> List[Finding]:
    """Ops whose outputs are never read, fetched, or persisted do pure
    wasted work (XLA DCEs them, but they still cost trace time and
    usually indicate a graph-construction bug).  Needs fetch info —
    skipped when `fetch_names` is unknown."""
    if ctx.fetch_names is None:
        return []
    prog = ctx.program
    reads = _global_reads(prog)
    fetch = set(ctx.fetch_names)
    out = []
    for blk in prog.blocks:
        for op in blk.ops:
            if op.type in _EFFECT_OPS or _is_collective(op.type) \
                    or op.has_attr("sub_block"):
                continue
            outs = [n for n in op.output_arg_names() if n != _EMPTY]
            if not outs:
                continue  # no-output ops are presumed effectful
            live = False
            for n in outs:
                v = _var_of(prog, blk, n)
                if n in reads or n in fetch \
                        or (v is not None and v.persistable):
                    live = True
                    break
            if not live:
                out.append(ctx.finding(
                    WARNING, "dead-op",
                    f"dead op: outputs {outs} are never read, fetched, "
                    f"or persisted", op=op))
    return out


@register_pass("write-never-read", tier=WARNING)
def check_write_never_read(ctx: VerifyContext) -> List[Finding]:
    """Vars written but never read anywhere (and not fetched /
    persistable / data) — usually a dangling output slot.  Needs fetch
    info — skipped when `fetch_names` is unknown."""
    if ctx.fetch_names is None:
        return []
    prog = ctx.program
    reads = _global_reads(prog)
    fetch = set(ctx.fetch_names)
    out = []
    reported: Set[str] = set()
    for blk in prog.blocks:
        for op in blk.ops:
            for n in op.output_arg_names():
                if n == _EMPTY or n in reads or n in fetch \
                        or n in reported:
                    continue
                v = _var_of(prog, blk, n)
                if v is not None and (v.persistable
                                      or getattr(v, "is_data", False)):
                    continue
                reported.add(n)
                out.append(ctx.finding(
                    WARNING, "write-never-read",
                    f"variable {n!r} is written but never read",
                    op=op, var=n))
    return out


@register_pass("partition-spec", tier=WARNING)
def check_partition_specs(ctx: VerifyContext) -> List[Finding]:
    """SPMD layout sanity (docs/spmd.md): a registered PartitionSpec
    override or a ZeRO `_sharding_axes` annotation that names an axis
    absent from the active mesh, or whose sharded dim does not divide
    the var's dim, silently degrades to replicated at compile — flag it
    here instead.  Needs an active mesh (`parallel.mesh.current_mesh`)
    — skipped outside any mesh context."""
    try:
        from ..parallel import mesh as mesh_lib
        from ..parallel import spec_layout
    except Exception:  # noqa: BLE001 - jax-less tooling environments
        return []
    mesh = mesh_lib.current_mesh()
    if mesh is None:
        return []
    prog = ctx.program
    overrides = spec_layout.registered_specs()

    # post-propagation shapes (ISSUE 18 satellite): a var whose shape a
    # transform rewrote is validated against what actually flows, not
    # the stale declared metadata.  Computed lazily — only when some
    # var carries a spec to check.
    _prop: Dict[str, tuple] = {}
    _prop_done = [False]

    def actual_shape(v, declared: tuple) -> tuple:
        if not _prop_done[0]:
            _prop_done[0] = True
            try:
                from . import shard_check
                _prop.update(shard_check.propagated_shapes(prog))
            except Exception:  # noqa: BLE001 - degrade to declared
                pass
        got = _prop.get(v.name)
        if got is None:
            return declared
        shape = got[0]
        if shape is None or len(shape) != len(declared):
            return declared
        # keep declared dims where propagation went symbolic
        return tuple(d if p in (-1, None) else int(p)
                     for p, d in zip(shape, declared))

    out = []
    seen: Set[str] = set()
    for blk in prog.blocks:
        for name, v in blk.vars.items():
            if name in seen:
                continue
            seen.add(name)
            shape = tuple(int(s) for s in (v.shape or ()))
            if shape and (name in overrides
                          or getattr(v, "_sharding_axes", None)):
                shape = actual_shape(v, shape)
            problems: List[str] = []
            if name in overrides:
                problems = spec_layout.validate_spec(
                    overrides[name], shape, mesh)
            else:
                axes = getattr(v, "_sharding_axes", None)
                if axes and shape and shape[0] > 1:
                    fits = [ax for ax in axes if ax in mesh.axis_names
                            and shape[0] % mesh.shape[ax] == 0]
                    if not fits:
                        missing = [ax for ax in axes
                                   if ax not in mesh.axis_names]
                        if missing:
                            problems.append(
                                f"sharding axes {tuple(axes)} name "
                                f"{missing} absent from mesh axes "
                                f"{tuple(mesh.axis_names)}")
                        else:
                            problems.append(
                                f"dim 0 of size {shape[0]} not divisible "
                                f"by any of its sharding axes "
                                f"{tuple(axes)} on mesh "
                                f"{dict(mesh.shape)}")
            if not problems:
                continue
            # provenance: the first op that touches the var
            op = None
            for o in blk.ops:
                if name in o.output_arg_names() \
                        or name in o.input_arg_names():
                    op = o
                    break
            for p in problems:
                out.append(ctx.finding(
                    WARNING, "partition-spec",
                    f"partition spec for {name!r} degrades to "
                    f"replicated: {p}", block=blk, op=op, var=name))
    # repeated verifications of one program version (eval clones,
    # cache-miss storms) re-reported identical misfits on every run —
    # dedup through the same registry as the warn-mode fix, cleared by
    # reset_finding_dedup()
    if len(_REPORTED) > _MAX_REPORTED:
        _REPORTED.clear()
    fresh = []
    for f in out:
        key = _finding_key(prog, f)
        if key not in _REPORTED:
            _REPORTED.add(key)
            fresh.append(f)
    return fresh


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

def _scope_name_set(scope) -> Optional[Set[str]]:
    if scope is None:
        return None
    names: Set[str] = set()
    s = scope
    while s is not None:
        vs = getattr(s, "_vars", None)
        if vs is None:
            break
        names.update(vs)
        s = getattr(s, "parent", None)
    return names


def _fetch_name(v) -> str:
    return v.name if hasattr(v, "name") else str(v)


def verify_program(program, feed=None, fetch_list=None, scope=None,
                   donated=None, passes: Optional[Iterable[str]] = None,
                   tiers: Optional[Iterable[str]] = None) \
        -> List[Finding]:
    """Run the verifier pipeline; returns the findings (empty = clean).

    feed:       feed dict or iterable of feed names (None = unknown)
    fetch_list: Variables or names the caller will fetch (None = unknown)
    scope:      executor Scope whose vars count as defined-at-entry
    donated:    var names whose buffers are donated to XLA
    passes:     restrict to these pass names
    tiers:      restrict to these tiers (e.g. ("error",))
    """
    feed_names = None
    if feed is not None:
        feed_names = set(feed.keys() if hasattr(feed, "keys") else feed)
    fetch_names = None
    if fetch_list is not None:
        fetch_names = [_fetch_name(v) for v in fetch_list]
    ctx = VerifyContext(program, feed_names=feed_names,
                        fetch_names=fetch_names,
                        scope_names=_scope_name_set(scope),
                        donated=donated)
    tiers = set(tiers) if tiers is not None else None
    wanted = set(passes) if passes is not None else None
    findings: List[Finding] = []
    for name, (tier, fn) in _PASSES.items():
        if wanted is not None and name not in wanted:
            continue
        if tiers is not None and tier not in tiers:
            continue
        findings.extend(fn(ctx))
    return findings


# warn-mode finding dedup (ISSUE 11 satellite): repeated
# maybe_verify_program calls on the same program — or on clone-identical
# programs (an eval clone re-verified under a new feed signature) —
# previously re-warned the identical findings on every compile-cache
# miss.  Keyed on (clone family, program version, finding identity), so
# a finding re-surfaces only when the program actually changes.
_REPORTED: Set[tuple] = set()
_MAX_REPORTED = 4096  # bounded: clear-on-full beats unbounded growth


def _finding_key(program, f: Finding) -> tuple:
    root = getattr(program, "clone_root",
                   getattr(program, "prog_id", id(program)))
    return (root, getattr(program, "version", 0), f.pass_name,
            f.severity, f.block_idx, f.op_id, f.op_type, f.var,
            f.message)


def reset_finding_dedup() -> None:
    """Forget which findings were already warned about (tests)."""
    _REPORTED.clear()


def maybe_verify_program(program, feed_names=None, fetch_names=None,
                         scope=None, donated=None) -> None:
    """Compile-cache-miss hook for Executor._prepare /
    CompiledProgram._compile: run the ERROR-tier passes under the
    FLAGS_verify_program gate.  Raises ProgramVerificationError on
    ERROR findings ('on'), warns and continues ('warn'), or is a no-op
    ('off').  Never runs on a cache hit — callers sit behind the
    compile cache — and books its wall time on the `verify_ms`
    profiler timer so the hot path stays provably free."""
    from ..fluid.flags import flag

    mode = str(flag("verify_program", "on")).lower()
    if mode in ("off", "0", "false", "no"):
        return
    from ..obs import span as obs_span
    from ..profiler import stat_add, timed

    with obs_span("verifier.run"), timed("verify_ms"):
        findings = verify_program(program, feed=feed_names,
                                  fetch_list=fetch_names, scope=scope,
                                  donated=donated, tiers=(ERROR,))
        errors = [f for f in findings if f.severity == ERROR]
        warns = [f for f in findings if f.severity == WARNING]
        stat_add("verifier_runs")
        if errors:
            stat_add("verifier_errors", len(errors))
        if warns:
            # ERROR-tier passes may emit WARNING-severity findings
            # (shard-consistency clamps / resharding predictions);
            # previously these were silently dropped here
            stat_add("verifier_warnings", len(warns))
    if warns and len(_REPORTED) <= _MAX_REPORTED:
        fresh_warns = []
        for f in warns:
            key = _finding_key(program, f)
            if key not in _REPORTED:
                _REPORTED.add(key)
                fresh_warns.append(f)
        if fresh_warns:
            import logging
            logging.getLogger("paddle_tpu.verifier").warning(
                "program verifier warnings:\n%s",
                "\n".join(f"  {f}" for f in fresh_warns))
    if not errors:
        return
    if mode in ("warn", "warning"):
        if len(_REPORTED) > _MAX_REPORTED:
            _REPORTED.clear()
        fresh = []
        for f in errors:
            key = _finding_key(program, f)
            if key not in _REPORTED:
                _REPORTED.add(key)
                fresh.append(f)
        if not fresh:
            return  # every finding already reported for this version
        warnings.warn(
            "program verifier found {} error(s) "
            "(FLAGS_verify_program=warn):\n{}".format(
                len(fresh), "\n".join(f"  {f}" for f in fresh)),
            RuntimeWarning, stacklevel=3)
        return
    raise ProgramVerificationError(errors)
