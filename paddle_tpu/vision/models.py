"""paddle.vision.models — LeNet and ResNet variants as dygraph Layers.

Reference: /root/reference/python/paddle/vision/models (lenet.py,
resnet.py: resnet18/34/50/101/152, vgg.py, mobilenetv1.py,
mobilenetv2.py).  The static-graph ResNet used for
the image-classification benchmark lives in
paddle_tpu/models/resnet.py; these are the 2.0 eager-Layer builds.
"""

from __future__ import annotations

from .. import nn

__all__ = ["LeNet", "ResNet", "resnet18", "resnet34", "resnet50",
           "resnet101", "resnet152", "VGG", "vgg11", "vgg13", "vgg16",
           "vgg19", "MobileNetV1", "MobileNetV2", "mobilenet_v1",
           "mobilenet_v2"]


class LeNet(nn.Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2))
        self.flatten = nn.Flatten()
        self.fc = nn.Sequential(
            nn.Linear(400, 120), nn.ReLU(),
            nn.Linear(120, 84), nn.ReLU(),
            nn.Linear(84, num_classes))

    def forward(self, x):
        return self.fc(self.flatten(self.features(x)))


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride,
                               padding=1, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(planes)
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2D(inplanes, planes, 1, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(planes)
        self.conv2 = nn.Conv2D(planes, planes, 3, stride=stride,
                               padding=1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(planes)
        self.conv3 = nn.Conv2D(planes, planes * 4, 1, bias_attr=False)
        self.bn3 = nn.BatchNorm2D(planes * 4)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        return self.relu(out + identity)


class ResNet(nn.Layer):
    def __init__(self, block, depth_cfg, num_classes=1000, in_ch=3):
        super().__init__()
        self.inplanes = 64
        self.conv1 = nn.Conv2D(in_ch, 64, 7, stride=2, padding=3,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(64)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, depth_cfg[0])
        self.layer2 = self._make_layer(block, 128, depth_cfg[1], 2)
        self.layer3 = self._make_layer(block, 256, depth_cfg[2], 2)
        self.layer4 = self._make_layer(block, 512, depth_cfg[3], 2)
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.flatten = nn.Flatten()
        self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, n, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False),
                nn.BatchNorm2D(planes * block.expansion))
        layers = [block(self.inplanes, planes, stride, downsample)]
        self.inplanes = planes * block.expansion
        for _ in range(1, n):
            layers.append(block(self.inplanes, planes))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        return self.fc(self.flatten(self.avgpool(x)))


def resnet18(num_classes=1000, **kw):
    return ResNet(BasicBlock, [2, 2, 2, 2], num_classes, **kw)


def resnet34(num_classes=1000, **kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], num_classes, **kw)


def resnet50(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], num_classes, **kw)


def resnet101(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], num_classes, **kw)


def resnet152(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 8, 36, 3], num_classes, **kw)


def _make_divisible(v, divisor=8, min_value=None):
    """reference vision/models/mobilenetv2.py _make_divisible: round
    channel counts to multiples of `divisor`, never dropping more than
    10%% — required for reference-checkpoint shape compatibility."""
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class VGG(nn.Layer):
    """VGG (reference vision/models/vgg.py): conv stages from a cfg list,
    adaptive pool to 7x7, 3-layer classifier."""

    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.with_pool = with_pool
        self.flatten = nn.Flatten()
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        self.classifier = nn.Sequential(
            nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(),
            nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        return self.classifier(self.flatten(x))


_VGG_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
          512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
          512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


def _vgg_features(cfg, batch_norm=False):
    layers, in_ch = [], 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(2, 2))
        else:
            layers.append(nn.Conv2D(in_ch, v, 3, padding=1))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v))
            layers.append(nn.ReLU())
            in_ch = v
    return nn.Sequential(*layers)


def vgg11(batch_norm=False, **kw):
    return VGG(_vgg_features(_VGG_CFGS["A"], batch_norm), **kw)


def vgg13(batch_norm=False, **kw):
    return VGG(_vgg_features(_VGG_CFGS["B"], batch_norm), **kw)


def vgg16(batch_norm=False, **kw):
    return VGG(_vgg_features(_VGG_CFGS["D"], batch_norm), **kw)


def vgg19(batch_norm=False, **kw):
    return VGG(_vgg_features(_VGG_CFGS["E"], batch_norm), **kw)


class _ConvBNReLU(nn.Layer):
    def __init__(self, in_c, out_c, k, stride=1, padding=0, groups=1,
                 act=True):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride,
                              padding=padding, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = nn.ReLU6() if act else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act is not None else x


class MobileNetV1(nn.Layer):
    """reference vision/models/mobilenetv1.py: depthwise-separable
    stacks; on TPU the depthwise convs lower to grouped XLA convolutions."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        s = lambda c: _make_divisible(c * scale)
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        layers = [_ConvBNReLU(3, s(32), 3, stride=2, padding=1)]
        for in_c, out_c, stride in cfg:
            layers.append(_ConvBNReLU(s(in_c), s(in_c), 3, stride=stride,
                                      padding=1, groups=s(in_c)))
            layers.append(_ConvBNReLU(s(in_c), s(out_c), 1))
        self.features = nn.Sequential(*layers)
        self.with_pool = with_pool
        self.flatten = nn.Flatten()
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        return self.fc(self.flatten(x))


class _InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand):
        super().__init__()
        hidden = int(round(in_c * expand))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand != 1:
            layers.append(_ConvBNReLU(in_c, hidden, 1))
        layers += [
            _ConvBNReLU(hidden, hidden, 3, stride=stride, padding=1,
                        groups=hidden),
            _ConvBNReLU(hidden, out_c, 1, act=False),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    """reference vision/models/mobilenetv2.py: inverted residuals with
    linear bottlenecks."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        s = lambda c: _make_divisible(c * scale)
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2),
               (6, 64, 4, 2), (6, 96, 3, 1), (6, 160, 3, 2),
               (6, 320, 1, 1)]
        layers = [_ConvBNReLU(3, s(32), 3, stride=2, padding=1)]
        in_c = s(32)
        for expand, c, n, stride in cfg:
            for i in range(n):
                layers.append(_InvertedResidual(
                    in_c, s(c), stride if i == 0 else 1, expand))
                in_c = s(c)
        last = _make_divisible(1280 * max(1.0, scale))
        layers.append(_ConvBNReLU(in_c, last, 1))
        self.features = nn.Sequential(*layers)
        self.with_pool = with_pool
        self.flatten = nn.Flatten()
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Sequential(nn.Dropout(0.2),
                                        nn.Linear(last, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        return self.classifier(self.flatten(x))


def mobilenet_v1(scale=1.0, **kw):
    return MobileNetV1(scale=scale, **kw)


def mobilenet_v2(scale=1.0, **kw):
    return MobileNetV2(scale=scale, **kw)
