"""paddle.vision.models — LeNet and ResNet variants as dygraph Layers.

Reference: /root/reference/python/paddle/vision/models (lenet.py,
resnet.py: resnet18/34/50/101/152).  The static-graph ResNet used for
the image-classification benchmark lives in
paddle_tpu/models/resnet.py; these are the 2.0 eager-Layer builds.
"""

from __future__ import annotations

from .. import nn

__all__ = ["LeNet", "ResNet", "resnet18", "resnet34", "resnet50",
           "resnet101", "resnet152"]


class LeNet(nn.Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2))
        self.flatten = nn.Flatten()
        self.fc = nn.Sequential(
            nn.Linear(400, 120), nn.ReLU(),
            nn.Linear(120, 84), nn.ReLU(),
            nn.Linear(84, num_classes))

    def forward(self, x):
        return self.fc(self.flatten(self.features(x)))


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride,
                               padding=1, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(planes)
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2D(inplanes, planes, 1, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(planes)
        self.conv2 = nn.Conv2D(planes, planes, 3, stride=stride,
                               padding=1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(planes)
        self.conv3 = nn.Conv2D(planes, planes * 4, 1, bias_attr=False)
        self.bn3 = nn.BatchNorm2D(planes * 4)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        return self.relu(out + identity)


class ResNet(nn.Layer):
    def __init__(self, block, depth_cfg, num_classes=1000, in_ch=3):
        super().__init__()
        self.inplanes = 64
        self.conv1 = nn.Conv2D(in_ch, 64, 7, stride=2, padding=3,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(64)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, depth_cfg[0])
        self.layer2 = self._make_layer(block, 128, depth_cfg[1], 2)
        self.layer3 = self._make_layer(block, 256, depth_cfg[2], 2)
        self.layer4 = self._make_layer(block, 512, depth_cfg[3], 2)
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.flatten = nn.Flatten()
        self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, n, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False),
                nn.BatchNorm2D(planes * block.expansion))
        layers = [block(self.inplanes, planes, stride, downsample)]
        self.inplanes = planes * block.expansion
        for _ in range(1, n):
            layers.append(block(self.inplanes, planes))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        return self.fc(self.flatten(self.avgpool(x)))


def resnet18(num_classes=1000, **kw):
    return ResNet(BasicBlock, [2, 2, 2, 2], num_classes, **kw)


def resnet34(num_classes=1000, **kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], num_classes, **kw)


def resnet50(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], num_classes, **kw)


def resnet101(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], num_classes, **kw)


def resnet152(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 8, 36, 3], num_classes, **kw)
