"""paddle.vision.transforms — numpy-based image preprocessing.

Reference: /root/reference/python/paddle/vision/transforms (Compose,
Resize, RandomCrop, RandomHorizontalFlip, Normalize, ToTensor, ...).
TPU-native note: transforms run HOST-side on numpy (they feed the
DataLoader's worker threads); nothing here touches the device — the
accelerator sees only the final batched arrays.
"""

from __future__ import annotations

import numbers
import random

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "Pad"]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor:
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        a = np.asarray(img)
        if a.ndim == 2:
            a = a[:, :, None]
        a = a.astype("float32") / 255.0
        if self.data_format == "CHW":
            a = np.transpose(a, (2, 0, 1))
        return a


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW"):
        self.mean = np.asarray(mean, "float32")
        self.std = np.asarray(std, "float32")
        self.data_format = data_format

    def __call__(self, img):
        a = np.asarray(img, "float32")
        shape = ((-1, 1, 1) if self.data_format == "CHW"
                 else (1, 1, -1))
        return (a - self.mean.reshape(shape)) / self.std.reshape(shape)


def _hwc(a):
    if a.ndim == 2:
        return a[:, :, None], True
    return a, False


class Resize:
    """Nearest-neighbor resize (no PIL dependency on the image).

    An int size resizes the SHORTER edge to that length preserving
    aspect ratio (reference paddle.vision.transforms.Resize); a
    (h, w) pair resizes to exactly that shape.
    """

    def __init__(self, size):
        self.size = int(size) if isinstance(size, numbers.Number) \
            else tuple(size)

    def __call__(self, img):
        a = np.asarray(img)
        a, squeeze = _hwc(a)
        if isinstance(self.size, int):
            # int() truncation, matching reference functional_cv2.resize
            ih, iw = a.shape[:2]
            if ih <= iw:
                h, w = self.size, max(1, int(iw * self.size / ih))
            else:
                h, w = max(1, int(ih * self.size / iw)), self.size
        else:
            h, w = self.size
        ys = (np.arange(h) * a.shape[0] / h).astype(int)
        xs = (np.arange(w) * a.shape[1] / w).astype(int)
        out = a[ys][:, xs]
        return out[:, :, 0] if squeeze else out


def _pad_to(a, h, w):
    """Zero-pad so the array is at least (h, w): crops always return
    the REQUESTED size (a silent smaller output would blow up later at
    batch stacking, far from the cause)."""
    ph = max(0, h - a.shape[0])
    pw = max(0, w - a.shape[1])
    if ph or pw:
        a = np.pad(a, ((ph // 2, ph - ph // 2),
                       (pw // 2, pw - pw // 2), (0, 0)))
    return a


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)

    def __call__(self, img):
        a = np.asarray(img)
        a, squeeze = _hwc(a)
        h, w = self.size
        a = _pad_to(a, h, w)
        y = (a.shape[0] - h) // 2
        x = (a.shape[1] - w) // 2
        out = a[y:y + h, x:x + w]
        return out[:, :, 0] if squeeze else out


class RandomCrop:
    def __init__(self, size, pad_if_needed=True):
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)
        self.pad_if_needed = pad_if_needed

    def __call__(self, img):
        a = np.asarray(img)
        a, squeeze = _hwc(a)
        h, w = self.size
        if self.pad_if_needed:
            a = _pad_to(a, h, w)
        elif a.shape[0] < h or a.shape[1] < w:
            raise ValueError(
                f"RandomCrop{self.size}: image {a.shape[:2]} is smaller "
                "and pad_if_needed=False")
        y = random.randint(0, max(0, a.shape[0] - h))
        x = random.randint(0, max(0, a.shape[1] - w))
        out = a[y:y + h, x:x + w]
        return out[:, :, 0] if squeeze else out


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        a = np.asarray(img)
        if a.ndim == 2:
            a = a[:, :, None]
        return np.transpose(a, self.order)


class Pad:
    """padding: int (all sides), (pad_x, pad_y), or (l, t, r, b) —
    the three forms the reference Pad transform accepts."""

    def __init__(self, padding, fill=0):
        if isinstance(padding, numbers.Number):
            p = int(padding)
            padding = (p, p, p, p)
        elif len(padding) == 2:
            px, py = padding
            padding = (px, py, px, py)
        elif len(padding) != 4:
            raise ValueError(
                f"Pad: padding must be an int, a (pad_x, pad_y) pair or "
                f"an (l, t, r, b) 4-tuple, got {padding!r}")
        self.padding = tuple(padding)
        self.fill = fill

    def __call__(self, img):
        a = np.asarray(img)
        a, squeeze = _hwc(a)
        l, t, r, b = self.padding
        out = np.pad(a, ((t, b), (l, r), (0, 0)), constant_values=self.fill)
        return out[:, :, 0] if squeeze else out
