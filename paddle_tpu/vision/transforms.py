"""paddle.vision.transforms — numpy-based image preprocessing.

Reference: /root/reference/python/paddle/vision/transforms (Compose,
Resize, RandomCrop, RandomHorizontalFlip, Normalize, ToTensor, ...).
TPU-native note: transforms run HOST-side on numpy (they feed the
DataLoader's worker threads); nothing here touches the device — the
accelerator sees only the final batched arrays.
"""

from __future__ import annotations

import numbers
import random

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "Pad",
           # round-5 tail (classes + functional re-exports below)
           "BaseTransform", "RandomResizedCrop", "BrightnessTransform",
           "ContrastTransform", "SaturationTransform", "HueTransform",
           "ColorJitter", "RandomRotation", "Grayscale",
           "to_tensor", "resize", "pad", "crop", "center_crop",
           "hflip", "vflip", "adjust_brightness", "adjust_contrast",
           "adjust_saturation", "adjust_hue", "rotate", "to_grayscale",
           "normalize", "functional"]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor:
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        a = np.asarray(img)
        if a.ndim == 2:
            a = a[:, :, None]
        a = a.astype("float32") / 255.0
        if self.data_format == "CHW":
            a = np.transpose(a, (2, 0, 1))
        return a


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW"):
        self.mean = np.asarray(mean, "float32")
        self.std = np.asarray(std, "float32")
        self.data_format = data_format

    def __call__(self, img):
        a = np.asarray(img, "float32")
        shape = ((-1, 1, 1) if self.data_format == "CHW"
                 else (1, 1, -1))
        return (a - self.mean.reshape(shape)) / self.std.reshape(shape)


def _hwc(a):
    if a.ndim == 2:
        return a[:, :, None], True
    return a, False


class Resize:
    """Resize (no PIL dependency — numpy sampling in
    transforms_functional.resize, the single implementation).

    An int size resizes the SHORTER edge to that length preserving
    aspect ratio (reference paddle.vision.transforms.Resize); a
    (h, w) pair resizes to exactly that shape.  Default interpolation
    is bilinear like the reference class.
    """

    def __init__(self, size, interpolation="bilinear"):
        self.size = int(size) if isinstance(size, numbers.Number) \
            else tuple(size)
        self.interpolation = interpolation

    def __call__(self, img):
        from . import transforms_functional as F_

        return F_.resize(img, self.size, self.interpolation)


def _pad_to(a, h, w):
    """Zero-pad so the array is at least (h, w): crops always return
    the REQUESTED size (a silent smaller output would blow up later at
    batch stacking, far from the cause)."""
    ph = max(0, h - a.shape[0])
    pw = max(0, w - a.shape[1])
    if ph or pw:
        a = np.pad(a, ((ph // 2, ph - ph // 2),
                       (pw // 2, pw - pw // 2), (0, 0)))
    return a


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)

    def __call__(self, img):
        a = np.asarray(img)
        a, squeeze = _hwc(a)
        h, w = self.size
        a = _pad_to(a, h, w)
        y = (a.shape[0] - h) // 2
        x = (a.shape[1] - w) // 2
        out = a[y:y + h, x:x + w]
        return out[:, :, 0] if squeeze else out


class RandomCrop:
    def __init__(self, size, pad_if_needed=True):
        self.size = (size, size) if isinstance(size, numbers.Number) \
            else tuple(size)
        self.pad_if_needed = pad_if_needed

    def __call__(self, img):
        a = np.asarray(img)
        a, squeeze = _hwc(a)
        h, w = self.size
        if self.pad_if_needed:
            a = _pad_to(a, h, w)
        elif a.shape[0] < h or a.shape[1] < w:
            raise ValueError(
                f"RandomCrop{self.size}: image {a.shape[:2]} is smaller "
                "and pad_if_needed=False")
        y = random.randint(0, max(0, a.shape[0] - h))
        x = random.randint(0, max(0, a.shape[1] - w))
        out = a[y:y + h, x:x + w]
        return out[:, :, 0] if squeeze else out


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if random.random() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        a = np.asarray(img)
        if a.ndim == 2:
            a = a[:, :, None]
        return np.transpose(a, self.order)


class Pad:
    """padding: int (all sides), (pad_x, pad_y), or (l, t, r, b) —
    the three forms the reference Pad transform accepts."""

    def __init__(self, padding, fill=0):
        if isinstance(padding, numbers.Number):
            p = int(padding)
            padding = (p, p, p, p)
        elif len(padding) == 2:
            px, py = padding
            padding = (px, py, px, py)
        elif len(padding) != 4:
            raise ValueError(
                f"Pad: padding must be an int, a (pad_x, pad_y) pair or "
                f"an (l, t, r, b) 4-tuple, got {padding!r}")
        self.padding = tuple(padding)
        self.fill = fill

    def __call__(self, img):
        a = np.asarray(img)
        a, squeeze = _hwc(a)
        l, t, r, b = self.padding
        out = np.pad(a, ((t, b), (l, r), (0, 0)), constant_values=self.fill)
        return out[:, :, 0] if squeeze else out


# -- round-5 tail: BaseTransform + color/geometry classes over the
# functional module (reference transforms/transforms.py) ----------------------

from . import transforms_functional as _F  # noqa: E402


class BaseTransform:
    """reference transforms.py BaseTransform: keys-aware callable base;
    subclasses implement _apply_image (and optionally _apply_* for
    other keys)."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def __call__(self, inputs):
        if isinstance(inputs, tuple):
            out = []
            for key, data in zip(self.keys, inputs):
                fn = getattr(self, f"_apply_{key}", None)
                out.append(fn(data) if fn else data)
            # elements beyond len(keys) pass through untouched (the
            # reference extends outputs with inputs[len(keys):]) — a
            # (img, label) pipeline must never lose its labels
            out.extend(inputs[len(self.keys):])
            return tuple(out)
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale, self.ratio = scale, ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        import numpy as _np

        a = _F._hwc(img)
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * _np.random.uniform(*self.scale)
            ar = _np.exp(_np.random.uniform(_np.log(self.ratio[0]),
                                            _np.log(self.ratio[1])))
            cw = int(round((target * ar) ** 0.5))
            ch = int(round((target / ar) ** 0.5))
            if 0 < cw <= w and 0 < ch <= h:
                top = _np.random.randint(0, h - ch + 1)
                left = _np.random.randint(0, w - cw + 1)
                patch = _F.crop(img, top, left, ch, cw)
                return _F.resize(patch, self.size, self.interpolation)
        return _F.resize(_F.center_crop(img, min(h, w)), self.size,
                         self.interpolation)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _factor(self):
        import numpy as _np

        return _np.random.uniform(max(0, 1 - self.value), 1 + self.value)

    def _apply_image(self, img):
        return _F.adjust_brightness(img, self._factor()) \
            if self.value > 0 else img


class ContrastTransform(BrightnessTransform):
    def _apply_image(self, img):
        return _F.adjust_contrast(img, self._factor()) \
            if self.value > 0 else img


class SaturationTransform(BrightnessTransform):
    def _apply_image(self, img):
        return _F.adjust_saturation(img, self._factor()) \
            if self.value > 0 else img


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        assert 0 <= value <= 0.5
        self.value = float(value)

    def _apply_image(self, img):
        import numpy as _np

        if self.value == 0:
            return img
        return _F.adjust_hue(
            img, _np.random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.ts = [BrightnessTransform(brightness),
                   ContrastTransform(contrast),
                   SaturationTransform(saturation), HueTransform(hue)]

    def _apply_image(self, img):
        import numpy as _np

        order = _np.random.permutation(len(self.ts))
        for i in order:
            img = self.ts[i]._apply_image(img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        self.degrees = ((-degrees, degrees)
                        if isinstance(degrees, (int, float))
                        else tuple(degrees))
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        import numpy as _np

        angle = _np.random.uniform(*self.degrees)
        return _F.rotate(img, angle, self.interpolation, self.expand,
                         self.center, self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return _F.to_grayscale(img, self.num_output_channels)


functional = _F
"""`paddle.vision.transforms.functional` — the stateless numpy image
ops (reference transforms/functional.py)."""

# make `import paddle_tpu.vision.transforms.functional` work even
# though transforms is a module, not a package (same pattern as
# nn/functional's submodule registration)
import sys as _sys  # noqa: E402

_sys.modules[__name__ + ".functional"] = _F

# reference transforms module also re-exports the functional names
to_tensor = _F.to_tensor
resize = _F.resize
pad = _F.pad
crop = _F.crop
center_crop = _F.center_crop
hflip = _F.hflip
vflip = _F.vflip
adjust_brightness = _F.adjust_brightness
adjust_contrast = _F.adjust_contrast
adjust_saturation = _F.adjust_saturation
adjust_hue = _F.adjust_hue
rotate = _F.rotate
to_grayscale = _F.to_grayscale
normalize = _F.normalize
