"""`paddle.vision.transforms.functional` (reference
python/paddle/vision/transforms/functional.py): the stateless image
ops behind the transform classes.

Images are numpy arrays, HWC (or HW for grayscale), uint8 or float —
the zero-egress analogue of the reference's cv2/PIL backends; every op
is pure numpy so data pipelines stay host-side (the device never sees
un-batched images)."""

from __future__ import annotations

import numpy as np


def _hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def to_tensor(pic, data_format="CHW"):
    """HWC uint8/float -> float32 in [0,1], CHW by default (reference
    functional.py to_tensor)."""
    a = _hwc(pic).astype("float32")
    if np.asarray(pic).dtype == np.uint8:
        a = a / 255.0
    if data_format.upper() == "CHW":
        a = a.transpose(2, 0, 1)
    return a


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    a = np.asarray(img, "float32")
    mean = np.asarray(mean, "float32")
    std = np.asarray(std, "float32")
    if data_format.upper() == "CHW":
        shape = (-1, 1, 1)
    else:
        shape = (1, 1, -1)
    return (a - mean.reshape(shape)) / std.reshape(shape)


def resize(img, size, interpolation="bilinear"):
    """Resize HWC to `size` (int: short side; (h, w): exact) with
    numpy bilinear/nearest sampling."""
    a = _hwc(img)
    h, w = a.shape[:2]
    if isinstance(size, int):
        if h <= w:
            oh, ow = size, max(1, int(round(w * size / h)))
        else:
            oh, ow = max(1, int(round(h * size / w))), size
    else:
        oh, ow = size
    if (oh, ow) == (h, w):
        return a if np.asarray(img).ndim == 3 else a[:, :, 0]
    if interpolation == "nearest":
        ri = np.clip(np.round(np.linspace(0, h - 1, oh)), 0,
                     h - 1).astype(int)
        ci = np.clip(np.round(np.linspace(0, w - 1, ow)), 0,
                     w - 1).astype(int)
        out = a[ri][:, ci]
    else:  # bilinear, align_corners=False convention
        ys = (np.arange(oh) + 0.5) * h / oh - 0.5
        xs = (np.arange(ow) + 0.5) * w / ow - 0.5
        y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
        x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
        y1 = np.clip(y0 + 1, 0, h - 1)
        x1 = np.clip(x0 + 1, 0, w - 1)
        wy = np.clip(ys - y0, 0, 1)[:, None, None]
        wx = np.clip(xs - x0, 0, 1)[None, :, None]
        af = a.astype("float32")
        top = af[y0][:, x0] * (1 - wx) + af[y0][:, x1] * wx
        bot = af[y1][:, x0] * (1 - wx) + af[y1][:, x1] * wx
        out = top * (1 - wy) + bot * wy
        if a.dtype == np.uint8:
            out = np.clip(np.round(out), 0, 255).astype(np.uint8)
        else:
            out = out.astype(a.dtype)
    return out if np.asarray(img).ndim == 3 else out[:, :, 0]


def pad(img, padding, fill=0, padding_mode="constant"):
    a = _hwc(img)
    if isinstance(padding, int):
        pl = pr = pt = pb = padding
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    out = np.pad(a, ((pt, pb), (pl, pr), (0, 0)), mode=mode, **kw)
    return out if np.asarray(img).ndim == 3 else out[:, :, 0]


def crop(img, top, left, height, width):
    a = _hwc(img)
    out = a[top:top + height, left:left + width]
    return out if np.asarray(img).ndim == 3 else out[:, :, 0]


def center_crop(img, output_size):
    a = _hwc(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    th, tw = output_size
    h, w = a.shape[:2]
    return crop(img, max(0, (h - th) // 2), max(0, (w - tw) // 2),
                th, tw)


def hflip(img):
    a = _hwc(img)
    out = a[:, ::-1]
    return out if np.asarray(img).ndim == 3 else out[:, :, 0]


def vflip(img):
    a = _hwc(img)
    out = a[::-1]
    return out if np.asarray(img).ndim == 3 else out[:, :, 0]


def _blend(a, b, ratio):
    out = a.astype("float32") * ratio + b.astype("float32") * (1 - ratio)
    if np.asarray(a).dtype == np.uint8:
        return np.clip(out, 0, 255).astype(np.uint8)
    return out.astype(np.asarray(a).dtype)


def adjust_brightness(img, brightness_factor):
    a = _hwc(img)
    return _blend(a, np.zeros_like(a), brightness_factor)


def adjust_contrast(img, contrast_factor):
    a = _hwc(img)
    mean = to_grayscale(a).astype("float32").mean()
    return _blend(a, np.full_like(a, mean, dtype=a.dtype
                                  if a.dtype != np.uint8 else np.uint8),
                  contrast_factor)


def adjust_saturation(img, saturation_factor):
    a = _hwc(img)
    gray = to_grayscale(a, num_output_channels=a.shape[2])
    return _blend(a, gray, saturation_factor)


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5]) through HSV space.
    Grayscale images (fewer than 3 channels) have no hue — returned
    unchanged, matching the reference's PIL 'L'-mode behavior."""
    assert -0.5 <= hue_factor <= 0.5, hue_factor
    a = _hwc(img)
    if a.shape[2] < 3:
        return np.asarray(img)
    dtype = a.dtype
    f = a.astype("float32") / (255.0 if dtype == np.uint8 else 1.0)
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    mx, mn = f.max(-1), f.min(-1)
    d = mx - mn + 1e-12
    h = np.zeros_like(mx)
    h = np.where(mx == r, ((g - b) / d) % 6, h)
    h = np.where(mx == g, (b - r) / d + 2, h)
    h = np.where(mx == b, (r - g) / d + 4, h)
    h = (h / 6.0 + hue_factor) % 1.0
    s = np.where(mx > 0, d / (mx + 1e-12), 0)
    v = mx
    i = np.floor(h * 6.0)
    fr = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - fr * s)
    t = v * (1 - (1 - fr) * s)
    i = i.astype(int) % 6
    r2 = np.choose(i, [v, q, p, p, t, v])
    g2 = np.choose(i, [t, v, v, q, p, p])
    b2 = np.choose(i, [p, p, t, v, v, q])
    out = np.stack([r2, g2, b2], -1)
    if dtype == np.uint8:
        return np.clip(out * 255.0 + 0.5, 0, 255).astype(np.uint8)
    return out.astype(dtype)


def rotate(img, angle, interpolation="nearest", expand=False,
           center=None, fill=0):
    """Rotate counter-clockwise by `angle` degrees about `center`
    (default: image center), nearest or bilinear sampling."""
    a = _hwc(img).astype("float32")
    h, w = a.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None \
        else (center[1], center[0])
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    if expand:
        corners = np.array([[-cx, -cy], [w - 1 - cx, -cy],
                            [-cx, h - 1 - cy], [w - 1 - cx, h - 1 - cy]])
        rot = corners @ np.array([[cos, sin], [-sin, cos]])
        # round away float epsilon before ceil: cos(90deg) ~ 6e-17
        # would otherwise add a spurious fill row/column
        ow = int(np.ceil(round(rot[:, 0].max() - rot[:, 0].min(),
                               6))) + 1
        oh = int(np.ceil(round(rot[:, 1].max() - rot[:, 1].min(),
                               6))) + 1
        ocx, ocy = (ow - 1) / 2.0, (oh - 1) / 2.0
    else:
        oh, ow, ocx, ocy = h, w, cx, cy
    yy, xx = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
    # inverse map: output coords -> input coords
    xs = (xx - ocx) * cos - (yy - ocy) * sin + cx
    ys = (xx - ocx) * sin + (yy - ocy) * cos + cy
    if interpolation == "bilinear":
        x0 = np.floor(xs).astype(int)
        y0 = np.floor(ys).astype(int)
        wx = (xs - x0)[..., None]
        wy = (ys - y0)[..., None]
        val = 0.0
        for (yi, xi, wgt) in [(y0, x0, (1 - wy) * (1 - wx)),
                              (y0, x0 + 1, (1 - wy) * wx),
                              (y0 + 1, x0, wy * (1 - wx)),
                              (y0 + 1, x0 + 1, wy * wx)]:
            inside = ((yi >= 0) & (yi < h) & (xi >= 0) & (xi < w))
            samp = np.where(
                inside[..., None],
                a[np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)], fill)
            val = val + samp * wgt
        out = val
    else:
        xi = np.round(xs).astype(int)
        yi = np.round(ys).astype(int)
        inside = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        out = np.where(
            inside[..., None],
            a[np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)], fill)
    if np.asarray(img).dtype == np.uint8:
        out = np.clip(np.round(out), 0, 255).astype(np.uint8)
    else:
        out = out.astype(np.asarray(img).dtype)
    return out if np.asarray(img).ndim == 3 else out[:, :, 0]


def to_grayscale(img, num_output_channels=1):
    a = _hwc(img)
    if a.shape[2] == 1:
        gray = a[..., 0].astype("float32")
    else:
        gray = (0.299 * a[..., 0].astype("float32")
                + 0.587 * a[..., 1] + 0.114 * a[..., 2])
    if np.asarray(img).dtype == np.uint8:
        gray = np.clip(np.round(gray), 0, 255).astype(np.uint8)
    else:
        gray = gray.astype(np.asarray(img).dtype)
    return np.repeat(gray[:, :, None], num_output_channels, axis=2)
