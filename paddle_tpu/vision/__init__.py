"""paddle.vision — datasets, transforms, models (reference
python/paddle/vision/, re-based: host-side numpy transforms, IDX/pickle
file parsers with zero-egress contract, eager-Layer models)."""

from . import datasets, models, transforms  # noqa: F401
