"""paddle.vision.datasets — MNIST / Cifar10 / FakeData.

Reference: /root/reference/python/paddle/vision/datasets (mnist.py,
cifar.py) which download + parse the standard archives.  This build is
zero-egress: `download=True` raises with instructions, and the parsers
read the STANDARD file formats (IDX for MNIST, the python-pickle batch
format for CIFAR) from a local path — drop the official files in and
they load.  FakeData generates deterministic synthetic samples for
tests/benchmarks (the reference uses fake readers the same way,
SURVEY §4.2 book tests).
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData",
           "DatasetFolder", "ImageFolder", "Flowers", "VOC2012"]

_NO_DOWNLOAD = ("this TPU build runs zero-egress: download the official "
                "archive on a connected machine and pass the local "
                "path(s)")


class MNIST(Dataset):
    """IDX-format MNIST (reference vision/datasets/mnist.py).

    Pass image_path/label_path to the (optionally gzipped) idx files.
    """

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if download or image_path is None or label_path is None:
            raise ValueError(f"MNIST: image_path and label_path are "
                             f"required ({_NO_DOWNLOAD})")
        self.transform = transform
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)
        assert len(self.images) == len(self.labels)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") \
            else open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(f"{path}: bad IDX image magic {magic}")
            data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
            return data.reshape(n, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(f"{path}: bad IDX label magic {magic}")
            return np.frombuffer(f.read(n), dtype=np.uint8).astype("int64")

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class FashionMNIST(MNIST):
    """Same IDX format, different archive."""


class Cifar10(Dataset):
    """CIFAR-10 python-pickle batches (reference vision/datasets/
    cifar.py): pass the batch file paths (data_batch_1..5 / test_batch).
    """

    _LABEL_KEY = b"labels"

    @staticmethod
    def _split_filter(batch_paths, names, mode):
        # mode selects the split by the archive's standard file names
        # (data_batch_* = train, test_batch = test), so passing the
        # whole extracted directory's files with mode='test' does what
        # the reference does instead of silently loading everything
        if any(n.startswith("data_batch") for n in names) and \
                any(n.startswith("test_batch") for n in names):
            want = "test_batch" if mode == "test" else "data_batch"
            return [p for p, n in zip(batch_paths, names)
                    if n.startswith(want)]
        return batch_paths

    def __init__(self, batch_paths=None, mode="train", transform=None,
                 download=False, backend=None):
        if download or not batch_paths:
            raise ValueError(f"{type(self).__name__}: batch_paths is "
                             f"required ({_NO_DOWNLOAD})")
        self.transform = transform
        names = [os.path.basename(p) for p in batch_paths]
        batch_paths = self._split_filter(batch_paths, names, mode)
        imgs, labels = [], []
        for p in batch_paths:
            with open(p, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            imgs.append(np.asarray(d[b"data"], np.uint8)
                        .reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
            labels.extend(d[self._LABEL_KEY])
        self.images = np.concatenate(imgs)
        self.labels = np.asarray(labels, "int64")

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class FakeData(Dataset):
    """Deterministic synthetic image dataset for tests/benchmarks."""

    def __init__(self, size=100, image_shape=(3, 32, 32), num_classes=10,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed * 100003 + idx)
        img = rng.randint(0, 256, self.image_shape).astype("uint8")
        label = np.int64(rng.randint(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class Cifar100(Cifar10):
    """CIFAR-100 python-pickle files (reference vision/datasets/
    cifar.py Cifar100): same batch format as CIFAR-10 but one
    train/test file each and 'fine_labels'."""

    _LABEL_KEY = b"fine_labels"

    @staticmethod
    def _split_filter(batch_paths, names, mode):
        if "train" in names and "test" in names:
            return [p for p, n in zip(batch_paths, names) if n == mode]
        return batch_paths


def _pil_loader(path_or_file):
    from PIL import Image

    img = Image.open(path_or_file)
    return np.asarray(img.convert("RGB"))


_IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".ppm",
                   ".tif", ".tiff", ".webp", ".npy")


def _default_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    return _pil_loader(path)


def _walk_valid_files(root, extensions, is_valid_file):
    exts = tuple(e.lower() for e in (extensions or _IMG_EXTENSIONS))
    valid = is_valid_file or (lambda p: p.lower().endswith(exts))
    out = []
    for dirpath, _, files in sorted(os.walk(root)):
        for fn in sorted(files):
            p = os.path.join(dirpath, fn)
            if valid(p):
                out.append(p)
    return out


class DatasetFolder(Dataset):
    """Class-per-subdirectory image dataset (reference
    vision/datasets/folder.py DatasetFolder): root/<class>/<img> walks
    into (image, class_index) samples; classes are sorted subdir names.
    `.npy` arrays load without PIL, everything else decodes to RGB."""

    def __init__(self, root, loader=None, extensions=None,
                 transform=None, is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        self.classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        if not self.classes:
            raise RuntimeError(f"DatasetFolder: no class subdirs in "
                               f"{root}")
        self.class_to_idx = {c: i for i, c in enumerate(self.classes)}
        self.samples = []
        for c in self.classes:
            for p in _walk_valid_files(os.path.join(root, c),
                                       extensions, is_valid_file):
                self.samples.append((p, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"DatasetFolder: no valid files under "
                               f"{root}")

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(target)


class ImageFolder(Dataset):
    """Flat/recursive image folder WITHOUT labels (reference
    vision/datasets/folder.py ImageFolder): every valid file under
    root becomes a [image] sample."""

    def __init__(self, root, loader=None, extensions=None,
                 transform=None, is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        self.transform = transform
        self.samples = _walk_valid_files(root, extensions,
                                         is_valid_file)
        if not self.samples:
            raise RuntimeError(f"ImageFolder: no valid files under "
                               f"{root}")

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]


class Flowers(Dataset):
    """Oxford Flowers-102 (reference vision/datasets/flowers.py):
    images tgz (jpg/image_%05d.jpg), scipy-format imagelabels.mat and
    setid.mat; mode selects the trnid/valid/tstid index list.  Labels
    are the .mat's 1-based classes shifted to 0-based int64."""

    _SETID_KEY = {"train": "trnid", "valid": "valid", "test": "tstid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False,
                 backend=None):
        import tarfile

        from scipy.io import loadmat

        if download or not all((data_file, label_file, setid_file)):
            raise ValueError(f"Flowers: data_file, label_file and "
                             f"setid_file are required ({_NO_DOWNLOAD})")
        if mode not in self._SETID_KEY:
            raise ValueError(f"Flowers: bad mode {mode!r}")
        self.transform = transform
        self.indexes = loadmat(setid_file)[self._SETID_KEY[mode]] \
            .ravel().astype("int64")
        self.labels = loadmat(label_file)["labels"].ravel() \
            .astype("int64") - 1
        # store raw JPEG bytes; decode lazily per __getitem__ (the
        # reference extracts per access too — eager decode of a real
        # 6k-image split would hold GBs resident)
        self._jpeg = {}
        wanted = set(self.indexes.tolist())
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                base = os.path.basename(m.name)
                if base.startswith("image_") and base.endswith(".jpg"):
                    num = int(base[len("image_"):-len(".jpg")])
                    if num in wanted:
                        self._jpeg[num] = tf.extractfile(m).read()

    def __len__(self):
        return len(self.indexes)

    def __getitem__(self, idx):
        import io as _io

        num = int(self.indexes[idx])
        img = _pil_loader(_io.BytesIO(self._jpeg[num]))
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(self.labels[num - 1])


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation (reference vision/datasets/
    voc2012.py): the devkit tar's ImageSets/Segmentation/{mode}.txt
    names the split; samples are (RGB image, label mask) arrays
    decoded from JPEGImages/ and SegmentationClass/."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        import io as _io
        import tarfile

        if download or data_file is None:
            raise ValueError(f"VOC2012: data_file required "
                             f"({_NO_DOWNLOAD})")
        if mode not in ("train", "val", "trainval"):
            raise ValueError(f"VOC2012: bad mode {mode!r}")
        self.transform = transform
        # keep encoded bytes; decode lazily per __getitem__ (a real
        # trainval split is thousands of images — eager int64 masks
        # alone would be GBs)
        with tarfile.open(data_file) as tf:
            byname = {m.name.split("VOCdevkit/VOC2012/", 1)[-1]: m
                      for m in tf.getmembers()
                      if "VOCdevkit/VOC2012/" in m.name}
            split = tf.extractfile(
                byname[f"ImageSets/Segmentation/{mode}.txt"]) \
                .read().decode().split()
            self._jpeg, self._png = [], []
            for name in split:
                self._jpeg.append(tf.extractfile(
                    byname[f"JPEGImages/{name}.jpg"]).read())
                self._png.append(tf.extractfile(
                    byname[f"SegmentationClass/{name}.png"]).read())

    def __len__(self):
        return len(self._jpeg)

    def __getitem__(self, idx):
        import io as _io

        from PIL import Image

        img = _pil_loader(_io.BytesIO(self._jpeg[idx]))
        # the mask PNG is palette-encoded class ids: DON'T convert
        # to RGB
        mask = np.asarray(Image.open(_io.BytesIO(self._png[idx]))) \
            .astype("int64")
        if self.transform is not None:
            img = self.transform(img)
        return img, mask
