"""paddle.vision.datasets — MNIST / Cifar10 / FakeData.

Reference: /root/reference/python/paddle/vision/datasets (mnist.py,
cifar.py) which download + parse the standard archives.  This build is
zero-egress: `download=True` raises with instructions, and the parsers
read the STANDARD file formats (IDX for MNIST, the python-pickle batch
format for CIFAR) from a local path — drop the official files in and
they load.  FakeData generates deterministic synthetic samples for
tests/benchmarks (the reference uses fake readers the same way,
SURVEY §4.2 book tests).
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "FakeData"]

_NO_DOWNLOAD = ("this TPU build runs zero-egress: download the official "
                "archive on a connected machine and pass the local "
                "path(s)")


class MNIST(Dataset):
    """IDX-format MNIST (reference vision/datasets/mnist.py).

    Pass image_path/label_path to the (optionally gzipped) idx files.
    """

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if download or image_path is None or label_path is None:
            raise ValueError(f"MNIST: image_path and label_path are "
                             f"required ({_NO_DOWNLOAD})")
        self.transform = transform
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)
        assert len(self.images) == len(self.labels)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") \
            else open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(f"{path}: bad IDX image magic {magic}")
            data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
            return data.reshape(n, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(f"{path}: bad IDX label magic {magic}")
            return np.frombuffer(f.read(n), dtype=np.uint8).astype("int64")

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class FashionMNIST(MNIST):
    """Same IDX format, different archive."""


class Cifar10(Dataset):
    """CIFAR-10 python-pickle batches (reference vision/datasets/
    cifar.py): pass the batch file paths (data_batch_1..5 / test_batch).
    """

    def __init__(self, batch_paths=None, mode="train", transform=None,
                 download=False, backend=None):
        if download or not batch_paths:
            raise ValueError(f"Cifar10: batch_paths is required "
                             f"({_NO_DOWNLOAD})")
        self.transform = transform
        # mode selects the split by the archive's standard file names
        # (data_batch_* = train, test_batch = test), so passing the whole
        # extracted directory's files with mode='test' does what the
        # reference does instead of silently loading everything
        names = [os.path.basename(p) for p in batch_paths]
        if any(n.startswith("data_batch") for n in names) and \
                any(n.startswith("test_batch") for n in names):
            want = "test_batch" if mode == "test" else "data_batch"
            batch_paths = [p for p, n in zip(batch_paths, names)
                           if n.startswith(want)]
        imgs, labels = [], []
        for p in batch_paths:
            with open(p, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            imgs.append(np.asarray(d[b"data"], np.uint8)
                        .reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
            labels.extend(d[b"labels"])
        self.images = np.concatenate(imgs)
        self.labels = np.asarray(labels, "int64")

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]


class FakeData(Dataset):
    """Deterministic synthetic image dataset for tests/benchmarks."""

    def __init__(self, size=100, image_shape=(3, 32, 32), num_classes=10,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed * 100003 + idx)
        img = rng.randint(0, 256, self.image_shape).astype("uint8")
        label = np.int64(rng.randint(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, label
