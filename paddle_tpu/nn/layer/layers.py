"""`paddle.nn.Layer` — the dygraph module base class.

Mirror of the reference's `python/paddle/fluid/dygraph/layers.py:64`
(`class Layer`) and its dygraph parameter type `ParamBase`
(`python/paddle/fluid/framework.py` dygraph branch): parameter/sublayer
auto-registration via `__setattr__`, state_dict save/load, train/eval
mode, forward pre/post hooks.

TPU-native re-design: parameters are eager Tensors wrapping immutable
`jax.Array`s (fluid/dygraph/varbase.py); initialization happens eagerly
through `Initializer.eager_value` instead of running startup-program init
ops; `paddle.jit.to_static`/`jax.jit` consumes `forward` directly since
the tape tracer records pure-functional jax calls.
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

from ...fluid import core, unique_name
from ...fluid.dygraph.varbase import Tensor
from ...fluid.initializer import ConstantInitializer, XavierInitializer
from ...fluid.param_attr import ParamAttr


class Parameter(Tensor):
    """A trainable parameter (the reference's dygraph `ParamBase`)."""

    def __init__(self, value, name=None, trainable=True, optimize_attr=None,
                 regularizer=None, need_clip=True):
        super().__init__(value, name=name or unique_name.generate("param"),
                         stop_gradient=not trainable, persistable=True)
        self.trainable = trainable
        self.optimize_attr = optimize_attr or {"learning_rate": 1.0}
        self.regularizer = regularizer
        self.need_clip = need_clip
        self.is_leaf_param = True

    @property
    def is_parameter(self):
        return True

    def __repr__(self):
        return (f"Parameter(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype}, trainable={self.trainable})\n"
                f"{self.numpy()}")


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    """Base class for all neural network modules
    (reference: fluid/dygraph/layers.py:64)."""

    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        if name_scope is None:
            name_scope = self.__class__.__name__.lower()
        self._full_name = unique_name.generate(name_scope)
        self._dtype = dtype
        self._parameters = OrderedDict()
        self._sub_layers = OrderedDict()
        self._buffers = OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()
        self._hook_id = [0]

    # -- identity ----------------------------------------------------------
    def full_name(self):
        return self._full_name

    # -- parameter / buffer creation ---------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """Create an eagerly-initialized Parameter (the dygraph analogue of
        LayerHelper.create_parameter, which appends startup-program init
        ops in static mode)."""
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype or self._dtype
        init = attr.initializer or default_initializer
        if init is None:
            init = ConstantInitializer(0.0) if is_bias else XavierInitializer()
        shape = [int(s) for s in shape]
        np_dt = core.np_dtype(dtype)
        value = init.eager_value(shape, np.dtype(np_dt).name)
        name = attr.name or unique_name.generate(
            f"{self._full_name}.{'b' if is_bias else 'w'}")
        return Parameter(
            value, name=name, trainable=attr.trainable,
            optimize_attr={"learning_rate": attr.learning_rate},
            regularizer=attr.regularizer, need_clip=attr.need_clip)

    def create_variable(self, name=None, persistable=False, dtype=None):
        value = np.zeros([1], dtype=core.np_dtype(dtype or self._dtype))
        return Tensor(value, name=name, persistable=persistable)

    def register_buffer(self, name, tensor, persistable=True):
        """Register a non-parameter state tensor (e.g. BN running mean)."""
        if not isinstance(tensor, Tensor) and tensor is not None:
            tensor = Tensor(tensor)
        self._buffers[name] = tensor
        if persistable:
            self._non_persistable_buffer_names.discard(name)
        else:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    # -- attribute magic ----------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError(
                    "call super().__init__() before assigning parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError(
                    "call super().__init__() before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
        elif buffers is not None and name in buffers:
            buffers[name] = value
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return (list(super().__dir__()) + list(self._parameters)
                + list(self._sub_layers) + list(self._buffers))

    # -- traversal ----------------------------------------------------------
    def children(self):
        for _, layer in self.named_children():
            yield layer

    def named_children(self):
        seen = set()
        for name, layer in self._sub_layers.items():
            if layer is not None and id(layer) not in seen:
                seen.add(id(layer))
                yield name, layer

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, layer in self.named_children():
            if id(layer) in layers_set:
                continue
            sub_prefix = prefix + ("." if prefix else "") + name
            yield from layer.named_sublayers(
                prefix=sub_prefix, include_self=True, layers_set=layers_set)

    def parameters(self, include_sublayers=True):
        return [p for _, p in
                self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        if include_sublayers:
            gen = self.named_sublayers(prefix=prefix, include_self=True)
        else:
            gen = [(prefix, self)]
        for layer_prefix, layer in gen:
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield layer_prefix + ("." if layer_prefix else "") + name, p

    def buffers(self, include_sublayers=True):
        return [b for _, b in
                self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        if include_sublayers:
            gen = self.named_sublayers(prefix=prefix, include_self=True)
        else:
            gen = [(prefix, self)]
        for layer_prefix, layer in gen:
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield layer_prefix + ("." if layer_prefix else "") + name, b

    def apply(self, fn):
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    # -- train / eval -------------------------------------------------------
    def train(self):
        self.training = True
        for layer in self.sublayers():
            layer.training = True
        return self

    def eval(self):
        self.training = False
        for layer in self.sublayers():
            layer.training = False
        return self

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id[0] += 1
        self._forward_pre_hooks[self._hook_id[0]] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id[0])

    def register_forward_post_hook(self, hook):
        self._hook_id[0] += 1
        self._forward_post_hooks[self._hook_id[0]] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id[0])

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        if destination is None:
            destination = OrderedDict()
        for name, p in self.named_parameters(
                prefix=structured_name_prefix.rstrip("."),
                include_sublayers=include_sublayers):
            destination[name] = p
        for name, b in self.named_buffers(
                prefix=structured_name_prefix.rstrip("."),
                include_sublayers=include_sublayers):
            # skip non-persistable buffers, mirroring the reference
            leaf = name.rsplit(".", 1)[-1]
            owner = self
            if "." in name:
                for part in name.split(".")[:-1]:
                    owner = getattr(owner, part)
            if leaf in owner._non_persistable_buffer_names:
                continue
            destination[name] = b
        return destination

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, value in state_dict.items():
            if name not in own:
                unexpected.append(name)
                continue
            target = own[name]
            arr = value.numpy() if isinstance(value, Tensor) else np.asarray(value)
            target.set_value(arr.astype(target.numpy().dtype))
        for name in own:
            if name not in state_dict:
                missing.append(name)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -- dtype / device -----------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._cast_params(dtype)
        return self

    def astype(self, dtype):
        self._cast_params(dtype)
        return self

    def _cast_params(self, dtype):
        np_dt = core.np_dtype(dtype)
        for p in self.parameters():
            p._value = p._value.astype(np_dt)
        for b in self.buffers():
            if b is not None and np.issubdtype(
                    np.asarray(b.numpy()).dtype, np.floating):
                b._value = b._value.astype(np_dt)
        self._dtype = core.convert_dtype(dtype)

    def float(self):
        return self.astype("float32")

    def bfloat16(self):
        return self.astype("bfloat16")

    # -- call ---------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    # -- repr ---------------------------------------------------------------
    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self.named_children():
            sub = repr(layer).split("\n")
            sub = [sub[0]] + ["  " + l for l in sub[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub))
        main = f"{self.__class__.__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"
