"""Transformer layers (reference: python/paddle/nn/layer/transformer.py —
MultiHeadAttention, TransformerEncoder/DecoderLayer, Transformer).

TPU-native: the attention core routes through
paddle_tpu.ops.pallas.attention (Pallas flash-attention kernel on TPU,
XLA oracle elsewhere); projections are single fused matmuls so XLA can
keep the whole layer on the MXU.  Layout is (batch, seq, d_model)
throughout, (batch, seq, heads, head_dim) inside attention — matching
the reference's 2.x API.
"""

from __future__ import annotations

import collections

import numpy as np

from ...fluid.dygraph.tracer import trace_fn, trace_op
from .. import functional as F
from .activation import GELU, ReLU
from .common import Dropout, Linear
from .container import LayerList
from .layers import Layer
from .norm import LayerNorm


class MultiHeadAttention(Layer):
    """(reference: nn/layer/transformer.py MultiHeadAttention)."""

    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _split_heads(self, x):
        h, d = self.num_heads, self.head_dim
        return trace_fn(
            lambda x: x.reshape(x.shape[0], x.shape[1], h, d), {"x": x})

    def gen_cache(self, key, value=None, type=None):
        if type == MultiHeadAttention.StaticCache:
            # cross-attention: precomputed k/v of the (encoder) memory
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value if value is not None
                                              else key))
            return self.StaticCache(k, v)
        # incremental self-attention: start EMPTY (0-length seq); each
        # forward concatenates the new step's k/v
        from ...fluid.dygraph.varbase import Tensor

        batch = key.shape[0]
        dt = np.asarray(self.k_proj.weight.numpy()).dtype
        empty = np.zeros((batch, 0, self.num_heads, self.head_dim), dt)
        return self.Cache(Tensor(empty), Tensor(np.array(empty)))

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        key = query if key is None else key
        value = key if value is None else value

        q = self._split_heads(self.q_proj(query))
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._split_heads(self.k_proj(key))
            v = self._split_heads(self.v_proj(value))
            if isinstance(cache, self.Cache):
                import jax.numpy as jnp

                k = trace_fn(lambda a, b: jnp.concatenate([a, b], axis=1),
                             {"a": cache.k, "b": k})
                v = trace_fn(lambda a, b: jnp.concatenate([a, b], axis=1),
                             {"a": cache.v, "b": v})
                cache = self.Cache(k, v)

        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.dropout if self.training else 0.0,
            training=self.training)
        out = trace_fn(
            lambda x: x.reshape(x.shape[0], x.shape[1], self.embed_dim),
            {"x": out})
        out = self.out_proj(out)
        if cache is not None and not isinstance(cache, self.StaticCache):
            return out, cache
        return out


def _dense_ffn_block(layer, x):
    """linear2(dropout(act(linear1(x)))) for encoder AND decoder
    layers — routed through F.fused_feedforward (ops/pallas/ffn.py:
    XLA path by default, opt-in Pallas kernel) when the activation is
    gelu/relu and biases exist; otherwise the layer-by-layer path."""
    if isinstance(layer.activation, GELU):
        act_name = ("gelu_tanh" if layer.activation._approximate
                    else "gelu")
    elif isinstance(layer.activation, ReLU):
        act_name = "relu"
    else:
        act_name = None
    if act_name is not None and layer.linear1.bias is not None \
            and layer.linear2.bias is not None:
        return F.fused_feedforward(
            x, layer.linear1.weight, layer.linear1.bias,
            layer.linear2.weight, layer.linear2.bias,
            activation=act_name, act_dropout=layer.dropout.p,
            training=layer.training)
    return layer.linear2(layer.dropout(layer.activation(
        layer.linear1(x))))


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 moe_experts=None, moe_capacity_factor=1.25):
        super().__init__()
        self._config = (d_model, nhead, dim_feedforward, dropout,
                        activation, attn_dropout, act_dropout,
                        normalize_before, weight_attr, bias_attr,
                        moe_experts, moe_capacity_factor)
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout,
            weight_attr=weight_attr, bias_attr=bias_attr)
        if moe_experts:
            # Switch-Transformer layer: the dense FFN becomes a top-1
            # routed expert mixture (nn.SwitchMoE; the reference has no
            # MoE — SURVEY.md §2.9)
            from .common import SwitchMoE

            self.moe = SwitchMoE(d_model, dim_feedforward, moe_experts,
                                 capacity_factor=moe_capacity_factor,
                                 weight_attr=weight_attr)
            self.linear1 = self.linear2 = None
        else:
            self.moe = None
            self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                                  bias_attr)
            self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                                  bias_attr)
        self.dropout = Dropout(act_dropout, mode="upscale_in_train")
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout, mode="upscale_in_train")
        self.dropout2 = Dropout(dropout, mode="upscale_in_train")
        self.activation = GELU() if activation == "gelu" else ReLU()

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)

        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        if self.moe is not None:
            # dropped (over-capacity) tokens ride the residual — the
            # standard Switch semantics.  The dense path's activation
            # dropout (inside the FFN at d_ff) is applied at the expert
            # OUTPUT instead: in-expert dropout isn't expressible in
            # the batched dispatch einsums, and Switch's expert dropout
            # regularizes the same signal path
            src = self.dropout(self.moe(src))
        else:
            src = _dense_ffn_block(self, src)
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src, type=MultiHeadAttention.Cache)


def _clone_layer(layer):
    """Fresh instance with the same constructor config: independent
    initialization and unique parameter names (a deepcopy would clone
    both, colliding optimizer state_dict keys)."""
    return type(layer)(*layer._config)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList(
            [encoder_layer] + [_clone_layer(encoder_layer)
                               for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, src_mask)
            else:
                output, new_cache = layer(output, src_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self._config = (d_model, nhead, dim_feedforward, dropout,
                        activation, attn_dropout, act_dropout,
                        normalize_before, weight_attr, bias_attr)
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout,
            weight_attr=weight_attr, bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout,
            weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr)
        self.dropout = Dropout(act_dropout, mode="upscale_in_train")
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout, mode="upscale_in_train")
        self.dropout2 = Dropout(dropout, mode="upscale_in_train")
        self.dropout3 = Dropout(dropout, mode="upscale_in_train")
        self.activation = GELU() if activation == "gelu" else ReLU()

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, incr_cache = self.self_attn(tgt, tgt, tgt, tgt_mask,
                                             cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask, cache[1])
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = _dense_ffn_block(self, tgt)
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (incr_cache, cache[1]))

    def gen_cache(self, memory):
        incr = self.self_attn.gen_cache(memory,
                                        type=MultiHeadAttention.Cache)
        static = self.cross_attn.gen_cache(
            memory, memory, type=MultiHeadAttention.StaticCache)
        return incr, static


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList(
            [decoder_layer] + [_clone_layer(decoder_layer)
                               for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        output = tgt
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, memory, tgt_mask, memory_mask)
            else:
                output, new_cache = layer(output, memory, tgt_mask,
                                          memory_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        cache = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            cache = list(zip(*cache))
        return cache


class Transformer(Layer):
    """Full encoder-decoder transformer
    (reference: nn/layer/transformer.py Transformer)."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            encoder_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            encoder_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(encoder_layer,
                                              num_encoder_layers,
                                              encoder_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            decoder_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            decoder_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(decoder_layer,
                                              num_decoder_layers,
                                              decoder_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        import jax.numpy as jnp

        from ...fluid.dygraph.varbase import Tensor

        mask = jnp.where(
            jnp.tril(jnp.ones((length, length), jnp.bool_)), 0.0,
            -np.inf).astype(jnp.float32)
        return Tensor(mask)
