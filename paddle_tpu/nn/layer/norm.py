"""Normalization layers (reference: python/paddle/nn/layer/norm.py; ops
batch_norm/layer_norm/instance_norm/group_norm, operators/batch_norm_op.cc,
layer_norm_op.cc)."""

from __future__ import annotations

import numpy as np

from ...fluid.initializer import ConstantInitializer
from .. import functional as F
from .layers import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            shape=[num_features], attr=weight_attr,
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter(
            shape=[num_features], attr=bias_attr, is_bias=True)
        self._mean = self.register_buffer(
            "_mean", np.zeros([num_features], np.float32))
        self._variance = self.register_buffer(
            "_variance", np.ones([num_features], np.float32))

    def forward(self, input):
        return F.batch_norm(
            input, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}"


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW", use_global_stats, name)


BatchNorm = _BatchNormBase


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm.  Under pjit/shard_map the batch axis is a
    mesh axis and the mean/var reduction rides a psum over it (the
    reference's sync_batch_norm_op.cu NCCL allreduce of statistics);
    single-device eager mode degenerates to plain BN."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon)
            new.weight = layer.weight
            new.bias = layer.bias
            new._mean = layer._mean
            new._variance = layer._variance
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=self._normalized_shape, attr=weight_attr,
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter(
            shape=self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, input):
        return F.layer_norm(input, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False or bias_attr is False:
            self.scale = None
            self.bias = None
        else:
            self.scale = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=ConstantInitializer(1.0))
            self.bias = self.create_parameter(
                shape=[num_features], attr=bias_attr, is_bias=True)

    def forward(self, input):
        return F.instance_norm(input, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


InstanceNorm1D = InstanceNorm2D
InstanceNorm3D = InstanceNorm2D


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=[num_channels], attr=weight_attr,
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter(
            shape=[num_channels], attr=bias_attr, is_bias=True)

    def forward(self, input):
        return F.group_norm(input, self._num_groups, self._epsilon,
                            self.weight, self.bias)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k

    def forward(self, input):
        return F.local_response_norm(input, self.size, self.alpha,
                                     self.beta, self.k)


class SpectralNorm(Layer):
    """Power-iteration spectral norm of a weight tensor
    (reference: nn/layer/norm.py SpectralNorm; op spectral_norm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            shape=[h], attr=None,
            default_initializer=None)
        self.weight_v = self.create_parameter(
            shape=[w], attr=None,
            default_initializer=None)

    def forward(self, x):
        import jax
        import jax.numpy as jnp

        from ...fluid.dygraph.tracer import trace_fn

        dim, iters, eps = self._dim, self._power_iters, self._eps

        def f(w, u, v):
            perm = [dim] + [i for i in range(w.ndim) if i != dim]
            wm = jnp.transpose(w, perm).reshape(w.shape[dim], -1)
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma, u, v

        out, u_new, v_new = trace_fn(
            f, {"w": x, "u": self.weight_u, "v": self.weight_v},
            multi_out=True)
        # reference SpectralNorm updates U/V in place with no grad each
        # forward so power iteration refines across steps
        self.weight_u._value = jax.lax.stop_gradient(
            u_new._value if hasattr(u_new, "_value") else u_new)
        self.weight_v._value = jax.lax.stop_gradient(
            v_new._value if hasattr(v_new, "_value") else v_new)
        return out
