"""Container layers (reference: python/paddle/fluid/dygraph/container.py:
Sequential, ParameterList, LayerList)."""

from __future__ import annotations

from .layers import Layer, Parameter


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) \
                and not isinstance(layers[0], Layer):
            layers = layers[0]
        if layers and isinstance(layers[0], tuple) \
                and not isinstance(layers[0], Layer):
            for name, layer in layers:
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers)
        return self._sub_layers[keys[idx]]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, input):
        for layer in self._sub_layers.values():
            input = layer(input)
        return input


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, layer in enumerate(sublayers):
                self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers)
        return self._sub_layers[keys[idx]]

    def __setitem__(self, idx, layer):
        keys = list(self._sub_layers)
        self._sub_layers[keys[idx]] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for layer in layers:
            self.append(layer)
        return self


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        keys = list(self._parameters)
        return self._parameters[keys[idx]]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self
