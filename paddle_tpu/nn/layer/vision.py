"""`paddle.nn.layer.vision` (reference nn/layer/vision.py): the vision
layer namespace — PixelShuffle lives in common.py here; this module
mirrors the reference's submodule so `paddle.nn.vision` resolves."""

from .common import PixelShuffle  # noqa: F401
