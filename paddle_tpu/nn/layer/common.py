"""Common layers (reference: python/paddle/nn/layer/common.py)."""

from __future__ import annotations

import numpy as np

from ...fluid.dygraph.tracer import trace_op
from ...fluid.initializer import (ConstantInitializer, NormalInitializer,
                                  XavierInitializer)
from .. import functional as F
from .layers import Layer


class Linear(Layer):
    """y = xW + b with W (in_features, out_features)
    (reference: nn/layer/common.py Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=XavierInitializer())
        self.bias = self.create_parameter(
            shape=[out_features], attr=bias_attr, is_bias=True)

    def forward(self, input):
        return F.linear(input, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, input):
        return F.dropout(input, p=self.p, axis=self.axis,
                         training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, input):
        return F.dropout2d(input, p=self.p, training=self.training,
                           data_format=self.data_format)


class Embedding(Layer):
    """(reference: nn/layer/common.py Embedding; op lookup_table_v2,
    operators/lookup_table_v2_op.cc)."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=NormalInitializer(0.0, 1.0))
        if padding_idx is not None:
            w = np.array(self.weight.numpy())
            w[padding_idx] = 0
            self.weight.set_value(w)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, input):
        return trace_op("flatten_contiguous_range", {"X": input},
                        {"start_axis": self.start_axis,
                         "stop_axis": self.stop_axis})


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest",
                         data_format=data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", align_corners=True,
                         data_format=data_format)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL", name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value)


class Pad2D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(Pad1D):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        import jax.numpy as jnp

        from ...fluid.dygraph.tracer import trace_fn

        def f(a, b):
            dot = jnp.sum(a * b, axis=self.axis)
            na = jnp.linalg.norm(a, axis=self.axis)
            nb = jnp.linalg.norm(b, axis=self.axis)
            return dot / jnp.maximum(na * nb, self.eps)

        return trace_fn(f, {"a": x1, "b": x2})


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[out_features, in1_features, in2_features],
            attr=weight_attr, default_initializer=XavierInitializer())
        self.bias = self.create_parameter(
            shape=[1, out_features], attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        import jax.numpy as jnp

        from ...fluid.dygraph.tracer import trace_fn

        def f(x1, x2, w, b=None):
            out = jnp.einsum("bi,oij,bj->bo", x1, w, x2)
            return out + b if b is not None else out

        ins = {"x1": x1, "x2": x2, "w": self.weight}
        if self.bias is not None:
            ins["b"] = self.bias
        return trace_fn(f, ins)


import contextlib
import threading

_MOE_AUX = threading.local()


@contextlib.contextmanager
def moe_aux_scope():
    """Collect the DIFFERENTIABLE Switch aux losses of every SwitchMoE
    forward in the scope (works under jit tracing, where the layer
    attribute channel is deliberately detached): yields a list that
    fills with one aux Tensor per routed call — sum them into the
    training loss."""
    prev = getattr(_MOE_AUX, "items", None)
    _MOE_AUX.items = []
    try:
        yield _MOE_AUX.items
    finally:
        _MOE_AUX.items = prev


class SwitchMoE(Layer):
    """Switch-Transformer feed-forward: top-1 routed mixture of expert
    FFNs (Fedus et al. 2021).  The reference has no MoE (SURVEY.md §2.9
    "NOT present in the reference"); this layer is the eager/model-side
    face of the TPU-native expert-parallel design in
    paddle_tpu.parallel.moe — the SAME dispatch algebra runs here on
    local experts and there sharded over an `ep` mesh axis.

    forward(x (B, S, H)) -> (B, S, H).  The Switch load-balance aux
    loss: in eager, `.aux_loss` after the call is a tape-connected
    Tensor (add `aux_weight * layer.aux_loss` to the training loss);
    under jit/functional_call the attribute is NOT set (it would leak a
    tracer) — the value instead rides the `moe_aux_loss` buffer through
    functional_call's new_state, detached (jit callers that want the
    aux gradient should use parallel.moe.build_switch_moe, whose apply
    returns it).
    """

    def __init__(self, d_model, d_ff, num_experts, capacity_factor=1.25,
                 weight_attr=None, name=None):
        super().__init__()
        self._d_model, self._d_ff = d_model, d_ff
        self._num_experts = num_experts
        self._capacity_factor = capacity_factor
        self.gate_weight = self.create_parameter(
            shape=[d_model, num_experts], attr=weight_attr,
            default_initializer=XavierInitializer())
        # explicit fans: the generic _fan_in_out would read the 3D
        # stacked-expert shape as a conv kernel and under-scale by
        # ~sqrt(d_ff) (code-review r5; per-expert fans match
        # parallel.moe.init_moe_params)
        self.w1 = self.create_parameter(
            shape=[num_experts, d_model, d_ff], attr=weight_attr,
            default_initializer=XavierInitializer(fan_in=d_model,
                                                  fan_out=d_ff))
        self.b1 = self.create_parameter(shape=[num_experts, d_ff],
                                        is_bias=True)
        self.w2 = self.create_parameter(
            shape=[num_experts, d_ff, d_model], attr=weight_attr,
            default_initializer=XavierInitializer(fan_in=d_ff,
                                                  fan_out=d_model))
        self.b2 = self.create_parameter(shape=[num_experts, d_model],
                                        is_bias=True)
        self.moe_aux_loss = self.register_buffer(
            "moe_aux_loss", np.zeros([], np.float32), persistable=False)
        self.aux_loss = None

    def forward(self, x):
        from ...fluid.dygraph.tracer import trace_fn
        from ...parallel.moe import switch_moe_local

        d_model, n_experts = self._d_model, self._num_experts
        cf = self._capacity_factor

        def f(x, wg, w1, b1, w2, b2):
            lead = x.shape[:-1]
            out, aux = switch_moe_local(
                {"wg": wg, "w1": w1, "b1": b1, "w2": w2, "b2": b2},
                x.reshape(-1, d_model), n_experts, capacity_factor=cf)
            return out.reshape(lead + (d_model,)), aux

        out, aux = trace_fn(
            f, {"x": x, "wg": self.gate_weight, "w1": self.w1,
                "b1": self.b1, "w2": self.w2, "b2": self.b2},
            multi_out=True)
        import jax
        from jax import lax

        # buffer: pure-state channel under functional_call (detached)
        self.moe_aux_loss._value = lax.stop_gradient(aux._value)
        # attribute: eager tape recipe only — never stash a tracer
        self.aux_loss = (None if isinstance(aux._value, jax.core.Tracer)
                         else aux)
        # scope: the differentiable channel (eager AND traced)
        items = getattr(_MOE_AUX, "items", None)
        if items is not None:
            items.append(aux)
        return out
