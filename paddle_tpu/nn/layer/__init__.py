from . import (activation, common, container, conv, layers, loss, norm,
               pooling, rnn, transformer)  # noqa: F401
