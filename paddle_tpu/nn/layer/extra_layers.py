"""`paddle.nn` layer tail — the remaining (uncommented) DEFINE_ALIAS
classes of the reference's python/paddle/nn/__init__.py: thin Layer
wrappers over the functional tail (nn/functional/extra.py), pooling /
transpose-conv variants, and the legacy fluid.dygraph Pool2D."""

from __future__ import annotations

import numpy as np

from .. import functional as F
from .conv import _ConvNd
from .layers import Layer


class LogSigmoid(Layer):
    def forward(self, x):
        return F.log_sigmoid(x)


class Softsign(Layer):
    def forward(self, x):
        return F.softsign(x)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training,
                           data_format=self.data_format)


class PairwiseDistance(Layer):
    """reference nn/layer/distance.py: p-norm of x - y along dim 1."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        import jax.numpy as jnp

        from ...fluid.dygraph.tracer import trace_fn

        def f(x, y):
            d = x - y + self.epsilon
            return jnp.linalg.norm(d, ord=self.p, axis=1,
                                   keepdims=self.keepdim)

        return trace_fn(f, {"x": x, "y": y})


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths):
        return F.ctc_loss(log_probs, labels, input_lengths,
                          label_lengths, blank=self.blank,
                          reduction=self.reduction)


class HSigmoidLoss(Layer):
    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        self.num_classes = num_classes
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size], attr=weight_attr)
        self.bias = self.create_parameter(
            [num_classes - 1, 1], attr=bias_attr, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes,
                               self.weight, bias=self.bias,
                               path_table=path_table,
                               path_code=path_code)


class BilinearTensorProduct(Layer):
    def __init__(self, input1_dim, input2_dim, output_dim,
                 name=None, weight_attr=None, bias_attr=None):
        super().__init__()
        self.weight = self.create_parameter(
            [output_dim, input1_dim, input2_dim], attr=weight_attr)
        self.bias = self.create_parameter([output_dim], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear_tensor_product(x1, x2, self.weight, self.bias)


class RowConv(Layer):
    def __init__(self, num_channels, future_context_size, param_attr=None,
                 act=None):
        super().__init__()
        self.act = act
        self.weight = self.create_parameter(
            [future_context_size + 1, num_channels], attr=param_attr)

    def forward(self, x):
        return F.row_conv(x, self.weight, act=self.act)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, dims=1, transposed=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(
            x, self.weight, self.bias, self._stride, self._padding,
            self._output_padding, self._groups, self._dilation,
            output_size, self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, dims=3, transposed=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(
            x, self.weight, self.bias, self._stride, self._padding,
            self._output_padding, self._groups, self._dilation,
            output_size, self._data_format)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False,
                 data_format="NCDHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode)

    def forward(self, x):
        k, s, p, cm = self.args
        return F.max_pool3d(x, k, stride=s, padding=p, ceil_mode=cm)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode=False, exclusive=True, divisor_override=None,
                 data_format="NCDHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode)

    def forward(self, x):
        k, s, p, cm = self.args
        return F.avg_pool3d(x, k, stride=s, padding=p, ceil_mode=cm)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size)


class Pool2D(Layer):
    """Legacy fluid.dygraph Pool2D (reference dygraph/nn.py Pool2D)."""

    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True, data_format="NCHW"):
        super().__init__()
        self.cfg = (pool_size, pool_type, pool_stride, pool_padding,
                    global_pooling, ceil_mode, exclusive, data_format)

    def forward(self, x):
        (ks, pt, st, pd, gp, cm, ex, df) = self.cfg
        if gp:
            import jax.numpy as jnp

            from ...fluid.dygraph.tracer import trace_fn

            red = (2, 3) if df == "NCHW" else (1, 2)
            fn = jnp.max if pt == "max" else jnp.mean
            return trace_fn(
                lambda x: fn(x, axis=red, keepdims=True), {"x": x})
        f = F.max_pool2d if pt == "max" else F.avg_pool2d
        return f(x, ks, stride=st, padding=pd, ceil_mode=cm,
                 data_format=df)
