"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py —
RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, SimpleRNN, LSTM, GRU;
C++ ops operators/lstm_op.cc, gru_op.cc, recurrent_op.cc).

TPU-native re-design: the reference runs RNNs either as monolithic
CPU/cuDNN kernels or as a `recurrent` sub-block interpreted step-by-step.
Here the whole sequence loop is ONE `jax.lax.scan` inside a single traced
function, so XLA compiles the time loop with static shapes — the
compiler-friendly control-flow idiom (SURVEY.md §7 "Control flow
lowering").
"""

from __future__ import annotations

import math

import numpy as np

from ...fluid.dygraph.tracer import trace_fn
from ...fluid.initializer import UniformInitializer
from .layers import Layer


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0):
        from ...fluid.dygraph.varbase import Tensor

        batch = batch_ref.shape[0]
        shape = shape or self.state_shape
        if isinstance(shape, (list, tuple)) and isinstance(
                shape[0], (list, tuple)):
            return tuple(
                Tensor(np.full([batch] + list(s), init_value, np.float32))
                for s in shape)
        return Tensor(np.full([batch] + list(shape), init_value, np.float32))


def _std_uniform(hidden_size):
    std = 1.0 / math.sqrt(hidden_size)
    return UniformInitializer(-std, std)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        init = _std_uniform(hidden_size)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=init)
        self.hidden_size = hidden_size
        self.activation = activation

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        import jax.numpy as jnp

        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else lambda x: jnp.maximum(x, 0)

        def f(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)

        h = trace_fn(f, {"x": inputs, "h": states, "wi": self.weight_ih,
                         "wh": self.weight_hh, "bi": self.bias_ih,
                         "bh": self.bias_hh})
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        init = _std_uniform(hidden_size)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=init)
        self.hidden_size = hidden_size

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states
        h_new, c_new = trace_fn(
            _lstm_step, {"x": inputs, "h": h, "c": c,
                         "wi": self.weight_ih, "wh": self.weight_hh,
                         "bi": self.bias_ih, "bh": self.bias_hh})
        return h_new, (h_new, c_new)


def _lstm_step(x, h, c, wi, wh, bi, bh):
    import jax
    import jax.numpy as jnp

    gates = x @ wi.T + bi + h @ wh.T + bh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new


def _gru_step(x, h, wi, wh, bi, bh):
    import jax
    import jax.numpy as jnp

    gi = x @ wi.T + bi
    gh = h @ wh.T + bh
    ir, iz, ic = jnp.split(gi, 3, axis=-1)
    hr, hz, hc = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(ir + hr)
    z = jax.nn.sigmoid(iz + hz)
    n = jnp.tanh(ic + r * hc)
    return (1 - z) * n + z * h


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        init = _std_uniform(hidden_size)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=init)
        self.hidden_size = hidden_size

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h = trace_fn(_gru_step, {"x": inputs, "h": states,
                                 "wi": self.weight_ih, "wh": self.weight_hh,
                                 "bi": self.bias_ih, "bh": self.bias_hh})
        return h, h


class _ScanRNNBase(Layer):
    """Multi-layer (optionally bidirectional) scan-based recurrence.

    mode in {"LSTM", "GRU", "RNN_TANH", "RNN_RELU"}; weights per
    (layer, direction) follow the cell layout above."""

    GATES = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        ndir = 2 if self.bidirect else 1
        g = self.GATES[mode]
        init = _std_uniform(hidden_size)
        self._all_weights = []
        for layer in range(num_layers):
            for d in range(ndir):
                in_sz = input_size if layer == 0 else hidden_size * ndir
                wi = self.create_parameter([g * hidden_size, in_sz],
                                           weight_ih_attr,
                                           default_initializer=init)
                wh = self.create_parameter([g * hidden_size, hidden_size],
                                           weight_hh_attr,
                                           default_initializer=init)
                bi = self.create_parameter([g * hidden_size], bias_ih_attr,
                                           is_bias=True,
                                           default_initializer=init)
                bh = self.create_parameter([g * hidden_size], bias_hh_attr,
                                           is_bias=True,
                                           default_initializer=init)
                suffix = f"{layer}" + ("_reverse" if d else "")
                self.add_parameter(f"weight_ih_l{suffix}", wi)
                self.add_parameter(f"weight_hh_l{suffix}", wh)
                self.add_parameter(f"bias_ih_l{suffix}", bi)
                self.add_parameter(f"bias_hh_l{suffix}", bh)
                self._all_weights.append((wi, wh, bi, bh))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import jax
        import jax.numpy as jnp

        mode = self.mode
        nl, ndir = self.num_layers, 2 if self.bidirect else 1
        hs = self.hidden_size
        time_major = self.time_major
        is_lstm = mode == "LSTM"
        dropout = self.dropout if self.training else 0.0

        ins = {"x": inputs}
        for i, (wi, wh, bi, bh) in enumerate(self._all_weights):
            ins[f"wi{i}"] = wi
            ins[f"wh{i}"] = wh
            ins[f"bi{i}"] = bi
            ins[f"bh{i}"] = bh
        if initial_states is not None:
            if is_lstm:
                ins["h0"], ins["c0"] = initial_states
            else:
                ins["h0"] = initial_states

        def run(x, h0=None, c0=None, **w):
            if not time_major:
                x = jnp.swapaxes(x, 0, 1)  # (T, B, C)
            batch = x.shape[1]
            if h0 is None:
                h0 = jnp.zeros((nl * ndir, batch, hs), x.dtype)
                c0 = jnp.zeros((nl * ndir, batch, hs), x.dtype)
            hs_out, cs_out = [], []
            for layer in range(nl):
                outs = []
                for d in range(ndir):
                    idx = layer * ndir + d
                    wi, wh, bi, bh = (w[f"wi{idx}"], w[f"wh{idx}"],
                                      w[f"bi{idx}"], w[f"bh{idx}"])
                    xs = jnp.flip(x, 0) if d else x

                    if is_lstm:
                        def step(carry, xt, wi=wi, wh=wh, bi=bi, bh=bh):
                            h, c = carry
                            h2, c2 = _lstm_step(xt, h, c, wi, wh, bi, bh)
                            return (h2, c2), h2

                        (hT, cT), ys = jax.lax.scan(
                            step, (h0[idx], c0[idx] if c0 is not None
                                   else jnp.zeros_like(h0[idx])), xs)
                        cs_out.append(cT)
                    elif mode == "GRU":
                        def step(h, xt, wi=wi, wh=wh, bi=bi, bh=bh):
                            h2 = _gru_step(xt, h, wi, wh, bi, bh)
                            return h2, h2

                        hT, ys = jax.lax.scan(step, h0[idx], xs)
                    else:
                        act = (jnp.tanh if mode == "RNN_TANH"
                               else jax.nn.relu)

                        def step(h, xt, wi=wi, wh=wh, bi=bi, bh=bh, act=act):
                            h2 = act(xt @ wi.T + bi + h @ wh.T + bh)
                            return h2, h2

                        hT, ys = jax.lax.scan(step, h0[idx], xs)
                    hs_out.append(hT)
                    outs.append(jnp.flip(ys, 0) if d else ys)
                x = jnp.concatenate(outs, axis=-1) if ndir == 2 else outs[0]
                if dropout and layer < nl - 1:
                    # a fixed-key dropout between layers (training only)
                    key = jax.random.PRNGKey(layer)
                    keep = 1.0 - dropout
                    x = jnp.where(jax.random.bernoulli(key, keep, x.shape),
                                  x / keep, 0.0)
            y = x if time_major else jnp.swapaxes(x, 0, 1)
            h_all = jnp.stack(hs_out, 0)
            if is_lstm:
                return y, h_all, jnp.stack(cs_out, 0)
            return y, h_all

        out = trace_fn(run, ins, multi_out=True)
        return out

    def __call__(self, inputs, initial_states=None, sequence_length=None):
        # bypass Layer.__call__'s single-output assumption cleanly
        for hook in self._forward_pre_hooks.values():
            hook(self, (inputs,))
        outs = self.forward(inputs, initial_states, sequence_length)
        if isinstance(outs, (list, tuple)) and len(outs) == 3:
            y, h, c = outs
            return y, (h, c)
        if isinstance(outs, (list, tuple)) and len(outs) == 2:
            return outs[0], outs[1]
        return outs


class LSTM(_ScanRNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kw):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class GRU(_ScanRNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kw):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class SimpleRNN(_ScanRNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class RNN(Layer):
    """Wraps a cell into a scan over time
    (reference: nn/layer/rnn.py RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import jax.numpy as jnp

        steps = inputs.shape[0 if self.time_major else 1]
        outputs = []
        states = initial_states
        idxs = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        for t in idxs:
            xt = inputs[:, t] if not self.time_major else inputs[t]
            out, states = self.cell(xt, states)
            outputs.append(out)
        if self.is_reverse:
            outputs = outputs[::-1]
        from ...fluid.dygraph.tracer import trace_fn

        axis = 0 if self.time_major else 1
        n = len(outputs)

        def stack(**kw):
            return jnp.stack([kw[f"x{i}"] for i in range(n)], axis=axis)

        y = trace_fn(stack, {f"x{i}": o for i, o in enumerate(outputs)})
        return y, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        import jax.numpy as jnp

        from ...fluid.dygraph.tracer import trace_fn

        sf = sb = None
        if initial_states is not None:
            sf, sb = initial_states
        yf, stf = self.rnn_fw(inputs, sf)
        yb, stb = self.rnn_bw(inputs, sb)
        y = trace_fn(lambda a, b: jnp.concatenate([a, b], -1),
                     {"a": yf, "b": yb})
        return y, (stf, stb)
