"""Convolution layers (reference: python/paddle/nn/layer/conv.py; ops
conv2d/conv3d/conv2d_transpose, operators/conv_op.cc)."""

from __future__ import annotations

import numpy as np

from ...fluid.initializer import MSRAInitializer
from .. import functional as F
from .layers import Layer


def _ntuple(v, n):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v] * n


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride,
                 padding, dilation, groups, padding_mode, weight_attr,
                 bias_attr, data_format, dims, transposed=False,
                 output_padding=0):
        super().__init__()
        assert in_channels % groups == 0
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _ntuple(kernel_size, dims)
        self._stride = _ntuple(stride, dims)
        self._padding = padding
        self._dilation = _ntuple(dilation, dims)
        self._groups = groups
        self._data_format = data_format
        self._output_padding = output_padding
        if transposed:
            filter_shape = [in_channels, out_channels // groups] \
                + self._kernel_size
        else:
            filter_shape = [out_channels, in_channels // groups] \
                + self._kernel_size
        fan_in = (in_channels // groups) * int(np.prod(self._kernel_size))
        self.weight = self.create_parameter(
            shape=filter_shape, attr=weight_attr,
            default_initializer=MSRAInitializer(uniform=True, fan_in=fan_in))
        self.bias = self.create_parameter(
            shape=[out_channels], attr=bias_attr, is_bias=True)

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}")


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format, dims=2)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, dims=2, transposed=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(
            x, self.weight, self.bias, self._stride, self._padding,
            self._output_padding, self._dilation, self._groups,
            output_size, self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format, dims=3)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)


class Conv1D(_ConvNd):
    """Conv1D via a squeeze/expand around conv2d (the reference lowers
    conv1d the same way, nn/layer/conv.py Conv1D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format, dims=1)

    def forward(self, x):
        from ...fluid.dygraph.tracer import trace_fn
        import jax.numpy as jnp

        w2 = trace_fn(lambda w: jnp.expand_dims(w, 2), {"w": self.weight})
        x2 = trace_fn(lambda x: jnp.expand_dims(x, 2), {"x": x})
        pad = self._padding
        pad2 = [0, pad] if isinstance(pad, int) else [0] + list(pad)
        out = F.conv2d(x2, w2, self.bias, [1] + self._stride, pad2,
                       [1] + self._dilation, self._groups)
        return trace_fn(lambda x: jnp.squeeze(x, 2), {"x": out})
