"""`paddle.nn.functional` tail — the remaining DEFINE_ALIAS surface of
the reference's python/paddle/nn/functional/__init__.py.

Three kinds of definitions, matching how the capability exists here:
  * thin wrappers over registered op lowerings (paddle_tpu/ops/*) —
    same relationship as the reference's functional layer over
    `core.ops.*`;
  * small jax compositions for pure-math functions the reference
    implements in Python;
  * loud, documented guards for the LoD/SelectedRows/parameter-server
    era names whose infrastructure this TPU redesign deliberately does
    not carry (SURVEY.md §2.4 N/A families, tools/op_parity.py) — the
    name resolves, the error explains the dense alternative.
"""

from __future__ import annotations

import numpy as np

from ...fluid.dygraph.tracer import trace_fn, trace_op

__all__ = []  # populated by _export


def _export(fn):
    __all__.append(fn.__name__)
    return fn


def _jnp():
    import jax.numpy as jnp

    return jnp


# -- activations / elementwise ------------------------------------------------

@_export
def log_sigmoid(x, name=None):
    import jax

    return trace_fn(lambda x: jax.nn.log_sigmoid(x), {"x": x})


@_export
def softsign(x, name=None):
    jnp = _jnp()
    return trace_fn(lambda x: x / (1 + jnp.abs(x)), {"x": x})


@_export
def soft_relu(x, threshold=40.0, name=None):
    jnp = _jnp()

    def f(x):
        return jnp.log1p(jnp.exp(jnp.clip(x, -threshold, threshold)))

    return trace_fn(f, {"x": x})


@_export
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    jnp = _jnp()

    def f(a, b):
        na = jnp.linalg.norm(a, axis=axis, keepdims=True)
        nb = jnp.linalg.norm(b, axis=axis, keepdims=True)
        denom = jnp.maximum(na * nb, eps)
        return jnp.sum(a * b, axis=axis, keepdims=True).squeeze(axis) \
            / denom.squeeze(axis)

    return trace_fn(f, {"a": x1, "b": x2})


# -- losses -------------------------------------------------------------------

@_export
def dice_loss(input, label, epsilon=1e-5, name=None):
    """reference nn/functional/loss.py dice_loss (soft dice over the
    last dim's class probabilities)."""
    jnp = _jnp()

    def f(x, y):
        yoh = jnp.squeeze(y, -1) if y.shape[-1] == 1 else y
        yf = jnp.eye(x.shape[-1], dtype=x.dtype)[yoh.astype(jnp.int32)]
        red = tuple(range(1, x.ndim))
        inter = jnp.sum(x * yf, axis=red)
        union = jnp.sum(x, axis=red) + jnp.sum(yf, axis=red)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))

    return trace_fn(f, {"x": input, "y": label})


@_export
def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """reference loss.py npair_loss: softmax CE over anchor-positive
    similarity + L2 on the embeddings."""
    jnp = _jnp()

    def f(a, p, y):
        import jax

        sim = a @ p.T                    # (B, B)
        same = (y[:, None] == y[None, :]).astype(a.dtype)
        tgt = same / jnp.sum(same, axis=1, keepdims=True)
        ce = jnp.mean(jnp.sum(
            -tgt * jax.nn.log_softmax(sim, axis=1), axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1))
                        + jnp.mean(jnp.sum(p * p, axis=1))) / 2
        return ce + reg

    return trace_fn(f, {"a": anchor, "p": positive, "y": labels})


@_export
def fsp_matrix(x, y):
    """reference loss.py fsp_matrix (flow-of-solution-procedure for
    distillation): (B, Cx, Cy) = x-channels x y-channels Gram over
    spatial positions."""
    jnp = _jnp()

    def f(x, y):
        b, cx, h, w = x.shape
        cy = y.shape[1]
        xf = x.reshape(b, cx, h * w)
        yf = y.reshape(b, cy, h * w)
        return jnp.einsum("bxs,bys->bxy", xf, yf) / (h * w)

    return trace_fn(f, {"x": x, "y": y})


@_export
def bpr_loss(input, label, name=None):
    return trace_op("bpr_loss", {"X": input, "Label": label})


@_export
def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    return trace_op("teacher_student_sigmoid_loss",
                    {"X": input, "Label": label},
                    {"soft_max_up_bound": soft_max_up_bound,
                     "soft_max_lower_bound": soft_max_lower_bound})


@_export
def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    """reference loss.py center_loss — centers live in a module-level
    buffer per (num_classes, dim) since the eager API has no
    parameter attr plumbing here; returns the per-sample loss."""
    from ...fluid.dygraph.varbase import Tensor

    key = (num_classes, int(input.shape[-1]))
    buf = _CENTER_BUFFERS.setdefault(
        key, Tensor(np.zeros(key, "float32"), stop_gradient=True))
    rate = Tensor(np.asarray([alpha], "float32"), stop_gradient=True)
    outs = trace_op("center_loss",
                    {"X": input, "Label": label, "Centers": buf,
                     "CenterUpdateRate": rate},
                    {"cluster_num": num_classes, "need_update":
                     bool(update_center)}, multi_out=True)
    if isinstance(outs, dict):
        new_centers = outs.get("SampleCenterDiff") or []
        return outs["Loss"][0] if "Loss" in outs else outs["Out"][0]
    return outs


_CENTER_BUFFERS = {}


@_export
def ctc_loss(log_probs, labels, input_lengths, label_lengths,
             blank=0, reduction="mean"):
    """reference loss.py ctc_loss over the warpctc lowering; log_probs
    (T, B, C)."""
    jnp = _jnp()
    loss = trace_op("warpctc",
                    {"Logits": log_probs, "Label": labels,
                     "LogitsLength": input_lengths,
                     "LabelLength": label_lengths},
                    {"blank": blank})
    if reduction == "mean":
        return trace_fn(
            lambda l, n: jnp.mean(l.reshape(-1)
                                  / jnp.maximum(n.astype(l.dtype), 1)),
            {"l": loss, "n": label_lengths})
    if reduction == "sum":
        return trace_fn(lambda l: jnp.sum(l), {"l": loss})
    return loss


@_export
def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    ins = {"X": input, "Label": label, "W": weight}
    if bias is not None:
        ins["Bias"] = bias
    if path_table is not None:
        ins["PathTable"] = path_table
    if path_code is not None:
        ins["PathCode"] = path_code
    outs = trace_op("hierarchical_sigmoid", ins,
                    {"num_classes": num_classes}, multi_out=True)
    return outs["Out"][0] if isinstance(outs, dict) else outs


@_export
def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None,
        name=None, sampler="uniform", custom_dist=None, seed=0,
        is_sparse=False, weight=None, bias=None):
    ins = {"Input": input, "Label": label, "Weight": weight}
    if bias is not None:
        ins["Bias"] = bias
    outs = trace_op("nce", ins,
                    {"num_total_classes": num_total_classes,
                     "num_neg_samples": num_neg_samples or 10,
                     "seed": seed, "sampler": 0}, multi_out=True)
    return outs["Cost"][0] if isinstance(outs, dict) else outs


# -- conv / pool family -------------------------------------------------------

def _squeeze_call(x, f, axis):
    """Run a 2D spatial op on 1D data by inserting a unit dim."""
    jnp = _jnp()
    un = trace_fn(lambda x: jnp.expand_dims(x, axis), {"x": x})
    out = f(un)
    return trace_fn(lambda x: jnp.squeeze(x, axis), {"x": out})


@_export
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NCL", name=None):
    """(B, C, L) conv via the conv2d lowering on (B, C, 1, L)."""
    from . import conv2d

    s = stride if isinstance(stride, int) else stride[0]
    d = dilation if isinstance(dilation, int) else dilation[0]
    if isinstance(padding, str):
        pad2 = padding
    else:
        p = padding if isinstance(padding, int) else padding[0]
        pad2 = [0, p]
    jnp = _jnp()
    x4 = trace_fn(lambda x: jnp.expand_dims(x, 2), {"x": x})
    w4 = trace_fn(lambda w: jnp.expand_dims(w, 2), {"w": weight})
    out = conv2d(x4, w4, bias=bias, stride=[1, s], padding=pad2,
                 dilation=[1, d], groups=groups)
    return trace_fn(lambda x: jnp.squeeze(x, 2), {"x": out})


@_export
def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    from . import conv2d_transpose

    s = stride if isinstance(stride, int) else stride[0]
    d = dilation if isinstance(dilation, int) else dilation[0]
    p = padding if isinstance(padding, int) else padding[0]
    op = output_padding if isinstance(output_padding, int) \
        else output_padding[0]
    jnp = _jnp()
    x4 = trace_fn(lambda x: jnp.expand_dims(x, 2), {"x": x})
    w4 = trace_fn(lambda w: jnp.expand_dims(w, 2), {"w": weight})
    out = conv2d_transpose(x4, w4, bias=bias, stride=[1, s],
                           padding=[0, p], output_padding=[0, op],
                           dilation=[1, d], groups=groups)
    return trace_fn(lambda x: jnp.squeeze(x, 2), {"x": out})


@_export
def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    from . import _add_channel_bias, _pair

    padding, padding_algorithm = _norm_pad3(padding)
    out = trace_op("conv3d_transpose",
                   {"Input": x, "Filter": weight},
                   {"strides": _pair(stride, 3), "paddings": padding,
                    "dilations": _pair(dilation, 3), "groups": groups,
                    "padding_algorithm": padding_algorithm,
                    "data_format": data_format})
    if bias is not None:
        out = _add_channel_bias(out, bias, 1)
    return out


def _pool1d(x, kernel_size, stride, padding, pooling_type, ceil_mode,
            name):
    from . import avg_pool2d, max_pool2d

    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    s = (stride if stride is not None else k)
    s = s if isinstance(s, int) else s[0]
    p = padding if isinstance(padding, int) else (
        padding if isinstance(padding, str) else padding[0])
    jnp = _jnp()
    x4 = trace_fn(lambda x: jnp.expand_dims(x, 2), {"x": x})
    f = max_pool2d if pooling_type == "max" else avg_pool2d
    pad2 = p if isinstance(p, str) else [0, p]
    out = f(x4, [1, k], stride=[1, s], padding=pad2,
            ceil_mode=ceil_mode)
    return trace_fn(lambda x: jnp.squeeze(x, 2), {"x": out})


@_export
def max_pool1d(x, kernel_size, stride=None, padding=0,
               return_mask=False, ceil_mode=False, name=None):
    return _pool1d(x, kernel_size, stride, padding, "max", ceil_mode,
                   name)


@_export
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool1d(x, kernel_size, stride, padding, "avg", ceil_mode,
                   name)


def _norm_pad3(padding):
    """3D padding: int -> [p, p, p]; str -> SAME/VALID."""
    if isinstance(padding, str):
        return [0, 0, 0], padding.upper()
    if isinstance(padding, int):
        return [padding] * 3, "EXPLICIT"
    return list(padding), "EXPLICIT"


def _pool3d(x, kernel_size, stride, padding, pooling_type, ceil_mode):
    from . import _pair

    stride = stride if stride is not None else kernel_size
    padding, padding_algorithm = _norm_pad3(padding)
    return trace_op("pool3d", {"X": x},
                    {"pooling_type": pooling_type,
                     "ksize": _pair(kernel_size, 3),
                     "strides": _pair(stride, 3), "paddings": padding,
                     "padding_algorithm": padding_algorithm,
                     "ceil_mode": ceil_mode, "adaptive": False,
                     "global_pooling": False})


@_export
def max_pool3d(x, kernel_size, stride=None, padding=0,
               return_mask=False, ceil_mode=False,
               data_format="NCDHW", name=None):
    return _pool3d(x, kernel_size, stride, padding, "max", ceil_mode)


@_export
def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None,
               data_format="NCDHW", name=None):
    return _pool3d(x, kernel_size, stride, padding, "avg", ceil_mode)


def _adaptive_pool_nd(x, output_size, spatial, reduce_fn):
    """Adaptive pooling over the trailing `spatial` dims via per-dim
    region splits (exact reference semantics for any output size)."""
    jnp = _jnp()
    sizes = ([output_size] * spatial
             if isinstance(output_size, int) else list(output_size))

    def f(x):
        out = x
        for i, osz in enumerate(sizes):
            axis = x.ndim - spatial + i
            isz = out.shape[axis]
            # region r covers [floor(r*isz/osz), ceil((r+1)*isz/osz))
            starts = [(r * isz) // osz for r in range(osz)]
            ends = [-(-((r + 1) * isz) // osz) for r in range(osz)]
            pieces = [reduce_fn(jnp.take(
                out, jnp.arange(s, e), axis=axis), axis=axis,
                keepdims=True) for s, e in zip(starts, ends)]
            out = jnp.concatenate(pieces, axis=axis)
        return out

    return trace_fn(f, {"x": x})


@_export
def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool_nd(x, output_size, 1, _jnp().mean)


@_export
def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool_nd(x, output_size, 1, _jnp().max)


@_export
def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool_nd(x, output_size, 3, _jnp().mean)


@_export
def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool_nd(x, output_size, 3, _jnp().max)


# -- vision / geometry --------------------------------------------------------

@_export
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    return trace_op("grid_sampler", {"X": x, "Grid": grid},
                    {"mode": mode, "padding_mode": padding_mode,
                     "align_corners": align_corners})


@_export
def affine_grid(theta, out_shape, align_corners=True, name=None):
    attrs = {"align_corners": align_corners}
    ins = {"Theta": theta}
    if hasattr(out_shape, "shape") and not isinstance(
            out_shape, (list, tuple)):
        ins["OutputShape"] = out_shape
    else:
        attrs["output_shape"] = [int(v) for v in out_shape]
    return trace_op("affine_grid", ins, attrs)


@_export
def affine_channel(x, scale=None, bias=None, data_layout="NCHW",
                   name=None):
    return trace_op("affine_channel",
                    {"X": x, "Scale": scale, "Bias": bias},
                    {"data_layout": data_layout})


@_export
def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    """Inverse of pixel_shuffle: (B, C, H, W) -> (B, C*r^2, H/r, W/r)
    with pixel_unshuffle(pixel_shuffle(y, r), r) == y.  NOT the
    space_to_depth op — that one reproduces the reference kernel's
    quirky buffer reinterpretation, a different permutation."""
    jnp = _jnp()
    r = int(downscale_factor)

    def f(x):
        b, c, h, w = x.shape
        y = x.reshape(b, c, h // r, r, w // r, r)
        y = jnp.transpose(y, (0, 1, 3, 5, 2, 4))
        return y.reshape(b, c * r * r, h // r, w // r)

    return trace_fn(f, {"x": x})


@_export
def space_to_depth(x, blocksize, name=None):
    return trace_op("space_to_depth", {"X": x},
                    {"blocksize": blocksize})


@_export
def shuffle_channel(x, group, name=None):
    return trace_op("shuffle_channel", {"X": x}, {"group": group})


@_export
def deformable_conv(x, offset, mask, weight, bias=None, stride=1,
                    padding=0, dilation=1, deformable_groups=1,
                    groups=1, im2col_step=1, name=None):
    from . import _add_channel_bias, _pair

    ins = {"Input": x, "Offset": offset, "Filter": weight}
    if mask is not None:
        ins["Mask"] = mask
    out = trace_op("deformable_conv", ins,
                   {"strides": _pair(stride), "paddings": _pair(padding),
                    "dilations": _pair(dilation),
                    "deformable_groups": deformable_groups,
                    "groups": groups, "im2col_step": im2col_step})
    if bias is not None:
        out = _add_channel_bias(out, bias, 1)
    return out


@_export
def roi_pool(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0,
             name=None):
    osz = ([output_size] * 2 if isinstance(output_size, int)
           else list(output_size))
    outs = trace_op("roi_pool", {"X": x, "ROIs": boxes},
                    {"pooled_height": osz[0], "pooled_width": osz[1],
                     "spatial_scale": spatial_scale}, multi_out=True)
    return outs["Out"][0] if isinstance(outs, dict) else outs


@_export
def prroi_pool(x, boxes, output_channels=None, spatial_scale=1.0,
               pooled_height=1, pooled_width=1, batch_roi_nums=None,
               name=None):
    return trace_op("prroi_pool", {"X": x, "ROIs": boxes},
                    {"pooled_height": pooled_height,
                     "pooled_width": pooled_width,
                     "spatial_scale": spatial_scale})


@_export
def psroi_pool(x, boxes, boxes_num=None, output_channels=1,
               spatial_scale=1.0, pooled_height=1, pooled_width=1,
               name=None):
    return trace_op("psroi_pool", {"X": x, "ROIs": boxes},
                    {"output_channels": output_channels,
                     "pooled_height": pooled_height,
                     "pooled_width": pooled_width,
                     "spatial_scale": spatial_scale})


@_export
def polygon_box_transform(input, name=None):
    return trace_op("polygon_box_transform", {"Input": input})


@_export
def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True,
                     align_mode=1, data_format="NCDHW"):
    if out_shape is not None:
        d, h, w = [int(v) for v in out_shape]
    elif scale is not None:
        d, h, w = [int(s * scale) for s in input.shape[2:5]]
    else:
        raise ValueError(
            "resize_trilinear needs out_shape or scale")
    return trace_op("trilinear_interp", {"X": input},
                    {"out_d": d, "out_h": h, "out_w": w,
                     "align_corners": align_corners,
                     "align_mode": align_mode,
                     "data_layout": data_format})


@_export
def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """reference nn.py image_resize_short: scale so the SHORT side hits
    out_short_len, keeping aspect."""
    h, w = int(input.shape[2]), int(input.shape[3])
    short = min(h, w)
    oh = int(round(h * out_short_len / short))
    ow = int(round(w * out_short_len / short))
    op = ("bilinear_interp" if resample.upper() == "BILINEAR"
          else "nearest_interp")
    return trace_op(op, {"X": input},
                    {"out_h": oh, "out_w": ow, "align_corners": True,
                     "align_mode": 1})


@_export
def random_crop(x, shape, seed=None):
    return trace_op("random_crop", {"X": x},
                    {"shape": list(shape),
                     "startup_seed": int(seed or 0)})


# -- sequence / misc op wrappers ----------------------------------------------

@_export
def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    return trace_op("add_position_encoding", {"X": input},
                    {"alpha": alpha, "beta": beta})


@_export
def bilinear_tensor_product(x, y, weight, bias=None, name=None):
    ins = {"X": x, "Y": y, "Weight": weight}
    if bias is not None:
        ins["Bias"] = bias
    return trace_op("bilinear_tensor_product", ins)


bilinear = bilinear_tensor_product
__all__.append("bilinear")


@_export
def row_conv(input, weight, act=None):
    out = trace_op("row_conv", {"X": input, "Filter": weight})
    if act:
        out = trace_op(act, {"X": out})
    return out


@_export
def spectral_norm(weight, u, v, dim=0, power_iters=1, eps=1e-12,
                  name=None):
    return trace_op("spectral_norm",
                    {"Weight": weight, "U": u, "V": v},
                    {"dim": dim, "power_iters": power_iters,
                     "eps": eps})


@_export
def data_norm(input, batch_size, batch_sum, batch_square_sum,
              epsilon=1e-4, name=None):
    return trace_op("data_norm",
                    {"X": input, "BatchSize": batch_size,
                     "BatchSum": batch_sum,
                     "BatchSquareSum": batch_square_sum},
                    {"epsilon": epsilon})


@_export
def continuous_value_model(input, cvm, use_cvm=True):
    return trace_op("cvm", {"X": input, "CVM": cvm},
                    {"use_cvm": use_cvm})


@_export
def gru_unit(input, hidden, weight, bias=None,
             activation="tanh", gate_activation="sigmoid",
             origin_mode=False):
    ins = {"Input": input, "HiddenPrev": hidden, "Weight": weight}
    if bias is not None:
        ins["Bias"] = bias
    outs = trace_op("gru_unit", ins,
                    {"activation": activation,
                     "gate_activation": gate_activation,
                     "origin_mode": origin_mode}, multi_out=True)
    if isinstance(outs, dict):
        return (outs["Hidden"][0], outs["ResetHiddenPrev"][0],
                outs["Gate"][0])
    return outs


@_export
def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """reference rnn.py lstm_unit over the lstm_unit op: the x/h
    projection happens OUTSIDE the op in the reference too."""
    outs = trace_op("lstm_unit",
                    {"X": x_t, "C_prev": cell_t_prev},
                    {"forget_bias": forget_bias}, multi_out=True)
    if isinstance(outs, dict):
        return outs["H"][0], outs["C"][0]
    return outs


@_export
def sequence_reshape(input, new_dim):
    x, lod = input if isinstance(input, tuple) else (input, None)
    return trace_op("sequence_reshape", {"X": x}, {"new_dim": new_dim})


@_export
def sequence_scatter(input, index, updates, name=None):
    return trace_op("sequence_scatter",
                    {"X": input, "Ids": index, "Updates": updates})


@_export
def im2sequence(input, filter_size=1, stride=1, padding=0,
                input_image_size=None, out_stride=1, name=None):
    from . import _pair

    return trace_op("im2sequence", {"X": input},
                    {"kernels": _pair(filter_size),
                     "strides": _pair(stride),
                     "paddings": _pair(padding, 4)})


@_export
def lod_reset(x, y=None, target_lod=None):
    ins = {"X": x}
    if y is not None:
        ins["Y"] = y
    return trace_op("lod_reset", ins,
                    {"target_lod": target_lod or []})


@_export
def tensor_array_to_tensor(input, axis=1, use_stack=False, name=None):
    outs = trace_op("tensor_array_to_tensor", {"X": list(input)},
                    {"axis": axis, "use_stack": use_stack},
                    multi_out=True)
    if isinstance(outs, dict):
        idx = outs.get("OutIndex", [None])[0]
        return outs["Out"][0], idx
    return outs


@_export
def pad_constant_like(x, y, pad_value=0.0, name=None):
    return trace_op("pad_constant_like", {"X": x, "Y": y},
                    {"pad_value": float(pad_value)})


# -- dropout variants ---------------------------------------------------------

@_export
def alpha_dropout(x, p=0.5, training=True, name=None):
    """SELU-preserving dropout (reference common.py alpha_dropout)."""
    if not training or p == 0.0:
        return x
    import jax

    from . import _traced_random

    jnp = _jnp()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def f(x, key):
        keep = jax.random.bernoulli(key, 1 - p, x.shape)
        a = (1 / ((1 - p) * (1 + p * alpha_p ** 2)) ** 0.5)
        b = -a * alpha_p * p
        return (a * jnp.where(keep, x, alpha_p) + b).astype(x.dtype)

    return _traced_random(f, x)


@_export
def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    """Channel-wise dropout on 5D input (reference common.py): whole
    (N, C) channels are zeroed together, mask shape (N, C, 1, 1, 1)."""
    if not training or p == 0.0:
        return x
    import jax

    from . import _traced_random

    jnp = _jnp()
    caxis = 1 if data_format == "NCDHW" else 4

    def f(x, key):
        mshape = [x.shape[0]] + [1] * 4
        mshape[caxis] = x.shape[caxis]
        keep = jax.random.bernoulli(key, 1 - p, tuple(mshape))
        return jnp.where(keep, x / (1 - p), 0.0).astype(x.dtype)

    return _traced_random(f, x)


# -- LoD / SelectedRows / PS-era names: documented descopes -------------------

def _na(name, why, alternative):
    def fn(*a, **k):
        raise NotImplementedError(
            f"paddle.nn.functional.{name} is not carried by this "
            f"TPU-native build: {why} (SURVEY.md §2.4 N/A families, "
            f"tools/op_parity.py). Use instead: {alternative}")

    fn.__name__ = name
    __all__.append(name)
    return fn


hash = _na(  # noqa: A001 - reference API shadows builtin
    "hash", "xxhash sparse-id hashing belongs to the parameter-server "
    "sparse-embedding path", "dense embedding lookups "
    "(paddle.nn.functional.embedding)")
filter_by_instag = _na(
    "filter_by_instag", "instance-tag filtering is part of the PS "
    "sparse-feature pipeline", "boolean masking with paddle.masked_select")
similarity_focus = _na(
    "similarity_focus", "a rarely-used CUDA op with data-dependent "
    "output patterns that defeat XLA static shapes",
    "explicit masking built from paddle.topk indices")
roi_perspective_transform = _na(
    "roi_perspective_transform", "rotated-ROI warping (RRPN) needs "
    "data-dependent gather patterns kept out of the static-shape op "
    "set", "paddle.nn.functional.grid_sample with precomputed grids")
deformable_roi_pooling = _na(
    "deformable_roi_pooling", "superseded by deformable_conv + "
    "roi_align in the supported detection path",
    "paddle.nn.functional.deformable_conv / roi_align")
multi_box_head = _na(
    "multi_box_head", "the SSD head builder creates parameters, which "
    "is a static-graph (LayerHelper) affair",
    "paddle.static.nn.multi_box_head (implemented) inside a static "
    "program, or prior_box + nn.Conv2D composition in dygraph")
merge_selected_rows = _na(
    "merge_selected_rows", "SelectedRows never materializes here "
    "(gradients are dense on TPU)", "dense tensors directly")
reorder_lod_tensor_by_rank = _na(
    "reorder_lod_tensor_by_rank", "LoD metadata is replaced by dense "
    "padding + explicit lengths", "paddle.gather over a rank index")
lod_append = _na(
    "lod_append", "LoD metadata is replaced by dense padding + "
    "explicit lengths", "sequence_pad / explicit length tensors")
dynamic_lstmp = _na(
    "dynamic_lstmp", "LoD-ragged projection LSTM; the dense-batch "
    "path covers the capability", "paddle.nn.LSTM (with projection "
    "via a Linear on outputs) over padded batches")
autoincreased_step_counter = _na(
    "autoincreased_step_counter", "global step state lives in the "
    "optimizer state pytree on TPU (host-side counters would break "
    "the fused step)", "the optimizer's own step counter "
    "(state['t']) or paddle.optimizer.lr schedulers")


# -- cell drivers (reference nn/functional/rnn.py) ----------------------------

@_export
def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Run a cell over time (reference functional rnn — the RNN layer
    is the same driver)."""
    from ..layer.rnn import RNN

    return RNN(cell, is_reverse=is_reverse, time_major=time_major)(
        inputs, initial_states, sequence_length)


@_export
def birnn(cell_fw, cell_bw, inputs, initial_states=None,
          sequence_length=None, time_major=False, **kwargs):
    from ..layer.rnn import BiRNN

    return BiRNN(cell_fw, cell_bw, time_major=time_major)(
        inputs, initial_states, sequence_length)


@_export
def lstm(input, init_h, init_c, weight, bias=None, hidden_size=None,
         num_layers=1, dropout_prob=0.0, is_bidirec=False, **kwargs):
    """reference rnn.py lstm (the cudnn-fused multi-layer LSTM op)."""
    ins = {"Input": input, "Weight": weight}
    if bias is not None:
        ins["Bias"] = bias
    if init_h is not None:
        ins["InitH"] = init_h
    if init_c is not None:
        ins["InitC"] = init_c
    outs = trace_op("lstm", ins,
                    {"hidden_size": hidden_size or 0,
                     "num_layers": num_layers,
                     "dropout_prob": dropout_prob,
                     "is_bidirec": is_bidirec}, multi_out=True)
    if isinstance(outs, dict):
        return (outs["Out"][0], outs.get("LastH", [None])[0],
                outs.get("LastC", [None])[0])
    return outs


@_export
def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           exclusive=True, data_format="NCDHW", name=None):
    """Legacy fluid-style pool3d signature over the pool3d lowering."""
    from . import _pair

    padding, padding_algorithm = _norm_pad3(pool_padding)
    return trace_op("pool3d", {"X": input},
                    {"pooling_type": pool_type,
                     "ksize": _pair(pool_size, 3),
                     "strides": _pair(pool_stride, 3),
                     "paddings": padding,
                     "padding_algorithm": padding_algorithm,
                     "ceil_mode": ceil_mode, "adaptive": False,
                     "global_pooling": global_pooling})


# -- detection op tail (reference nn/functional/vision.py + extension) --------

@_export
def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       return_rois_num=False, name=None):
    outs = trace_op("generate_proposals",
                    {"Scores": scores, "BboxDeltas": bbox_deltas,
                     "ImInfo": im_info, "Anchors": anchors,
                     "Variances": variances},
                    {"pre_nms_topN": pre_nms_top_n,
                     "post_nms_topN": post_nms_top_n,
                     "nms_thresh": nms_thresh, "min_size": min_size,
                     "eta": eta}, multi_out=True)
    rois = outs["RpnRois"][0]
    probs = outs["RpnRoiProbs"][0]
    if return_rois_num:
        return rois, probs, outs.get("RpnRoisNum", [None])[0]
    return rois, probs


@_export
def distribute_fpn_proposals(fpn_rois, min_level, max_level,
                             refer_level, refer_scale,
                             rois_num=None, name=None):
    outs = trace_op("distribute_fpn_proposals", {"FpnRois": fpn_rois},
                    {"min_level": min_level, "max_level": max_level,
                     "refer_level": refer_level,
                     "refer_scale": refer_scale}, multi_out=True)
    return (outs["MultiFpnRois"],
            outs["RestoreIndex"][0])


@_export
def collect_fpn_proposals(multi_rois, multi_scores, min_level,
                          max_level, post_nms_top_n, rois_num=None,
                          name=None):
    outs = trace_op("collect_fpn_proposals",
                    {"MultiLevelRois": list(multi_rois),
                     "MultiLevelScores": list(multi_scores)},
                    {"post_nms_topN": post_nms_top_n}, multi_out=True)
    return outs["FpnRois"][0] if isinstance(outs, dict) else outs


@_export
def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    outs = trace_op("density_prior_box",
                    {"Input": input, "Image": image},
                    {"densities": list(densities or []),
                     "fixed_sizes": list(fixed_sizes or []),
                     "fixed_ratios": list(fixed_ratios or []),
                     "variances": list(variance),
                     "clip": clip, "steps": list(steps),
                     "offset": offset,
                     "flatten_to_2d": flatten_to_2d}, multi_out=True)
    return outs["Boxes"][0], outs["Variances"][0]


@_export
def box_decoder_and_assign(prior_box, prior_box_var, target_box,
                           box_score, box_clip, name=None):
    outs = trace_op("box_decoder_and_assign",
                    {"PriorBox": prior_box,
                     "PriorBoxVar": prior_box_var,
                     "TargetBox": target_box, "BoxScore": box_score},
                    {"box_clip": box_clip}, multi_out=True)
    return (outs["DecodeBox"][0],
            outs["OutputAssignBox"][0])


@_export
def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    return trace_op("retinanet_detection_output",
                    {"BBoxes": list(bboxes), "Scores": list(scores),
                     "Anchors": list(anchors), "ImInfo": im_info},
                    {"score_threshold": score_threshold,
                     "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                     "nms_threshold": nms_threshold,
                     "nms_eta": nms_eta})


@_export
def retinanet_target_assign(bbox_pred, cls_logits, anchor_box,
                            anchor_var, gt_boxes, gt_labels, is_crowd,
                            im_info, num_classes=1,
                            positive_overlap=0.5,
                            negative_overlap=0.4):
    outs = trace_op("retinanet_target_assign",
                    {"Anchor": anchor_box, "GtBoxes": gt_boxes,
                     "GtLabels": gt_labels, "IsCrowd": is_crowd,
                     "ImInfo": im_info},
                    {"positive_overlap": positive_overlap,
                     "negative_overlap": negative_overlap},
                    multi_out=True)
    loc_idx = outs["LocationIndex"][0]
    score_idx = outs["ScoreIndex"][0]
    tgt_lbl = outs["TargetLabel"][0]
    tgt_bbox = outs["TargetBBox"][0]
    fg_num = outs.get("ForegroundNumber", [None])[0]
    return (None, None, tgt_bbox, tgt_lbl, loc_idx, score_idx, fg_num)


@_export
def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd, im_info,
                      rpn_batch_size_per_im=256,
                      rpn_straddle_thresh=0.0, rpn_fg_fraction=0.5,
                      rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    outs = trace_op("rpn_target_assign",
                    {"Anchor": anchor_box, "GtBoxes": gt_boxes,
                     "IsCrowd": is_crowd, "ImInfo": im_info},
                    {"rpn_batch_size_per_im": rpn_batch_size_per_im,
                     "rpn_straddle_thresh": rpn_straddle_thresh,
                     "rpn_fg_fraction": rpn_fg_fraction,
                     "rpn_positive_overlap": rpn_positive_overlap,
                     "rpn_negative_overlap": rpn_negative_overlap,
                     "use_random": use_random}, multi_out=True)
    return (outs["LocationIndex"][0], outs["ScoreIndex"][0],
            outs["TargetBBox"][0], outs["TargetLabel"][0],
            outs.get("BBoxInsideWeight", [None])[0])


@_export
def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    ins = {"X": input, "MatchIndices": matched_indices}
    if negative_indices is not None:
        ins["NegIndices"] = negative_indices
    outs = trace_op("target_assign", ins,
                    {"mismatch_value": mismatch_value or 0},
                    multi_out=True)
    return outs["Out"][0], outs["OutWeight"][0]


@_export
def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.25,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False,
                             is_cascade_rcnn=False):
    outs = trace_op("generate_proposal_labels",
                    {"RpnRois": rpn_rois, "GtClasses": gt_classes,
                     "IsCrowd": is_crowd, "GtBoxes": gt_boxes,
                     "ImInfo": im_info},
                    {"batch_size_per_im": batch_size_per_im,
                     "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
                     "bg_thresh_hi": bg_thresh_hi,
                     "bg_thresh_lo": bg_thresh_lo,
                     "bbox_reg_weights": list(bbox_reg_weights),
                     "class_nums": class_nums or 81,
                     "use_random": use_random}, multi_out=True)
    return (outs["Rois"][0], outs["LabelsInt32"][0],
            outs["BboxTargets"][0], outs["BboxInsideWeights"][0],
            outs["BboxOutsideWeights"][0])


@_export
def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms,
                         rois, labels_int32, num_classes, resolution):
    outs = trace_op("generate_mask_labels",
                    {"ImInfo": im_info, "GtClasses": gt_classes,
                     "IsCrowd": is_crowd, "GtSegms": gt_segms,
                     "Rois": rois, "LabelsInt32": labels_int32},
                    {"num_classes": num_classes,
                     "resolution": resolution}, multi_out=True)
    return (outs["MaskRois"][0], outs["RoiHasMaskInt32"][0],
            outs["MaskInt32"][0])
