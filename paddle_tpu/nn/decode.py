"""Seq2seq decoding API (reference python/paddle/fluid/layers/rnn.py
BeamSearchDecoder:1015 + dynamic_decode:1569, re-exported by
python/paddle/nn/__init__.py).

TPU-native re-design: beams live DENSELY as a flattened (batch*beam)
leading dim — no LoD, no SelectedRows; parent hand-off is a gather
over that dim, exactly the transformer beam decode's bookkeeping
(paddle_tpu/models/transformer_wmt.py beam_decode).  dynamic_decode
drives the decoder step-by-step eagerly (dygraph mode — the
reference's dygraph path is the same python loop); for a fully
compiled decode use the models' lax.while_loop implementations.
"""

from __future__ import annotations

import numpy as np

from ..fluid.dygraph.tracer import trace_fn
from ..fluid.dygraph.varbase import Tensor


def _tree_map(f, t):
    if isinstance(t, (list, tuple)):
        return type(t)(_tree_map(f, x) for x in t)
    return f(t)


def _tree_leaves(t):
    if isinstance(t, (list, tuple)):
        out = []
        for x in t:
            out.extend(_tree_leaves(x))
        return out
    return [t]


class Decoder:
    """Abstract decode contract (reference rnn.py Decoder:964):
    initialize() -> (initial_inputs, initial_states, initial_finished);
    step() -> (outputs, next_states, next_inputs, finished)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam search over an RNN cell (reference rnn.py
    BeamSearchDecoder:1015).  States and inputs carry a flattened
    (batch*beam_size) leading dim."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- helpers (the reference exposes these as static methods) ----------

    def tile_beam_merge_with_batch(self, x):
        """(B, ...) -> (B*K, ...) by repeating each row K times."""
        import jax.numpy as jnp

        k = self.beam_size
        return trace_fn(
            lambda x: jnp.repeat(x, k, axis=0), {"x": x})

    # -- contract ---------------------------------------------------------

    def initialize(self, initial_cell_states):
        import jax.numpy as jnp

        states = _tree_map(self.tile_beam_merge_with_batch,
                           initial_cell_states)
        bk = int(_tree_leaves(states)[0].shape[0])
        b, k = bk // self.beam_size, self.beam_size
        tokens = Tensor(np.full((bk,), self.start_token, "int64"),
                        stop_gradient=True)
        inputs = (self.embedding_fn(tokens) if self.embedding_fn
                  else tokens)
        # beam 0 starts live, the rest at -inf so step 1 fans out from
        # one beam per batch element (the reference's kInitLogProb)
        lp = np.full((b, k), -1e9, "float32")
        lp[:, 0] = 0.0
        self._log_probs = Tensor(lp, stop_gradient=True)
        finished = Tensor(np.zeros((b, k), bool), stop_gradient=True)
        # the finished mask also lives on the decoder so step() works
        # standalone per the Decoder contract (not only under
        # dynamic_decode)
        self._finished_in = finished
        return inputs, states, finished

    def step(self, time, inputs, states, **kwargs):
        import jax.numpy as jnp

        cell_out, next_states = self.cell(inputs, states, **kwargs)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        k = self.beam_size

        def beam_step(logits, lp, fin):
            bk, v = logits.shape
            b = bk // k
            logp = jnp.log(jnp.maximum(
                jnp.exp(logits - jnp.max(logits, -1, keepdims=True))
                / jnp.sum(jnp.exp(
                    logits - jnp.max(logits, -1, keepdims=True)),
                    -1, keepdims=True), 1e-20)).reshape(b, k, v)
            # finished beams only extend with end_token at no cost
            mask = jnp.full((v,), -1e9).at[self.end_token].set(0.0)
            logp = jnp.where(fin[:, :, None], mask[None, None, :], logp)
            total = lp[:, :, None] + logp           # (b, k, v)
            flat = total.reshape(b, k * v)
            top, idx = jax.lax.top_k(flat, k)
            parent = idx // v                        # (b, k) in [0, k)
            token = (idx % v).astype(jnp.int64)
            fin2 = jnp.take_along_axis(fin, parent, axis=1) \
                | (token == self.end_token)
            gather = (jnp.arange(b)[:, None] * k + parent).reshape(-1)
            return top, parent, token, fin2, gather

        import jax

        outs = trace_fn(
            lambda logits, lp, fin: beam_step(logits, lp, fin),
            {"logits": cell_out, "lp": self._log_probs,
             "fin": self._finished_in}, multi_out=True)
        top, parent, token, fin2, gather = outs
        self._log_probs = top.detach()
        # reorder every cell state by the parent pointers
        next_states = _tree_map(
            lambda s: trace_fn(
                lambda s, g: jnp.take(s, g.astype(jnp.int32), axis=0),
                {"s": s, "g": gather}), next_states)
        flat_tok = trace_fn(lambda t: t.reshape(-1), {"t": token})
        inputs = (self.embedding_fn(flat_tok) if self.embedding_fn
                  else flat_tok)
        self._finished_in = fin2
        outputs = {"predicted_ids": token, "parent_ids": parent,
                   "scores": top}
        return outputs, next_states, inputs, fin2

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Drive a Decoder until every sequence finishes or max_step_num
    (reference rnn.py dynamic_decode:1569).  Returns (outputs,
    final_states) — outputs stacked along time axis 1 (or 0 when
    output_time_major)."""
    import jax.numpy as jnp

    max_step_num = max_step_num or 64
    inputs, states, finished = decoder.initialize(inits)
    collected = []
    seq_len = None
    for t in range(int(max_step_num)):
        outputs, states, inputs, finished = decoder.step(
            t, inputs, states, **kwargs)
        collected.append(outputs)
        fin_np = np.asarray(finished.numpy(), bool)
        if seq_len is None:
            seq_len = np.full(fin_np.shape, 0, "int64")
        seq_len = np.where((seq_len == 0) & fin_np, t + 1, seq_len)
        if fin_np.all():
            break
    seq_len = np.where(seq_len == 0, len(collected), seq_len)
    axis = 0 if output_time_major else 1

    def stack_vals(vals):
        n = len(vals)

        def f(**kw):
            return jnp.stack([kw[f"x{i}"] for i in range(n)],
                             axis=axis)

        return trace_fn(f, {f"x{i}": v for i, v in enumerate(vals)})

    if isinstance(collected[0], dict):
        stacked = {k: stack_vals([c[k] for c in collected])
                   for k in collected[0]}
    elif isinstance(collected[0], (list, tuple)):
        stacked = type(collected[0])(
            stack_vals([c[i] for c in collected])
            for i in range(len(collected[0])))
    else:
        stacked = stack_vals(collected)
    outputs, states = decoder.finalize(stacked, states, seq_len)
    if return_length:
        return outputs, states, Tensor(seq_len, stop_gradient=True)
    return outputs, states
