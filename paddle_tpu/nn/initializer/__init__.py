"""`paddle.nn.initializer` — 2.0-style initializer names over the fluid
initializer implementations (reference:
python/paddle/nn/initializer/__init__.py)."""

from ...fluid.initializer import (BilinearInitializer as Bilinear,
                                  ConstantInitializer as Constant,
                                  MSRAInitializer,
                                  NormalInitializer as Normal,
                                  NumpyArrayInitializer as Assign,
                                  TruncatedNormalInitializer as
                                  TruncatedNormal,
                                  UniformInitializer as Uniform,
                                  XavierInitializer)


class KaimingNormal(MSRAInitializer):
    def __init__(self, fan_in=None, name=None):
        super().__init__(uniform=False, fan_in=fan_in)


class KaimingUniform(MSRAInitializer):
    def __init__(self, fan_in=None, name=None):
        super().__init__(uniform=True, fan_in=fan_in)


class XavierNormal(XavierInitializer):
    def __init__(self, fan_in=None, fan_out=None, name=None):
        super().__init__(uniform=False, fan_in=fan_in, fan_out=fan_out)


class XavierUniform(XavierInitializer):
    def __init__(self, fan_in=None, fan_out=None, name=None):
        super().__init__(uniform=True, fan_in=fan_in, fan_out=fan_out)
