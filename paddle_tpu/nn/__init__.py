"""`paddle.nn`-equivalent package (reference: python/paddle/nn/__init__.py).

Layer classes are dygraph modules over the eager jax engine; the same
`forward` traces under `paddle_tpu.jit.to_static` / `jax.jit` into one XLA
computation (the TPU replacement for the reference's dy2static AST
transpiler, SURVEY.md §7 step 8).
"""

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer.activation import (ELU, GELU, SELU, Hardshrink, Hardsigmoid,
                               Hardswish, Hardtanh, LeakyReLU, LogSoftmax,
                               Maxout, Mish, PReLU, ReLU, ReLU6, Sigmoid,
                               Silu, Softmax, Softplus, Softshrink, Swish,
                               Tanh, Tanhshrink, ThresholdedReLU)
from .layer.common import (Bilinear, CosineSimilarity, Dropout, Dropout2D, SwitchMoE,
                           Embedding, Flatten, Linear, Pad1D, Pad2D, Pad3D,
                           PixelShuffle, Upsample, UpsamplingBilinear2D,
                           UpsamplingNearest2D)
from .layer.container import LayerList, ParameterList, Sequential
from .layer.conv import Conv1D, Conv2D, Conv2DTranspose, Conv3D
from .layer.layers import Layer, Parameter
from .layer.loss import (BCELoss, BCEWithLogitsLoss, CrossEntropyLoss,
                         KLDivLoss, L1Loss, MarginRankingLoss, MSELoss,
                         NLLLoss, SmoothL1Loss)
from .layer.norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
                         GroupNorm, InstanceNorm1D, InstanceNorm2D,
                         InstanceNorm3D, LayerNorm, LocalResponseNorm,
                         SpectralNorm, SyncBatchNorm)
from .layer.pooling import (AdaptiveAvgPool2D, AdaptiveMaxPool2D, AvgPool1D,
                            AvgPool2D, MaxPool1D, MaxPool2D)
from .layer.rnn import (RNN, BiRNN, GRU, GRUCell, LSTM, LSTMCell,
                        RNNCellBase, SimpleRNN, SimpleRNNCell)
from .layer.transformer import (MultiHeadAttention, Transformer,
                                TransformerDecoder, TransformerDecoderLayer,
                                TransformerEncoder, TransformerEncoderLayer)

# 2.0 nn tail (reference nn/__init__.py uncommented DEFINE_ALIAS set)
from .layer import conv, loss  # noqa: F401 - submodule aliases
from .layer import vision  # noqa: F401
from .layer.extra_layers import (AdaptiveAvgPool1D, AdaptiveAvgPool3D,
                                 AdaptiveMaxPool1D, AdaptiveMaxPool3D,
                                 AlphaDropout, AvgPool3D,
                                 BilinearTensorProduct, CTCLoss,
                                 Conv1DTranspose, Conv3DTranspose,
                                 Dropout3D, HSigmoidLoss, LogSigmoid,
                                 MaxPool3D, PairwiseDistance, Pool2D,
                                 RowConv, Softsign)
from ..fluid.clip import (ClipGradByGlobalNorm, ClipGradByNorm,
                          ClipGradByValue)
from ..fluid.layers import clip, clip_by_norm  # noqa: F401
from .decode import BeamSearchDecoder, Decoder, dynamic_decode
