"""paddle_tpu: a TPU-native deep-learning framework with the capabilities
of Fluid-era PaddlePaddle (reference: breeze1982/Paddle, read-only at
/root/reference — studied for behavior/API, re-designed for TPU).

Architecture (vs. the reference, SURVEY.md §7):
  * Program IR (paddle_tpu/fluid/framework.py) — pure-Python serializable
    graph instead of a C++ protobuf + Python mirror pair.
  * Op lowering registry (paddle_tpu/ops/) — op -> jax/XLA emitter instead
    of per-(place,dtype,layout) kernel registries.
  * Executor (paddle_tpu/fluid/executor.py) — whole-block jit compilation
    instead of a per-op interpreter.
  * append_backward (paddle_tpu/fluid/backward.py) — grad-op synthesis via
    cached jax.vjp instead of 650 hand-written GradOpMakers.
  * Distributed (paddle_tpu/parallel/, paddle_tpu/distributed/) — device
    meshes + XLA collectives over ICI instead of NCCL rings + program
    transpilers.
"""

from __future__ import annotations

__version__ = "0.1.0"

from . import fluid
from . import ops
from .fluid import (CPUPlace, CUDAPlace, TPUPlace, Executor, ParamAttr,
                    Program, Variable, append_backward, cpu_places,
                    cuda_places, default_main_program,
                    default_startup_program, global_scope, program_guard,
                    scope_guard, tpu_places, in_dygraph_mode)
from .fluid.layers.tensor import data

enable_static = lambda: None  # static mode is the default, as in 1.x


def disable_static():
    raise NotImplementedError("dygraph mode: see paddle_tpu.fluid.dygraph")
