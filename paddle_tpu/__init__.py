"""paddle_tpu: a TPU-native deep-learning framework with the capabilities
of Fluid-era PaddlePaddle (reference: breeze1982/Paddle, read-only at
/root/reference — studied for behavior/API, re-designed for TPU).

Architecture (vs. the reference, SURVEY.md §7):
  * Program IR (paddle_tpu/fluid/framework.py) — pure-Python serializable
    graph instead of a C++ protobuf + Python mirror pair.
  * Op lowering registry (paddle_tpu/ops/) — op -> jax/XLA emitter instead
    of per-(place,dtype,layout) kernel registries.
  * Executor (paddle_tpu/fluid/executor.py) — whole-block jit compilation
    instead of a per-op interpreter.
  * append_backward (paddle_tpu/fluid/backward.py) — grad-op synthesis via
    cached jax.vjp instead of 650 hand-written GradOpMakers.
  * Distributed (paddle_tpu/parallel/, paddle_tpu/distributed/) — device
    meshes + XLA collectives over ICI instead of NCCL rings + program
    transpilers.
"""

from __future__ import annotations

__version__ = "0.1.0"

from . import fluid
from . import ops
from . import nn
from . import optimizer
from . import tensor
from . import jit
from . import models
from . import amp
from . import io
from . import metric
from . import hapi
from .hapi import Model, summary
from .framework_io import load, save
from . import distribution
from . import vision
from . import text
from . import dataset
from . import inference
from . import transforms
from . import profiler
from . import obs
from . import ckpt
from . import utils
from . import reader
from .batch import batch
from . import static
from . import onnx
from .fluid.flags import get_flags, set_flags
from .nn.layer.layers import Layer  # 2.0 alias: paddle.nn.Layer
from .tensor import (to_tensor, zeros, ones, full, zeros_like, ones_like,
                     full_like, arange, linspace, eye, rand, randn, randint,
                     randperm, uniform, normal, bernoulli, multinomial,
                     seed, concat, stack, split, squeeze, unsqueeze,
                     reshape, transpose, flatten, cast, matmul, bmm, dot,
                     mv, t, kron, addmm, tril, triu, diag, meshgrid, where,
                     nonzero, unique, flip, roll, tile, expand, expand_as,
                     broadcast_to, gather, gather_nd, scatter,
                     scatter_nd_add, index_select, index_sample,
                     masked_select, argmax, argmin, argsort, sort, topk,
                     add, subtract, multiply, divide, pow, clip, scale,
                     isnan, isinf, isfinite, norm, dist, equal, not_equal,
                     greater_than, greater_equal, less_than, less_equal,
                     logical_and, logical_or, logical_not, logical_xor,
                     equal_all, allclose, cumsum, cumprod, assign, clone,
                     numel, std, var, median, logsumexp, sum, mean, prod,
                     exp, log, sqrt, rsqrt, abs, ceil, floor, round, sin,
                     cos, tan, tanh, reciprocal, square, sign, erf,
                     maximum, minimum)
from .tensor import max, min  # noqa: A004 (paddle API shadows builtins)
# 2.0 top-level API tail (reference python/paddle/__init__.py
# DEFINE_ALIAS set): re-exports of existing lowerings + the small
# additions at the end of paddle_tpu/tensor
from .tensor import (acos, asin, atan, cosh, sinh, log1p, log2, log10,
                     mod, remainder, floor_divide, floor_mod, trace,
                     cross, cholesky, histogram, increment, is_empty,
                     empty, empty_like, chunk, stanh, shard_index,
                     unstack, strided_slice, add_n, addcmul,
                     broadcast_shape, einsum, has_inf, has_nan,
                     inverse, is_tensor, mm, multiplex, rank,
                     scatter_nd, tensordot, unbind, set_default_dtype,
                     get_default_dtype, set_printoptions,
                     get_tensor_from_selected_rows)
from .tensor import all, any, slice  # noqa: A004 (shadows builtins)
from .fluid import (CUDAPinnedPlace, LoDTensor, LoDTensorArray,
                    is_compiled_with_cuda)
from .fluid.layers import (create_global_var, create_parameter,
                           elementwise_add, elementwise_sub,
                           elementwise_mul, elementwise_div,
                           elementwise_floordiv, elementwise_mod,
                           elementwise_pow, fill_constant, reduce_max,
                           reduce_mean, reduce_min, reduce_prod,
                           reduce_sum, shape)
from .fluid.dygraph.parallel import DataParallel


def get_cuda_rng_state():
    """No CUDA generators on this build (TPU-first; RNG is stateless
    jax keys / the TPU hardware generator) — the reference returns a
    list of per-device generator states, so the TPU answer is the
    empty list."""
    return []


def set_cuda_rng_state(state_list):
    if state_list:
        raise ValueError(
            "set_cuda_rng_state: this build has no CUDA generators "
            "(TPU-first, stateless jax PRNG); only an empty state list "
            "is accepted.")
from .fluid.dygraph.base import enable_dygraph as disable_static_mode
from .fluid.dygraph import to_variable, no_grad, grad
from .fluid.dygraph.varbase import Tensor
from .fluid import (CPUPlace, CUDAPlace, TPUPlace, Executor, ParamAttr,
                    Program, Variable, append_backward, cpu_places,
                    cuda_places, default_main_program,
                    default_startup_program, global_scope, program_guard,
                    scope_guard, tpu_places, in_dygraph_mode)
from .fluid.layers.tensor import data

def enable_static():
    from .fluid.dygraph import disable_dygraph

    disable_dygraph()


def disable_static(place=None):
    from .fluid.dygraph import enable_dygraph

    enable_dygraph(place)
from . import incubate  # noqa: E402,F401
