"""Python side of the inference C ABI (core_native/c_api.cc).

The C layer hands raw pointers + shapes across the ABI; this module
turns them into arrays, drives the Predictor, and hands back contiguous
bytes.  It deliberately knows nothing about the C structs — the whole
contract is (address, shape) in, (bytes, shape) out."""

from __future__ import annotations

import ctypes
import os

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # C hosts select the backend via env only; the env var alone doesn't
    # beat the TPU plugin (see tests/fixtures/infer_loader.py) — both
    # are needed, and this module is the ABI's Python entry point
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

from . import Config, Predictor


def new_predictor(prefix: str) -> Predictor:
    return Predictor(Config(prefix))


def run_f32(pred: Predictor, addr: int, shape) -> tuple:
    """One f32 tensor in, one f32 tensor out, zero avoidable copies.

    The C buffer is viewed (not copied — `device_put` inside the
    predictor's bucketed dispatch is the one host read, and it happens
    before this function returns, while the caller's buffer is alive).
    The output rides a LazyFetch handle end to end and materializes
    exactly once, here at the ABI boundary — the same sanctioned-sync
    contract as the training hot path (docs/async_hot_path.md)."""
    n = int(np.prod(shape))
    buf = (ctypes.c_float * n).from_address(int(addr))
    x = np.ctypeslib.as_array(buf).reshape([int(s) for s in shape])
    handle = pred.run_handles([x])[0]
    out = np.ascontiguousarray(
        handle.numpy(), dtype=np.float32)  # sync-ok: ABI boundary
    return out.tobytes(), [int(s) for s in out.shape]
