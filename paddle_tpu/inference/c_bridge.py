"""Python side of the inference C ABI (core_native/c_api.cc).

The C layer hands raw pointers + shapes across the ABI; this module
turns them into arrays, drives the Predictor, and hands back contiguous
bytes.  It deliberately knows nothing about the C structs — the whole
contract is (address, shape) in, (bytes, shape) out."""

from __future__ import annotations

import ctypes
import os

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # C hosts select the backend via env only; the env var alone doesn't
    # beat the TPU plugin (see tests/fixtures/infer_loader.py) — both
    # are needed, and this module is the ABI's Python entry point
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

from . import Config, Predictor


def new_predictor(prefix: str) -> Predictor:
    return Predictor(Config(prefix))


def run_f32(pred: Predictor, addr: int, shape) -> tuple:
    n = int(np.prod(shape))
    buf = (ctypes.c_float * n).from_address(int(addr))
    x = np.ctypeslib.as_array(buf).reshape([int(s) for s in shape]).copy()
    outs = pred.run([x])
    out = np.ascontiguousarray(np.asarray(outs[0]), dtype=np.float32)
    return out.tobytes(), [int(s) for s in out.shape]
