"""Inference stack — export, load, and serve compiled models.

Reference: paddle/fluid/inference (~36k LoC C++, SURVEY.md §1 L7):
`AnalysisPredictor` (load model -> PrepareProgram -> IR pass pipeline ->
NaiveExecutor -> ZeroCopyRun, api/analysis_predictor.cc:129,532,762)
plus `paddle.jit.save/load` (dygraph/jit.py -> TranslatedLayer) and
`save_inference_model` (fluid/io.py) with ProgramDesc protobuf as the
serialized graph format.

TPU-native re-design: the serialized artifact is **StableHLO** (via
jax.export) — the XLA-native exchange format replacing ProgramDesc.
`save_inference_model(path, layer, input_spec)` functionalizes an
nn.Layer forward, folds the weights in as constants (the reference's
params.pdparams fusion), lowers to StableHLO bytes + a small JSON
manifest.  `Predictor` deserializes and compiles once, then `run()` is
ZeroCopyRun: jitted execution with no Python graph interpretation.  The
reference's 45-pass IR optimization pipeline is XLA's optimization
pipeline — applied at deserialize/compile time, not export time.
"""

from __future__ import annotations

import json
import os
import warnings

import numpy as np

_WARNED: set = set()


def _warn_once(key: str, msg: str) -> None:
    if key in _WARNED:
        return
    _WARNED.add(key)
    warnings.warn(msg, stacklevel=3)


def save_inference_model(path_prefix, layer, input_spec, fold_params=True,
                         cipher=None, key=None):
    """Export `layer.forward` over `input_spec` to StableHLO.

    input_spec: list of (shape, dtype) or arrays providing example
    shapes.  Writes <prefix>.stablehlo + <prefix>.json manifest (+
    <prefix>.pdiparams when fold_params=False).  With `cipher` + `key`
    (inference.crypto) the StableHLO artifact is stored ENCRYPTED — the
    reference's encrypted-model path (framework/io/crypto)."""
    import jax
    from jax import export as jexport

    from ..jit import functional_call, functional_state

    layer.eval()
    state = functional_state(layer)

    specs = []
    for s in input_spec:
        if isinstance(s, tuple) and len(s) == 2 and isinstance(s[0],
                                                               (list, tuple)):
            shape, dtype = s
        else:
            arr = np.asarray(s.numpy() if hasattr(s, "numpy") else s)
            shape, dtype = arr.shape, arr.dtype
        specs.append(jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype)))

    if fold_params:
        def fn(*xs):
            out, _ = functional_call(layer, state, *xs)
            return out

        exp = jexport.export(jax.jit(fn))(*specs)
        params_path = None
    else:
        def fn(state, *xs):
            out, _ = functional_call(layer, state, *xs)
            return out

        state_spec = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                      for k, v in state.items()}
        exp = jexport.export(jax.jit(fn))(state_spec, *specs)
        params_path = path_prefix + ".pdiparams"
        from ..framework_io import save as psave

        psave(state, params_path)
        if cipher is not None or key is not None:
            raise NotImplementedError(
                "save_inference_model: encryption with fold_params=False "
                "would leave the .pdiparams weights in PLAINTEXT; fold "
                "the params (fold_params=True) so the whole model is one "
                "encrypted StableHLO artifact")

    d = os.path.dirname(path_prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    blob = exp.serialize()
    if key is not None and cipher is None:
        from .crypto import AESCipher

        cipher = AESCipher("CTR")
    if cipher is not None:
        if key is None:
            raise ValueError("save_inference_model: cipher given "
                             "without key")
        blob = cipher.encrypt(bytes(blob), key)
    with open(path_prefix + ".stablehlo", "wb") as f:
        f.write(blob)
    manifest = {
        "format": "stablehlo",
        "encrypted": cipher is not None,
        "cipher": (type(cipher).__name__ + ":" + cipher._mode
                   if cipher is not None else None),
        "fold_params": fold_params,
        "inputs": [{"shape": list(s.shape), "dtype": np.dtype(s.dtype).name}
                   for s in specs],
        "params_file": os.path.basename(params_path) if params_path
        else None,
    }
    with open(path_prefix + ".json", "w") as f:
        json.dump(manifest, f, indent=2)
    return path_prefix


def load_inference_model(path_prefix):
    """-> Predictor (the AnalysisPredictor role)."""
    return Predictor(Config(path_prefix))


class Config:
    """Predictor config (reference: inference/api paddle_analysis_config
    AnalysisConfig) — the TPU build keeps the knob surface minimal since
    XLA owns optimization/memory."""

    def __init__(self, model_path_prefix=None):
        self.model_prefix = model_path_prefix
        self.device = None  # default jax device
        self.cipher = None
        self.cipher_key = None
        # serving-path knobs (ISSUE 2 satellite): these used to be
        # silently ignored; now they map onto the bucketed runner's
        # donation / exact-shape compile options
        self.memory_optim = False
        self.ir_optim = True
        self._bound_predictor = None

    def set_model(self, prefix):
        self.model_prefix = prefix

    def set_cipher(self, key, cipher=None):
        """Key (+ cipher, default AES-CTR) for encrypted models
        (reference predictor SetModelBuffer-over-decrypted-bytes
        path)."""
        from .crypto import AESCipher

        self.cipher_key = key
        self.cipher = cipher or AESCipher("CTR")

    def _flag_changed(self, flag: str) -> None:
        pred = self._bound_predictor
        if pred is not None and pred._runner is not None:
            _warn_once(
                f"late:{flag}",
                f"Config.{flag}() called after the predictor compiled "
                f"its first entry: already-compiled bucket entries keep "
                f"their old options; only new entries (and new Engines "
                f"built from this predictor) pick the flag up")

    def enable_memory_optim(self):
        """Donate feed buffers to XLA on the bucketed serving path, so
        activations may reuse the feed memory in HBM (the reference's
        memory-optim pass, re-mapped onto XLA buffer donation)."""
        self.memory_optim = True
        self._flag_changed("enable_memory_optim")

    def switch_ir_optim(self, flag=True):
        """flag=False compiles exact request shapes instead of padded
        buckets (the reference's IR-pass toggle, re-mapped onto the
        bucketing policy; XLA's own pipeline always runs)."""
        self.ir_optim = bool(flag)
        self._flag_changed("switch_ir_optim")


class Predictor:
    """ZeroCopyRun-style predictor (analysis_predictor.cc:762): compile
    once, feed/fetch device arrays with no per-call graph work."""

    def __init__(self, config):
        from jax import export as jexport

        prefix = config.model_prefix
        with open(prefix + ".json") as f:
            self.manifest = json.load(f)
        with open(prefix + ".stablehlo", "rb") as f:
            blob = f.read()
        if self.manifest.get("encrypted"):
            if config.cipher_key is None:
                raise ValueError(
                    "encrypted inference model: call "
                    "Config.set_cipher(key) before create_predictor")
            cipher = config.cipher
            mode = (self.manifest.get("cipher") or ":CTR").split(":")[-1]
            if cipher is None or getattr(cipher, "_mode", mode) != mode:
                from .crypto import AESCipher

                cipher = AESCipher(mode)  # manifest wins: wrong-mode
                # decrypt would garble the blob into an opaque parse error
            blob = cipher.decrypt(blob, config.cipher_key)
        self._exported = jexport.deserialize(bytearray(blob))
        self._params = None
        if self.manifest.get("params_file"):
            from ..framework_io import load as pload

            self._params = pload(os.path.join(
                os.path.dirname(prefix), self.manifest["params_file"]))
        self._config = config
        self._runner = None
        config._bound_predictor = self

    def get_input_names(self):
        return [f"x{i}" for i in range(len(self.manifest["inputs"]))]

    # -- bucketed serving path (ISSUE 2) ----------------------------------
    def _traceable_fn(self):
        """The exported computation as a jax-traceable callable —
        what the serving BucketedRunner / Engine AOT-compiles per
        bucket.  Unfolded params ride along as trace-time constants."""
        exported, params = self._exported, self._params
        if params is not None:
            return lambda *xs: exported.call(params, *xs)
        return lambda *xs: exported.call(*xs)

    def _fixed_batch(self):
        """The export's static leading dim, when every input shares one.

        StableHLO artifacts are exported over concrete shapes, so the
        batch dim is baked in: the bucketed runner must pad every
        request UP to this value (and chunk larger ones through it) —
        exactly one compiled entry per input signature."""
        shapes = [i["shape"] for i in self.manifest["inputs"]]
        if shapes and all(len(s) >= 1 for s in shapes):
            leads = {s[0] for s in shapes}
            if len(leads) == 1:
                return int(leads.pop())
        return None

    def _bucketed_runner(self):
        if self._runner is None:
            from ..serving.bucketing import BucketedRunner, bucket_ladder

            fixed = self._fixed_batch()
            bucketed = self._config.ir_optim
            if fixed is not None:
                buckets = [fixed]
                if not self._config.ir_optim:
                    _warn_once(
                        "ir_optim_fixed_export",
                        "switch_ir_optim(False) requests exact-shape "
                        "compiles, but this model was exported with a "
                        "fixed batch dim — requests must be padded to "
                        "it; the flag is ignored for this predictor")
                bucketed = True
            else:
                buckets = bucket_ladder(8)
            self._runner = BucketedRunner(
                self._traceable_fn(), buckets,
                donate=self._config.memory_optim, bucketed=bucketed)
        return self._runner

    def _normalize(self, inputs):
        vals = []
        for x, spec in zip(inputs, self.manifest["inputs"]):
            a = np.asarray(x.numpy() if hasattr(x, "numpy") else x)
            vals.append(a.astype(spec["dtype"], copy=False))
        return vals

    def run_handles(self, inputs):
        """ZeroCopyRun through the bucketed compile cache: -> list of
        LazyFetch handles over DEVICE arrays (no transfer; materialize
        at the caller's sanctioned boundary).  One compiled entry per
        (bucket, signature) — a request batch size never seen before
        pads onto an existing bucket instead of retracing."""
        from ..fluid.executor import LazyFetch

        vals = self._normalize(inputs)
        if any(v.ndim == 0 for v in vals):
            # no batch dim to bucket over: direct exported call
            out = self._traceable_fn()(*vals)
            outs = list(out) if isinstance(out, (list, tuple)) else [out]
        else:
            outs = self._bucketed_runner().run(vals)
        return [LazyFetch(o, name=f"fetch{i}")
                for i, o in enumerate(outs)]

    def run(self, inputs):
        """inputs: list of arrays in manifest order -> list of outputs."""
        return [h.numpy() for h in self.run_handles(inputs)]


def create_predictor(config):
    return Predictor(config)
