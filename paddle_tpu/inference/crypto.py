"""Encrypted-model io — the TPU port of the reference's crypto layer
(/root/reference/paddle/fluid/framework/io/crypto/: cipher.cc
CipherFactory::CreateCipher:22, aes_cipher.cc, cipher_utils.cc
CipherUtils::GenKey:25).

The reference wraps cryptopp AES (CTR / GCM variants) so inference
models and parameters can ship encrypted and be decrypted in memory by
the predictor.  Here the `cryptography` package provides the same AES
primitives; the on-disk format is `nonce || ciphertext [|| tag]` like
the reference's cipher-engine framing.
"""

from __future__ import annotations

import os

__all__ = ["Cipher", "AESCipher", "CipherFactory", "CipherUtils"]


class Cipher:
    """Abstract cipher (reference crypto/cipher.h)."""

    def encrypt(self, plaintext: bytes, key: bytes) -> bytes:
        raise NotImplementedError

    def decrypt(self, ciphertext: bytes, key: bytes) -> bytes:
        raise NotImplementedError

    def encrypt_to_file(self, plaintext: bytes, key: bytes, path: str):
        with open(path, "wb") as f:
            f.write(self.encrypt(plaintext, key))

    def decrypt_from_file(self, key: bytes, path: str) -> bytes:
        with open(path, "rb") as f:
            return self.decrypt(f.read(), key)


class AESCipher(Cipher):
    """AES in CTR or GCM mode (reference aes_cipher.cc variants
    AES_CTR_NoPadding / AES_GCM_NoPadding)."""

    def __init__(self, mode="CTR", iv_size=16, tag_size=16):
        if mode not in ("CTR", "GCM"):
            raise ValueError(f"AESCipher: unsupported mode {mode!r}")
        self._mode = mode
        self._iv_size = iv_size
        self._tag_size = tag_size

    def encrypt(self, plaintext: bytes, key: bytes) -> bytes:
        from cryptography.hazmat.primitives.ciphers import (
            Cipher as _C, algorithms, modes)

        iv = os.urandom(self._iv_size)
        if self._mode == "GCM":
            enc = _C(algorithms.AES(key), modes.GCM(iv)).encryptor()
            ct = enc.update(plaintext) + enc.finalize()
            return iv + ct + enc.tag
        enc = _C(algorithms.AES(key), modes.CTR(iv)).encryptor()
        return iv + enc.update(plaintext) + enc.finalize()

    def decrypt(self, ciphertext: bytes, key: bytes) -> bytes:
        from cryptography.hazmat.primitives.ciphers import (
            Cipher as _C, algorithms, modes)

        iv = ciphertext[:self._iv_size]
        if self._mode == "GCM":
            tag = ciphertext[-self._tag_size:]
            body = ciphertext[self._iv_size:-self._tag_size]
            dec = _C(algorithms.AES(key), modes.GCM(iv, tag)).decryptor()
            return dec.update(body) + dec.finalize()
        dec = _C(algorithms.AES(key), modes.CTR(iv)).decryptor()
        return dec.update(ciphertext[self._iv_size:]) + dec.finalize()


class CipherFactory:
    """reference cipher.cc CipherFactory::CreateCipher: resolves a
    cipher from a config name (default AES_CTR_NoPadding)."""

    @staticmethod
    def create_cipher(config_file=None) -> Cipher:
        name = "AES_CTR_NoPadding"
        if config_file:
            with open(config_file) as f:
                for line in f:
                    if line.strip().startswith("cipher_name"):
                        name = line.split(":")[-1].strip()
        if name.startswith("AES_CTR"):
            return AESCipher("CTR")
        if name.startswith("AES_GCM"):
            return AESCipher("GCM")
        raise ValueError(f"unknown cipher {name!r}")


class CipherUtils:
    """reference cipher_utils.cc."""

    @staticmethod
    def gen_key(length_bits: int = 256) -> bytes:
        return os.urandom(length_bits // 8)

    @staticmethod
    def gen_key_to_file(length_bits: int, path: str) -> bytes:
        key = CipherUtils.gen_key(length_bits)
        with open(path, "wb") as f:
            f.write(key)
        return key

    @staticmethod
    def read_key_from_file(path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()
