"""paddle.nn layer tail: surface completeness vs the reference's
uncommented DEFINE_ALIAS set, layer-vs-functional equivalence for the
new classes, and the dense BeamSearchDecoder/dynamic_decode."""

import re

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.fluid import dygraph


@pytest.fixture(autouse=True)
def _dygraph():
    with dygraph.guard():
        yield


def _t(a, dtype="float32"):
    return paddle.to_tensor(np.asarray(a, dtype=dtype))


def test_nn_surface_complete():
    import os
    if not os.path.isdir("/root/reference"):
        pytest.skip("reference source tree not present in this environment")
    names = set()
    for line in open("/root/reference/python/paddle/nn/__init__.py"):
        s = line.strip()
        if s.startswith("#"):
            continue
        m = re.match(r"from [\w.]+ import (\w+)\s+#DEFINE_ALIAS", s)
        if m:
            names.add(m.group(1))
    missing = sorted(n for n in names if not hasattr(nn, n))
    assert missing == [], f"nn surface gaps: {missing}"


def test_simple_layers_match_functional():
    r = np.random.RandomState(0)
    x = r.randn(3, 7).astype("float32")
    np.testing.assert_allclose(nn.LogSigmoid()(_t(x)).numpy(),
                               F.log_sigmoid(_t(x)).numpy())
    np.testing.assert_allclose(nn.Softsign()(_t(x)).numpy(),
                               F.softsign(_t(x)).numpy())
    a, b = r.rand(4, 6).astype("float32"), r.rand(4, 6).astype("float32")
    pd = nn.PairwiseDistance(p=2.0)(_t(a), _t(b)).numpy()
    np.testing.assert_allclose(
        pd, np.linalg.norm(a - b + 1e-6, axis=1), rtol=1e-5)


def test_pool_and_conv_layers():
    r = np.random.RandomState(1)
    x3 = _t(r.rand(2, 3, 4, 6, 8))
    assert list(nn.MaxPool3D(2, stride=2)(x3).shape) == [2, 3, 2, 3, 4]
    assert list(nn.AvgPool3D(2, stride=2)(x3).shape) == [2, 3, 2, 3, 4]
    assert list(nn.AdaptiveAvgPool3D(2)(x3).shape) == [2, 3, 2, 2, 2]
    assert list(nn.AdaptiveMaxPool1D(3)(_t(r.rand(2, 3, 9))).shape) \
        == [2, 3, 3]

    ct1 = nn.Conv1DTranspose(3, 5, 4, stride=2)
    y = ct1(_t(r.rand(2, 3, 8)))
    assert y.shape[0:2] == [2, 5]
    ct3 = nn.Conv3DTranspose(2, 4, 3, stride=1)
    y3 = ct3(_t(r.rand(1, 2, 4, 4, 4)))
    assert y3.shape[0:2] == [1, 4]

    p2 = nn.Pool2D(pool_size=2, pool_type="avg", pool_stride=2)
    assert list(p2(_t(r.rand(2, 3, 8, 8))).shape) == [2, 3, 4, 4]
    pg = nn.Pool2D(pool_type="max", global_pooling=True)
    assert list(pg(_t(r.rand(2, 3, 8, 8))).shape) == [2, 3, 1, 1]


def test_loss_layers():
    r = np.random.RandomState(2)
    T, B, C = 6, 2, 5
    loss = nn.CTCLoss(blank=0)(
        _t(r.rand(T, B, C)), _t(np.array([[1, 2], [2, 3]], "int32"),
                                "int32"),
        _t(np.array([T, T], "int64"), "int64"),
        _t(np.array([2, 2], "int64"), "int64"))
    assert np.isfinite(float(loss.numpy()))

    hs = nn.HSigmoidLoss(8, 6)
    out = hs(_t(r.rand(4, 8)), _t(r.randint(0, 6, (4, 1)), "int64"))
    assert np.isfinite(float(out.numpy().sum()))

    btp = nn.BilinearTensorProduct(4, 5, 6)
    y = btp(_t(r.rand(3, 4)), _t(r.rand(3, 5)))
    assert list(y.shape) == [3, 6]

    rc = nn.RowConv(8, 2)
    y = rc(_t(r.rand(2, 5, 8)))
    assert list(y.shape) == [2, 5, 8]


def test_alpha_dropout_layer_respects_eval():
    x = _t(np.random.RandomState(3).randn(16, 16))
    layer = nn.AlphaDropout(p=0.4)
    layer.eval()
    np.testing.assert_allclose(layer(x).numpy(), x.numpy())
    layer.train()
    assert not np.allclose(layer(x).numpy(), x.numpy())


class _ToyCell(nn.RNNCellBase):
    """Deterministic 'cell': logits prefer token (state_sum + 1) mod V,
    making the greedy rollout predictable."""

    V = 6

    def __init__(self):
        super().__init__()

    def forward(self, inputs, states, **kw):
        import jax.numpy as jnp

        from paddle_tpu.fluid.dygraph.tracer import trace_fn

        def f(tok, s):
            nxt = (s[:, 0] + 1).astype(jnp.int32) % self.V
            logits = -10.0 * jnp.ones((tok.shape[0], self.V))
            logits = logits.at[jnp.arange(tok.shape[0]), nxt].set(0.0)
            s2 = s + 1
            return logits, s2

        return trace_fn(f, {"tok": inputs, "s": states}, multi_out=True)


def test_beam_search_decoder_greedy_equivalence():
    cell = _ToyCell()
    B, K = 2, 3
    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=5,
                               beam_size=K)
    init_state = _t(np.zeros((B, 1), "float32"))
    outputs, _ = nn.dynamic_decode(dec, inits=init_state,
                                   max_step_num=8)
    ids = outputs["predicted_ids"].numpy()  # (B, T, K)
    assert ids.shape[0] == B and ids.shape[2] == K
    # the toy cell deterministically emits 1,2,3,4,5(end): beam 0 must
    # follow it, finish at the end token, and pad with end thereafter
    np.testing.assert_array_equal(ids[0, :5, 0], [1, 2, 3, 4, 5])
    assert (ids[0, 5:, 0] == 5).all()
    scores = outputs["scores"].numpy()
    assert np.isfinite(scores[:, :, 0]).all()
