"""paddle_tpu.obs (ISSUE 6): span tracing, flow links, cost gauges.

Covers the tentpole's acceptance criteria: a combined 3-step-train +
serving-request trace shows flow-linked spans across >= 3 threads, the
live mfu_pct gauge derives from cached XLA cost_analysis on CPU, and
disabled-mode tracing leaves the hot-path counters untouched.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import obs, profiler
from paddle_tpu.fluid import framework
from paddle_tpu.fluid.executor import Scope, scope_guard
from paddle_tpu.obs.tracing import NULL_SPAN, Tracer

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
import tracetool  # noqa: E402


@pytest.fixture
def clean_tracer():
    """Fresh disabled tracer state around each test."""
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.reset()


def _simple_program():
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        x = fluid.data("x", [-1, 4], "float32")
        y = fluid.layers.fc(x, 2)
        loss = fluid.layers.reduce_mean(y)
    return main, startup, loss


# ---------------------------------------------------------------------------
# span semantics
# ---------------------------------------------------------------------------

class TestSpans:
    def test_disabled_returns_null_span(self, clean_tracer):
        s = obs.span("anything")
        assert s is NULL_SPAN
        with s:
            pass
        assert len(obs.TRACER) == 0
        obs.add_span("retro", 0.0, 1.0)
        assert len(obs.TRACER) == 0

    def test_spans_nest_and_close_under_exceptions(self, clean_tracer):
        obs.enable()
        with pytest.raises(ValueError):
            with obs.span("outer"):
                with obs.span("inner"):
                    raise ValueError("boom")
        recs = obs.TRACER.records()
        names = [r[0] for r in recs]
        assert names == ["inner", "outer"]  # inner closes first
        # nesting: inner lies within outer on the same thread
        (i_name, i_tid, _, i_t0, i_dur, _, _) = recs[0]
        (o_name, o_tid, _, o_t0, o_dur, _, _) = recs[1]
        assert i_tid == o_tid
        assert o_t0 <= i_t0 and i_t0 + i_dur <= o_t0 + o_dur + 1e-9
        # the stack unwound completely
        assert obs.current_span() is None

    def test_leaked_child_closes_with_parent(self, clean_tracer):
        obs.enable()
        with obs.span("parent"):
            # simulate a begin-without-end leak (the span-leak lint
            # flags this shape in product code)
            child = obs.TRACER.span("child")
            child.__enter__()
        assert obs.current_span() is None
        assert [r[0] for r in obs.TRACER.records()] == ["parent"]

    def test_buffer_cap_counts_drops(self, clean_tracer):
        obs.enable()
        old = obs.TRACER.capacity
        obs.TRACER.capacity = 2
        try:
            for _ in range(5):
                with obs.span("e"):
                    pass
            assert len(obs.TRACER) == 2
            assert obs.TRACER.dropped == 3
            assert obs.TRACER.summary()["dropped"] == 3
        finally:
            obs.TRACER.capacity = old

    def test_flow_links_cross_threads(self, clean_tracer, tmp_path):
        obs.enable()
        fid = obs.new_flow()

        def worker():
            with obs.span("consume", flow=fid):
                pass

        with obs.span("produce", flow=fid):
            pass
        t = threading.Thread(target=worker, name="worker-thread")
        t.start()
        t.join()
        path = str(tmp_path / "flow.json")
        obs.export_trace(path)
        doc = json.loads(open(path).read())
        flows = [e for e in doc["traceEvents"] if e.get("cat") == "flow"]
        assert {e["ph"] for e in flows} == {"s", "f"}
        assert len({e["tid"] for e in flows}) == 2
        assert all(e["id"] == fid for e in flows)

    def test_single_span_flow_emits_no_dangling_link(self, clean_tracer):
        obs.enable()
        with obs.span("solo", flow=obs.new_flow()):
            pass
        doc = obs.TRACER.chrome_trace()
        assert not [e for e in doc["traceEvents"]
                    if e.get("cat") == "flow"]

    def test_attrs_exported_as_args(self, clean_tracer, tmp_path):
        obs.enable()
        with obs.span("tagged", attrs={"k": "v"}):
            pass
        doc = obs.TRACER.chrome_trace()
        ev = next(e for e in doc["traceEvents"] if e.get("ph") == "X")
        assert ev["args"] == {"k": "v"}


# ---------------------------------------------------------------------------
# acceptance: one trace, train + serving, >= 3 linked threads, live MFU
# ---------------------------------------------------------------------------

class TestEndToEndTrace:
    def _train_3_steps(self, tmp_path):
        main, startup, loss = _simple_program()
        path = str(tmp_path / "part-0.txt")
        rng = np.random.RandomState(0)
        with open(path, "w") as f:
            for _ in range(12):  # batch 4 -> 3 steps
                f.write("4 " + " ".join(
                    f"{v:.6f}" for v in rng.randn(4)) + "\n")
        scope = Scope()
        with scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
            ds.set_batch_size(4)
            ds.set_use_var([main.global_block().var("x")])
            ds.set_filelist([path])
            ds.load_into_memory()
            exe.train_from_dataset(main, ds, fetch_list=[loss])

    def _serve_one_request(self):
        import jax.numpy as jnp

        from paddle_tpu import serving

        w = jnp.ones((4, 2), jnp.float32)
        eng = serving.Engine(lambda x: x @ w,
                             serving.EngineConfig(max_queue_delay_ms=0.0))
        try:
            out = eng.infer([np.ones((2, 4), np.float32)], timeout=60)
            np.testing.assert_allclose(out[0], np.full((2, 2), 4.0))
        finally:
            eng.shutdown(drain=True)

    def test_combined_trace_links_three_threads(self, clean_tracer,
                                                tmp_path):
        """Acceptance: ONE Chrome-trace export of a 3-step train run +
        one serving request shows flow-linked spans across >= 3 threads
        (feed producer, serving dispatch, serving completer)."""
        obs.enable(reset=True)
        self._train_3_steps(tmp_path)
        self._serve_one_request()
        obs.disable()
        path = str(tmp_path / "combined.json")
        n = obs.export_trace(path)
        assert n > 0
        s = tracetool.summarize(tracetool.load_trace(path), top=100)
        names = {r["name"] for r in s["top_spans"]}
        # the whole stack is in the one file
        assert {"feed.stage", "feed.ring_get", "executor.prepare",
                "executor.dispatch", "serving.admit", "serving.dispatch",
                "serving.complete"} <= names
        thread_names = {t["name"] for t in s["threads"]}
        assert {"feed-producer", "serving-dispatch",
                "serving-complete"} <= thread_names
        # flow links span >= 3 distinct threads overall
        doc = tracetool.load_trace(path)
        flow_tids = {}
        for e in doc["traceEvents"]:
            if e.get("cat") == "flow":
                flow_tids.setdefault(e["id"], set()).add(e["tid"])
        linked_tids = set()
        for tids in flow_tids.values():
            if len(tids) > 1:
                linked_tids |= tids
        assert len(linked_tids) >= 3, (
            f"flow-linked spans cover only threads {linked_tids}")
        assert s["cross_thread_flows"] >= 4  # 3 feed batches + request

    def test_serving_flow_survives_batcher_handoff(self, clean_tracer):
        """The request's flow id minted at submit() reappears on the
        dispatch- and completer-thread spans."""
        import jax.numpy as jnp

        from paddle_tpu import serving

        obs.enable(reset=True)
        w = jnp.ones((4, 2), jnp.float32)
        eng = serving.Engine(lambda x: x @ w,
                             serving.EngineConfig(max_queue_delay_ms=0.0))
        try:
            eng.infer([np.ones((2, 4), np.float32)], timeout=60)
        finally:
            eng.shutdown(drain=True)
        obs.disable()
        recs = obs.TRACER.records()
        by_name = {}
        for name, _tid, tname, _t0, _dur, flows, _attrs in recs:
            if flows:
                by_name.setdefault(name, set()).update(flows)
        admit = by_name.get("serving.admit", set())
        assert admit, "no flow on the admission span"
        for stage in ("serving.coalesce", "serving.dispatch",
                      "serving.complete"):
            assert admit & by_name.get(stage, set()), (
                f"flow id lost between admit and {stage}")


# ---------------------------------------------------------------------------
# cost attribution
# ---------------------------------------------------------------------------

class TestCostAttribution:
    def test_mfu_gauge_from_cached_cost_analysis(self, clean_tracer):
        """Acceptance: obs.snapshot() reports a nonzero mfu_pct derived
        from the cost_analysis cached with the CompileCache entry —
        on CPU, with tracing never enabled (gauges are always-on)."""
        from paddle_tpu.obs import cost as obs_cost

        obs_cost.reset_programs()
        main, startup, loss = _simple_program()
        with scope_guard(Scope()):
            exe = fluid.Executor()
            exe.run(startup)
            feed = {"x": np.ones((2, 4), "float32")}
            for _ in range(3):
                exe.run(main, feed=feed, fetch_list=[loss])
            # cost is cached WITH the compile-cache entry
            entry = next(e for e in exe._cache.values()
                         if e.fetch_names == [loss.name])
            assert entry.cost is not None
            assert entry.cost.flops > 0
            assert entry.cost.dispatches == 3
        snap = obs.snapshot()
        assert snap["cost"]["device_class"] == "cpu-fallback"
        assert snap["cost"]["mfu_pct"] > 0.0
        prog = next(p for p in snap["cost"]["programs"]
                    if p["label"] == entry.cost.label)
        assert prog["mfu_pct"] > 0.0 and prog["flops"] > 0
        assert prog["step_ms"] > 0.0

    def test_cost_capture_can_be_disabled(self, clean_tracer,
                                          monkeypatch):
        monkeypatch.setenv("PADDLE_OBS_COST", "0")
        main, startup, loss = _simple_program()
        with scope_guard(Scope()):
            exe = fluid.Executor()
            exe.run(startup)
            feed = {"x": np.ones((2, 4), "float32")}
            (out,) = exe.run(main, feed=feed, fetch_list=[loss])
            entry = next(e for e in exe._cache.values()
                         if e.fetch_names == [loss.name])
            assert entry.cost is None and entry.fn_compiled is None
            assert np.isfinite(out).all()

    def test_aot_fallback_on_signature_drift(self, clean_tracer):
        """An AOT executable that rejects its arguments (signature
        drift under the cached entry) must fall back to the jit path —
        permanently — instead of failing the run."""
        main, startup, loss = _simple_program()
        with scope_guard(Scope()):
            exe = fluid.Executor()
            exe.run(startup)
            feed = {"x": np.ones((2, 4), "float32")}
            (want,) = exe.run(main, feed=feed, fetch_list=[loss])
            entry = next(e for e in exe._cache.values()
                         if e.fetch_names == [loss.name])
            assert entry.fn_compiled is not None

            def rejecting(*args):
                raise TypeError("Argument types differ from the types "
                                "for which this computation was compiled")

            entry.fn_compiled = rejecting
            (out,) = exe.run(main, feed=feed, fetch_list=[loss])
            np.testing.assert_allclose(out, want, rtol=1e-6)
            assert entry.fn_compiled is None  # permanent fallback
            (out2,) = exe.run(main, feed=feed, fetch_list=[loss])
            np.testing.assert_allclose(out2, want, rtol=1e-6)

    def test_collective_bytes_on_wire_counter(self, clean_tracer,
                                              fresh_programs):
        """collective_bytes_<type> records the logical payload at
        lowering time — the EQuARX assertion seam."""
        import paddle_tpu.distributed.collective as coll

        profiler.stat_reset("collective_bytes_c_allreduce_sum")
        profiler.stat_reset("collective_count_c_allreduce_sum")
        main, startup, scope = fresh_programs
        x = fluid.data("x", [8, 4], "float32")
        y = coll.all_reduce(x)
        compiled = fluid.CompiledProgram(main).with_data_parallel()
        exe = fluid.Executor()
        X = np.arange(32, dtype="float32").reshape(8, 4)
        exe.run(compiled, feed={"x": X}, fetch_list=[y])
        stats = profiler.get_int_stats()
        # per-shard payload: 8 rows over 8 shards = (1, 4) f32 = 16 B
        assert stats.get("collective_bytes_c_allreduce_sum") == 16
        assert stats.get("collective_count_c_allreduce_sum") == 1
        snap = obs.snapshot()
        assert snap["cost"]["collective_bytes"].get(
            "c_allreduce_sum") == 16
        # cache hit: no re-trace, counter stays flat
        exe.run(compiled, feed={"x": X}, fetch_list=[y])
        assert profiler.get_int_stats()[
            "collective_bytes_c_allreduce_sum"] == 16

    def test_serving_bucket_cost_registered(self, clean_tracer):
        import jax.numpy as jnp

        from paddle_tpu.obs import cost as obs_cost
        from paddle_tpu.serving.bucketing import BucketedRunner

        obs_cost.reset_programs()
        w = jnp.ones((4, 4), jnp.float32)
        runner = BucketedRunner(lambda x: x @ w, buckets=[8])
        for _ in range(2):
            runner.run([np.ones((3, 4), np.float32)])
        labels = {pc.label for pc in obs_cost.programs()}
        assert "serving.bucket8" in labels
        pc = next(p for p in obs_cost.programs()
                  if p.label == "serving.bucket8")
        assert pc.flops > 0 and pc.dispatches == 2


# ---------------------------------------------------------------------------
# disabled-mode overhead: hot-path counters unchanged
# ---------------------------------------------------------------------------

class TestDisabledOverhead:
    def test_disabled_tracing_keeps_sync_counters_flat(self,
                                                       clean_tracer):
        """Acceptance: with tracing disabled, executor_sync_count and
        the per-step dispatch timing counters behave exactly as the
        async hot path promises (zero syncs, dispatch_ms accumulating,
        no span recorded anywhere)."""
        assert not obs.enabled()
        main, startup, loss = _simple_program()
        with scope_guard(Scope()):
            exe = fluid.Executor()
            exe.run(startup)
            feed = {"x": np.ones((2, 4), "float32")}
            exe.run(main, feed=feed, fetch_list=[loss])  # compile step
            profiler.stat_reset("executor_sync_count")
            profiler.time_reset()
            handles = None
            for _ in range(5):
                handles = exe.run(main, feed=feed, fetch_list=[loss],
                                  return_numpy=False)
            # dispatch-only loop performed ZERO device->host transfers
            assert profiler.get_int_stats().get(
                "executor_sync_count", 0) == 0
            times = profiler.get_time_stats()
            assert times.get("dispatch_ms", 0) > 0
            assert times.get("compile_ms", 0.0) == 0.0  # all cache hits
            float(handles[0])  # sync-ok: outside the measured loop
            assert profiler.get_int_stats()["executor_sync_count"] == 1
        assert len(obs.TRACER) == 0  # nothing recorded while disabled


# ---------------------------------------------------------------------------
# snapshot / tracetool round trip
# ---------------------------------------------------------------------------

class TestTracetoolRoundTrip:
    def test_export_summarize_roundtrip(self, clean_tracer, tmp_path):
        obs.enable(reset=True)
        fid = obs.new_flow()
        with obs.span("a", flow=fid):
            time.sleep(0.001)
        t = threading.Thread(
            target=lambda: obs.add_span("b", time.perf_counter(), 1e-4,
                                        flow=fid),
            name="other")
        t.start()
        t.join()
        obs.disable()
        path = str(tmp_path / "rt.json")
        n = obs.export_trace(path)
        assert n == 2
        s = tracetool.summarize(tracetool.load_trace(path))
        assert s["spans"] == 2 and s["cross_thread_flows"] == 1
        assert {r["name"] for r in s["top_spans"]} == {"a", "b"}
        # the embedded snapshot made stall/MFU reporting possible
        assert "stall_attribution" in s
        assert s["device_class"] == "cpu-fallback"

    def test_tracetool_diff(self, clean_tracer, tmp_path):
        tr = Tracer()
        tr.enable()
        tr.add_span("x", 0.0, 0.010)
        a = str(tmp_path / "a.json")
        tr.export(a)
        tr.add_span("x", 1.0, 0.030)
        tr.add_span("y", 1.0, 0.005)
        b = str(tmp_path / "b.json")
        tr.export(b)
        rows = tracetool.diff_traces(tracetool.load_trace(a),
                                     tracetool.load_trace(b))
        byname = {r["name"]: r for r in rows}
        assert byname["x"]["a_count"] == 1 and byname["x"]["b_count"] == 2
        assert byname["x"]["delta_ms"] == pytest.approx(30.0, abs=0.5)
        assert byname["y"]["a_count"] == 0

    def test_tracetool_selftest_clean(self):
        assert tracetool.selftest(verbose=False) == 0

    def test_snapshot_shape(self, clean_tracer):
        snap = obs.snapshot()
        assert set(snap) == {"spans", "counters", "timers_ms", "cost",
                             "host", "op_profile", "devprof", "memory",
                             "numerics"}
        assert {"device_class", "peak_flops", "mfu_pct",
                "programs", "collective_bytes"} <= set(snap["cost"])
        assert snap["host"] == 0  # tagged with jax.process_index()
        assert "orphaned_flows" in snap["spans"]


# ---------------------------------------------------------------------------
# span-leak lint rule
# ---------------------------------------------------------------------------

class TestSpanLeakRule:
    def _lint(self):
        import tpulint

        return tpulint.load_lint()

    def test_flags_unclosed_span(self, tmp_path):
        lint = self._lint()
        bad = tmp_path / "paddle_tpu" / "obs"
        bad.mkdir(parents=True)
        (bad / "leaky.py").write_text(
            "def f(obs):\n"
            "    s = obs.span('x')\n"          # leak: assigned
            "    s.__enter__()\n"
            "    with obs.span('ok'):\n"       # closed
            "        pass\n"
            "    return obs.span('deleg')\n")  # delegation: allowed
        # the other watched paths must exist for the rule to walk
        for rel in ("paddle_tpu/profiler", "paddle_tpu/serving",
                    "paddle_tpu/transforms", "paddle_tpu/ckpt",
                    "paddle_tpu/tune"):
            (tmp_path / rel).mkdir(parents=True, exist_ok=True)
        for rel in ("paddle_tpu/fluid/executor.py",
                    "paddle_tpu/parallel/compiler.py",
                    "paddle_tpu/dataset/feed_pipeline.py",
                    "paddle_tpu/transforms/__init__.py",
                    "paddle_tpu/analysis/verifier.py",
                    "paddle_tpu/obs/telemetry.py",
                    "paddle_tpu/obs/devprof.py",
                    "paddle_tpu/obs/memprof.py",
                    "paddle_tpu/obs/numerics.py",
                    "paddle_tpu/fluid/aot_cache.py",
                    "paddle_tpu/parallel/quant_collectives.py",
                    "paddle_tpu/ops/pallas/attention.py",
                    "bench.py"):
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text("")
        findings = lint.run_rules(root=str(tmp_path),
                                  rules=["span-leak"])
        assert len(findings) == 1
        assert findings[0].line == 2

    def test_suppression_marker(self, tmp_path):
        lint = self._lint()
        d = tmp_path / "paddle_tpu" / "obs"
        d.mkdir(parents=True)
        (d / "m.py").write_text(
            "def f(obs):\n"
            "    s = obs.span('x')  # span-ok: closed by caller\n"
            "    return [s]\n")
        for rel in ("paddle_tpu/profiler", "paddle_tpu/serving",
                    "paddle_tpu/transforms", "paddle_tpu/ckpt",
                    "paddle_tpu/tune"):
            (tmp_path / rel).mkdir(parents=True, exist_ok=True)
        for rel in ("paddle_tpu/fluid/executor.py",
                    "paddle_tpu/parallel/compiler.py",
                    "paddle_tpu/dataset/feed_pipeline.py",
                    "paddle_tpu/transforms/__init__.py",
                    "paddle_tpu/analysis/verifier.py",
                    "paddle_tpu/obs/telemetry.py",
                    "paddle_tpu/obs/devprof.py",
                    "paddle_tpu/obs/memprof.py",
                    "paddle_tpu/obs/numerics.py",
                    "paddle_tpu/fluid/aot_cache.py",
                    "paddle_tpu/parallel/quant_collectives.py",
                    "paddle_tpu/ops/pallas/attention.py",
                    "bench.py"):
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text("")
        assert not lint.run_rules(root=str(tmp_path),
                                  rules=["span-leak"])

    def test_shipped_tree_is_clean(self):
        lint = self._lint()
        findings = lint.run_rules(rules=["span-leak"])
        assert not findings, "\n".join(str(f) for f in findings)

    def test_obs_entries_on_hot_path_watchlist(self):
        lint = self._lint()
        watched = set(lint.hot_path_sync.WATCHLIST)
        assert ("paddle_tpu/obs/tracing.py", "Tracer.add_span") in watched
        assert ("paddle_tpu/obs/cost.py",
                "ProgramCost.observe_dispatch") in watched
