"""paddle.static.nn tail (reference static/nn/__init__.py __all__):
surface completeness + executor-backed smoke/oracle tests for the
param-creating static layers and case/switch_case control flow."""

import ast

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
import paddle_tpu.static as static


@pytest.fixture
def prog():
    from paddle_tpu.fluid import framework, unique_name
    from paddle_tpu.fluid.executor import Scope, scope_guard

    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with unique_name.guard():
            with scope_guard(Scope()):
                yield main, startup


def _run(main, startup, feed, fetch):
    exe = fluid.Executor()
    exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=fetch)


def test_static_nn_surface_complete():
    import os
    if not os.path.isdir("/root/reference"):
        pytest.skip("reference source tree not present in this environment")
    names = None
    for node in ast.walk(ast.parse(open(
            "/root/reference/python/paddle/static/nn/__init__.py"
    ).read())):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if getattr(t, "id", "") == "__all__":
                    names = set(ast.literal_eval(node.value))
    missing = sorted(n for n in names if not hasattr(static.nn, n))
    assert missing == [], f"static.nn gaps: {missing}"


def test_bilinear_tensor_product(prog):
    main, startup = prog
    x = fluid.data("x", [-1, 4], "float32")
    y = fluid.data("y", [-1, 5], "float32")
    out = static.nn.bilinear_tensor_product(x, y, size=6)
    xv = np.random.RandomState(0).rand(3, 4).astype("float32")
    yv = np.random.RandomState(1).rand(3, 5).astype("float32")
    (o,) = _run(main, startup, {"x": xv, "y": yv}, [out])
    assert o.shape == (3, 6) and np.isfinite(o).all()


def test_row_conv_and_spectral_norm(prog):
    main, startup = prog
    x = fluid.data("x", [-1, 5, 8], "float32")
    out = static.nn.row_conv(x, future_context_size=2)
    w = static.nn.create_parameter([4, 6], "float32")
    wn = static.nn.spectral_norm(w, power_iters=2)
    xv = np.random.RandomState(2).rand(2, 5, 8).astype("float32")
    o, wv = _run(main, startup, {"x": xv}, [out, wn])
    assert o.shape == xv.shape
    # spectral norm bounds the top singular value near 1
    assert np.linalg.svd(wv, compute_uv=False)[0] < 2.0


def test_data_norm_and_nce(prog):
    main, startup = prog
    x = fluid.data("x", [-1, 6], "float32")
    out = static.nn.data_norm(x)
    emb = fluid.data("e", [-1, 8], "float32")
    lbl = fluid.data("l", [-1, 1], "int64")
    cost = static.nn.nce(emb, lbl, num_total_classes=12,
                         num_neg_samples=3)
    xv = np.random.RandomState(3).rand(4, 6).astype("float32")
    ev = np.random.RandomState(4).rand(4, 8).astype("float32")
    lv = np.array([[1], [2], [3], [0]], "int64")
    o, c = _run(main, startup, {"x": xv, "e": ev, "l": lv},
                [out, cost])
    assert o.shape == xv.shape and np.isfinite(c).all()


def test_deform_conv2d_and_conv3d_transpose(prog):
    main, startup = prog
    x = fluid.data("x", [-1, 3, 8, 8], "float32")
    # 3x3 kernel -> offset 2*3*3 channels, mask 3*3
    off = fluid.data("off", [-1, 18, 8, 8], "float32")
    mask = fluid.data("m", [-1, 9, 8, 8], "float32")
    out = static.nn.deform_conv2d(x, off, mask, num_filters=4,
                                  filter_size=3, padding=1)
    x3 = fluid.data("x3", [-1, 2, 4, 4, 4], "float32")
    out3 = static.nn.conv3d_transpose(x3, 5, filter_size=3)
    r = np.random.RandomState(5)
    o, o3 = _run(main, startup,
                 {"x": r.rand(2, 3, 8, 8).astype("float32"),
                  "off": np.zeros((2, 18, 8, 8), "float32"),
                  "m": np.ones((2, 9, 8, 8), "float32"),
                  "x3": r.rand(2, 2, 4, 4, 4).astype("float32")},
                 [out, out3])
    assert o.shape == (2, 4, 8, 8)
    assert o3.shape[:2] == (2, 5)


def test_case_and_switch_case(prog):
    main, startup = prog
    x = fluid.data("x", [1], "float32")
    one = lambda: fluid.layers.fill_constant([1], "float32", 1.0)
    two = lambda: fluid.layers.fill_constant([1], "float32", 2.0)
    three = lambda: fluid.layers.fill_constant([1], "float32", 3.0)
    pred_hi = x > fluid.layers.fill_constant([1], "float32", 10.0)
    pred_lo = x > fluid.layers.fill_constant([1], "float32", 0.0)
    out = static.nn.case([(pred_hi, one), (pred_lo, two)],
                         default=three)
    idx = fluid.data("i", [1], "int64")
    sw = static.nn.switch_case(idx, {0: one, 1: two, 3: three})
    (a, s0) = _run(main, startup,
                   {"x": np.array([5.0], "float32"),
                    "i": np.array([1], "int64")}, [out, sw])
    assert float(a) == 2.0   # first true pred wins (pred_lo)
    assert float(s0) == 2.0  # index 1 -> two
    exe = fluid.Executor()
    (b, s1) = exe.run(main, feed={"x": np.array([50.0], "float32"),
                                  "i": np.array([7], "int64")},
                      fetch_list=[out, sw])
    assert float(b) == 1.0   # pred_hi wins
    assert float(s1) == 3.0  # unknown index -> default (max-index fn)


def test_multi_box_head(prog):
    main, startup = prog
    img = fluid.data("img", [-1, 3, 32, 32], "float32")
    f1 = fluid.data("f1", [-1, 8, 8, 8], "float32")
    f2 = fluid.data("f2", [-1, 8, 4, 4], "float32")
    locs, confs, boxes, vars_ = static.nn.multi_box_head(
        [f1, f2], img, base_size=32, num_classes=5,
        aspect_ratios=[[2.0], [2.0]], min_ratio=20, max_ratio=90,
        flip=True)
    r = np.random.RandomState(6)
    lv, cv, bv, vv = _run(
        main, startup,
        {"img": r.rand(2, 3, 32, 32).astype("float32"),
         "f1": r.rand(2, 8, 8, 8).astype("float32"),
         "f2": r.rand(2, 8, 4, 4).astype("float32")},
        [locs, confs, boxes, vars_])
    n_priors = bv.shape[0]
    assert lv.shape == (2, n_priors, 4)
    assert cv.shape == (2, n_priors, 5)
    assert vv.shape == (n_priors, 4)


def test_data_norm_accumulates_stats(prog):
    """The *Out slots alias the persistable stats — they must CHANGE
    after a run (review finding: without the slots the layer is a
    permanent identity)."""
    main, startup = prog
    x = fluid.data("x", [-1, 3], "float32")
    out = static.nn.data_norm(x)
    exe = fluid.Executor()
    exe.run(startup)
    from paddle_tpu.fluid.executor import global_scope

    xv = (np.random.RandomState(7).rand(8, 3) + 5).astype("float32")
    # params in creation order: w_0=batch_size, w_1=batch_sum, w_2=sq
    name = "data_norm_0.w_1"
    before = np.asarray(global_scope().find_var(name).get_tensor())
    exe.run(main, feed={"x": xv}, fetch_list=[out])
    after = np.asarray(global_scope().find_var(name).get_tensor())
    assert not np.allclose(before, after), "stats did not accumulate"


def test_spectral_norm_refines_u(prog):
    main, startup = prog
    w = static.nn.create_parameter([4, 6], "float32")
    wn = static.nn.spectral_norm(w, power_iters=1)
    exe = fluid.Executor()
    exe.run(startup)
    from paddle_tpu.fluid.executor import global_scope

    # u/v are the spectral_norm helper's params (creation order)
    uname = "spectral_norm_0.w_0"
    exe.run(main, fetch_list=[wn])
    u1 = np.asarray(global_scope().find_var(uname).get_tensor()).copy()
    exe.run(main, fetch_list=[wn])
    u2 = np.asarray(global_scope().find_var(uname).get_tensor())
    assert not np.allclose(u1, u2), "power-iteration u never refined"


def test_multi_box_head_scalar_steps(prog):
    main, startup = prog
    img = fluid.data("img", [-1, 3, 16, 16], "float32")
    f1 = fluid.data("f1", [-1, 4, 4, 4], "float32")
    locs, confs, boxes, _ = static.nn.multi_box_head(
        [f1], img, base_size=16, num_classes=3,
        aspect_ratios=[[2.0]], min_sizes=[[4.0]], max_sizes=[[8.0]],
        steps=[4.0])  # scalar per map, like the reference API
    r = np.random.RandomState(8)
    lv, = _run(main, startup,
               {"img": r.rand(1, 3, 16, 16).astype("float32"),
                "f1": r.rand(1, 4, 4, 4).astype("float32")}, [locs])
    assert lv.shape[0] == 1 and lv.shape[2] == 4


def test_loud_unsupported_knobs(prog):
    main, startup = prog
    x = fluid.data("x", [-1, 6], "float32")
    with pytest.raises(NotImplementedError, match="scale_and_shift"):
        static.nn.data_norm(x, enable_scale_and_shift=True)
    lbl = fluid.data("l", [-1, 1], "int64")
    with pytest.raises(NotImplementedError, match="sampler"):
        static.nn.nce(x, lbl, 10, sampler="log_uniform")
    x3 = fluid.data("x3", [-1, 2, 4, 4, 4], "float32")
    with pytest.raises(NotImplementedError, match="output_size"):
        static.nn.conv3d_transpose(x3, 5, output_size=[8, 8, 8],
                                   filter_size=3)
