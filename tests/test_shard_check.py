"""Static sharding analyzer (ISSUE 18): PartitionSpec propagation as a
verifier pass, predicted collective cost, and re-shard feasibility.

Positive sweep: the shard-consistency analyzer reports zero ERROR
findings over the fixture + book-model zoos under a pure data mesh,
the 3-D acceptance mesh, and a degenerate-pipe mesh.  Negative sweep:
each mis-sharded program in tests/fixtures/broken_shardings.py draws
its finding with `program#<id> block<idx> op<id>` provenance.  Cost
model: `comm_report` predicts the SPMD-inserted collective wire bytes
of the acceptance transformer within ±25% of the measured
`collective_bytes_spmd_*` counters, quant off AND int8.  Elastic:
`feasibility` refuses a 16-row batch onto a 3-device mesh and accepts
8→4 with a bytes-per-device delta.  Hot path: cache-hit steps pay zero
verifier time with a mesh current (the pass rides the existing
compile-miss seam).  Registry: a typo'd `register_spec` bumps the
`spec_clamped` stat instead of degrading silently."""

import os
import re
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import profiler
from paddle_tpu.analysis import comm_report, feasibility, shard_check
from paddle_tpu.analysis.verifier import reset_finding_dedup
from paddle_tpu.fluid import framework, unique_name
from paddle_tpu.fluid.executor import Scope, scope_guard
from paddle_tpu.parallel import mesh as mesh_lib
from paddle_tpu.parallel import spec_layout
from jax.sharding import PartitionSpec as P

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import test_book_models as book  # noqa: E402
from fixtures import programs as fixture_programs  # noqa: E402
from fixtures.broken_shardings import BROKEN_SHARDINGS  # noqa: E402
from test_spmd_sharding import build_tiny_transformer  # noqa: E402

_PROVENANCE = re.compile(r"program#\d+ block\d+ op\d+")

SWEEP_MESHES = (
    {"data": 8},
    {"data": 2, "fsdp": 2, "tp": 2},
    {"data": 2, "fsdp": 2, "tp": 2, "pipe": 1},
)


@pytest.fixture(autouse=True)
def _clean_context():
    saved = os.environ.get("PADDLE_QUANT_COLLECTIVES")
    yield
    if saved is None:
        os.environ.pop("PADDLE_QUANT_COLLECTIVES", None)
    else:
        os.environ["PADDLE_QUANT_COLLECTIVES"] = saved
    mesh_lib.set_current_mesh(None)
    spec_layout.clear_specs()
    reset_finding_dedup()


def _errors(findings):
    return [f for f in findings if f.severity == "error"]


# ---------------------------------------------------------------------------
# negative sweep: every broken-sharding fixture fires, with provenance
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(BROKEN_SHARDINGS))
def test_broken_sharding_fires_with_provenance(name):
    build, mesh, overrides, severity, substr = BROKEN_SHARDINGS[name]
    for var, entries in overrides.items():
        spec_layout.register_spec(var, P(*entries))
    try:
        findings = shard_check.check_program_dict(build(), mesh)
    finally:
        spec_layout.clear_specs()
    hits = [f for f in findings
            if f.severity == severity and substr in f.message]
    assert hits, (name, [str(f) for f in findings])
    for f in hits:
        assert _PROVENANCE.search(f.location), (name, f.location)


def test_clean_program_has_no_findings_under_every_sweep_mesh():
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup), unique_name.guard():
        loss = build_tiny_transformer()
        fluid.optimizer.Adam(0.01).minimize(loss)
    for mesh in SWEEP_MESHES:
        findings = shard_check.check_program(
            main, mesh, batch_rows=16, fetch_list=[loss.name])
        assert not findings, (mesh, [str(f) for f in findings])


# ---------------------------------------------------------------------------
# positive sweep: shipped zoos are shard-clean on every mesh
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(fixture_programs.FIXTURES))
def test_fixture_zoo_shard_clean(name):
    main, startup, fetch = fixture_programs.FIXTURES[name]()
    fl = [v.name if hasattr(v, "name") else str(v) for v in fetch or ()]
    for mesh in SWEEP_MESHES:
        for prog, f in ((main, fl), (startup, None)):
            errs = _errors(shard_check.check_program(
                prog, mesh, fetch_list=f))
            assert not errs, (name, mesh, [str(e) for e in errs])


@pytest.mark.parametrize("name", sorted(book.BOOK_BUILDERS))
def test_book_model_zoo_shard_clean(name):
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup), unique_name.guard():
        fetch = book.BOOK_BUILDERS[name]()
    fl = [v.name if hasattr(v, "name") else str(v) for v in fetch or ()]
    for mesh in SWEEP_MESHES:
        for prog, f in ((main, fl), (startup, None)):
            errs = _errors(shard_check.check_program(
                prog, mesh, fetch_list=f))
            assert not errs, (name, mesh, [str(e) for e in errs])


# ---------------------------------------------------------------------------
# cost model: predicted vs measured wire bytes, quant off AND int8
# ---------------------------------------------------------------------------

def _train_and_measure(axes):
    """One compile of the acceptance transformer under `axes`;
    returns (program, measured collective_bytes_spmd_* delta)."""
    rng = np.random.RandomState(0)
    IDS = rng.randint(0, 32, size=(16, 1)).astype("int64")
    L = rng.randint(0, 8, size=(16, 1)).astype("int64")
    main, startup = framework.Program(), framework.Program()
    scope = Scope()
    try:
        with framework.program_guard(main, startup), \
                unique_name.guard(), scope_guard(scope):
            loss = build_tiny_transformer()
            fluid.optimizer.Adam(0.01).minimize(loss)
            exe = fluid.Executor()
            exe.run(startup)
            bs = fluid.BuildStrategy()
            bs.mesh_axes = axes
            compiled = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name, build_strategy=bs)
            pre = profiler.get_int_stats()
            # the spmd counters book once per compile — one step is
            # enough to materialize them
            exe.run(compiled, feed={"ids": IDS, "label": L},
                    fetch_list=[loss])
            post = profiler.get_int_stats()
        measured = sum(
            v - pre.get(k, 0) for k, v in post.items()
            if k.startswith("collective_bytes_spmd_"))
        return main, measured
    finally:
        mesh_lib.set_current_mesh(None)


@pytest.mark.parametrize("quant", [None, "int8"])
def test_comm_report_within_25pct_of_measured(quant):
    if quant is None:
        os.environ.pop("PADDLE_QUANT_COLLECTIVES", None)
    else:
        os.environ["PADDLE_QUANT_COLLECTIVES"] = quant
    axes = {"data": 2, "fsdp": 2, "tp": 2}
    main, measured = _train_and_measure(axes)
    assert measured > 0
    rep = comm_report(main, axes, batch_rows=16,
                      feed=["ids", "label"])
    predicted = rep["predicted_total"]
    assert rep["mode"] == "spmd"
    assert bool(rep["quant"]) == (quant == "int8")
    err = abs(predicted - measured) / measured
    assert err <= 0.25, (quant, predicted, measured, rep["predicted"])


def test_comm_report_explicit_regime_sums_collective_events():
    d = {
        "blocks": [{
            "idx": 0, "parent_idx": -1,
            "vars": [
                {"name": "x", "shape": [8, 4], "dtype": "float32",
                 "is_data": True},
                {"name": "out", "shape": [8, 4], "dtype": "float32"},
            ],
            "ops": [{
                "id": 1, "type": "c_allreduce_sum",
                "inputs": {"X": ["x"]}, "outputs": {"Out": ["out"]},
                "attrs": {"ring_id": 0},
            }],
        }],
    }
    rep = comm_report(shard_check.ProgramView(d), {"data": 2},
                     feed=["x"])
    assert rep["mode"] == "explicit"
    assert rep["predicted_total"] > 0


# ---------------------------------------------------------------------------
# elastic feasibility precheck
# ---------------------------------------------------------------------------

def test_feasibility_refuses_nondividing_shrink_accepts_dividing():
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup), unique_name.guard():
        loss = build_tiny_transformer()
        fluid.optimizer.Adam(0.01).minimize(loss)

    bad = feasibility(main, {"data": 8}, {"data": 3}, batch_rows=16)
    assert not bad["feasible"]
    assert any("does not divide" in p for p in bad["problems"]), bad

    ok = feasibility(main, {"data": 8}, {"data": 4}, batch_rows=16)
    assert ok["feasible"], ok["problems"]
    assert ok["old_devices"] == 8 and ok["new_devices"] == 4
    assert isinstance(ok["delta_bytes_per_device"], int)
    assert ok["new_bytes_per_device"] >= ok["old_bytes_per_device"]

    # growing onto the 3-D mesh shrinks resident bytes per device
    grow = feasibility(main, {"data": 8},
                       {"data": 2, "fsdp": 2, "tp": 2}, batch_rows=16)
    assert grow["feasible"], grow["problems"]
    assert grow["new_bytes_per_device"] < grow["old_bytes_per_device"]


# ---------------------------------------------------------------------------
# hot path: the pass rides the compile-miss seam only
# ---------------------------------------------------------------------------

def test_shard_consistency_not_paid_on_cache_hits():
    main, startup = framework.Program(), framework.Program()
    scope = Scope()
    try:
        with framework.program_guard(main, startup), \
                unique_name.guard(), scope_guard(scope):
            x = fluid.data("x", [-1, 8], "float32")
            y = fluid.layers.fc(x, 4)
            exe = fluid.Executor()
            exe.run(startup)
            bs = fluid.BuildStrategy()
            bs.mesh_axes = {"data": 8}
            compiled = fluid.CompiledProgram(main).with_data_parallel(
                build_strategy=bs)
            feed = {"x": np.ones((8, 8), "float32")}
            exe.run(compiled, feed=feed, fetch_list=[y])  # miss
            runs0 = profiler.get_int_stats().get("verifier_runs", 0)
            ms0 = profiler.get_time_stats().get("verify_ms", 0.0)
            assert runs0 >= 1
            for _ in range(4):  # hits: zero verifier (and analyzer) time
                exe.run(compiled, feed=feed, fetch_list=[y])
            assert profiler.get_int_stats().get(
                "verifier_runs", 0) == runs0
            assert profiler.get_time_stats().get(
                "verify_ms", 0.0) == ms0
    finally:
        mesh_lib.set_current_mesh(None)


# ---------------------------------------------------------------------------
# spec_layout: a typo'd override is clamped LOUDLY
# ---------------------------------------------------------------------------

def test_typod_register_spec_bumps_spec_clamped_stat():
    mesh = mesh_lib.make_mesh({"data": 8})
    spec_layout.register_spec("fc_7.w_0", P("bogus_axis"))
    try:
        before = profiler.get_int_stats().get("spec_clamped", 0)
        spec = spec_layout.spec_for("fc_7.w_0", (16, 32), mesh)
        after = profiler.get_int_stats().get("spec_clamped", 0)
        assert spec == P()  # clamped to what the mesh carries
        assert after > before
    finally:
        spec_layout.clear_specs()


def test_typod_override_surfaces_as_clamp_warning():
    d = {
        "blocks": [{
            "idx": 0, "parent_idx": -1,
            "vars": [
                {"name": "x", "shape": [8, 16], "dtype": "float32",
                 "is_data": True},
                {"name": "fc_7.w_0", "shape": [16, 32],
                 "dtype": "float32", "persistable": True},
                {"name": "y", "shape": [8, 32], "dtype": "float32"},
            ],
            "ops": [{
                "id": 1, "type": "mul",
                "inputs": {"X": ["x"], "Y": ["fc_7.w_0"]},
                "outputs": {"Out": ["y"]}, "attrs": {},
            }],
        }],
    }
    spec_layout.register_spec("fc_7.w_0", P("bogus_axis"))
    try:
        findings = shard_check.check_program_dict(d, {"data": 8})
    finally:
        spec_layout.clear_specs()
    warns = [f for f in findings if f.severity == "warning"
             and "dropped" in f.message]
    assert warns, [str(f) for f in findings]


# ---------------------------------------------------------------------------
# quant byte model identities (the calibration the CLI also asserts)
# ---------------------------------------------------------------------------

def test_quant_phase_byte_formulas():
    # 1024 elems over 4 ranks: 256-elem chunk = one 256-wide block ->
    # 4*(256 int8 codes + 1 fp32 scale) = 1040 wire bytes per phase
    assert shard_check._quant_phase_bytes(1024, 4) == 1040
    # plain (ungrouped) path: 512 codes + 2 scales = 520... plus the
    # 4-byte scale per 256-block: 512 + 2*4 = 520
    assert shard_check._quant_plain_bytes(512) == 520
