"""paddle.distribution tests (reference test_distribution.py: Normal /
Uniform / Categorical sample/entropy/log_prob/kl against scipy-style
numpy oracles)."""

import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distribution import (Categorical, MultivariateNormalDiag,
                                     Normal, Uniform, kl_divergence)
from paddle_tpu.fluid.dygraph import guard


@pytest.fixture(autouse=True)
def dygraph():
    with guard():
        paddle.seed(0)
        yield


class TestNormal:
    def test_log_prob_entropy(self):
        loc, scale = np.array([0.0, 1.0], "float32"), \
            np.array([1.0, 2.0], "float32")
        d = Normal(loc, scale)
        v = np.array([0.5, -1.0], "float32")
        ref = (-(v - loc) ** 2 / (2 * scale ** 2) - np.log(scale)
               - 0.5 * math.log(2 * math.pi))
        np.testing.assert_allclose(d.log_prob(v).numpy(), ref, rtol=1e-5)
        ref_ent = 0.5 + 0.5 * math.log(2 * math.pi) + np.log(scale)
        np.testing.assert_allclose(d.entropy().numpy(), ref_ent,
                                   rtol=1e-5)

    def test_sample_moments(self):
        d = Normal(2.0, 3.0)
        s = d.sample((20000,)).numpy()
        assert abs(s.mean() - 2.0) < 0.1
        assert abs(s.std() - 3.0) < 0.1

    def test_kl_zero_for_same(self):
        d = Normal(np.float32(1.0), np.float32(2.0))
        np.testing.assert_allclose(
            kl_divergence(d, Normal(np.float32(1.0), np.float32(2.0)))
            .numpy(), 0.0, atol=1e-6)


class TestUniform:
    def test_log_prob_in_out(self):
        d = Uniform(0.0, 2.0)
        lp = d.log_prob(np.array([1.0, 3.0], "float32")).numpy()
        np.testing.assert_allclose(lp[0], -math.log(2.0), rtol=1e-6)
        assert np.isneginf(lp[1])

    def test_sample_range_and_entropy(self):
        d = Uniform(1.0, 4.0)
        s = d.sample((5000,)).numpy()
        assert s.min() >= 1.0 and s.max() < 4.0
        np.testing.assert_allclose(d.entropy().numpy(), math.log(3.0),
                                   rtol=1e-6)


class TestCategorical:
    def test_log_prob_and_entropy(self):
        logits = np.log(np.array([[0.2, 0.3, 0.5]], "float32"))
        d = Categorical(logits)
        lp = d.log_prob(np.array([2], "int64")).numpy()
        np.testing.assert_allclose(lp, [math.log(0.5)], rtol=1e-5)
        p = np.array([0.2, 0.3, 0.5])
        np.testing.assert_allclose(d.entropy().numpy(),
                                   [-(p * np.log(p)).sum()], rtol=1e-5)

    def test_sample_distribution(self):
        logits = np.log(np.array([0.1, 0.9], "float32"))
        d = Categorical(logits)
        s = d.sample((8000,)).numpy()
        assert abs((s == 1).mean() - 0.9) < 0.03

    def test_kl(self):
        a = Categorical(np.log(np.array([0.5, 0.5], "float32")))
        b = Categorical(np.log(np.array([0.9, 0.1], "float32")))
        ref = 0.5 * math.log(0.5 / 0.9) + 0.5 * math.log(0.5 / 0.1)
        np.testing.assert_allclose(kl_divergence(a, b).numpy(), ref,
                                   rtol=1e-5)


class TestMVNDiag:
    def test_log_prob_matches_normal_product(self):
        loc = np.array([0.0, 1.0], "float32")
        scale = np.array([1.0, 2.0], "float32")
        d = MultivariateNormalDiag(loc, scale)
        v = np.array([0.3, -0.7], "float32")
        per_dim = Normal(loc, scale).log_prob(v).numpy()
        np.testing.assert_allclose(d.log_prob(v).numpy(), per_dim.sum(),
                                   rtol=1e-5)

    def test_grad_flows_through_log_prob(self):
        from paddle_tpu.fluid.dygraph import to_variable

        loc = to_variable(np.zeros(3, "float32"))
        loc.stop_gradient = False
        d = Normal(loc, np.ones(3, "float32"))
        lp = d.log_prob(np.array([1.0, 2.0, 3.0], "float32"))
        s = lp.sum() if hasattr(lp, "sum") else lp
        import paddle_tpu.tensor as T

        loss = T.sum(lp) if hasattr(T, "sum") else s
        loss.backward()
        np.testing.assert_allclose(loc.grad.numpy(), [1.0, 2.0, 3.0],
                                   rtol=1e-5)
