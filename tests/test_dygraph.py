"""Dygraph engine tests: eager ops, tape autograd, hooks, double grad.

Methodology mirrors the reference's test_imperative_basic.py /
test_imperative_double_grad.py (loss.backward() vs hand-derived grads)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import dygraph
from paddle_tpu.fluid.dygraph import Tensor, to_variable


@pytest.fixture(autouse=True)
def _dygraph_mode():
    with dygraph.guard():
        yield


def test_eager_basic_math():
    x = to_variable(np.array([1.0, 2.0, 3.0], np.float32))
    y = to_variable(np.array([4.0, 5.0, 6.0], np.float32))
    z = x * y + 2.0
    np.testing.assert_allclose(z.numpy(), [6.0, 12.0, 20.0])
    assert z.stop_gradient  # no grad-requiring inputs


def test_backward_simple():
    x = Tensor(np.array([2.0, 3.0], np.float32), stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_backward_chain_and_accumulation():
    x = Tensor(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32),
               stop_gradient=False)
    # x used twice: grads must accumulate
    y = (x * x + x * 3.0).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * x.numpy() + 3.0)


def test_grad_accumulates_across_backwards():
    x = Tensor(np.array([1.0], np.float32), stop_gradient=False)
    (x * 2.0).sum().backward()
    (x * 3.0).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])
    x.clear_grad()
    assert x.grad is None


def test_no_grad():
    x = Tensor(np.array([1.0], np.float32), stop_gradient=False)
    with dygraph.no_grad():
        y = x * 2.0
    assert y.stop_gradient
    assert y._grad_node is None


def test_detach_breaks_graph():
    x = Tensor(np.array([2.0], np.float32), stop_gradient=False)
    y = x * 3.0
    z = y.detach() * 2.0
    assert z._grad_node is None


def test_second_backward_raises():
    x = Tensor(np.array([1.0], np.float32), stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_retain_graph():
    x = Tensor(np.array([1.0], np.float32), stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0])


def test_matmul_grad():
    a = Tensor(np.random.rand(3, 4).astype(np.float32), stop_gradient=False)
    b = Tensor(np.random.rand(4, 5).astype(np.float32), stop_gradient=False)
    out = (a @ b).sum()
    out.backward()
    np.testing.assert_allclose(a.grad.numpy(),
                               np.ones((3, 5)) @ b.numpy().T, rtol=1e-5)
    np.testing.assert_allclose(b.grad.numpy(),
                               a.numpy().T @ np.ones((3, 5)), rtol=1e-5)


def test_trace_op_softmax_ce():
    logits = Tensor(np.random.rand(4, 10).astype(np.float32),
                    stop_gradient=False)
    labels = Tensor(np.random.randint(0, 10, (4, 1)).astype(np.int64))
    outs = dygraph.trace_op(
        "softmax_with_cross_entropy",
        {"Logits": logits, "Label": labels},
        {"soft_label": False, "axis": -1}, multi_out=True)
    loss = outs["Loss"][0].mean()
    loss.backward()
    assert logits.grad is not None
    assert logits.grad.shape == [4, 10]


def test_paddle_grad_api():
    x = Tensor(np.array([3.0], np.float32), stop_gradient=False)
    y = x * x
    (gx,) = dygraph.grad(y, x)
    np.testing.assert_allclose(gx.numpy(), [6.0])
    assert x.grad is None  # paddle.grad must not touch .grad


def test_double_grad():
    x = Tensor(np.array([3.0], np.float32), stop_gradient=False)
    y = x * x * x  # y = x^3, dy/dx = 3x^2, d2y/dx2 = 6x
    (gx,) = dygraph.grad(y, x, create_graph=True)
    np.testing.assert_allclose(gx.numpy(), [27.0])
    assert not gx.stop_gradient
    (ggx,) = dygraph.grad(gx, x)
    np.testing.assert_allclose(ggx.numpy(), [18.0])


def test_grad_interior_tensor():
    x = Tensor(np.array([2.0], np.float32), stop_gradient=False)
    y = x * 3.0
    z = y * y
    (gy,) = dygraph.grad(z, y)
    np.testing.assert_allclose(gy.numpy(), [12.0])


def test_register_hook():
    x = Tensor(np.array([1.0], np.float32), stop_gradient=False)
    seen = []

    def hook(g):
        seen.append(g.numpy().copy())
        return g * 2.0

    x.register_hook(hook)
    (x * 5.0).sum().backward()
    assert len(seen) == 1
    np.testing.assert_allclose(seen[0], [5.0])
    np.testing.assert_allclose(x.grad.numpy(), [10.0])


def test_grad_tensor_seed():
    x = Tensor(np.array([1.0, 2.0], np.float32), stop_gradient=False)
    y = x * 2.0
    y.backward(grad_tensor=Tensor(np.array([1.0, 10.0], np.float32)))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 20.0])


def test_reshape_transpose_grad():
    x = Tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
               stop_gradient=False)
    y = x.reshape([3, 2]).transpose([1, 0])
    (y * y).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * x.numpy())


def test_indexing_grad():
    x = Tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
               stop_gradient=False)
    y = x[0]
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               [[1, 1, 1], [0, 0, 0]])


def test_setitem_and_mutation():
    x = Tensor(np.zeros((2, 2), np.float32))
    x[0, 0] = 5.0
    assert x.numpy()[0, 0] == 5.0
    x.fill_(1.0)
    np.testing.assert_allclose(x.numpy(), np.ones((2, 2)))


def test_comparison_and_cast():
    x = to_variable(np.array([1.0, 2.0], np.float32))
    y = to_variable(np.array([2.0, 2.0], np.float32))
    assert (x < y).numpy().tolist() == [True, False]
    z = x.astype("int64")
    # jax_enable_x64 is off (TPU-native default): int64 narrows to int32
    assert z.dtype in ("int64", "int32")


def test_multi_root_same_node():
    # Two outputs of the SAME tape node given as backward roots must not
    # double-count consumers (regression: discovery stalled upstream nodes).
    x = Tensor(np.array([2.0], np.float32), stop_gradient=False)
    y = x * 3.0
    outs = dygraph.trace_fn(lambda v: (v * 2.0, v * 5.0), {"v": y},
                            multi_out=True)
    a, b = outs
    gx = dygraph.grad([a, b], [x], grad_outputs=[
        Tensor(np.ones(1, np.float32)), Tensor(np.ones(1, np.float32))])
    np.testing.assert_allclose(gx[0].numpy(), [21.0])  # 3*(2+5)


def test_hook_fires_once_on_accumulated_grad():
    # Hook semantics: fires ONCE on the fully-accumulated gradient, not per
    # contribution (regression).
    x = Tensor(np.array([1.0], np.float32), stop_gradient=False)
    calls = []

    def hook(g):
        calls.append(g.numpy().copy())
        return g * 10.0

    x.register_hook(hook)
    # x consumed twice -> two partial grads 2.0 and 3.0 accumulate to 5.0
    y = (x * 2.0 + x * 3.0).sum()
    y.backward()
    assert len(calls) == 1
    np.testing.assert_allclose(calls[0], [5.0])
    np.testing.assert_allclose(x.grad.numpy(), [50.0])


def test_create_graph_after_consumed_graph_raises():
    x = Tensor(np.array([1.0], np.float32), stop_gradient=False)
    y = (x * x).sum()
    y.backward()  # consumes graph
    with pytest.raises(RuntimeError):
        dygraph.grad(y, x, create_graph=True)


def test_branching_graph():
    # Diamond: x -> a, b -> c; dependency counting must wait for both paths.
    x = Tensor(np.array([2.0], np.float32), stop_gradient=False)
    a = x * 2.0
    b = x * 3.0
    c = (a * b).sum()  # c = 6x^2, dc/dx = 12x = 24
    c.backward()
    np.testing.assert_allclose(x.grad.numpy(), [24.0])
