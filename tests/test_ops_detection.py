"""Detection op tests (reference unittests: test_prior_box_op.py,
test_anchor_generator_op.py, test_box_coder_op.py, test_iou_similarity_op.py,
test_bipartite_match_op.py, test_multiclass_nms_op.py, test_yolo_box_op.py,
test_sigmoid_focal_loss_op.py, test_roi_align_op.py, test_box_clip_op.py).
Oracles are direct numpy re-derivations of the reference C++ kernels."""

import math

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.executor import Scope, scope_guard

from op_test import OpTest, randf, run_single_op

run_det_op = run_single_op




def np_iou(a, b, off=0.0):
    ix1 = np.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = np.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = np.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = np.minimum(a[:, None, 3], b[None, :, 3])
    inter = np.maximum(ix2 - ix1 + off, 0) * np.maximum(iy2 - iy1 + off, 0)
    aa = (a[:, 2] - a[:, 0] + off) * (a[:, 3] - a[:, 1] + off)
    ab = (b[:, 2] - b[:, 0] + off) * (b[:, 3] - b[:, 1] + off)
    union = aa[:, None] + ab[None, :] - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-10), 0.0)


def rand_boxes(n, seed, scale=10.0):
    rng = np.random.RandomState(seed)
    xy = rng.rand(n, 2) * scale
    wh = rng.rand(n, 2) * scale / 2 + 0.5
    return np.concatenate([xy, xy + wh], axis=1).astype("float32")


def test_iou_similarity():
    a, b = rand_boxes(4, 1), rand_boxes(6, 2)
    out = run_det_op("iou_similarity", {"X": a, "Y": b},
                     {"box_normalized": True}, ["Out"])["Out"]
    np.testing.assert_allclose(out, np_iou(a, b), rtol=1e-5, atol=1e-6)


def test_prior_box_matches_reference_loop():
    feat = np.zeros((1, 8, 2, 2), "float32")
    img = np.zeros((1, 3, 32, 32), "float32")
    attrs = {"min_sizes": [4.0], "max_sizes": [8.0],
             "aspect_ratios": [2.0], "flip": True, "clip": True,
             "variances": [0.1, 0.1, 0.2, 0.2], "offset": 0.5,
             "step_w": 0.0, "step_h": 0.0}
    d = run_det_op("prior_box", {"Input": feat, "Image": img}, attrs,
                   ["Boxes", "Variances"])
    boxes, variances = d["Boxes"], d["Variances"]
    # ars expand to [1, 2, 0.5] -> 3 + 1 max_size = 4 priors
    assert boxes.shape == (2, 2, 4, 4)
    step = 32 / 2
    cx, cy = (0 + 0.5) * step, (0 + 0.5) * step
    want00 = []
    for ar in [1.0, 2.0, 0.5]:
        bw, bh = 4 * math.sqrt(ar) / 2, 4 / math.sqrt(ar) / 2
        want00.append([(cx - bw) / 32, (cy - bh) / 32,
                       (cx + bw) / 32, (cy + bh) / 32])
    sq = math.sqrt(4.0 * 8.0) / 2
    want00.append([(cx - sq) / 32, (cy - sq) / 32,
                   (cx + sq) / 32, (cy + sq) / 32])
    np.testing.assert_allclose(boxes[0, 0],
                               np.clip(np.asarray(want00), 0, 1),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(variances[1, 1, 2], [0.1, 0.1, 0.2, 0.2])


def test_anchor_generator_matches_reference_loop():
    feat = np.zeros((1, 8, 2, 3), "float32")
    d = run_det_op("anchor_generator", {"Input": feat},
                   {"anchor_sizes": [32.0, 64.0], "aspect_ratios": [1.0],
                    "stride": [16.0, 16.0], "offset": 0.5,
                    "variances": [0.1, 0.1, 0.2, 0.2]},
                   ["Anchors", "Variances"])
    a = d["Anchors"]
    assert a.shape == (2, 3, 2, 4)
    xc = 1 * 16.0 + 0.5 * 15.0
    yc = 0 * 16.0 + 0.5 * 15.0
    base = round(math.sqrt(16 * 16 / 1.0))
    aw = 32.0 / 16.0 * base
    np.testing.assert_allclose(
        a[0, 1, 0],
        [xc - 0.5 * (aw - 1), yc - 0.5 * (aw - 1),
         xc + 0.5 * (aw - 1), yc + 0.5 * (aw - 1)], rtol=1e-5)


def test_box_coder_encode_decode_roundtrip():
    prior = rand_boxes(5, 3)
    target = rand_boxes(4, 4)
    var = [0.1, 0.1, 0.2, 0.2]
    enc = run_det_op("box_coder",
                     {"PriorBox": prior, "TargetBox": target},
                     {"code_type": "encode_center_size",
                      "box_normalized": True, "variance": var},
                     ["OutputBox"])["OutputBox"]
    assert enc.shape == (4, 5, 4)
    dec = run_det_op("box_coder",
                     {"PriorBox": prior, "TargetBox": enc},
                     {"code_type": "decode_center_size",
                      "box_normalized": True, "variance": var, "axis": 0},
                     ["OutputBox"])["OutputBox"]
    # decoding the encoding recovers each target against every prior
    for j in range(5):
        np.testing.assert_allclose(dec[:, j], target, rtol=1e-4, atol=1e-4)


def test_box_clip():
    boxes = np.array([[[-5.0, -3.0, 50.0, 40.0]]], "float32")
    im_info = np.array([[20.0, 30.0, 1.0]], "float32")
    out = run_det_op("box_clip", {"Input": boxes, "ImInfo": im_info}, {},
                     ["Output"])["Output"]
    np.testing.assert_allclose(out[0, 0], [0, 0, 29, 19])


def test_bipartite_match_greedy():
    # classic example: global max first, then next-best disjoint pair
    dist = np.array([[0.1, 0.9, 0.3],
                     [0.8, 0.2, 0.7]], "float32")
    d = run_det_op("bipartite_match", {"DistMat": dist},
                   {"match_type": "bipartite"},
                   ["ColToRowMatchIndices", "ColToRowMatchDist"],
                   {"ColToRowMatchIndices": "int32"})
    idx, dst = d["ColToRowMatchIndices"][0], d["ColToRowMatchDist"][0]
    np.testing.assert_array_equal(idx, [1, 0, -1])
    np.testing.assert_allclose(dst, [0.8, 0.9, 0.0], rtol=1e-5)


def test_bipartite_match_per_prediction():
    dist = np.array([[0.1, 0.9, 0.6],
                     [0.8, 0.2, 0.65]], "float32")
    d = run_det_op("bipartite_match", {"DistMat": dist},
                   {"match_type": "per_prediction",
                    "dist_threshold": 0.5},
                   ["ColToRowMatchIndices", "ColToRowMatchDist"],
                   {"ColToRowMatchIndices": "int32"})
    idx = d["ColToRowMatchIndices"][0]
    # col 2 unmatched by bipartite but best row 1 has 0.65 >= 0.5
    np.testing.assert_array_equal(idx, [1, 0, 1])


def test_multiclass_nms_dense():
    # 2 well-separated boxes + 1 overlapping duplicate, 1 class
    boxes = np.array([[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5],
                       [20, 20, 30, 30]]], "float32")
    scores = np.array([[[0.0, 0.0, 0.0],      # background
                        [0.9, 0.8, 0.7]]], "float32")  # class 1
    d = run_det_op("multiclass_nms3",
                   {"BBoxes": boxes, "Scores": scores},
                   {"background_label": 0, "score_threshold": 0.1,
                    "nms_top_k": 3, "keep_top_k": 3,
                    "nms_threshold": 0.5, "normalized": True},
                   ["Out", "NmsRoisNum"], {"NmsRoisNum": "int32"})
    out, num = d["Out"], d["NmsRoisNum"]
    assert num[0] == 2  # duplicate suppressed
    assert out.shape == (1, 3, 6)
    np.testing.assert_allclose(out[0, 0, :2], [1, 0.9], rtol=1e-5)
    np.testing.assert_allclose(out[0, 0, 2:], [0, 0, 10, 10], rtol=1e-5)
    np.testing.assert_allclose(out[0, 1, :2], [1, 0.7], rtol=1e-5)
    assert out[0, 2, 0] == -1  # padding row


def test_yolo_box_formula():
    b, a, h, w, cnum = 1, 1, 2, 2, 2
    rng = np.random.RandomState(7)
    x = rng.randn(b, a * (5 + cnum), h, w).astype("float32")
    img = np.array([[64, 64]], "int32")
    anchors = [10, 14]
    d = run_det_op("yolo_box", {"X": x, "ImgSize": img},
                   {"anchors": anchors, "class_num": cnum,
                    "conf_thresh": 0.0, "downsample_ratio": 32,
                    "clip_bbox": False, "scale_x_y": 1.0},
                   ["Boxes", "Scores"])
    boxes, sc = d["Boxes"], d["Scores"]
    sig = lambda v: 1 / (1 + np.exp(-v))
    # cell (i=1, j=0) -> flat row h*w index 0*2+1
    cx = (1 + sig(x[0, 0, 0, 1])) * 64 / w
    cy = (0 + sig(x[0, 1, 0, 1])) * 64 / h
    bw = np.exp(x[0, 2, 0, 1]) * 10 * 64 / (32 * w)
    bh = np.exp(x[0, 3, 0, 1]) * 14 * 64 / (32 * h)
    np.testing.assert_allclose(
        boxes[0, 1], [cx - bw / 2, cy - bh / 2, cx + bw / 2, cy + bh / 2],
        rtol=1e-4)
    conf = sig(x[0, 4, 0, 1])
    np.testing.assert_allclose(sc[0, 1, 0], sig(x[0, 5, 0, 1]) * conf,
                               rtol=1e-4)


def test_sigmoid_focal_loss_matches_numpy():
    rng = np.random.RandomState(8)
    x = rng.randn(6, 3).astype("float32")
    label = np.array([[0], [1], [2], [3], [1], [0]], "int32")
    fg = np.array([4], "int32")
    out = run_det_op("sigmoid_focal_loss",
                     {"X": x, "Label": label, "FgNum": fg},
                     {"gamma": 2.0, "alpha": 0.25}, ["Out"])["Out"]
    p = 1 / (1 + np.exp(-x))
    tgt = (label == np.arange(1, 4)[None, :]).astype("float32")
    ce = -(tgt * np.log(p) + (1 - tgt) * np.log(1 - p))
    w = tgt * 0.25 * (1 - p) ** 2 + (1 - tgt) * 0.75 * p ** 2
    np.testing.assert_allclose(out, w * ce / 4.0, rtol=1e-4, atol=1e-5)


def test_roi_align_constant_region():
    # constant image -> every pooled value equals that constant
    x = np.full((1, 2, 8, 8), 3.0, "float32")
    rois = np.array([[1.0, 1.0, 5.0, 5.0]], "float32")
    out = run_det_op("roi_align",
                     {"X": x, "ROIs": rois,
                      "RoisNum": np.array([1], "int32")},
                     {"pooled_height": 2, "pooled_width": 2,
                      "spatial_scale": 1.0, "sampling_ratio": 2},
                     ["Out"])["Out"]
    assert out.shape == (1, 2, 2, 2)
    np.testing.assert_allclose(out, 3.0, rtol=1e-5)


def test_roi_align_batch_mapping():
    # two images with distinct constants; RoisNum maps rois to images
    x = np.stack([np.full((1, 4, 4), 1.0), np.full((1, 4, 4), 5.0)]
                 ).astype("float32")
    rois = np.array([[0, 0, 3, 3], [0, 0, 3, 3]], "float32")
    out = run_det_op("roi_align",
                     {"X": x, "ROIs": rois,
                      "RoisNum": np.array([1, 1], "int32")},
                     {"pooled_height": 1, "pooled_width": 1,
                      "spatial_scale": 1.0, "sampling_ratio": 2},
                     ["Out"])["Out"]
    np.testing.assert_allclose(out[0, 0, 0, 0], 1.0, rtol=1e-5)
    np.testing.assert_allclose(out[1, 0, 0, 0], 5.0, rtol=1e-5)


def test_detection_layers_build():
    """Layer wrappers wire into a Program and execute."""
    from paddle_tpu.fluid import framework, unique_name

    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup), unique_name.guard():
        import paddle_tpu.fluid.layers as layers

        feat = fluid.data("feat", [1, 8, 2, 2], "float32")
        img = fluid.data("img", [1, 3, 32, 32], "float32")
        boxes, variances = layers.prior_box(feat, img, min_sizes=[4.0])
        a = fluid.data("a", [3, 4], "float32")
        b = fluid.data("b", [2, 4], "float32")
        iou = layers.iou_similarity(a, b)
    with scope_guard(Scope()):
        exe = fluid.Executor()
        bo, io = exe.run(
            main,
            feed={"feat": np.zeros((1, 8, 2, 2), "float32"),
                  "img": np.zeros((1, 3, 32, 32), "float32"),
                  "a": rand_boxes(3, 9), "b": rand_boxes(2, 10)},
            fetch_list=[boxes, iou])
    assert np.asarray(bo).shape == (2, 2, 1, 4)
    assert np.asarray(io).shape == (3, 2)


def test_density_prior_box_matches_reference_loop():
    feat = np.zeros((1, 8, 2, 2), "float32")
    img = np.zeros((1, 3, 32, 32), "float32")
    d = run_det_op("density_prior_box", {"Input": feat, "Image": img},
                   {"fixed_sizes": [4.0], "fixed_ratios": [1.0],
                    "densities": [2], "variances": [0.1, 0.1, 0.2, 0.2],
                    "offset": 0.5, "step_w": 0.0, "step_h": 0.0},
                   ["Boxes", "Variances"])
    boxes = d["Boxes"]
    assert boxes.shape == (2, 2, 4, 4)  # 1 ratio * 2^2 density
    # replicate the reference loop for cell (0, 0), first sub-box
    step = 16.0
    step_avg = int((step + step) * 0.5)
    shift = step_avg // 2
    cx = cy = 0.5 * step
    dcx = cx - step_avg / 2.0 + shift / 2.0
    want0 = [max((dcx - 2.0) / 32, 0), max((dcx - 2.0) / 32, 0),
             min((dcx + 2.0) / 32, 1), min((dcx + 2.0) / 32, 1)]
    np.testing.assert_allclose(boxes[0, 0, 0], want0, rtol=1e-5)


def test_polygon_box_transform():
    x = np.zeros((1, 8, 2, 2), "float32")
    x[0, 0, 1, 1] = 1.0   # x-offset channel at cell (1,1)
    d = run_det_op("polygon_box_transform", {"Input": x}, {}, ["Output"])
    o = d["Output"]
    # even channel uses column index: 4*col - in
    assert o[0, 0, 1, 1] == 4.0 * 1 - 1.0
    assert o[0, 0, 1, 0] == 0.0
    # odd channel uses row index
    assert o[0, 1, 1, 1] == 4.0 * 1
    assert o[0, 1, 0, 1] == 0.0


def test_target_assign():
    x = rand_boxes(3, 20).reshape(1, 3, 4)
    match = np.array([[0, -1, 2, 1]], "int32")
    d = run_det_op("target_assign", {"X": x, "MatchIndices": match},
                   {"mismatch_value": -5.0}, ["Out", "OutWeight"])
    np.testing.assert_allclose(d["Out"][0, 0], x[0, 0])
    np.testing.assert_allclose(d["Out"][0, 2], x[0, 2])
    np.testing.assert_allclose(d["Out"][0, 3], x[0, 1])
    assert np.all(d["Out"][0, 1] == -5.0)
    np.testing.assert_array_equal(d["OutWeight"][0, :, 0], [1, 0, 1, 1])


def test_mine_hard_examples_max_negative():
    cls_loss = np.array([[0.1, 0.9, 0.5, 0.7, 0.2, 0.3]], "float32")
    match = np.array([[2, -1, -1, -1, -1, 0]], "int32")  # 2 positives
    dist = np.zeros((1, 6), "float32")
    d = run_det_op("mine_hard_examples",
                   {"ClsLoss": cls_loss, "MatchIndices": match,
                    "MatchDist": dist},
                   {"neg_pos_ratio": 1.5, "mining_type": "max_negative",
                    "neg_dist_threshold": 0.5},
                   ["NegIndices", "UpdatedMatchIndices"],
                   {"NegIndices": "int32",
                    "UpdatedMatchIndices": "int32"})
    # 2 pos * 1.5 = 3 negatives allowed: highest-loss negs are cols 1,3,2
    np.testing.assert_array_equal(d["NegIndices"][0], [0, 1, 1, 1, 0, 0])
    np.testing.assert_array_equal(d["UpdatedMatchIndices"], match)


def test_mine_hard_examples_neg_dist_threshold():
    # IsEligibleMining: an unmatched prior with match_dist >=
    # neg_dist_threshold (a near-miss with high gt overlap) must never
    # be selected as a hard negative, even with the highest loss.
    cls_loss = np.array([[0.1, 0.9, 0.5, 0.7, 0.2, 0.3]], "float32")
    match = np.array([[2, -1, -1, -1, -1, 0]], "int32")
    dist = np.array([[0.9, 0.8, 0.1, 0.1, 0.1, 0.7]], "float32")
    d = run_det_op("mine_hard_examples",
                   {"ClsLoss": cls_loss, "MatchIndices": match,
                    "MatchDist": dist},
                   {"neg_pos_ratio": 1.5, "mining_type": "max_negative",
                    "neg_dist_threshold": 0.5},
                   ["NegIndices", "UpdatedMatchIndices"],
                   {"NegIndices": "int32",
                    "UpdatedMatchIndices": "int32"})
    # col 1 (loss 0.9) is excluded by dist 0.8 >= 0.5; remaining
    # eligible negs are cols 2,3,4 — all within the 3-neg budget.
    np.testing.assert_array_equal(d["NegIndices"][0], [0, 0, 1, 1, 1, 0])


def test_matrix_nms_decays_overlaps():
    boxes = np.array([[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5],
                       [20, 20, 30, 30]]], "float32")
    scores = np.array([[[0.0, 0.0, 0.0],
                        [0.9, 0.8, 0.7]]], "float32")
    d = run_det_op("matrix_nms", {"BBoxes": boxes, "Scores": scores},
                   {"background_label": 0, "score_threshold": 0.1,
                    "post_threshold": 0.0, "nms_top_k": 3,
                    "keep_top_k": 3, "use_gaussian": False},
                   ["Out", "RoisNum"], {"RoisNum": "int32"})
    out = d["Out"]
    # top box keeps 0.9; far box keeps 0.7; overlapped box decayed
    np.testing.assert_allclose(out[0, 0, 1], 0.9, rtol=1e-5)
    np.testing.assert_allclose(out[0, 1, 1], 0.7, rtol=1e-5)
    iou = np_iou(boxes[0][:1], boxes[0][1:2])[0, 0]
    np.testing.assert_allclose(out[0, 2, 1], 0.8 * (1 - iou), rtol=1e-4)
    assert d["RoisNum"][0] == 3


def test_matrix_nms_gaussian_decay():
    boxes = np.array([[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5]]],
                     "float32")
    scores = np.array([[[0.0, 0.0], [0.9, 0.8]]], "float32")
    d = run_det_op("matrix_nms", {"BBoxes": boxes, "Scores": scores},
                   {"background_label": 0, "score_threshold": 0.1,
                    "post_threshold": 0.0, "nms_top_k": 2,
                    "keep_top_k": 2, "use_gaussian": True,
                    "gaussian_sigma": 2.0},
                   ["Out", "RoisNum"], {"RoisNum": "int32"})
    iou = np_iou(boxes[0][:1], boxes[0][1:2])[0, 0]
    want = 0.8 * np.exp(-iou * iou * 2.0)  # max_iou of leader = 0
    np.testing.assert_allclose(d["Out"][0, 1, 1], want, rtol=1e-4)
    assert d["Out"][0, 1, 1] < 0.8  # decayed, never amplified


def test_generate_proposals_basic():
    # 1 image, 2x2 feature map, 1 anchor/cell, zero deltas -> proposals
    # are the clipped anchors ranked by score
    h = w = 2
    anchors = np.zeros((h, w, 1, 4), "float32")
    for i in range(h):
        for j in range(w):
            anchors[i, j, 0] = [j * 8, i * 8, j * 8 + 7, i * 8 + 7]
    scores = np.array([[[[0.1, 0.9], [0.8, 0.2]]]], "float32")  # (1,1,2,2)
    deltas = np.zeros((1, 4, h, w), "float32")
    im_shape = np.array([[16.0, 16.0]], "float32")
    d = run_det_op("generate_proposals_v2",
                   {"Scores": scores, "BboxDeltas": deltas,
                    "ImShape": im_shape, "Anchors": anchors,
                    "Variances": np.ones((h, w, 1, 4), "float32")},
                   {"pre_nms_topN": 4, "post_nms_topN": 3,
                    "nms_thresh": 0.5, "min_size": 1.0},
                   ["RpnRois", "RpnRoiProbs", "RpnRoisNum"],
                   {"RpnRoisNum": "int32"})
    rois, num = d["RpnRois"], d["RpnRoisNum"]
    assert num[0] == 3
    np.testing.assert_allclose(d["RpnRoiProbs"][0, :, 0],
                               [0.9, 0.8, 0.2], rtol=1e-5)
    # highest score 0.9 at (h=0, w=1) -> anchor [8, 0, 15, 7]
    np.testing.assert_allclose(rois[0, 0], [8, 0, 15, 7], atol=1e-4)
    np.testing.assert_allclose(rois[0, 1], [0, 8, 7, 15], atol=1e-4)


def test_detection_output_layer(fresh_programs):
    """detection_output = decode + NMS through the layer composition."""
    main, startup, scope = fresh_programs
    loc = fluid.data("loc", [1, 3, 4], "float32")
    sc = fluid.data("sc", [1, 3, 2], "float32")
    pb = fluid.data("pb", [3, 4], "float32")
    pv = fluid.data("pv", [3, 4], "float32")
    import paddle_tpu.fluid.layers as layers

    out, num = layers.detection_output(loc, sc, pb, pv,
                                       score_threshold=0.1,
                                       nms_top_k=3, keep_top_k=3)
    exe = fluid.Executor()
    priors = np.array([[0, 0, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9],
                       [0.1, 0.1, 0.5, 0.5]], "float32")
    logits = np.array([[[-2.0, 2.0], [-1.5, 1.5], [2.0, -2.0]]],
                      "float32")
    o, n = exe.run(main, feed={
        "loc": np.zeros((1, 3, 4), "float32"),
        "sc": logits, "pb": priors, "pv": np.ones((3, 4), "float32")},
        fetch_list=[out, num])
    o, n = np.asarray(o), np.asarray(n)
    assert n[0] == 2  # two confident foreground priors survive
    want_top = 1 / (1 + np.exp(-4.0))  # softmax([-2, 2])[1]
    np.testing.assert_allclose(o[0, 0, 1], want_top, rtol=1e-5)


def test_yolov3_loss_matches_numpy_oracle():
    """Replicates the reference yolov3_loss_op.h loops in numpy on a
    tiny config and checks the fused lowering."""
    rng = np.random.RandomState(11)
    n, h, w, cnum = 1, 2, 2, 2
    anchors = [10.0, 14.0, 40.0, 40.0]
    mask = [0]
    a = len(mask)
    x = rng.randn(n, a * (5 + cnum), h, w).astype("float32")
    gt = np.array([[[0.3, 0.6, 0.2, 0.3], [0, 0, 0, 0]]], "float32")
    gtl = np.array([[1, 0]], "int32")
    downsample, ignore_thresh = 32, 0.5
    input_size = downsample * h

    d = run_det_op("yolov3_loss",
                   {"X": x, "GTBox": gt, "GTLabel": gtl},
                   {"anchors": anchors, "anchor_mask": mask,
                    "class_num": cnum, "ignore_thresh": ignore_thresh,
                    "downsample_ratio": downsample,
                    "use_label_smooth": False, "scale_x_y": 1.0},
                   ["Loss", "ObjectnessMask", "GTMatchMask"],
                   {"GTMatchMask": "int32"})

    sig = lambda v: 1 / (1 + np.exp(-v))
    sce = lambda l, t: max(l, 0) - l * t + np.log1p(np.exp(-abs(l)))

    def iou_c(b1, b2):
        l = max(b1[0] - b1[2] / 2, b2[0] - b2[2] / 2)
        r = min(b1[0] + b1[2] / 2, b2[0] + b2[2] / 2)
        t = max(b1[1] - b1[3] / 2, b2[1] - b2[3] / 2)
        b = min(b1[1] + b1[3] / 2, b2[1] + b2[3] / 2)
        inter = max(r - l, 0) * max(b - t, 0)
        return inter / (b1[2] * b1[3] + b2[2] * b2[3] - inter)

    xr = x[0].reshape(a, 5 + cnum, h, w)
    g0 = gt[0, 0]
    # best anchor over both anchors (wh iou)
    an_ious = [iou_c([0, 0, 10 / 64, 14 / 64], [0, 0, g0[2], g0[3]]),
               iou_c([0, 0, 40 / 64, 40 / 64], [0, 0, g0[2], g0[3]])]
    best_n = int(np.argmax(an_ious))
    assert best_n == 0  # matched anchor is in the mask
    gi, gj = int(g0[0] * w), int(g0[1] * h)
    tx, ty = g0[0] * w - gi, g0[1] * h - gj
    tw = np.log(g0[2] * input_size / anchors[0])
    th = np.log(g0[3] * input_size / anchors[1])
    sc = 2 - g0[2] * g0[3]
    loss = (sce(xr[0, 0, gj, gi], tx) + sce(xr[0, 1, gj, gi], ty)
            + abs(xr[0, 2, gj, gi] - tw)
            + abs(xr[0, 3, gj, gi] - th)) * sc
    # class loss (no smooth): label 1
    loss += sce(xr[0, 5, gj, gi], 0.0) + sce(xr[0, 6, gj, gi], 1.0)
    # objectness: decode preds, ignore > thresh
    for j in range(a):
        for k in range(h):
            for l in range(w):
                pred = [(l + sig(xr[j, 0, k, l])) / w,
                        (k + sig(xr[j, 1, k, l])) / h,
                        np.exp(xr[j, 2, k, l]) * anchors[0] / input_size,
                        np.exp(xr[j, 3, k, l]) * anchors[1] / input_size]
                best_iou = iou_c(pred, g0)
                is_pos = (k == gj and l == gi)
                if is_pos:
                    loss += sce(xr[j, 4, k, l], 1.0)
                elif best_iou <= ignore_thresh:
                    loss += sce(xr[j, 4, k, l], 0.0)
    np.testing.assert_allclose(d["Loss"][0], loss, rtol=1e-4)
    assert d["ObjectnessMask"][0, 0, gj, gi] == 1.0
    np.testing.assert_array_equal(d["GTMatchMask"][0], [0, -1])


def test_roi_pool_matches_reference_loop():
    x = np.random.RandomState(13).randn(1, 2, 8, 8).astype("float32")
    rois = np.array([[1.0, 1.0, 6.0, 6.0], [0.0, 0.0, 3.0, 4.0]],
                    "float32")
    d = run_det_op("roi_pool",
                   {"X": x, "ROIs": rois,
                    "RoisNum": np.array([2], "int32")},
                   {"pooled_height": 2, "pooled_width": 2,
                    "spatial_scale": 1.0}, ["Out"])

    # numpy re-derivation of roi_pool_op.h
    def ref_pool(img, roi, P=2, Q=2):
        x0, y0, x1, y1 = [int(round(v)) for v in roi]
        rh, rw = max(y1 - y0 + 1, 1), max(x1 - x0 + 1, 1)
        bh, bw = rh / P, rw / Q
        out = np.zeros((img.shape[0], P, Q), "float32")
        for p in range(P):
            for q in range(Q):
                hs = min(max(int(np.floor(p * bh)) + y0, 0), 8)
                he = min(max(int(np.ceil((p + 1) * bh)) + y0, 0), 8)
                ws = min(max(int(np.floor(q * bw)) + x0, 0), 8)
                we = min(max(int(np.ceil((q + 1) * bw)) + x0, 0), 8)
                if he <= hs or we <= ws:
                    continue
                out[:, p, q] = img[:, hs:he, ws:we].max(axis=(1, 2))
        return out

    for i, roi in enumerate(rois):
        np.testing.assert_allclose(d["Out"][i], ref_pool(x[0], roi),
                                   rtol=1e-5)


def test_distribute_then_collect_fpn():
    # rois sized to land on different levels
    rois = np.array([[0, 0, 20, 20],      # small -> low level
                     [0, 0, 500, 500],    # big -> high level
                     [0, 0, 24, 24]], "float32")
    d = run_det_op("distribute_fpn_proposals", {"FpnRois": rois},
                   {"min_level": 2, "max_level": 5, "refer_level": 4,
                    "refer_scale": 224},
                   ["MultiFpnRois", "MultiLevelRoIsNum", "RestoreIndex"],
                   {"MultiLevelRoIsNum": "int32", "RestoreIndex": "int32"})
    # NOTE: multi-output slots come back as the FIRST entry only through
    # this harness; assert on RestoreIndex which is single
    ri = d["RestoreIndex"].reshape(-1)
    assert sorted(ri.tolist()) == [0, 1, 2]
    # level of each roi: small ones level<=refer, big one clipped to max
    scale = np.sqrt([20 * 20, 500 * 500, 24 * 24])
    lvl = np.clip(np.floor(np.log2(scale / 224 + 1e-6)) + 4, 2, 5)
    assert lvl[1] == 5 and lvl[0] == 2

    # collect: two levels with front-packed rois
    r1 = np.array([[0, 0, 10, 10], [0, 0, 0, 0]], "float32")
    r2 = np.array([[5, 5, 9, 9], [0, 0, 0, 0]], "float32")
    s1 = np.array([0.9, 0.0], "float32")
    s2 = np.array([0.7, 0.0], "float32")
    d = run_det_op("collect_fpn_proposals",
                   {"MultiLevelRois": [r1, r2],
                    "MultiLevelScores": [s1, s2]},
                   {"post_nms_topN": 3}, ["FpnRois", "RoisNum"],
                   {"RoisNum": "int32"})
    np.testing.assert_allclose(d["FpnRois"][0], [0, 0, 10, 10])
    np.testing.assert_allclose(d["FpnRois"][1], [5, 5, 9, 9])
    assert d["RoisNum"][0] == 2


def test_generate_proposals_v1_iminfo_scale():
    """v1 measures min_size in original-image pixels via ImInfo scale."""
    h = w = 2
    anchors = np.zeros((h, w, 1, 4), "float32")
    for i in range(h):
        for j in range(w):
            anchors[i, j, 0] = [j * 8, i * 8, j * 8 + 5, i * 8 + 5]
    scores = np.array([[[[0.9, 0.8], [0.7, 0.6]]]], "float32")
    deltas = np.zeros((1, 4, h, w), "float32")
    im_info = np.array([[16.0, 16.0, 2.0]], "float32")  # scale 2
    d = run_det_op("generate_proposals",
                   {"Scores": scores, "BboxDeltas": deltas,
                    "ImInfo": im_info, "Anchors": anchors,
                    "Variances": np.ones((h, w, 1, 4), "float32")},
                   {"pre_nms_topN": 4, "post_nms_topN": 4,
                    "nms_thresh": 0.9, "min_size": 4.0},
                   ["RpnRois", "RpnRoiProbs", "RpnRoisNum"],
                   {"RpnRoisNum": "int32"})
    # box side 6 px on the feature grid -> (6-1)/2 + 1 = 3.5 < 4 in
    # original pixels: every proposal is dropped under v1 scaling
    assert d["RpnRoisNum"][0] == 0


def test_box_decoder_and_assign():
    prior = np.array([[0, 0, 9, 9]], "float32")
    # class 0: zero deltas; class 1: shift right by width
    target = np.array([[0, 0, 0, 0, 1.0, 0, 0, 0]], "float32")
    score = np.array([[0.2, 0.8]], "float32")
    d = run_det_op("box_decoder_and_assign",
                   {"PriorBox": prior, "TargetBox": target,
                    "BoxScore": score},
                   {"box_clip": 4.135},
                   ["DecodeBox", "OutputAssignBox"])
    np.testing.assert_allclose(d["DecodeBox"][0, :4], [0, 0, 9, 9],
                               atol=1e-4)
    np.testing.assert_allclose(d["DecodeBox"][0, 4:], [10, 0, 19, 9],
                               atol=1e-4)
    # argmax class is 1 -> assigned box is the shifted decode
    np.testing.assert_allclose(d["OutputAssignBox"][0], [10, 0, 19, 9],
                               atol=1e-4)
    # bg score dominating changes NOTHING: reference never compares bg
    d2 = run_det_op("box_decoder_and_assign",
                    {"PriorBox": prior, "TargetBox": target,
                     "BoxScore": np.array([[0.9, 0.1]], "float32")},
                    {"box_clip": 4.135},
                    ["DecodeBox", "OutputAssignBox"])
    np.testing.assert_allclose(d2["OutputAssignBox"][0], [10, 0, 19, 9],
                               atol=1e-4)


def test_rpn_target_assign_masks():
    anchors = np.array([[0, 0, 9, 9], [20, 20, 29, 29],
                        [100, 100, 109, 109]], "float32")
    gt = np.array([[[0, 0, 9, 9]]], "float32")  # matches anchor 0
    d = run_det_op("rpn_target_assign",
                   {"Anchor": anchors, "GtBoxes": gt},
                   {"rpn_batch_size_per_im": 4, "rpn_fg_fraction": 0.5,
                    "rpn_positive_overlap": 0.7,
                    "rpn_negative_overlap": 0.3},
                   ["ScoreTarget", "LocationTarget", "LocationWeight",
                    "ScoreWeight"],
                   {"ScoreTarget": "int32"})
    st = d["ScoreTarget"][0, :, 0]
    assert st[0] == 1          # perfect-overlap anchor is positive
    assert st[1] in (0, -1) and st[2] in (0, -1)
    assert d["LocationWeight"][0, 0, 0] == 1.0
    # location target for the exact match is all zeros
    np.testing.assert_allclose(d["LocationTarget"][0, 0], 0.0, atol=1e-5)


def test_retinanet_detection_output():
    # one level, 2 anchors, 2 classes; zero deltas -> decoded == anchors
    anchors = np.array([[0, 0, 9, 9], [20, 20, 29, 29]], "float32")
    deltas = np.zeros((1, 2, 4), "float32")
    scores = np.array([[[0.9, 0.02], [0.03, 0.6]]], "float32")
    im_info = np.array([[32.0, 32.0, 1.0]], "float32")
    d = run_det_op("retinanet_detection_output",
                   {"BBoxes": [deltas], "Scores": [scores],
                    "Anchors": [anchors], "ImInfo": im_info},
                   {"score_threshold": 0.05, "nms_top_k": 4,
                    "keep_top_k": 3, "nms_threshold": 0.3},
                   ["Out", "RoisNum"], {"RoisNum": "int32"})
    out, num = d["Out"], d["RoisNum"]
    assert num[0] == 2
    # best: class 0 @ anchor 0 score .9; then class 1 @ anchor 1 score .6
    np.testing.assert_allclose(out[0, 0, :2], [0, 0.9], rtol=1e-5)
    np.testing.assert_allclose(out[0, 0, 2:], [0, 0, 9, 9], atol=1e-4)
    np.testing.assert_allclose(out[0, 1, :2], [1, 0.6], rtol=1e-5)
    assert out[0, 2, 0] == -1


def test_generate_proposal_labels():
    rois = np.array([[[0, 0, 9, 9],        # IoU 1 with gt0 -> fg
                      [0, 0, 11, 11],      # high IoU -> fg
                      [40, 40, 49, 49],    # no overlap -> bg
                      [100, 100, 109, 109]]], "float32")  # bg
    gtb = np.array([[[0, 0, 9, 9], [0, 0, 0, 0]]], "float32")
    gtc = np.array([[3, 0]], "int32")
    d = run_det_op("generate_proposal_labels",
                   {"RpnRois": rois, "GtClasses": gtc, "GtBoxes": gtb},
                   {"batch_size_per_im": 4, "fg_fraction": 0.5,
                    "fg_thresh": 0.5, "bg_thresh_hi": 0.5,
                    "bg_thresh_lo": 0.0, "class_nums": 5,
                    "bbox_reg_weights": [0.1, 0.1, 0.2, 0.2]},
                   ["Rois", "LabelsInt32", "BboxTargets",
                    "BboxInsideWeights", "RoisNum"],
                   {"LabelsInt32": "int32", "RoisNum": "int32"})
    labels = d["LabelsInt32"][0]
    # fg rows lead with label 3; bg rows labeled 0
    n_fg = int(np.sum(labels == 3))
    assert n_fg >= 1          # at least the exact-match roi (+ gt row)
    assert np.sum(labels == 0) >= 2
    assert d["RoisNum"][0] == 4
    # no degenerate (0,0,0,0) padding row is ever sampled as a roi
    sampled = d["Rois"][0][:int(d["RoisNum"][0])]
    w = sampled[:, 2] - sampled[:, 0]
    assert np.all(w > 0)
    # fg rows carry bbox targets in the class-3 slot with inside weight 1
    fg_rows = np.where(labels == 3)[0]
    tgt = d["BboxTargets"][0].reshape(4, 5, 4)
    inw = d["BboxInsideWeights"][0].reshape(4, 5, 4)
    assert np.all(inw[fg_rows, 3] == 1.0)
    # the exact-match roi's target is ~0 (identity encode)
    exact = fg_rows[np.argmin(np.abs(tgt[fg_rows, 3]).sum(axis=1))]
    np.testing.assert_allclose(tgt[exact, 3], 0.0, atol=1e-5)


def test_locality_aware_nms_merges_neighbors():
    # three near-identical boxes in sequence merge into one candidate
    # with summed score and score-weighted coords; a far box survives
    boxes = np.array([[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5],
                       [1, 1, 11, 11], [30, 30, 40, 40]]], "float32")
    scores = np.array([[[0.5, 0.3, 0.2, 0.6]]], "float32")
    d = run_det_op("locality_aware_nms",
                   {"BBoxes": boxes, "Scores": scores},
                   {"background_label": -1, "score_threshold": 0.01,
                    "nms_top_k": 4, "keep_top_k": 4,
                    "nms_threshold": 0.3, "normalized": False},
                   ["Out", "RoisNum"], {"RoisNum": "int32"})
    out, num = d["Out"], d["RoisNum"]
    assert num[0] == 2
    # merged cluster score = 0.5+0.3+0.2 = 1.0 ranks above the far 0.6
    np.testing.assert_allclose(out[0, 0, 1], 1.0, rtol=1e-5)
    # merge order: m01 = (b0*.5+b1*.3)/.8; m012 = (m01*.8+b2*.2)/1.0
    m01 = (np.array([0, 0, 10, 10]) * 0.5
           + np.array([0.5, 0.5, 10.5, 10.5]) * 0.3) / 0.8
    m012 = (m01 * 0.8 + np.array([1, 1, 11, 11]) * 0.2) / 1.0
    np.testing.assert_allclose(out[0, 0, 2:], m012, rtol=1e-4)
    np.testing.assert_allclose(out[0, 1, 1], 0.6, rtol=1e-5)


def test_locality_aware_nms_polygons():
    """8-coordinate quad path: overlapping quads merge with weighted
    coords + summed score (PolyIoU via the S-H convex clipper)."""
    q1 = [0, 0, 10, 0, 10, 10, 0, 10]
    q2 = [0.5, 0.5, 10.5, 0.5, 10.5, 10.5, 0.5, 10.5]
    far = [50, 50, 60, 50, 60, 60, 50, 60]
    boxes = np.array([[q1, q2, far]], "float32")
    scores = np.array([[[0.6, 0.4, 0.9]]], "float32")
    d = run_det_op("locality_aware_nms",
                   {"BBoxes": boxes, "Scores": scores},
                   {"background_label": -1, "score_threshold": 0.01,
                    "nms_top_k": 3, "keep_top_k": 3,
                    "nms_threshold": 0.3, "normalized": False},
                   ["Out", "RoisNum"], {"RoisNum": "int32"})
    assert d["RoisNum"][0] == 2
    # merged head: coords weighted 0.6/0.4, score 1.0 ranks first
    np.testing.assert_allclose(d["Out"][0, 0, 1], 1.0, rtol=1e-5)
    want = (np.array(q1) * 0.6 + np.array(q2) * 0.4)
    np.testing.assert_allclose(d["Out"][0, 0, 2:], want, rtol=1e-4)
    np.testing.assert_allclose(d["Out"][0, 1, 1], 0.9, rtol=1e-5)


def test_poly_iou_known_values():
    from paddle_tpu.ops.detection_ops import poly_iou
    sq = np.array([[0, 0], [10, 0], [10, 10], [0, 10]], "float32")
    half = np.array([[5, 0], [15, 0], [15, 10], [5, 10]], "float32")
    disjoint = np.array([[20, 20], [30, 20], [30, 30], [20, 30]],
                        "float32")
    np.testing.assert_allclose(float(poly_iou(sq, sq)), 1.0, rtol=1e-5)
    np.testing.assert_allclose(float(poly_iou(sq, half)), 50 / 150,
                               rtol=1e-4)
    np.testing.assert_allclose(float(poly_iou(sq, disjoint)), 0.0,
                               atol=1e-6)
    # rotated square (diamond) inside the square: inter = diamond area
    diamond = np.array([[5, 0], [10, 5], [5, 10], [0, 5]], "float32")
    np.testing.assert_allclose(float(poly_iou(sq, diamond)),
                               50 / 100, rtol=1e-4)


def test_generate_mask_labels():
    # one image, one gt (class 2) with a square polygon, two rois
    im_info = np.array([[100, 100, 1.0]], "float32")
    gt_classes = np.array([[2]], "int32")
    is_crowd = np.array([[0]], "int32")
    # square polygon covering [10,10]-[30,30]
    segms = np.array([[[[[10, 10], [30, 10], [30, 30], [10, 30]]]]],
                     "float32")  # (1, 1, 1, 4, 2)
    verts = np.array([[[4]]], "int32")
    rois = np.array([[[10, 10, 30, 30], [60, 60, 80, 80]]], "float32")
    labels = np.array([[2, 0]], "int32")
    d = run_det_op("generate_mask_labels",
                   {"ImInfo": im_info, "GtClasses": gt_classes,
                    "IsCrowd": is_crowd, "GtSegms": segms,
                    "GtSegmsVerts": verts, "Rois": rois,
                    "LabelsInt32": labels},
                   {"num_classes": 3, "resolution": 4},
                   ["MaskRois", "RoiHasMaskInt32", "MaskInt32"],
                   {"RoiHasMaskInt32": "int32", "MaskInt32": "int32"})
    np.testing.assert_array_equal(d["RoiHasMaskInt32"][0], [1, 0])
    m = d["MaskInt32"][0, 0].reshape(3, 16)
    # class-2 block: roi == polygon box -> all 16 pixels inside
    np.testing.assert_array_equal(m[2], np.ones(16, "int32"))
    # other class blocks stay ignore (-1)
    np.testing.assert_array_equal(m[0], -np.ones(16, "int32"))
    # bg roi: everything ignore
    assert (d["MaskInt32"][0, 1] == -1).all()


def test_generate_mask_labels_partial_coverage():
    im_info = np.array([[100, 100, 1.0]], "float32")
    gt_classes = np.array([[1]], "int32")
    is_crowd = np.array([[0]], "int32")
    # polygon covers the left half of the roi
    segms = np.array([[[[[0, 0], [10, 0], [10, 20], [0, 20]]]]],
                     "float32")
    verts = np.array([[[4]]], "int32")
    rois = np.array([[[0, 0, 20, 20]]], "float32")
    labels = np.array([[1]], "int32")
    d = run_det_op("generate_mask_labels",
                   {"ImInfo": im_info, "GtClasses": gt_classes,
                    "IsCrowd": is_crowd, "GtSegms": segms,
                    "GtSegmsVerts": verts, "Rois": rois,
                    "LabelsInt32": labels},
                   {"num_classes": 2, "resolution": 4},
                   ["MaskRois", "RoiHasMaskInt32", "MaskInt32"],
                   {"RoiHasMaskInt32": "int32", "MaskInt32": "int32"})
    m = d["MaskInt32"][0, 0].reshape(2, 4, 4)[1]
    # left two columns covered, right two empty
    np.testing.assert_array_equal(m[:, :2], np.ones((4, 2), "int32"))
    np.testing.assert_array_equal(m[:, 2:], np.zeros((4, 2), "int32"))


def test_locality_aware_nms_subthreshold_breaks_chain():
    """The reference walk (GetMaxScoreIndexWithLocalityAware) runs over
    ALL boxes — score_threshold is applied only to the merged head
    scores afterwards.  So a low-score far box DOES break a merge
    chain, and the two overlapping high-score boxes end up as separate
    heads (the second then suppressed by greedy NMS)."""
    boxes = np.array([[[0, 0, 10, 10], [50, 50, 60, 60],
                       [0.5, 0.5, 10.5, 10.5]]], "float32")
    scores = np.array([[[0.9, 0.005, 0.8]]], "float32")
    d = run_det_op("locality_aware_nms",
                   {"BBoxes": boxes, "Scores": scores},
                   {"background_label": -1, "score_threshold": 0.01,
                    "nms_top_k": 3, "keep_top_k": 3,
                    "nms_threshold": 0.3, "normalized": False},
                   ["Out", "RoisNum"], {"RoisNum": "int32"})
    # heads: 0.9, 0.005 (dropped by threshold), 0.8 (NMS-suppressed
    # by the 0.9 head it overlaps)
    assert d["RoisNum"][0] == 1
    np.testing.assert_allclose(d["Out"][0, 0, 1], 0.9, rtol=1e-5)


def test_locality_aware_nms_subthreshold_boxes_merge_above_threshold():
    """Boxes individually below score_threshold still participate in
    the walk; their merged head score can clear the threshold and must
    be emitted (reference applies the threshold to merged scores)."""
    boxes = np.array([[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5],
                       [1, 1, 11, 11]]], "float32")
    scores = np.array([[[0.04, 0.04, 0.04]]], "float32")
    d = run_det_op("locality_aware_nms",
                   {"BBoxes": boxes, "Scores": scores},
                   {"background_label": -1, "score_threshold": 0.1,
                    "nms_top_k": 3, "keep_top_k": 3,
                    "nms_threshold": 0.3, "normalized": False},
                   ["Out", "RoisNum"], {"RoisNum": "int32"})
    assert d["RoisNum"][0] == 1
    np.testing.assert_allclose(d["Out"][0, 0, 1], 0.12, rtol=1e-5)
