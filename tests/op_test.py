"""Declarative op-test harness: the TPU port of the reference's OpTest
methodology (/root/reference/python/paddle/fluid/tests/unittests/
op_test.py:226 check_output:1021, check_grad:1324,
get_numeric_gradient:101).

A test sets `op_type`, `inputs`, `attrs`, `outputs` (NumPy oracle);
`check_output` builds a one-op Program, runs it through the real Executor
(whole-block XLA compilation), and compares.  `check_grad` compares
append_backward's analytic gradients against central-difference numeric
gradients of sum(output) computed by re-running the forward program.
"""

from __future__ import annotations

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import core, framework, unique_name
from paddle_tpu.fluid.executor import Scope, scope_guard


class OpTest:
    op_type: str = ""
    inputs: dict = {}
    outputs: dict = {}
    attrs: dict = {}

    # -- program construction ---------------------------------------------
    def _build(self, for_grad=False, grad_inputs=(), grad_output=None):
        main, startup = framework.Program(), framework.Program()
        feed = {}
        with framework.program_guard(main, startup), unique_name.guard():
            block = main.global_block()
            in_map = {}
            for slot, val in self.inputs.items():
                arrs = val if isinstance(val, list) else [val]
                names = []
                for i, a in enumerate(arrs):
                    a = np.asarray(a)
                    name = f"in_{slot}_{i}"
                    block.create_var(
                        name=name, shape=a.shape,
                        dtype=core.convert_dtype(a.dtype), is_data=True,
                        stop_gradient=not (for_grad and slot in grad_inputs))
                    feed[name] = a
                    names.append(name)
                in_map[slot] = names
            out_map = {}
            fetch_names = []
            for slot, val in self.outputs.items():
                arrs = val if isinstance(val, list) else [val]
                names = []
                for i, a in enumerate(arrs):
                    name = f"out_{slot}_{i}"
                    block.create_var(name=name,
                                     dtype=core.convert_dtype(
                                         np.asarray(a).dtype))
                    names.append(name)
                    fetch_names.append((slot, i, name))
                out_map[slot] = names
            block.append_op(self.op_type, inputs=in_map, outputs=out_map,
                            attrs=dict(self.attrs))

            grad_fetch = []
            if for_grad:
                out_var = block.var(
                    dict((s, n) for s, i, n in fetch_names
                         if i == 0)[grad_output])
                loss = fluid.layers.reduce_sum(out_var)
                # cast non-f32 losses for a uniform scalar target
                pgs = fluid.append_backward(
                    loss, parameter_list=None,
                    no_grad_set={n for s, ns in in_map.items()
                                 for n in ns if s not in grad_inputs})
                for slot in grad_inputs:
                    for n in in_map[slot]:
                        grad_fetch.append(framework.grad_var_name(n))
        return main, startup, feed, fetch_names, grad_fetch

    # -- checks ------------------------------------------------------------
    def check_output(self, atol=1e-5, rtol=1e-5, no_check_set=()):
        main, startup, feed, fetch_names, _ = self._build()
        scope = Scope()
        with scope_guard(scope):
            exe = fluid.Executor()
            if startup.num_ops():
                exe.run(startup)
            outs = exe.run(main, feed=feed,
                           fetch_list=[n for _, _, n in fetch_names])
        for (slot, i, name), got in zip(fetch_names, outs):
            if slot in no_check_set:
                continue
            want = self.outputs[slot]
            want = np.asarray(want[i] if isinstance(want, list) else want)
            np.testing.assert_allclose(
                np.asarray(got, dtype=np.float64)
                if want.dtype.kind == "f" else got,
                want.astype(np.float64) if want.dtype.kind == "f" else want,
                atol=atol, rtol=rtol,
                err_msg=f"{self.op_type} output {slot}[{i}]")

    def check_grad(self, inputs_to_check, output_name,
                   max_relative_error=5e-3, delta=5e-3,
                   numeric_grad_delta=None):
        delta = numeric_grad_delta or delta
        main, startup, feed, fetch_names, grad_fetch = self._build(
            for_grad=True, grad_inputs=tuple(inputs_to_check),
            grad_output=output_name)
        scope = Scope()
        with scope_guard(scope):
            exe = fluid.Executor()
            if startup.num_ops():
                exe.run(startup)
            analytic = exe.run(main, feed=feed, fetch_list=grad_fetch)

            # forward-only program for numeric diff
            fwd_main, fwd_startup, fwd_feed, fwd_fetch, _ = self._build()
            out_names = [n for s, i, n in fwd_fetch if s == output_name]

            def f(feed_dict):
                outs = exe.run(fwd_main, feed=feed_dict,
                               fetch_list=out_names)
                return float(sum(np.sum(np.asarray(o, np.float64))
                                 for o in outs))

            idx = 0
            for slot in inputs_to_check:
                arrs = self.inputs[slot]
                arrs = arrs if isinstance(arrs, list) else [arrs]
                for i, a in enumerate(arrs):
                    a = np.asarray(a)
                    name = f"in_{slot}_{i}"
                    numeric = np.zeros(a.size, np.float64)
                    flat = a.reshape(-1)
                    for j in range(a.size):
                        orig = flat[j]
                        flat[j] = orig + delta
                        fp = f(fwd_feed | {name: a})
                        flat[j] = orig - delta
                        fm = f(fwd_feed | {name: a})
                        flat[j] = orig
                        numeric[j] = (fp - fm) / (2 * delta)
                    got = np.asarray(analytic[idx], np.float64).reshape(-1)
                    idx += 1
                    abs_err = np.abs(got - numeric)
                    denom = np.maximum(np.maximum(np.abs(got),
                                                  np.abs(numeric)), 1e-3)
                    rel = (abs_err / denom).max()
                    assert rel <= max_relative_error, (
                        f"{self.op_type} grad {slot}: max rel err {rel:.4e} "
                        f"(analytic {got[:5]}, numeric {numeric[:5]})")


def randf(*shape, low=-1.0, high=1.0, seed=None):
    rng = np.random.RandomState(seed if seed is not None else abs(hash(shape)) % 2**31)
    return rng.uniform(low, high, size=shape).astype("float32")


def run_single_op(op_type, inputs, attrs, out_slots, out_dtypes=None):
    """Build + run a one-op Program through the real Executor, returning
    outputs by slot name (shared harness for the table-driven test
    files)."""
    import paddle_tpu.fluid as _fluid
    from paddle_tpu.fluid.executor import Scope as _Scope
    from paddle_tpu.fluid.executor import scope_guard as _scope_guard

    t = OpTest()
    t.op_type, t.inputs, t.attrs = op_type, inputs, attrs
    t.outputs = {s: np.zeros(1, (out_dtypes or {}).get(s, "float32"))
                 for s in out_slots}
    main, startup, feed, fetch_names, _ = t._build()
    with _scope_guard(_Scope()):
        exe = _fluid.Executor()
        outs = exe.run(main, feed=feed,
                       fetch_list=[n for _, _, n in fetch_names])
    return {slot: np.asarray(o)
            for (slot, i, n), o in zip(fetch_names, outs)}
