"""Auto-checkpoint / preemption recovery (VERDICT r3 task 7): kill
training mid-job, restart, resume to the same final loss — the
reference mechanism is TrainEpochRange
(/root/reference/python/paddle/fluid/incubate/checkpoint/
auto_checkpoint.py:265) hooking every epoch; ours checkpoints scope
persistables through the orbax-backed sharded writer
(paddle_tpu/io/checkpoint.py)."""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "acp_worker.py")


def _run(out, ckpt_dir, preempt_at=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["PADDLE_TPU_CHECKPOINT_DIR"] = str(ckpt_dir)
    env["PADDLE_JOB_ID"] = "acp_test"
    if preempt_at is not None:
        env["PREEMPT_AT"] = str(preempt_at)
    else:
        env.pop("PREEMPT_AT", None)
    return subprocess.run([sys.executable, FIXTURE, str(out)], env=env,
                          cwd=REPO, capture_output=True, text=True,
                          timeout=300)


def _losses(path):
    out = {}
    with open(path) as f:
        for line in f:
            e, l = line.split()
            out[int(e)] = float(l)  # resumed epochs overwrite
    return out


def test_preempt_resume_matches_uninterrupted(tmp_path):
    # uninterrupted reference run
    ref_out = tmp_path / "ref.txt"
    rc = _run(ref_out, tmp_path / "ckpt_ref")
    assert rc.returncode == 0, rc.stdout + rc.stderr
    ref = _losses(ref_out)
    assert sorted(ref) == list(range(6))

    # preempted run: dies at end of epoch 2 (before that epoch's save)
    out = tmp_path / "preempted.txt"
    rc1 = _run(out, tmp_path / "ckpt", preempt_at=2)
    assert rc1.returncode == 17  # simulated preemption

    # restart: must resume after the last COMPLETE epoch and finish
    rc2 = _run(out, tmp_path / "ckpt")
    assert rc2.returncode == 0, rc2.stdout + rc2.stderr
    assert "restored_epoch: 1" in rc2.stdout  # epoch 2's save never ran
    got = _losses(out)
    assert sorted(got) == list(range(6))
    for e in range(6):
        np.testing.assert_allclose(got[e], ref[e], rtol=1e-6,
                                   err_msg=f"epoch {e} diverged")


def test_no_checkpoint_dir_is_plain_range():
    import paddle_tpu.fluid.incubate.checkpoint.auto_checkpoint as acp

    r = acp.train_epoch_range(
        4, checker=acp.AutoCheckpointChecker(ckpt_dir=None))
    assert list(r) == [0, 1, 2, 3]


def test_sharded_async_checkpoint_roundtrip(tmp_path):
    """The orbax engine: sharded jax arrays round-trip; async_save
    overlaps and wait() completes it."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.io.checkpoint import (async_save, load_state,
                                          save_state)
    from paddle_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"data": 8})
    x = jax.device_put(jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                       NamedSharding(mesh, P("data")))
    state = {"w/scope": x, "step": np.int64(7)}
    p = str(tmp_path / "ck1")
    save_state(state, p)
    back = load_state(p)
    np.testing.assert_array_equal(np.asarray(back["w/scope"]),
                                  np.asarray(x))
    assert int(back["step"]) == 7

    p2 = str(tmp_path / "ck2")
    saver = async_save({"a": jnp.ones((16,))}, p2)
    saver.wait()
    np.testing.assert_array_equal(np.asarray(load_state(p2)["a"]),
                                  np.ones((16,)))
