"""Static-analysis subsystem tests (ISSUE 3): the Program verifier
(paddle_tpu/analysis/verifier.py) and the tpulint framework
(paddle_tpu/analysis/lint/).

Positive sweep: the verifier reports zero ERROR findings over every
fixture program (tests/fixtures/programs.py) and the book-model zoo
(tests/test_book_models.py BOOK_BUILDERS).  Negative sweep: each pass
fires on a deliberately-corrupted Program — unknown op type,
use-before-def, fetch+donate conflict, collective under a conditional —
with `program#<id> block<idx> op<id> (<type>)` provenance.  Hot-path
contract: the verifier runs ONLY on a compile-cache miss
(profiler-asserted zero verifier time on cache-hit steps).  Lint side:
the shipped tree is clean under every registered rule, each rule fires
on crafted violations, suppression markers work, and the
tools/run_lints.py aggregator gates it all (this file IS its tier-1
wiring — a rule regression fails the suite here).
"""

import os
import re
import subprocess
import sys
import warnings

import numpy as np
import pytest

import paddle_tpu
import paddle_tpu.fluid as fluid
from paddle_tpu import profiler
from paddle_tpu.analysis import (ERROR, WARNING, Finding,
                                 ProgramVerificationError,
                                 registered_passes, verify_program)
from paddle_tpu.analysis.verifier import maybe_verify_program
from paddle_tpu.fluid import framework, unique_name
from paddle_tpu.fluid.executor import Scope, scope_guard

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO_ROOT, "tools")
_TESTS = os.path.dirname(os.path.abspath(__file__))
for _p in (TOOLS, _TESTS):
    if _p not in sys.path:
        sys.path.insert(0, _p)

from fixtures import programs as fixture_programs  # noqa: E402
import test_book_models as book  # noqa: E402

from tpulint import load_lint  # noqa: E402

lint = load_lint()


def _errors(findings):
    return [f for f in findings if f.severity == ERROR]


# ---------------------------------------------------------------------------
# Verifier: positive sweep over the fixture + book-model zoos
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(fixture_programs.FIXTURES))
def test_fixture_zoo_verifies_clean(name):
    main, startup, fetch = fixture_programs.FIXTURES[name]()
    for label, prog, fl in (("main", main, fetch),
                            ("startup", startup, None)):
        errs = _errors(verify_program(prog, fetch_list=fl))
        assert not errs, (name, label, errs)


@pytest.mark.parametrize("name", sorted(book.BOOK_BUILDERS))
def test_book_model_zoo_verifies_clean(name):
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup), unique_name.guard():
        fetch = book.BOOK_BUILDERS[name]()
    for label, prog, fl in (("main", main, fetch),
                            ("startup", startup, None)):
        errs = _errors(verify_program(prog, fetch_list=fl))
        assert not errs, (name, label, errs)


def test_all_passes_registered():
    names = set(registered_passes())
    assert {"op-registry", "def-before-use", "block-linkage",
            "donation-safety", "collective-order",
            "shard-consistency"} <= names
    assert {"dead-op", "write-never-read"} <= set(
        registered_passes(tier=WARNING))


# ---------------------------------------------------------------------------
# Verifier: negative sweep — each pass fires on a corrupted Program
# ---------------------------------------------------------------------------

def _simple_program():
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup), unique_name.guard():
        x = fluid.data("x", [-1, 4], "float32")
        y = fluid.layers.fc(x, 2)
    return main, startup, x, y

_PROVENANCE_RE = re.compile(r"^program#\d+ block\d+ op\d+ \([\w.]+\)")


def test_unknown_op_type_fires():
    main, _startup, _x, y = _simple_program()
    main.global_block().append_op(
        type="totally_bogus_op", inputs={"X": [y]},
        outputs={"Out": [y]}, infer_shape=False)
    errs = _errors(verify_program(main))
    assert any(f.pass_name == "op-registry" for f in errs), errs
    f = next(f for f in errs if f.pass_name == "op-registry")
    assert f.op_type == "totally_bogus_op"
    # greppable provenance: program#<id> block<idx> op<id> (<type>)
    assert _PROVENANCE_RE.match(str(f)), str(f)


def test_use_before_def_fires():
    main, _startup, _x, _y = _simple_program()
    main.global_block().ops[0].inputs.setdefault("X", []).append(
        "phantom_never_written")
    errs = _errors(verify_program(main))
    assert any(f.pass_name == "def-before-use"
               and "phantom_never_written" in f.message for f in errs), errs


def test_read_before_write_in_block_fires():
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup), unique_name.guard():
        x = fluid.data("x", [-1, 4], "float32")
        y = fluid.layers.fc(x, 2)
        z = fluid.layers.relu(y)
    blk = main.global_block()
    # move the producer of z's input after its consumer
    relu_op = blk.ops[-1]
    blk.ops.remove(relu_op)
    blk.ops.insert(0, relu_op)
    errs = _errors(verify_program(main, fetch_list=[z]))
    assert any(f.pass_name == "def-before-use"
               and "read before it is written" in f.message
               for f in errs), errs


def test_fetch_donate_conflict_fires():
    main, _startup, _x, y = _simple_program()
    errs = _errors(verify_program(main, fetch_list=[y],
                                  donated=[y.name]))
    assert any(f.pass_name == "donation-safety" and f.var == y.name
               for f in errs), errs
    # without the donation the same program is clean
    assert not _errors(verify_program(main, fetch_list=[y]))


def _conditional_collective_program():
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup), unique_name.guard():
        x = fluid.data("x", [-1, 4], "float32")
        cond = fluid.data("cond", [1], "bool")
        sub = main._create_block()
        sub.append_op(
            "c_allreduce_sum", inputs={"X": [x.name]},
            outputs={"Out": [x.name]}, attrs={"ring_id": 0},
            infer_shape=False)
        main._rollback()
        main.current_block().append_op(
            "conditional_block",
            inputs={"Cond": [cond.name], "Input": [x.name]},
            outputs={"Out": ["@EMPTY@"], "Scope": ["@EMPTY@"]},
            attrs={"sub_block": sub.idx, "is_scalar_condition": True},
            infer_shape=False)
    return main


def test_collective_under_conditional_fires():
    main = _conditional_collective_program()
    errs = _errors(verify_program(main))
    assert any(f.pass_name == "collective-order"
               and f.op_type == "c_allreduce_sum" for f in errs), errs
    # the finding points INTO the sub-block
    f = next(f for f in errs if f.pass_name == "collective-order")
    assert f.block_idx == 1


def test_p2p_send_recv_under_conditional_is_clean():
    """send_v2/recv_v2 pairs inside a conditional sub-block are a
    supported pattern (the p2p queue pairs them at lowering,
    test_distributed.py::test_send_recv_in_conditional_block) — only
    ring collectives are order-checked."""
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup), unique_name.guard():
        x = fluid.data("x", [8, 4], "float32")
        cond = fluid.data("cond", [1], "bool")
        sub = main._create_block()
        sub.append_op("send_v2", inputs={"X": [x.name]}, outputs={},
                      attrs={"ring_id": 0, "peer": 3},
                      infer_shape=False)
        sub.append_op("recv_v2", inputs={},
                      outputs={"Out": ["recv_out"]},
                      attrs={"ring_id": 0, "peer": 0,
                             "out_shape": [1, 4], "dtype": "float32"},
                      infer_shape=False)
        main._rollback()
        main.current_block().append_op(
            "conditional_block",
            inputs={"Cond": [cond.name], "Input": [x.name]},
            outputs={"Out": ["@EMPTY@"], "Scope": ["@EMPTY@"]},
            attrs={"sub_block": sub.idx, "is_scalar_condition": True},
            infer_shape=False)
    assert not [f for f in _errors(verify_program(main))
                if f.pass_name == "collective-order"]


def test_dangling_sub_block_fires():
    main, _startup, _x, y = _simple_program()
    main.global_block().append_op(
        "conditional_block", inputs={"Cond": [y.name]},
        outputs={"Out": ["@EMPTY@"]},
        attrs={"sub_block": 99}, infer_shape=False)
    errs = _errors(verify_program(main))
    assert any(f.pass_name == "block-linkage"
               and "sub_block" in f.message for f in errs), errs


def test_dead_op_warning_tier():
    main, _startup, x, y = _simple_program()
    with framework.program_guard(main):
        dead = fluid.layers.relu(y)  # never fetched, never read
    findings = verify_program(main, fetch_list=[y])
    dead_hits = [f for f in findings if f.pass_name == "dead-op"]
    assert dead_hits and all(f.severity == WARNING for f in dead_hits)
    # ERROR-tier-only invocation (what the executor runs) skips it
    assert not [f for f in verify_program(main, fetch_list=[y],
                                          tiers=(ERROR,))
                if f.pass_name == "dead-op"]


# ---------------------------------------------------------------------------
# Verifier: provenance formatting (op_callstack)
# ---------------------------------------------------------------------------

def test_op_callstack_provenance():
    paddle_tpu.set_flags({"FLAGS_op_callstack": True})
    try:
        main, startup = framework.Program(), framework.Program()
        with framework.program_guard(main, startup), \
                unique_name.guard():
            x = fluid.data("x", [-1, 4], "float32")
            y = fluid.layers.fc(x, 2)
        main.global_block().append_op(
            type="totally_bogus_op", inputs={"X": [y]},
            outputs={"Out": [y]}, infer_shape=False)  # <- reported line
    finally:
        paddle_tpu.set_flags({"FLAGS_op_callstack": False})
    errs = _errors(verify_program(main))
    f = next(f for f in errs if f.pass_name == "op-registry")
    assert f.callstack, "op_callstack not recorded on the op"
    text = str(f)
    assert "at " in text and "test_static_analysis.py" in text, text


# ---------------------------------------------------------------------------
# Executor integration: FLAGS_verify_program gate + cache-miss-only
# ---------------------------------------------------------------------------

def _run_ctx():
    main, startup = framework.Program(), framework.Program()
    scope = Scope()
    return main, startup, scope


def test_executor_raises_on_corrupt_program():
    main, startup, scope = _run_ctx()
    with framework.program_guard(main, startup), unique_name.guard(), \
            scope_guard(scope):
        x = fluid.data("x", [-1, 4], "float32")
        y = fluid.layers.fc(x, 2)
        exe = fluid.Executor()
        exe.run(startup)
        main.global_block().append_op(
            type="totally_bogus_op", inputs={"X": [y]},
            outputs={"Out": [y]}, infer_shape=False)
        with pytest.raises(ProgramVerificationError) as ei:
            exe.run(main, feed={"x": np.ones((2, 4), "float32")},
                    fetch_list=[y])
        assert "totally_bogus_op" in str(ei.value)
        assert "program#" in str(ei.value)


def test_verify_program_warn_and_off_modes():
    main, _startup, _x, y = _simple_program()
    main.global_block().append_op(
        type="totally_bogus_op", inputs={"X": [y]},
        outputs={"Out": [y]}, infer_shape=False)
    paddle_tpu.set_flags({"FLAGS_verify_program": "warn"})
    try:
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            maybe_verify_program(main)  # must NOT raise
        assert any("totally_bogus_op" in str(x.message) for x in w), \
            [str(x.message) for x in w]
        paddle_tpu.set_flags({"FLAGS_verify_program": "off"})
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            maybe_verify_program(main)
        assert not w
    finally:
        paddle_tpu.set_flags({"FLAGS_verify_program": "on"})


def test_verifier_runs_only_on_cache_miss():
    """The hot-path contract: verification happens once per compiled
    entry; cache-hit steps pay ZERO verifier time (profiler-asserted)."""
    main, startup, scope = _run_ctx()
    with framework.program_guard(main, startup), unique_name.guard(), \
            scope_guard(scope):
        x = fluid.data("x", [-1, 4], "float32")
        y = fluid.layers.fc(x, 2)
        exe = fluid.Executor()
        exe.run(startup)
        feed = {"x": np.ones((3, 4), "float32")}
        exe.run(main, feed=feed, fetch_list=[y])  # compile-cache miss

        runs0 = profiler.get_int_stats().get("verifier_runs", 0)
        ms0 = profiler.get_time_stats().get("verify_ms", 0.0)
        assert runs0 >= 1
        for _ in range(5):  # cache hits: same program/signature
            exe.run(main, feed=feed, fetch_list=[y])
        assert profiler.get_int_stats().get("verifier_runs", 0) == runs0
        assert profiler.get_time_stats().get("verify_ms", 0.0) == ms0

        # a NEW feed signature is a fresh miss -> verified again
        exe.run(main, feed={"x": np.ones((7, 4), "float32")},
                fetch_list=[y])
        assert profiler.get_int_stats().get("verifier_runs", 0) == \
            runs0 + 1


# ---------------------------------------------------------------------------
# tpulint: shipped tree is clean; every rule fires on crafted input
# ---------------------------------------------------------------------------

def test_lint_rules_registered():
    assert set(lint.registered_rules()) >= {
        "hot-path-sync", "lock-order", "untraced-side-effect"}


def test_shipped_tree_is_lint_clean():
    findings = lint.run_rules()
    assert not findings, "\n".join(str(f) for f in findings)


def test_hot_path_shim_surface():
    """tools/check_hot_path_sync.py keeps its historical CLI surface as
    a thin shim over the framework rule."""
    import check_hot_path_sync as shim

    assert shim.check_repo() == []
    assert len(shim.WATCHLIST) >= 20
    assert shim.SYNC_OK == "# sync-ok"
    # shim and framework share ONE watchlist manifest
    assert shim.WATCHLIST is lint.hot_path_sync.WATCHLIST


def test_feed_pipeline_on_hot_path_watchlist():
    """ISSUE 4: the pod-scale feed pipeline's entry points are lint-
    watched — the producer/ring feed path carries the same zero-sync
    contract as the executor dispatch loop, and
    test_shipped_tree_is_lint_clean above proves the shipped tree
    honors it."""
    watched = set(lint.hot_path_sync.WATCHLIST)
    for qual in ("FeedPipeline.__iter__", "FeedPipeline._produce",
                 "DeviceRing.put", "DeviceRing.get"):
        assert ("paddle_tpu/dataset/feed_pipeline.py", qual) in watched
    # _FeedPrefetcher (the compatibility adapter) stays watched too
    assert ("paddle_tpu/fluid/executor.py", "_FeedPrefetcher") in watched


def test_transforms_on_hot_path_watchlist():
    """ISSUE 5: the graph-transform entry points are lint-watched —
    transforms run only on the compile-cache-miss path and manipulate
    Program metadata, so they carry the zero-sync contract (no device
    array may ever flow through a pass)."""
    watched = set(lint.hot_path_sync.WATCHLIST)
    for qual in ("maybe_transform_program", "apply_transforms"):
        assert ("paddle_tpu/transforms/__init__.py", qual) in watched


def test_telemetry_on_hot_path_watchlist():
    """ISSUE 10: the live-telemetry entry points are lint-watched — the
    sampler thread, the watchdog evaluator and the HTTP handler run
    concurrently with every training/serving loop and must read
    host-side tables only; obs/telemetry.py is also in the span-leak
    watched set, and test_shipped_tree_is_lint_clean above proves the
    shipped tree honors both."""
    watched = set(lint.hot_path_sync.WATCHLIST)
    for qual in ("Collector.sample_once", "Collector._loop",
                 "Watchdog.evaluate", "Watchdog.observe",
                 "_Handler.do_GET"):
        assert ("paddle_tpu/obs/telemetry.py", qual) in watched
    assert "paddle_tpu/obs/telemetry.py" in lint.span_leak.WATCHED


def test_devprof_on_hot_path_watchlist():
    """ISSUE 12: the devprof capture path is lint-watched — the
    dispatch hook runs inside every executor.run and the window
    start/finish + xplane parse sit between profiled steps, so none of
    them may block on device sync; obs/devprof.py is also in the
    span-leak watched set (profile_window must always close its
    window, even when the capture fails)."""
    watched = set(lint.hot_path_sync.WATCHLIST)
    for qual in ("note_dispatch", "maybe_autostop",
                 "DevprofWindow.start", "DevprofWindow.finish",
                 "parse_xplane_bytes"):
        assert ("paddle_tpu/obs/devprof.py", qual) in watched
    assert "paddle_tpu/obs/devprof.py" in lint.span_leak.WATCHED


def test_quant_collectives_on_hot_path_watchlist():
    """ISSUE 16: the int8 collective codec's entry points are lint-
    watched — pack/quantize/dequantize trace INSIDE the jitted step,
    where a host sync or numpy materialization would stall every
    quantized gradient reduction; parallel/quant_collectives.py is
    also in the span-leak watched set."""
    watched = set(lint.hot_path_sync.WATCHLIST)
    for qual in ("pack", "quantize_blockwise", "dequantize_blockwise",
                 "quant_allreduce_sum"):
        assert ("paddle_tpu/parallel/quant_collectives.py",
                qual) in watched
    assert "paddle_tpu/parallel/quant_collectives.py" \
        in lint.span_leak.WATCHED


def test_memprof_on_hot_path_watchlist():
    """ISSUE 14: the memory-ledger entry points are lint-watched —
    set/add run on the dispatch/ring/ckpt hot paths, ledger_gauges on
    the telemetry sampler thread and oom_report on the dispatch
    except-path, so all of them must stay host-registry reads;
    obs/memprof.py is also in the span-leak watched set, and
    test_shipped_tree_is_lint_clean above proves the shipped tree
    honors both."""
    watched = set(lint.hot_path_sync.WATCHLIST)
    for qual in ("set_entry", "add_entry", "ledger_gauges",
                 "oom_report"):
        assert ("paddle_tpu/obs/memprof.py", qual) in watched
    assert "paddle_tpu/obs/memprof.py" in lint.span_leak.WATCHED


def test_numerics_on_hot_path_watchlist():
    """ISSUE 15: the numeric-health entry points are lint-watched —
    note_dispatch_stats/note_loss_scale run ON the dispatch hot path
    (bounded host appends of device references), drain/health_gauges
    on the telemetry sampler thread (the sanctioned LazyFetch-style
    materialization boundary), and bisect_nonfinite is offline
    forensics; obs/numerics.py is also in the span-leak watched set,
    and test_shipped_tree_is_lint_clean above proves the shipped tree
    honors both."""
    watched = set(lint.hot_path_sync.WATCHLIST)
    for qual in ("note_dispatch_stats", "note_loss_scale", "drain",
                 "health_gauges", "bisect_nonfinite"):
        assert ("paddle_tpu/obs/numerics.py", qual) in watched
    assert "paddle_tpu/obs/numerics.py" in lint.span_leak.WATCHED


def test_fleet_and_aot_cache_on_hot_path_watchlist():
    """ISSUE 17: the multi-tenant fleet's admission/dispatch entry
    points and the persistent AOT cache's load/store are lint-watched
    — registry dispatch and quota checks run on client threads racing
    the dispatch loop, and aot_cache load/store handle DEVICE
    executables on compile-miss paths; both modules are also in the
    span-leak watched set (serving/ via the directory entry,
    fluid/aot_cache.py explicitly)."""
    watched = set(lint.hot_path_sync.WATCHLIST)
    for rel, qual in (
            ("paddle_tpu/serving/batcher.py", "DynamicBatcher.submit"),
            ("paddle_tpu/serving/batcher.py",
             "DynamicBatcher._pop_best"),
            ("paddle_tpu/serving/registry.py", "ModelRegistry.submit"),
            ("paddle_tpu/serving/registry.py", "_TenantCache.put"),
            ("paddle_tpu/serving/registry.py", "_TenantCache._evicted"),
            ("paddle_tpu/fluid/aot_cache.py", "try_load"),
            ("paddle_tpu/fluid/aot_cache.py", "try_store"),
            ("paddle_tpu/fluid/aot_cache.py",
             "compile_entry_with_cache")):
        assert (rel, qual) in watched
    assert "paddle_tpu/fluid/aot_cache.py" in lint.span_leak.WATCHED
    assert "paddle_tpu/serving" in lint.span_leak.WATCHED


def test_autotune_on_hot_path_watchlist():
    """ISSUE 19: the autotuner's trial/commit entry points are lint-
    watched — trials dispatch through the real executor hot path where
    the ONLY sanctioned sync is the per-trial block_until_ready in
    tuner._sync ('# sync-ok: trial measurement boundary'), and the
    record store/load path is compile-miss disk I/O with the same
    never-touch-device contract as the AOT cache; paddle_tpu/tune is
    also in the span-leak watched set (a leaked autotune.search span
    would fold a whole search into the next profile)."""
    watched = set(lint.hot_path_sync.WATCHLIST)
    for rel, qual in (
            ("paddle_tpu/tune/tuner.py", "_sync"),
            ("paddle_tpu/tune/tuner.py", "_measure_program"),
            ("paddle_tpu/tune/tuner.py", "search_program"),
            ("paddle_tpu/tune/record.py", "try_load"),
            ("paddle_tpu/tune/record.py", "try_store")):
        assert (rel, qual) in watched
    assert "paddle_tpu/tune" in lint.span_leak.WATCHED


def test_shard_check_on_hot_path_watchlist():
    """ISSUE 18: the static sharding analyzer's entry points are
    lint-watched — shard_consistency_pass runs on the compile-cache-
    miss path inside the verifier pipeline, and run/comm_report/
    feasibility must stay pure host-side metadata walks (the analyzer
    predicts collective traffic, it must never CAUSE any);
    test_shipped_tree_is_lint_clean above proves the shipped tree
    honors it."""
    watched = set(lint.hot_path_sync.WATCHLIST)
    for qual in ("shard_consistency_pass", "_ShardChecker.run",
                 "comm_report", "feasibility"):
        assert ("paddle_tpu/analysis/shard_check.py", qual) in watched


def test_fast_decode_on_hot_path_watchlist():
    """ISSUE 20: the fast-decode entry points are lint-watched — the
    chunk scheduler (_prefill_tick) and the lazy-growth /
    extend-backpressure path (_ensure_pages, _grow_to) run every
    engine step between decode dispatches, and the ragged
    paged-attention dispatch seam traces INSIDE the decode jit;
    ops/pallas/attention.py is also in the span-leak watched set, and
    test_shipped_tree_is_lint_clean above proves the shipped tree
    honors both."""
    watched = set(lint.hot_path_sync.WATCHLIST)
    for qual in ("AutoregressiveEngine._prefill_tick",
                 "AutoregressiveEngine._ensure_pages",
                 "AutoregressiveEngine._grow_to"):
        assert ("paddle_tpu/serving/engine.py", qual) in watched
    assert ("paddle_tpu/ops/pallas/attention.py",
            "paged_attention") in watched
    assert "paddle_tpu/ops/pallas/attention.py" \
        in lint.span_leak.WATCHED


def test_hot_path_rule_fires_on_unsanctioned_sync(tmp_path):
    bad = tmp_path / "paddle_tpu" / "fluid"
    bad.mkdir(parents=True)
    (bad / "executor.py").write_text(
        "class Executor:\n"
        "    def run(self):\n"
        "        import numpy as np\n"
        "        return np.asarray(self.x)\n"
        "    def _dispatch(self):\n"
        "        return np.asarray(self.y)  # sync-ok: test boundary\n")
    msgs = lint.hot_path_sync.check_file(
        str(bad / "executor.py"), ["Executor.run", "Executor._dispatch"],
        root=str(tmp_path))
    assert len(msgs) == 1 and "Executor.run" in msgs[0], msgs


def test_hot_path_rule_flags_renamed_function(tmp_path):
    f = tmp_path / "m.py"
    f.write_text("def other():\n    pass\n")
    msgs = lint.hot_path_sync.check_file(
        str(f), ["Executor.run"], root=str(tmp_path))
    assert len(msgs) == 1 and "not found" in msgs[0], msgs


_LOCK_CYCLE_SRC = """
import threading, jax

class A:
    def __init__(self):
        self.lock_a = threading.Lock()
        self.b = B()
    def foo(self):
        with self.lock_a:
            self.b.bar()
    def put(self, x):
        with self.lock_a:
            return jax.device_put(x)

class B:
    def __init__(self):
        self.lock_b = threading.Lock()
        self.a = A()
    def bar(self):
        with self.lock_b:
            pass
    def baz(self):
        with self.lock_b:
            self.a.foo()
"""


def test_lock_order_rule_finds_cycle_and_device_work():
    findings = lint.lock_order.check_sources({"x.py": _LOCK_CYCLE_SRC})
    msgs = [f.message for f in findings]
    assert any("lock-order cycle" in m for m in msgs), msgs
    assert any("device_put while holding" in m for m in msgs), msgs


def test_lock_order_rule_finds_self_deadlock():
    src = ("import threading\n"
           "class D:\n"
           "    def __init__(self):\n"
           "        self.mu = threading.Lock()\n"
           "    def outer(self):\n"
           "        with self.mu:\n"
           "            self.inner()\n"
           "    def inner(self):\n"
           "        with self.mu:\n"
           "            pass\n")
    findings = lint.lock_order.check_sources({"z.py": src})
    assert any("re-acquires non-reentrant lock D.mu" in f.message
               for f in findings), findings


def test_lock_order_compile_lock_exempt():
    src = ("import threading, jax\n"
           "class E:\n"
           "    def __init__(self):\n"
           "        self._compile_lock = threading.Lock()\n"
           "    def build(self, x):\n"
           "        with self._compile_lock:\n"
           "            return jax.device_put(x)\n")
    assert not lint.lock_order.check_sources({"c.py": src})


_LOCAL_RECEIVER_SRC = """
import threading

class Batcher:
    def __init__(self):
        self._lock = threading.Lock()
        self.w = Worker()
    def push(self):
        with self._lock:
            w = self.w
            w.drain()

class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.b = Batcher()
    def drain(self):
        with self._lock:
            pass
    def kick(self):
        with self._lock:
            b = self.b
            b.push()
"""


def test_lock_order_resolves_plain_local_receivers():
    """`w = self.w; w.drain()` must resolve like `self.w.drain()` — the
    call-graph edge (and the cycle) survives the local alias."""
    findings = lint.lock_order.check_sources({"a.py": _LOCAL_RECEIVER_SRC})
    assert any("lock-order cycle" in f.message
               and "Batcher._lock" in f.message
               and "Worker._lock" in f.message
               for f in findings), [f.message for f in findings]


_MODULE_SINGLETON_SRC = """
import threading

class Engine:
    def __init__(self):
        self.mu = threading.Lock()
    def run(self):
        with self.mu:
            _PUMP.go()

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
    def go(self):
        lk = self._lock
        with lk:
            _ENGINE.run()

_ENGINE = Engine()
_PUMP = Pump()
"""


def test_lock_order_resolves_module_singletons_and_lock_aliases():
    """Module-level `_ENGINE = Engine()` receivers and `lk = self._lock`
    acquisitions both resolve; the cross-singleton cycle is reported."""
    findings = lint.lock_order.check_sources(
        {"b.py": _MODULE_SINGLETON_SRC})
    assert any("lock-order cycle" in f.message
               and "Engine.mu" in f.message and "Pump._lock" in f.message
               for f in findings), [f.message for f in findings]


def test_lock_order_untyped_locals_stay_unresolved():
    """A local bound from an arbitrary call has no known type: no edge
    may be invented, even when a wrong guess would close a cycle."""
    src = ("import threading\n"
           "class G:\n"
           "    def __init__(self):\n"
           "        self.mu = threading.Lock()\n"
           "    def a(self, x):\n"
           "        with self.mu:\n"
           "            h = x.get()\n"
           "            h.b()\n"
           "class H:\n"
           "    def __init__(self):\n"
           "        self.mu = threading.Lock()\n"
           "    def b(self):\n"
           "        with self.mu:\n"
           "            pass\n"
           "    def c(self, y):\n"
           "        with self.mu:\n"
           "            g = y.get()\n"
           "            g.a(None)\n")
    assert not lint.lock_order.check_sources({"c.py": src})


_SIDE_EFFECT_SRC = """
import jax

class C:
    def step(self, x):
        self.count += 1
        return x
    def go(self, x):
        return jax.jit(self.step)(x)

@jax.jit
def f(x):
    global N
    N = 1
    return x
"""


def test_side_effect_rule_fires():
    findings = lint.side_effects.check_source("y.py", _SIDE_EFFECT_SRC)
    msgs = [f.message for f in findings]
    assert any("mutates self.count" in m for m in msgs), msgs
    assert any("assigns global 'N'" in m for m in msgs), msgs


def test_side_effect_closure_box_exempt():
    # closure-cell mutation is the sanctioned trace-time side channel
    src = ("import jax\n"
           "def make():\n"
           "    box = []\n"
           "    def step(x):\n"
           "        box[:] = [1]\n"
           "        return x\n"
           "    return jax.jit(step)\n")
    assert not lint.side_effects.check_source("ok.py", src)


def test_suppression_markers():
    assert lint.suppressed("x = 1  # tpulint: disable=lock-order",
                           "lock-order")
    assert lint.suppressed("x = 1  # tpulint: disable=all", "anything")
    assert lint.suppressed("x = 1  # sync-ok: boundary", "hot-path-sync",
                           marker="# sync-ok")
    assert not lint.suppressed("x = 1  # tpulint: disable=lock-order",
                               "hot-path-sync")
    assert not lint.suppressed("x = 1", "lock-order")


# ---------------------------------------------------------------------------
# CI aggregator: tools/run_lints.py + tools/tpulint.py CLIs
# ---------------------------------------------------------------------------

def test_run_lints_aggregator_clean():
    proc = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "run_lints.py"),
         "--skip-op-coverage"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_run_lints_aggregator_fails_on_regression(tmp_path):
    # an empty tree is missing every watched hot-path file: the
    # aggregator must fail, proving a rule regression fails tier-1
    proc = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "run_lints.py"),
         "--skip-op-coverage", "--root", str(tmp_path)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "finding" in proc.stderr


def test_shapecheck_cli_selftest():
    proc = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "shapecheck.py"),
         "--selftest"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "selftest ok" in proc.stdout


def test_shapecheck_cli_dump_roundtrip(tmp_path):
    """Executor-grade verification of a Program.to_dict() dump, then
    the same dump with a planted dtype drift (exit 1 + finding)."""
    import json

    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup), unique_name.guard():
        x = fluid.data("x", [8, 4], "float32")
        y = fluid.layers.fc(x, 4)
    d = main.to_dict()
    clean = tmp_path / "prog.json"
    clean.write_text(json.dumps(d))
    proc = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "shapecheck.py"),
         str(clean), "--feed", "x", "--fetch", y.name],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr

    # plant the renamed/removed-var signature (catchable without jax:
    # dataflow corruption, not numeric rule evaluation)
    op0 = d["blocks"][0]["ops"][0]
    slot = next(iter(op0["inputs"]))
    op0["inputs"][slot] = ["ghost" for _ in op0["inputs"][slot]]
    dirty = tmp_path / "dirty.json"
    dirty.write_text(json.dumps(d))
    proc = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "shapecheck.py"),
         str(dirty), "--feed", "x", "--fetch", y.name],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "renamed or removed" in proc.stderr


def test_tpulint_cli():
    proc = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "tpulint.py"), "--list"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0
    for rule in ("hot-path-sync", "lock-order", "untraced-side-effect"):
        assert rule in proc.stdout
    proc = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "tpulint.py"),
         "--rule", "no-such-rule"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2
