"""Fast decode (ISSUE 20): ragged paged-attention Pallas kernel,
chunked prefill, lazy KV page growth, and multi-layer KV.

Tier-1, CPU-only (conftest pins JAX_PLATFORMS=cpu).  Covers the
acceptance criteria:
  (a) interpret-mode ragged-kernel parity vs the dense XLA
      `paged_attention` reference across ragged lengths / page counts,
      including length-0 and scratch-page-0 lanes,
  (b) the Mosaic-rejection path falls back to XLA with a counted
      warning (no crash),
  (c) chunked-prefill output parity vs single-shot prefill, and the
      one-chunk-per-step interleaving bound (a long prompt admitted
      mid-decode stalls in-flight decode by at most one chunk's step),
  (d) lazy-growth page-accounting invariants (allocated ==
      pages_needed(len) + slack at every step, all pages freed at
      retirement, admission reservation proportional to the prompt),
  (e) extend-backpressure pause/resume and the all-paused preemption
      escape (typed, never kills co-batched requests),
  (f) multi-layer KV parity vs stacked single-layer caches, and a
      2-layer LayeredDecoder engine vs a dense numpy reference.
"""

import warnings

import numpy as np
import pytest

from paddle_tpu import profiler, serving
from paddle_tpu.serving import EngineOverloaded, LayeredDecoder


def _stat(name):
    return profiler.get_int_stats().get(name, 0)


# ---------------------------------------------------------------------------
# ragged paged-attention kernel: interpret-mode parity vs dense XLA
# ---------------------------------------------------------------------------

def _paged_case(lengths, t=1, page_size=4, heads=2, dim=8, width=None,
                seed=0):
    """Random paged K/V layout for a batch of ragged sequences:
    each sequence owns ceil(len/S) distinct pages; unused row entries
    point at scratch page 0; the whole pool (scratch included) is
    random so masking bugs can't hide behind zeros."""
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    b = len(lengths)
    width = width or max(2, max(
        -(-max(1, ln) // page_size) for ln in lengths))
    rows = np.zeros((b, width), np.int32)
    nxt = 1
    for i, ln in enumerate(lengths):
        for j in range(-(-max(1, ln) // page_size)):
            if ln > 0:
                rows[i, j] = nxt
                nxt += 1
    pool = (nxt, page_size, heads, dim)
    q = jnp.asarray(rng.randn(b, t, heads, dim).astype(np.float32))
    kp = jnp.asarray(rng.randn(*pool).astype(np.float32))
    vp = jnp.asarray(rng.randn(*pool).astype(np.float32))
    return (q, kp, vp, jnp.asarray(rows),
            jnp.asarray(np.asarray(lengths, np.int32)))


class TestRaggedKernelParity:
    @pytest.mark.parametrize("lengths", [
        [5, 13, 0],          # ragged + a length-0 (scratch-only) lane
        [1, 16, 3],          # single-token, exact page multiple, short
        [7],                 # single sequence
        [4, 4, 4, 4],        # uniform (the degenerate rectangle)
        [0, 0],              # every lane masked
    ])
    def test_decode_parity_sweep(self, lengths):
        """T == 1 decode: the interpret-mode kernel must match the
        dense-gather XLA path to fp32 tolerance, including lanes that
        only ever touch the scratch page."""
        from paddle_tpu.ops.pallas import attention as A

        q, kp, vp, rows, lens = _paged_case(lengths)
        out_k = A.paged_attention(q, kp, vp, rows, lens,
                                  interpret=True)
        out_d = A.paged_attention(q, kp, vp, rows, lens)  # dense on CPU
        ok, od = np.asarray(out_k), np.asarray(out_d)
        assert np.all(np.isfinite(ok))
        np.testing.assert_allclose(ok, od, rtol=1e-5, atol=1e-5)

    def test_causal_tail_parity(self):
        """T > 1 with the default q_positions: the T queries sit at
        the newest T positions with causal masking between them."""
        from paddle_tpu.ops.pallas import attention as A

        q, kp, vp, rows, lens = _paged_case([9, 14], t=6, seed=1)
        out_k = A.paged_attention(q, kp, vp, rows, lens,
                                  interpret=True)
        out_d = A.paged_attention(q, kp, vp, rows, lens)
        np.testing.assert_allclose(np.asarray(out_k),
                                   np.asarray(out_d),
                                   rtol=1e-5, atol=1e-5)

    def test_chunk_positions_parity(self):
        """Explicit q_positions (the chunked-prefill form): queries at
        absolute positions offset..offset+T-1 against lengths
        offset+T, exactly what the engine's chunk entry passes."""
        import jax.numpy as jnp

        from paddle_tpu.ops.pallas import attention as A

        off, t = 8, 4
        q, kp, vp, rows, lens = _paged_case([off + t], t=t, seed=2)
        qpos = (off + jnp.arange(t, dtype=jnp.int32))[None, :]
        out_k = A.paged_attention(q, kp, vp, rows, lens,
                                  q_positions=qpos, interpret=True)
        out_d = A.paged_attention(q, kp, vp, rows, lens,
                                  q_positions=qpos)
        np.testing.assert_allclose(np.asarray(out_k),
                                   np.asarray(out_d),
                                   rtol=1e-5, atol=1e-5)


class TestRaggedFallback:
    def test_mosaic_rejection_falls_back_with_counted_warning(
            self, monkeypatch):
        """On a 'TPU' whose Mosaic rejects the kernel (here: the CPU
        backend, which cannot compile a non-interpret pallas_call),
        dispatch must warn ONCE per shape, count the fallback in
        serving_ragged_fallback_total, and return the dense result —
        never crash."""
        from paddle_tpu.ops.pallas import attention as A

        q, kp, vp, rows, lens = _paged_case([5, 9], seed=3)
        ref = np.asarray(A.paged_attention(q, kp, vp, rows, lens))

        monkeypatch.setattr(A, "on_tpu", lambda: True)
        A._RAGGED_PROBE_CACHE.clear()
        before = _stat("serving_ragged_fallback_total")
        try:
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                out = np.asarray(
                    A.paged_attention(q, kp, vp, rows, lens))
                # second call hits the cached probe verdict: no new
                # probe, no second warning, no double count
                out2 = np.asarray(
                    A.paged_attention(q, kp, vp, rows, lens))
        finally:
            A._RAGGED_PROBE_CACHE.clear()
        assert _stat("serving_ragged_fallback_total") == before + 1
        msgs = [str(w.message) for w in caught
                if "ragged paged-attention" in str(w.message)]
        assert len(msgs) == 1, msgs
        assert "falls back" in msgs[0]
        np.testing.assert_array_equal(out, ref)
        np.testing.assert_array_equal(out2, ref)


# ---------------------------------------------------------------------------
# toy decoders with closed-form numpy references
# ---------------------------------------------------------------------------

def _toy_lm():
    """Single-layer toy LM (the test_serving classic): embedding is
    Q=K=V, one output projection; greedy decode has a dense numpy
    reference."""
    import jax.numpy as jnp

    V, D = 13, 4
    rng = np.random.RandomState(3)
    embn = rng.randn(V, D).astype(np.float32)
    wn = rng.randn(D, V).astype(np.float32)
    emb, w = jnp.asarray(embn), jnp.asarray(wn)

    def qkv_fn(tokens, positions):
        x = emb[tokens]
        q = x[:, :, None, :]
        return q, q, q

    def out_fn(attn):
        return attn[:, :, 0, :] @ w

    def ref(prompt, n):
        seq = list(prompt)
        out = []
        for _ in range(n):
            x = embn[np.array(seq)]
            L = len(seq)
            s = x @ x.T / np.sqrt(D)
            s[np.triu(np.ones((L, L), bool), 1)] = -1e30
            e = np.exp(s - s.max(axis=1, keepdims=True))
            p = e / e.sum(axis=1, keepdims=True)
            logits = (p @ x)[-1] @ wn
            out.append(int(np.argmax(logits)))
            seq.append(out[-1])
        return out

    return qkv_fn, out_fn, ref, D


def _toy_transformer(num_layers=2):
    """N-layer toy transformer for the LayeredDecoder contract:
    per-layer projection W_i gives Q=K=V=x@W_i, residual merge,
    shared unembedding — with a dense numpy greedy reference."""
    import jax.numpy as jnp

    V, D = 11, 4
    rng = np.random.RandomState(9)
    embn = rng.randn(V, D).astype(np.float32)
    wsn = [rng.randn(D, D).astype(np.float32)
           for _ in range(num_layers)]
    woutn = rng.randn(D, V).astype(np.float32)
    emb = jnp.asarray(embn)
    ws = [jnp.asarray(w) for w in wsn]
    wout = jnp.asarray(woutn)

    def make_layer(w):
        def qkv(x, positions):
            h = x @ w
            hh = h[:, :, None, :]
            return hh, hh, hh

        def merge(x, attn):
            return x + attn[:, :, 0, :]

        return (qkv, merge)

    model = LayeredDecoder(
        embed=lambda tokens, positions: emb[tokens],
        layers=[make_layer(w) for w in ws],
        unembed=lambda x: x @ wout)

    def ref(prompt, n):
        seq = list(prompt)
        out = []
        for _ in range(n):
            x = embn[np.array(seq)]
            L = len(seq)
            mask = np.triu(np.ones((L, L), bool), 1)
            for wn_ in wsn:
                h = x @ wn_
                s = h @ h.T / np.sqrt(D)
                s[mask] = -1e30
                e = np.exp(s - s.max(axis=1, keepdims=True))
                p = e / e.sum(axis=1, keepdims=True)
                x = x + p @ h
            logits = x[-1] @ woutn
            out.append(int(np.argmax(logits)))
            seq.append(out[-1])
        return out

    return model, ref


# ---------------------------------------------------------------------------
# chunked prefill
# ---------------------------------------------------------------------------

class TestChunkedPrefill:
    def test_chunked_matches_single_shot_and_reference(self):
        """The same prompt through 3 chunks of 4 and through one
        single-shot prefill must produce identical greedy tokens (and
        both must match the dense numpy reference)."""
        qkv_fn, out_fn, ref, D = _toy_lm()
        prompt = np.array([1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11])
        kw = dict(num_heads=1, head_dim=D, num_pages=64, page_size=4,
                  max_slots=2, max_pages_per_seq=8)
        chunked = serving.AutoregressiveEngine(
            qkv_fn, out_fn, prompt_buckets=(4, 16), prefill_chunk=4,
            **kw)
        single = serving.AutoregressiveEngine(
            qkv_fn, out_fn, prompt_buckets=(16,), prefill_chunk=16,
            **kw)
        c0 = _stat("serving_prefill_chunks")
        toks_c = chunked.generate(prompt, max_new_tokens=6)
        assert _stat("serving_prefill_chunks") - c0 == 3
        toks_s = single.generate(prompt, max_new_tokens=6)
        expect = ref(list(prompt), 6)
        assert list(map(int, toks_c)) == expect
        assert list(map(int, toks_s)) == expect

    def test_long_prompt_interleaves_with_decode(self):
        """The one-chunk-per-step bound: while a long prompt prefills
        chunk by chunk, the co-resident decode slot advances one token
        EVERY step — the long prompt never stalls in-flight decode by
        more than one chunk's step time (the scripted step() loop is
        the batcher clock)."""
        qkv_fn, out_fn, ref, D = _toy_lm()
        eng = serving.AutoregressiveEngine(
            qkv_fn, out_fn, num_heads=1, head_dim=D, num_pages=64,
            page_size=4, max_slots=2, max_pages_per_seq=16,
            prompt_buckets=(4, 16), prefill_chunk=4)
        short = eng.submit(np.array([1, 2, 3]), max_new_tokens=32)
        eng.step()  # admit + prefill + first decode for the short one
        assert eng._slot_gen[0] >= 1
        # 12-token prompt -> 3 chunks; admitted mid-decode
        c0 = _stat("serving_prefill_chunks")
        long_req = eng.submit(np.arange(12) % 13, max_new_tokens=4)
        prefill_steps = 0
        for _ in range(10):
            d0 = _stat("serving_decode_steps")
            g0 = eng._slot_gen[0]
            eng.step()
            # every step during the long prefill still ran ONE decode
            # for the in-flight short request — the stall bound
            assert _stat("serving_decode_steps") == d0 + 1
            assert eng._slot_gen[0] == g0 + 1
            if any(j.req is long_req
                   for j in eng._prefilling.values()):
                prefill_steps += 1
            else:
                break
        # chunk 1 landed on the admit step, chunks 2-3 on the two
        # observed-prefilling steps: one chunk per step, never more
        assert prefill_steps == 2
        assert _stat("serving_prefill_chunks") - c0 == 3
        eng.run_until_idle()
        assert list(map(int, long_req.result(timeout=60))) \
            == ref(list(np.arange(12) % 13), 4)
        assert list(map(int, short.result(timeout=60))) \
            == ref([1, 2, 3], 32)


# ---------------------------------------------------------------------------
# lazy KV page growth
# ---------------------------------------------------------------------------

class TestLazyGrowth:
    def test_admission_reservation_proportional_to_prompt(self):
        """Admission reserves pages_needed(prompt) + slack — NOT the
        worst-case prompt + max_new_tokens (the acceptance criterion:
        serving_kv_pages_in_use after admitting a short prompt with an
        honest max_seq is proportional to the prompt)."""
        qkv_fn, out_fn, ref, D = _toy_lm()
        eng = serving.AutoregressiveEngine(
            qkv_fn, out_fn, num_heads=1, head_dim=D, num_pages=64,
            page_size=4, max_slots=2, max_pages_per_seq=16,
            prompt_buckets=(8,), page_slack=1)
        table = eng.kv.table
        req = eng.submit(np.array([1, 2, 3, 4, 5]),
                         max_new_tokens=32)  # honest max: 10 pages
        eng.step()
        owned = len(table.pages_of(id(req)))
        assert owned == table.pages_needed(5) + 1  # 2 + slack
        assert owned < table.pages_needed(5 + 32 - 1)
        assert _stat("serving_kv_pages_in_use") == owned
        eng.run_until_idle()
        req.result(timeout=60)

    def test_growth_invariant_every_step_and_freed_at_retirement(self):
        """At every engine step each decoding slot owns exactly
        min(pages_needed(len) + slack, max_pages_per_seq) pages (pool
        permitting), and retirement returns every page."""
        qkv_fn, out_fn, ref, D = _toy_lm()
        eng = serving.AutoregressiveEngine(
            qkv_fn, out_fn, num_heads=1, head_dim=D, num_pages=64,
            page_size=4, max_slots=2, max_pages_per_seq=16,
            prompt_buckets=(8,), page_slack=1)
        table = eng.kv.table
        req = eng.submit(np.array([1, 2, 3, 4, 5]), max_new_tokens=12)
        grew = set()
        while not req.done():
            eng.step()
            for i, r in enumerate(eng._slots):
                if r is None or i in eng._prefilling:
                    continue
                owned = len(table.pages_of(id(r)))
                expect = min(table.pages_needed(eng._slot_len[i])
                             + eng.page_slack, eng.max_pages_per_seq)
                assert owned == expect, \
                    (owned, expect, eng._slot_len[i])
                grew.add(owned)
        assert len(grew) > 1, "sequence never grew a page"
        assert table.in_use == 0
        assert _stat("serving_kv_pages_in_use") == 0
        assert _stat("serving_kv_pages_capacity") == table.capacity
        req.result(timeout=60)

    def test_backpressure_pauses_slot_then_completes(self):
        """Pool exhaustion mid-decode pauses the starved slot (typed
        backpressure, counted) while the co-batched slot keeps
        decoding; when the neighbour retires and frees pages the
        paused slot resumes and produces the SAME tokens as an
        unconstrained run."""
        qkv_fn, out_fn, ref, D = _toy_lm()
        # capacity 7 data pages (page 0 is scratch): B's final length
        # (4 + 10 tokens at page_size 2) needs exactly 7 pages, so it
        # CAN finish once A retires — but while A still holds its
        # pages the combined demand overshoots and B must pause
        eng = serving.AutoregressiveEngine(
            qkv_fn, out_fn, num_heads=1, head_dim=D, num_pages=8,
            page_size=2, max_slots=2, max_pages_per_seq=8,
            prompt_buckets=(4,), page_slack=1)
        p0 = _stat("serving_kv_paused_total")
        b0 = _stat("serving_kv_backpressure_total")
        k0 = _stat("serving_kv_preempt_total")
        a = eng.submit(np.array([1, 2, 3, 4]), max_new_tokens=5)
        b = eng.submit(np.array([5, 6, 7, 8]), max_new_tokens=10)
        eng.run_until_idle()
        assert _stat("serving_kv_backpressure_total") > b0
        assert _stat("serving_kv_paused_total") > p0
        # a pause is a stall, not a failure: nobody was preempted and
        # both requests completed in full
        assert _stat("serving_kv_preempt_total") == k0
        assert list(map(int, a.result(timeout=60))) \
            == ref([1, 2, 3, 4], 5)
        assert list(map(int, b.result(timeout=60))) \
            == ref([5, 6, 7, 8], 10)
        assert eng.kv.table.in_use == 0

    def test_all_paused_preemption_escape(self):
        """When EVERY decoding slot is paused and zero pages are free,
        the engine preempts (early-retires, truncated-success) the
        longest generation instead of livelocking — no request ever
        fails with an exception."""
        qkv_fn, out_fn, ref, D = _toy_lm()
        eng = serving.AutoregressiveEngine(
            qkv_fn, out_fn, num_heads=1, head_dim=D, num_pages=6,
            page_size=2, max_slots=2, max_pages_per_seq=8,
            prompt_buckets=(4,), page_slack=1)
        k0 = _stat("serving_kv_preempt_total")
        a = eng.submit(np.array([1, 2, 3, 4]), max_new_tokens=8)
        b = eng.submit(np.array([5, 6, 7, 8]), max_new_tokens=8)
        eng.run_until_idle()
        assert _stat("serving_kv_preempt_total") > k0
        ta = a.result(timeout=60)
        tb = b.result(timeout=60)
        # truncated but successful: a non-empty prefix of the
        # unconstrained greedy decode
        for toks, prompt in ((ta, [1, 2, 3, 4]), (tb, [5, 6, 7, 8])):
            assert 1 <= len(toks) <= 8
            assert list(map(int, toks)) \
                == ref(prompt, 8)[:len(toks)]
        assert eng.kv.table.in_use == 0


# ---------------------------------------------------------------------------
# multi-layer KV
# ---------------------------------------------------------------------------

class TestMultiLayerKV:
    def test_layered_pool_matches_stacked_single_layer_caches(self):
        """write_prefill on an (L, P, S, H, D) pool scatters each
        layer exactly like L independent single-layer pools given the
        same page row — including chunked writes at an offset."""
        import jax.numpy as jnp

        from paddle_tpu.serving.kv_cache import (PagedKVCache,
                                                 write_prefill)

        L, P, S, H, D = 2, 8, 4, 1, 4
        rng = np.random.RandomState(5)
        multi = PagedKVCache(P, S, H, D, num_layers=L)
        singles = [PagedKVCache(P, S, H, D) for _ in range(L)]
        assert multi.k.shape == (L, P, S, H, D)

        rows = jnp.asarray(np.array([3, 5, 0, 0], np.int32))
        for start, ln in ((0, 6), (6, 3)):  # chunk 1, then chunk 2
            k = rng.randn(L, 6, H, D).astype(np.float32)
            v = rng.randn(L, 6, H, D).astype(np.float32)
            mk, mv = write_prefill(multi.k, multi.v, rows, ln,
                                   jnp.asarray(k), jnp.asarray(v),
                                   start=start)
            multi.k, multi.v = mk, mv
            for li, c in enumerate(singles):
                ck, cv = write_prefill(c.k, c.v, rows, ln,
                                       jnp.asarray(k[li]),
                                       jnp.asarray(v[li]),
                                       start=start)
                c.k, c.v = ck, cv
        for li, c in enumerate(singles):
            np.testing.assert_array_equal(np.asarray(multi.k[li]),
                                          np.asarray(c.k))
            np.testing.assert_array_equal(np.asarray(multi.v[li]),
                                          np.asarray(c.v))

    def test_layered_pool_is_one_allocation(self):
        """One PageTable, one ledger entry: a page id covers all
        layers, and bytes_per_page counts every layer's plane."""
        from paddle_tpu.serving.kv_cache import PagedKVCache

        one = PagedKVCache(8, 4, 1, 4)
        two = PagedKVCache(8, 4, 1, 4, num_layers=2)
        assert two.table.bytes_per_page \
            == 2 * one.table.bytes_per_page
        with pytest.raises(ValueError):
            PagedKVCache(8, 4, 1, 4, num_layers=0)

    def test_two_layer_engine_matches_reference_single_shot(self):
        model, ref = _toy_transformer(num_layers=2)
        eng = serving.AutoregressiveEngine(
            model=model, num_heads=1, head_dim=4, num_pages=32,
            page_size=4, max_slots=2, max_pages_per_seq=8,
            prompt_buckets=(8,))
        assert eng.kv.num_layers == 2
        toks = eng.generate(np.array([1, 2, 3, 4, 5]),
                            max_new_tokens=6)
        assert list(map(int, toks)) == ref([1, 2, 3, 4, 5], 6)

    def test_two_layer_engine_matches_reference_chunked(self):
        """An N-layer decoder through CHUNKED prefill: every chunk
        runs all layers against the shared multi-layer pool in one
        fused step."""
        model, ref = _toy_transformer(num_layers=2)
        eng = serving.AutoregressiveEngine(
            model=model, num_heads=1, head_dim=4, num_pages=32,
            page_size=4, max_slots=2, max_pages_per_seq=8,
            prompt_buckets=(4, 16), prefill_chunk=4)
        prompt = np.arange(10) % 11
        c0 = _stat("serving_prefill_chunks")
        toks = eng.generate(prompt, max_new_tokens=5)
        assert _stat("serving_prefill_chunks") - c0 == 3
        assert list(map(int, toks)) == ref(list(prompt), 5)


# ---------------------------------------------------------------------------
# zero-transfer contract through the new paths
# ---------------------------------------------------------------------------

class TestZeroTransferContract:
    def test_chunked_lazy_decode_zero_d2h_per_token(self):
        """The PR-2 contract survives chunked prefill + lazy growth:
        the whole generate (chunked prefill, page extends, decode
        flood) performs exactly ONE sanctioned materialization, at the
        response boundary."""
        qkv_fn, out_fn, ref, D = _toy_lm()
        eng = serving.AutoregressiveEngine(
            qkv_fn, out_fn, num_heads=1, head_dim=D, num_pages=64,
            page_size=4, max_slots=2, max_pages_per_seq=16,
            prompt_buckets=(4, 16), prefill_chunk=4)
        # warm every compiled entry off the measured window
        eng.generate(np.arange(12) % 13, max_new_tokens=4)
        profiler.stat_reset("executor_sync_count")
        toks = eng.generate(np.arange(12) % 13, max_new_tokens=8)
        assert len(toks) == 8
        assert _stat("executor_sync_count") == 1
