"""Tests for paddle.amp (auto_cast + GradScaler), paddle.save/load,
paddle.metric, and the hapi Model trainer."""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.fluid.dygraph import guard, to_variable


@pytest.fixture(autouse=True)
def dygraph():
    with guard():
        yield


class TestAutoCast:
    def test_white_list_casts(self):
        x = to_variable(np.random.rand(4, 8).astype("float32"))
        w = to_variable(np.random.rand(8, 4).astype("float32"))
        with paddle.amp.auto_cast():
            y = paddle.matmul(x, w)
            z = paddle.exp(x)  # black list: stays f32
        assert y.dtype == "bfloat16"
        assert z.dtype == "float32"
        assert paddle.matmul(x, w).dtype == "float32"

    def test_custom_lists(self):
        x = to_variable(np.random.rand(4, 4).astype("float32"))
        with paddle.amp.auto_cast(custom_white_list={"exp"},
                                  custom_black_list={"matmul_v2"}):
            assert paddle.exp(x).dtype == "bfloat16"
            assert paddle.matmul(x, x).dtype == "float32"

    def test_o2_casts_everything_but_blacklist(self):
        x = to_variable(np.random.rand(4, 4).astype("float32"))
        with paddle.amp.auto_cast(level="O2"):
            assert (x + x).dtype == "bfloat16"
            assert paddle.nn.functional.softmax(x).dtype == "float32"

    def test_grad_flows_back_f32(self):
        lin = paddle.nn.Linear(8, 4)
        x = to_variable(np.random.rand(2, 8).astype("float32"))
        with paddle.amp.auto_cast():
            y = lin(x)
        y.astype("float32").mean().backward()
        g = lin.weight.grad
        assert g is not None and g.dtype == "float32"


class TestGradScaler:
    def test_scale_and_good_step(self):
        sc = paddle.amp.GradScaler(init_loss_scaling=256.0)
        lin = paddle.nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        x = to_variable(np.random.rand(4, 4).astype("float32"))
        w0 = lin.weight.numpy().copy()
        loss = lin(x).mean()
        sc.scale(loss).backward()
        sc.step(opt)
        assert not np.allclose(lin.weight.numpy(), w0)  # applied

    def test_inf_skips_step_and_decays_scale(self):
        import jax.numpy as jnp

        sc = paddle.amp.GradScaler(init_loss_scaling=1024.0,
                                   decr_every_n_nan_or_inf=1)
        lin = paddle.nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=lin.parameters())
        w0 = lin.weight.numpy().copy()
        lin.weight._grad = jnp.full((4, 1), np.inf, dtype=jnp.float32)
        lin.bias._grad = jnp.zeros((1,), jnp.float32)
        sc.step(opt)
        np.testing.assert_allclose(lin.weight.numpy(), w0)  # skipped
        assert sc.get_loss_scaling() == 512.0

    def test_scale_growth(self):
        sc = paddle.amp.GradScaler(init_loss_scaling=2.0,
                                   incr_every_n_steps=2)
        lin = paddle.nn.Linear(2, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.0,
                                   parameters=lin.parameters())
        import jax.numpy as jnp

        for _ in range(2):
            lin.weight._grad = jnp.ones((2, 1), jnp.float32)
            sc.step(opt)
        assert sc.get_loss_scaling() == 4.0

    def test_state_dict(self):
        sc = paddle.amp.GradScaler(init_loss_scaling=128.0)
        sd = sc.state_dict()
        sc2 = paddle.amp.GradScaler()
        sc2.set_state_dict(sd)
        assert sc2.get_loss_scaling() == 128.0


class TestSaveLoad:
    def test_state_dict_roundtrip(self, tmp_path):
        net = paddle.nn.Sequential(paddle.nn.Linear(4, 8),
                                   paddle.nn.BatchNorm1D(8))
        p = str(tmp_path / "m.pdparams")
        paddle.save(net.state_dict(), p)
        loaded = paddle.load(p)
        net2 = paddle.nn.Sequential(paddle.nn.Linear(4, 8),
                                    paddle.nn.BatchNorm1D(8))
        missing, unexpected = net2.set_state_dict(loaded)
        assert not missing and not unexpected
        np.testing.assert_allclose(net2[0].weight.numpy(),
                                   net[0].weight.numpy())

    def test_nested_object(self, tmp_path):
        p = str(tmp_path / "obj.pd")
        obj = {"step": 7, "arrs": [np.arange(3), {"w": np.eye(2)}]}
        paddle.save(obj, p)
        back = paddle.load(p)
        assert back["step"] == 7
        np.testing.assert_allclose(back["arrs"][1]["w"], np.eye(2))


class TestMetrics:
    def test_accuracy_topk(self):
        m = paddle.metric.Accuracy(topk=(1, 2))
        pred = np.array([[0.1, 0.9, 0.0], [0.5, 0.1, 0.4],
                         [0.2, 0.3, 0.5]])
        label = np.array([[1], [2], [2]])
        m.update(m.compute(pred, label))
        top1, top2 = m.accumulate()
        assert abs(top1 - 2 / 3) < 1e-6
        assert abs(top2 - 3 / 3) < 1e-6

    def test_precision_recall(self):
        p = paddle.metric.Precision()
        r = paddle.metric.Recall()
        preds = np.array([0.9, 0.8, 0.2, 0.7])
        labels = np.array([1, 0, 1, 1])
        p.update(preds, labels)
        r.update(preds, labels)
        assert abs(p.accumulate() - 2 / 3) < 1e-6
        assert abs(r.accumulate() - 2 / 3) < 1e-6

    def test_auc_perfect_and_random(self):
        auc = paddle.metric.Auc()
        auc.update(np.array([0.9, 0.8, 0.1, 0.2]), np.array([1, 1, 0, 0]))
        assert auc.accumulate() > 0.99
        auc.reset()
        auc.update(np.array([0.5, 0.5, 0.5, 0.5]), np.array([1, 0, 1, 0]))
        assert abs(auc.accumulate() - 0.5) < 0.01


class _RegData(paddle.io.Dataset):
    def __init__(self, n=64):
        rng = np.random.RandomState(0)
        self.x = rng.rand(n, 8).astype("float32")
        self.y = (self.x @ rng.rand(8, 1)).astype("float32")

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def _reg_model():
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 16),
                               paddle.nn.ReLU(),
                               paddle.nn.Linear(16, 1))
    m = paddle.Model(net)
    m.prepare(optimizer=paddle.optimizer.Adam(
        learning_rate=0.01, parameters=net.parameters()),
        loss=paddle.nn.MSELoss())
    return m


class TestHapiModel:
    def _model(self):
        return _reg_model()

    def test_fit_reduces_loss(self):
        m = self._model()
        hist = m.fit(_RegData(), batch_size=16, epochs=4, verbose=0)
        assert hist[-1]["loss"] < hist[0]["loss"]

    def test_evaluate_and_predict(self):
        m = self._model()
        m.fit(_RegData(), batch_size=16, epochs=2, verbose=0)
        logs = m.evaluate(_RegData(), batch_size=32, verbose=0)
        assert "loss" in logs
        preds = m.predict(_RegData(), batch_size=32, stack_outputs=True)
        assert preds[0].shape == (64, 1)

    def test_save_load(self, tmp_path):
        m = self._model()
        m.fit(_RegData(), batch_size=32, epochs=1, verbose=0)
        path = str(tmp_path / "ckpt")
        m.save(path)
        m2 = self._model()
        m2.load(path)
        np.testing.assert_allclose(
            m2.network[0].weight.numpy(), m.network[0].weight.numpy())

    def test_early_stopping(self):
        from paddle_tpu.hapi.callbacks import EarlyStopping

        m = self._model()
        es = EarlyStopping(monitor="loss", patience=0, mode="min",
                           baseline=0.0)  # nothing beats 0 -> stop asap
        hist = m.fit(_RegData(), eval_data=_RegData(), batch_size=32,
                     epochs=5, verbose=0, callbacks=[es])
        assert len(hist) < 5

    def test_classification_with_metric(self):
        class Cls(paddle.io.Dataset):
            def __init__(self):
                rng = np.random.RandomState(0)
                self.x = rng.rand(64, 4).astype("float32")
                self.y = (self.x.sum(-1) > 2).astype("int64")[:, None]

            def __len__(self):
                return 64

            def __getitem__(self, i):
                return self.x[i], self.y[i]

        net = paddle.nn.Linear(4, 2)
        m = paddle.Model(net)
        m.prepare(optimizer=paddle.optimizer.Adam(
            learning_rate=0.05, parameters=net.parameters()),
            loss=paddle.nn.CrossEntropyLoss(),
            metrics=paddle.metric.Accuracy())
        hist = m.fit(Cls(), batch_size=16, epochs=5, verbose=0)
        assert hist[-1]["acc"] > 0.6


class TestHapiStaticAdapter:
    """VERDICT r3 next #9: the static (whole-step-compiled) adapter
    trains MNIST-style data to the same loss as the dygraph adapter,
    and amp_configs are honored rather than stored."""

    def _mnist_bits(self):
        rng = np.random.RandomState(7)
        x = rng.rand(128, 1, 28, 28).astype("float32")
        y = rng.randint(0, 10, (128, 1)).astype("int64")
        return x, y

    def _lenet_model(self, seed):
        paddle.seed(seed)
        from paddle_tpu.vision.models import LeNet
        net = LeNet()
        m = paddle.Model(net)
        m.prepare(
            optimizer=paddle.optimizer.Adam(learning_rate=0.003,
                                            parameters=net.parameters()),
            loss=paddle.nn.CrossEntropyLoss())
        return m

    def _run_epochs(self, m, x, y, batch=32, epochs=2):
        losses = []
        for _ in range(epochs):
            for i in range(0, len(x), batch):
                (l,), _ = m.train_batch([x[i:i + batch]],
                                        [y[i:i + batch]])
                losses.append(l)
        return losses

    def test_static_matches_dygraph_loss(self):
        x, y = self._mnist_bits()

        paddle.disable_static()
        m_dy = self._lenet_model(0)
        assert m_dy._adapter is None
        dy_losses = self._run_epochs(m_dy, x, y, epochs=4)

        paddle.enable_static()
        try:
            m_st = self._lenet_model(0)
            assert m_st._adapter is not None
            st_losses = self._run_epochs(m_st, x, y, epochs=4)
        finally:
            paddle.disable_static()

        # identical seeds + data: trajectories agree to float tolerance
        np.testing.assert_allclose(st_losses, dy_losses, rtol=2e-2,
                                   atol=2e-2)
        # and the step actually optimizes (16 steps of memorizing 128
        # random labels: expect a clear dip, not convergence)
        assert st_losses[-1] < st_losses[0] * 0.97

    def test_static_eval_and_predict(self):
        x, y = self._mnist_bits()
        paddle.enable_static()
        try:
            m = self._lenet_model(1)
            self._run_epochs(m, x, y, epochs=1)
            lv, _ = m.eval_batch([x[:16]], [y[:16]])
            assert np.isfinite(lv[0])
            (probs,) = m.predict_batch([x[:4]])
            assert probs.shape == (4, 10)
        finally:
            paddle.disable_static()

    def test_static_amp_trains(self):
        x, y = self._mnist_bits()
        paddle.enable_static()
        try:
            paddle.seed(2)
            from paddle_tpu.vision.models import LeNet
            net = LeNet()
            m = paddle.Model(net)
            m.prepare(
                optimizer=paddle.optimizer.Adam(
                    learning_rate=0.003, parameters=net.parameters()),
                loss=paddle.nn.CrossEntropyLoss(),
                amp_configs={"level": "O1",
                             "init_loss_scaling": 1024.0})
            losses = self._run_epochs(m, x, y, epochs=4)
        finally:
            paddle.disable_static()
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.97

    def test_dygraph_amp_configs_used(self):
        x, y = self._mnist_bits()
        paddle.disable_static()
        paddle.seed(3)
        from paddle_tpu.vision.models import LeNet
        net = LeNet()
        m = paddle.Model(net)
        m.prepare(
            optimizer=paddle.optimizer.Adam(
                learning_rate=0.003, parameters=net.parameters()),
            loss=paddle.nn.CrossEntropyLoss(),
            amp_configs={"level": "O1"})
        losses = self._run_epochs(m, x, y, epochs=1)
        assert hasattr(m, "_scaler")  # the GradScaler actually engaged
        assert np.isfinite(losses).all()


class TestHapiProcessWorkers:
    def test_fit_with_process_worker_loader(self):
        """hapi Model.fit over the multiprocess DataLoader (fork workers
        forked AFTER jax initialized — safe because the dataset is pure
        numpy; the fit loop consumes the pumped native queue)."""
        m = _reg_model()
        loader = paddle.io.DataLoader(_RegData(), batch_size=16,
                                      num_workers=2, timeout=60,
                                      use_process_workers=True)
        hist = m.fit(loader, epochs=4, verbose=0)
        assert hist[-1]["loss"] < hist[0]["loss"]
