"""Deliberately mis-sharded programs (ISSUE 18 fault-injection
harness).

Each case below re-creates a real layout-bug class the
shard-consistency verifier pass (analysis/shard_check.py) exists to
catch, as a plain `Program.to_dict()`-shaped dict (the same currency
tools/shardcheck.py consumes, so every case also works jax-free):

* `axis_reused_in_override` — a `register_spec` override naming one
  mesh axis on two dims of the same tensor (a spec no mesh can carry);
* `nondividing_after_reshape` — a weight whose pattern-rule shard is
  legal at declaration but stops dividing after a reshape carries it
  onto a smaller dim;
* `collective_on_absent_axis` — an explicit c_allreduce_sum whose ring
  resolves to an axis the active mesh does not have (the classic
  "works on the 2-D mesh, crashes on the tp-only mesh" bug);
* `oversized_replicated_weight` — a multi-MB parameter that every
  device holds in full because nothing shards it (WARNING tier: legal,
  but the ZeRO memory win silently evaporated).

Tests iterate BROKEN_SHARDINGS; each entry carries the mesh to analyze
under, any spec_layout overrides to register first, and the
severity + message substring the analyzer must report with
`program#<id> block<idx> op<id>` provenance.
"""


def _axis_reuse():
    return {
        "blocks": [{
            "idx": 0, "parent_idx": -1,
            "vars": [
                {"name": "x", "shape": [8, 16], "dtype": "float32",
                 "is_data": True},
                {"name": "dup_0.w_0", "shape": [16, 32],
                 "dtype": "float32", "persistable": True},
                {"name": "y", "shape": [8, 32], "dtype": "float32"},
            ],
            "ops": [{
                "id": 1, "type": "mul",
                "inputs": {"X": ["x"], "Y": ["dup_0.w_0"]},
                "outputs": {"Out": ["y"]}, "attrs": {},
            }],
        }],
    }


def _nondividing_after_reshape():
    return {
        "blocks": [{
            "idx": 0, "parent_idx": -1,
            "vars": [
                {"name": "fc_9.w_0", "shape": [6, 4],
                 "dtype": "float32", "persistable": True},
                {"name": "w2", "shape": [3, 8], "dtype": "float32"},
            ],
            "ops": [{
                "id": 1, "type": "reshape2",
                "inputs": {"X": ["fc_9.w_0"]},
                "outputs": {"Out": ["w2"]},
                "attrs": {"shape": [3, 8]},
            }],
        }],
    }


def _collective_on_absent_axis():
    return {
        "blocks": [{
            "idx": 0, "parent_idx": -1,
            "vars": [
                {"name": "g", "shape": [8, 4], "dtype": "float32",
                 "is_data": True},
                {"name": "g_sum", "shape": [8, 4],
                 "dtype": "float32"},
            ],
            "ops": [{
                "id": 1, "type": "c_allreduce_sum",
                "inputs": {"X": ["g"]}, "outputs": {"Out": ["g_sum"]},
                "attrs": {"ring_id": 0},
            }],
        }],
    }


def _oversized_replicated_weight():
    # (1024, 512) float32 = 2 MiB, over the 1 MiB default floor; on a
    # pure data mesh nothing shards it, so all 8 devices hold a copy
    return {
        "blocks": [{
            "idx": 0, "parent_idx": -1,
            "vars": [
                {"name": "x", "shape": [8, 1024], "dtype": "float32",
                 "is_data": True},
                {"name": "fc_big.w_0", "shape": [1024, 512],
                 "dtype": "float32", "persistable": True},
                {"name": "y", "shape": [8, 512], "dtype": "float32"},
            ],
            "ops": [{
                "id": 1, "type": "mul",
                "inputs": {"X": ["x"], "Y": ["fc_big.w_0"]},
                "outputs": {"Out": ["y"]}, "attrs": {},
            }],
        }],
    }


# name -> (builder, mesh_axes, spec_layout overrides to register,
#          expected severity, expected message substring)
BROKEN_SHARDINGS = {
    "axis_reused_in_override": (
        _axis_reuse, {"data": 2, "fsdp": 2, "tp": 2},
        {"dup_0.w_0": ("fsdp", "fsdp")},
        "error", "used twice"),
    "nondividing_after_reshape": (
        _nondividing_after_reshape, {"fsdp": 2, "tp": 4}, {},
        "error", "not divisible"),
    "collective_on_absent_axis": (
        _collective_on_absent_axis, {"tp": 8}, {},
        "error", "absent from mesh axes"),
    "oversized_replicated_weight": (
        _oversized_replicated_weight, {"data": 8}, {},
        "warning", "fully replicated"),
}
