"""Auto-checkpoint preemption fixture: trains N epochs; if PREEMPT_AT is
set, kills itself (simulated preemption) at the END of that epoch,
after the checkpoint save.  Writes per-epoch losses to OUT."""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, "/root/repo")
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu.fluid as fluid
import paddle_tpu.fluid.incubate.checkpoint.auto_checkpoint as acp

out_path = sys.argv[1]
preempt_at = int(os.environ.get("PREEMPT_AT", "-1"))

main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    x = fluid.data("x", [-1, 8], "float32")
    yt = fluid.data("yt", [-1, 1], "float32")
    pred = fluid.layers.fc(x, 1)
    loss = fluid.layers.reduce_mean(
        fluid.layers.loss.square_error_cost(pred, yt))
    fluid.optimizer.SGD(0.1).minimize(loss)
exe = fluid.Executor()
exe.run(startup)

W = np.random.RandomState(42).randn(8, 1).astype("float32")
losses = []
r = acp.train_epoch_range(6, program=main)
for epoch in r:
    rng = np.random.RandomState(100 + epoch)  # per-epoch data, restart-stable
    for _ in range(20):
        X = rng.randn(16, 8).astype("float32")
        L, = exe.run(main, feed={"x": X, "yt": X @ W}, fetch_list=[loss])
    losses.append(float(L))
    with open(out_path, "a") as f:
        f.write(f"{epoch} {float(L):.8f}\n")
    if epoch == preempt_at:
        os._exit(17)  # simulated preemption AFTER this epoch's save...
        # (train_epoch_range saves after the yield resumes; see test)
print("restored_epoch:", r.restored_epoch)
