"""Deliberately buggy transform-pass variants (ISSUE 11 fault-injection
harness).

Each pass below re-creates a real rewrite-bug class the shape-consistency
verifier pass (analysis/shape_check.py) exists to catch:

* `broken_layout_wrong_perm` — NHWC anchor rewrite that permutes the
  declared output shape with the WRONG permutation (swapped H/W), the
  classic layout-pass transposition bug;
* `broken_fold_bn_dtype` — a fold_bn whose synthesized chain drops the
  dtype (declares the folded bias float16 while the chain computes in
  float32);
* `broken_dce_overeager` — dead-op elimination that removes a writer
  whose output a later op still reads;
* `broken_subblock_rename` — a sub-block rewrite that renames an op's
  input to a name no scope declares and no op writes.

All register with `default=False`, so `enabled_passes()` never selects
them — tests opt in explicitly via
`apply_transforms(program, passes=["broken_..."])`.  Every touched op
is tagged via `tag_provenance`, so the resulting findings carry the
`[pass=...]` attribution the acceptance criteria require.
"""

from paddle_tpu.transforms import register_transform, tag_provenance

BROKEN_PASSES = (
    "broken_layout_wrong_perm",
    "broken_fold_bn_dtype",
    "broken_dce_overeager",
    "broken_subblock_rename",
)

_WRONG_PERM = (0, 3, 2, 1)  # correct NHWC perm is (0, 2, 3, 1)


@register_transform(
    "broken_layout_wrong_perm", default=False,
    help_str="FAULT INJECTION: NHWC anchor rewrite with a swapped-H/W "
             "declared-shape permutation")
def broken_layout_wrong_perm(ctx) -> int:
    block = ctx.program.global_block()
    for op in block.ops:
        if op.type != "conv2d":
            continue
        op.attrs["data_format"] = "NHWC"
        op.attrs["nhwc_in"] = ["Input"]
        # keep the output NHWC (no nhwc_out) but record the WRONG
        # permutation in the declared metadata
        out = op.output("Output")[0]
        v = block.vars.get(out)
        if v is not None and v.shape is not None and len(v.shape) == 4:
            s = v.shape
            v.shape = tuple(s[i] for i in _WRONG_PERM)
        tag_provenance(op, "broken_layout_wrong_perm")
        return 1
    return 0


@register_transform(
    "broken_fold_bn_dtype", default=False,
    help_str="FAULT INJECTION: fold_bn whose synthesized bias var "
             "drops to float16")
def broken_fold_bn_dtype(ctx) -> int:
    from paddle_tpu.transforms import fold_bn

    n = fold_bn.run(ctx)
    if not n:
        return 0
    block = ctx.program.global_block()
    broken = 0
    for name, v in block.vars.items():
        if "@fold_bn." in name and name.endswith(".bias"):
            v.dtype = "float16"  # the chain still computes float32
            for op in block.ops:
                if name in op.output_arg_names():
                    tag_provenance(op, "broken_fold_bn_dtype")
            broken += 1
    return broken


@register_transform(
    "broken_dce_overeager", default=False,
    help_str="FAULT INJECTION: DCE that removes a writer whose output "
             "is still read")
def broken_dce_overeager(ctx) -> int:
    block = ctx.program.global_block()
    read_anywhere = {
        n for b in ctx.program.blocks for o in b.ops
        for n in o.input_arg_names()}
    for op in block.ops:
        outs = [n for n in op.output_arg_names() if n != "@EMPTY@"]
        if not outs or not all(n in read_anywhere for n in outs):
            continue
        ok = True
        for n in outs:
            v = block.vars.get(n)
            if v is None or v.persistable or getattr(v, "is_data", False):
                ok = False
                break
        if not ok:
            continue
        block.ops.remove(op)
        # "normalize" the surviving consumers, the way a rewrite pass
        # stamps everything it touched — this is what attributes the
        # findings to this pass
        for o in block.ops:
            if any(n in o.input_arg_names() for n in outs):
                tag_provenance(o, "broken_dce_overeager")
        return 1
    return 0


@register_transform(
    "broken_subblock_rename", default=False,
    help_str="FAULT INJECTION: sub-block rewrite renaming an op input "
             "to an undeclared name")
def broken_subblock_rename(ctx) -> int:
    prog = ctx.program
    for blk in prog.blocks[1:]:
        declared_outside = set()
        b = blk.parent_block
        while b is not None:
            declared_outside.update(b.vars)
            b = b.parent_block
        for op in blk.ops:
            for slot, names in op.inputs.items():
                for i, n in enumerate(names):
                    if n in declared_outside:
                        op.inputs[slot][i] = n + "@renamed"
                        tag_provenance(op, "broken_subblock_rename")
                        return 1
    return 0
