"""Crash-injection training worker (tests/test_checkpoint.py).

Runs a deterministic tiny regression job through
`Executor.train_from_dataset` with the auto-checkpoint loop configured
ENTIRELY through the PADDLE_CKPT_* environment contract
(fluid/flags.py), so the test also proves the env wiring.  Each step
appends one fsync'd line to the output file:

    <executor_step> <loss> <batch_x_mean>

`<batch_x_mean>` is fetched from the program itself, so the line is
direct evidence of WHICH batch fed that step — a resumed run that
replayed the wrong remaining data order cannot match the golden file.

env:
    DATA_DIR        directory of MultiSlot part files (written by the test)
    EPOCHS          passes over the dataset (default 1)
    BATCH_SIZE      rows per step (default 10)
    KILL_AT_STEP    SIGKILL self at this executor step boundary (-1: never);
                    fires AFTER the step's checkpoint-cadence hook, so a
                    kill can land mid-async-write (half-written tmp dir)
    PADDLE_CKPT_*   auto-checkpoint knobs (dir, cadence, retention)
argv:
    [1] output losses file (appended; the test merges runs by step)
"""

import os
import signal
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu.fluid as fluid  # noqa: E402
from paddle_tpu.fluid import framework, unique_name  # noqa: E402
from paddle_tpu.fluid.executor import Scope, scope_guard  # noqa: E402


def main():
    out_path = sys.argv[1]
    data_dir = os.environ["DATA_DIR"]
    epochs = int(os.environ.get("EPOCHS", "1"))
    batch_size = int(os.environ.get("BATCH_SIZE", "10"))
    kill_at = int(os.environ.get("KILL_AT_STEP", "-1"))
    files = sorted(os.path.join(data_dir, f)
                   for f in os.listdir(data_dir) if f.endswith(".txt"))

    main_prog, startup = framework.Program(), framework.Program()
    main_prog.random_seed = 123
    scope = Scope()
    with framework.program_guard(main_prog, startup), \
            unique_name.guard(), scope_guard(scope):
        x = fluid.data("x", [-1, 8], "float32")
        y = fluid.data("y", [-1, 1], "float32")
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.loss.square_error_cost(pred, y))
        xmean = fluid.layers.reduce_mean(x)
        fluid.optimizer.SGD(0.1).minimize(loss)

        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(batch_size)
        ds.set_use_var([x, y])
        ds.set_filelist(files)
        ds.set_shuffle_seed(7)
        ds.load_into_memory()

        exe = fluid.Executor()
        exe.run(startup)

        out = open(out_path, "a")

        def on_step(step, step_in_epoch, fetches):
            line = (f"{step} {float(fetches[0].numpy().ravel()[0]):.9g} "
                    f"{float(fetches[1].numpy().ravel()[0]):.9g}\n")
            out.write(line)
            out.flush()
            os.fsync(out.fileno())
            if step == kill_at:
                os.kill(os.getpid(), signal.SIGKILL)  # preemption

        for _ in range(epochs):
            exe.train_from_dataset(main_prog, ds,
                                   fetch_list=[loss, xmean],
                                   step_callback=on_step)
        out.close()
    print("worker done")


if __name__ == "__main__":
    main()
