"""Fixture Program zoo for the static-analysis tests (ISSUE 3
satellite): every builder constructs one representative static graph on
the Program IR — training (backward + optimizer), control flow
(while sub-blocks), shared parameters, normalization state — and the
verifier must report zero ERROR findings over each of them
(tests/test_static_analysis.py), alongside the book-model graphs.

Each builder returns (main_program, startup_program, fetch_list) and
only BUILDS the graph; nothing here touches the executor, so the zoo
stays cheap enough to verify exhaustively.
"""

from __future__ import annotations

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, unique_name


def _build(body):
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with unique_name.guard():
            fetch = body()
    return main, startup, fetch


def linear_sgd():
    """fc -> mse -> SGD: forward + backward + optimizer ops."""

    def body():
        x = fluid.data("x", [-1, 4], "float32")
        yt = fluid.data("yt", [-1, 1], "float32")
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.loss.square_error_cost(pred, yt))
        fluid.optimizer.SGD(0.01).minimize(loss)
        return [loss]

    return _build(body)


def mlp_adam():
    """Deeper net + Adam (optimizer moment state, shared helper vars)."""

    def body():
        x = fluid.data("x", [-1, 8], "float32")
        yt = fluid.data("yt", [-1, 1], "float32")
        h = fluid.layers.fc(x, 16, act="relu")
        h = fluid.layers.fc(h, 16, act="tanh")
        pred = fluid.layers.fc(h, 1, bias_attr=False)
        loss = fluid.layers.reduce_mean(
            fluid.layers.loss.square_error_cost(pred, yt))
        fluid.optimizer.Adam(1e-3).minimize(loss)
        return [loss]

    return _build(body)


def while_counter():
    """`while` sub-block with loop-carried state (block linkage +
    loop-carried def-before-use)."""

    def body():
        from paddle_tpu.fluid.layers import tensor as t

        i = t.fill_constant([1], "int32", 0)
        limit = t.fill_constant([1], "int32", 5)
        acc = t.fill_constant([1], "float32", 0.0)
        cond = fluid.layers.less_than(i, limit)
        w = fluid.layers.While(cond)
        with w.block():
            from paddle_tpu.fluid.layers.tensor import assign

            ni = fluid.layers.increment(i, value=1, in_place=False)
            na = fluid.layers.elementwise_add(
                acc, fluid.layers.cast(ni, "float32"))
            assign(ni, i)
            assign(na, acc)
            assign(fluid.layers.less_than(i, limit), cond)
        return [acc]

    return _build(body)


def shared_embedding_ngram():
    """word2vec-style shared embedding table (param reuse across ops)."""

    def body():
        words = [fluid.data(n, [-1, 1], "int64")
                 for n in ("w0", "w1", "w2")]
        nxt = fluid.data("nxt", [-1, 1], "int64")
        embeds = [fluid.layers.embedding(
            fluid.layers.reshape(w, [-1]), size=[32, 8],
            param_attr="shared_emb") for w in words]
        concat = fluid.layers.concat(embeds, axis=1)
        hidden = fluid.layers.fc(concat, 16, act="sigmoid")
        logits = fluid.layers.fc(hidden, 32)
        loss = fluid.layers.reduce_mean(
            fluid.layers.softmax_with_cross_entropy(
                logits, fluid.layers.reshape(nxt, [-1, 1])))
        fluid.optimizer.SGD(0.1).minimize(loss)
        return [loss]

    return _build(body)


def batchnorm_eval_clone():
    """batch_norm training graph + its clone(for_test=True) twin
    (pruned backward ops must still verify)."""

    def body():
        x = fluid.data("x", [-1, 6], "float32")
        yt = fluid.data("yt", [-1, 1], "float32")
        h = fluid.layers.fc(x, 8)
        h = fluid.layers.batch_norm(h)
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.loss.square_error_cost(pred, yt))
        fluid.optimizer.SGD(0.01).minimize(loss)
        return [loss]

    main, startup, fetch = _build(body)
    # the for_test clone is itself a fixture program; callers verify it
    # via the clone() entry below
    return main, startup, fetch


def batchnorm_for_test():
    main, startup, fetch = batchnorm_eval_clone()
    test_prog = main.clone(for_test=True)
    return test_prog, startup, fetch


FIXTURES = {
    "linear_sgd": linear_sgd,
    "mlp_adam": mlp_adam,
    "while_counter": while_counter,
    "shared_embedding_ngram": shared_embedding_ngram,
    "batchnorm_train": batchnorm_eval_clone,
    "batchnorm_for_test": batchnorm_for_test,
}


def build_all():
    """Yield (name, main, startup, fetch_list) for every fixture."""
    for name, builder in FIXTURES.items():
        main, startup, fetch = builder()
        yield name, main, startup, fetch
