"""Fresh-process inference loader: run a saved inference model with NO
model-building code (VERDICT r3 Missing #5 round-trip contract).

Usage: python infer_loader.py <model_dir> <input.npy> <output.npy>
"""

import os
import sys

import numpy as np

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # the env var alone doesn't beat the TPU plugin; both are needed
    import jax
    jax.config.update("jax_platforms", "cpu")

import paddle_tpu.fluid as fluid


def main():
    dirname, in_path, out_path = sys.argv[1:4]
    exe = fluid.Executor()
    program, feed_names, fetch_vars = fluid.io.load_inference_model(
        dirname, exe)
    x = np.load(in_path)
    outs = exe.run(program, feed={feed_names[0]: x},
                   fetch_list=[v.name for v in fetch_vars])
    np.save(out_path, np.asarray(outs[0]))


if __name__ == "__main__":
    main()
