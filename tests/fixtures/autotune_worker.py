"""Autotune subprocess worker (tests/test_autotune.py).

Builds the SAME deterministic eval-mode conv+bn trunk in every process
(`aot_cache.program_token` hashes prog_id + the program dict, and
prog_id is sequential per process — an identical build order gives
identical stable record keys across processes), runs AT_STEPS executor
dispatches under the env-configured PADDLE_AUTOTUNE mode, and dumps
the fetched output plus every autotune_* counter as JSON to argv[1].

The tuning configuration comes entirely from the environment
(PADDLE_AUTOTUNE / PADDLE_AUTOTUNE_DIR / PADDLE_AUTOTUNE_TRIAL_STEPS,
plus PADDLE_QUANT_COLLECTIVES to drift the volatile signature), so the
calling test composes cold-search / warm-replay / off / drifted runs
from one deterministic program.
"""

import json
import os
import sys

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu import profiler
from paddle_tpu.fluid import framework


def build():
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        x = fluid.data("x", [4, 3, 12, 12], "float32")
        y = fluid.layers.conv2d(x, 8, 3, padding=1, bias_attr=True)
        y = fluid.layers.batch_norm(y, act="relu", is_test=True)
        y = fluid.layers.conv2d(y, 8, 3, padding=1, bias_attr=False)
        y = fluid.layers.batch_norm(y, act="relu", is_test=True)
    return main, startup, y.name


def main(out_path: str) -> None:
    steps = int(os.environ.get("AT_STEPS", "2"))
    main_prog, startup, yname = build()
    exe = fluid.Executor()
    exe.run(startup)
    # give the running bn stats non-default values (fixed seed: every
    # process bakes the same statistics, so outputs compare exactly)
    rng = np.random.RandomState(23)
    scope = fluid.executor.global_scope()
    for v in main_prog.list_vars():
        if not v.persistable or scope.get(v.name) is None:
            continue
        cur = np.asarray(scope.get(v.name))
        if cur.ndim != 1:
            continue
        scope.set(v.name, rng.uniform(0.5, 1.5,
                                      cur.shape).astype(cur.dtype))
    feed = {"x": np.linspace(-1.0, 1.0, 4 * 3 * 12 * 12,
                             dtype=np.float32).reshape(4, 3, 12, 12)}
    out = None
    for _ in range(steps):
        (out,) = exe.run(main_prog, feed=feed, fetch_list=[yname])
    s = profiler.get_int_stats()
    with open(out_path, "w") as f:
        json.dump({
            "out": np.asarray(out).tolist(),
            "stats": {k: v for k, v in s.items()
                      if k.startswith("autotune")},
            "compiles": s.get("executor_compile_count", 0),
        }, f)


if __name__ == "__main__":
    main(sys.argv[1])
