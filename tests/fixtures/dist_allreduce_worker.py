"""2-process collective training fixture (reference pattern:
test_dist_base.py `_run_cluster` model files like dist_mnist.py).

Each worker joins the global mesh, trains a tiny regression model on its
batch shard with gradients combined by XLA sharding propagation (the
allreduce), and writes its final loss.  The test compares against a
single-process run — losses must match bit-for-bit-ish because the
GLOBAL batch and seed are identical.
"""

import os
import sys

# own platform config: workers inherit the test env; force a clean
# single-local-device CPU runtime regardless
os.environ["XLA_FLAGS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np


def make_data(steps=20, batch=16, dim=8, seed=0):
    rng = np.random.RandomState(seed)
    W = rng.randn(dim, 1).astype("float32")
    xs = rng.randn(steps, batch, dim).astype("float32")
    ys = xs @ W
    return xs, ys


def train(out_path):
    import jax
    import jax.numpy as jnp

    import paddle_tpu.distributed as dist
    from paddle_tpu.parallel.mesh import (DATA_AXIS, global_mesh,
                                          replicated, shard_host_batch)

    env = dist.init_parallel_env()
    rank, world = env.rank, env.world_size
    mesh = global_mesh({DATA_AXIS: world})

    xs, ys = make_data()
    dim = xs.shape[-1]
    params = {"w": jnp.zeros((dim, 1), jnp.float32),
              "b": jnp.zeros((1,), jnp.float32)}
    params = jax.device_put(params, replicated(mesh))

    def loss_fn(p, x, y):
        pred = x @ p["w"] + p["b"]
        return jnp.mean((pred - y) ** 2)

    @jax.jit
    def step(p, x, y):
        l, g = jax.value_and_grad(loss_fn)(p, x, y)
        return jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, p, g), l

    loss = None
    for i in range(xs.shape[0]):
        # this process's shard of the global batch
        per = xs.shape[1] // world
        xl = xs[i, rank * per:(rank + 1) * per]
        yl = ys[i, rank * per:(rank + 1) * per]
        gx, gy = shard_host_batch(mesh, (xl, yl))
        params, loss = step(params, gx, gy)
    with open(out_path % rank, "w") as f:
        f.write(repr(float(loss)))


def spawn_entry(out_path):
    """Entry for the spawn() API test (must be module-level importable)."""
    train(out_path)


if __name__ == "__main__":
    train(sys.argv[1])
