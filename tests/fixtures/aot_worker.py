"""AOT-cache subprocess worker (tests/test_aot_cache.py).

Builds a small two-layer fluid program, runs ONE executor dispatch —
the first dispatch is exactly where the persistent AOT cache seam sits
(fluid/aot_cache.compile_entry_with_cache) — and dumps the fetched
output plus every aot_cache_* counter/timer as JSON to argv[1].

The cache configuration comes entirely from the environment
(PADDLE_AOT_CACHE / PADDLE_AOT_CACHE_DIR / PADDLE_QUANT_COLLECTIVES),
so the calling test composes cold / warm / off / drifted runs from the
same deterministic program.
"""

import json
import os
import sys

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu import profiler
from paddle_tpu.fluid import framework


def main(out_path: str) -> None:
    d = int(os.environ.get("AOT_DIM", "16"))
    main_prog, startup = framework.Program(), framework.Program()
    with framework.program_guard(main_prog, startup):
        x = fluid.data("x", [-1, d], "float32")
        h = fluid.layers.fc(x, size=d, act="tanh")
        y = fluid.layers.fc(h, size=d)
    exe = fluid.Executor()
    exe.run(startup)
    feed = {"x": np.linspace(-1.0, 1.0, 4 * d,
                             dtype=np.float32).reshape(4, d)}
    (out,) = exe.run(main_prog, feed=feed, fetch_list=[y])
    t = profiler.get_time_stats()
    s = profiler.get_int_stats()
    with open(out_path, "w") as f:
        json.dump({
            "out": np.asarray(out).tolist(),
            "compile_ms": t.get("compile_ms", 0.0),
            "aot_cache_load_ms": t.get("aot_cache_load_ms", 0.0),
            "stats": {k: v for k, v in s.items()
                      if k.startswith("aot_cache")},
        }, f)


if __name__ == "__main__":
    main(sys.argv[1])
