"""ResNet model tests — counterpart of the reference's SE-ResNeXt
convergence fixtures (unittests/seresnext_test_base.py): build the program,
train a few steps on tiny shapes, assert loss decreases and bn stats move."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.executor import Scope, scope_guard
from paddle_tpu.models import resnet


def _tiny_batch(rng, batch, classes):
    imgs = rng.rand(batch, 3, 32, 32).astype("float32")
    labels = rng.randint(0, classes, size=(batch, 1)).astype("int64")
    return imgs, labels


def test_resnet18_trains():
    batch, classes = 8, 10
    main, startup, feeds, fetches = resnet.build_train_program(
        depth=18, class_num=classes, image_shape=(3, 32, 32),
        batch_size=batch, width=8,
        optimizer=fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9))
    rng = np.random.RandomState(0)
    imgs, labels = _tiny_batch(rng, batch, classes)
    with scope_guard(Scope()):
        exe = fluid.Executor()
        exe.run(startup)
        losses = []
        for _ in range(6):
            out = exe.run(main, feed={"image": imgs, "label": labels},
                          fetch_list=fetches)
            losses.append(float(out[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_resnet50_builds_and_steps():
    # full bottleneck topology at toy width/resolution: checks the whole
    # 50-layer program lowers and executes, cheaply.
    batch, classes = 2, 10
    main, startup, feeds, fetches = resnet.build_train_program(
        depth=50, class_num=classes, image_shape=(3, 32, 32),
        batch_size=batch, width=4,
        optimizer=fluid.optimizer.Momentum(learning_rate=0.01, momentum=0.9))
    n_convs = sum(1 for op in main.global_block().ops if op.type == "conv2d")
    assert n_convs == 53  # 49 stem/block convs + 4 projection shortcuts
    rng = np.random.RandomState(1)
    imgs, labels = _tiny_batch(rng, batch, classes)
    with scope_guard(Scope()):
        exe = fluid.Executor()
        exe.run(startup)
        out = exe.run(main, feed={"image": imgs, "label": labels},
                      fetch_list=fetches)
    assert np.isfinite(float(out[0]))


def test_resnet_piecewise_lr():
    batch, classes = 4, 10
    main, startup, feeds, fetches = resnet.build_train_program(
        depth=18, class_num=classes, image_shape=(3, 32, 32),
        batch_size=batch, width=8, lr_boundaries=[2, 4],
        lr_values=[0.1, 0.01, 0.001])
    rng = np.random.RandomState(2)
    imgs, labels = _tiny_batch(rng, batch, classes)
    with scope_guard(Scope()):
        exe = fluid.Executor()
        exe.run(startup)
        for _ in range(5):
            out = exe.run(main, feed={"image": imgs, "label": labels},
                          fetch_list=fetches)
        assert np.isfinite(float(out[0]))
