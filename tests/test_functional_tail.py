"""nn.functional tail (tests for paddle_tpu/nn/functional/extra.py):
surface completeness vs the reference's DEFINE_ALIAS list, numpy
oracles for the compositions, smoke + shape checks for the op-backed
wrappers, and the documented-descope guards."""

import re

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.fluid import dygraph


@pytest.fixture(autouse=True)
def _dygraph():
    with dygraph.guard():
        yield


def _t(a, dtype="float32"):
    return paddle.to_tensor(np.asarray(a, dtype=dtype))


def test_functional_surface_complete():
    import os
    if not os.path.isdir("/root/reference"):
        pytest.skip("reference source tree not present in this environment")
    src = open(
        "/root/reference/python/paddle/nn/functional/__init__.py").read()
    names = set(re.findall(r"from [\w.]+ import (\w+)\s+#DEFINE_ALIAS",
                           src))
    missing = sorted(n for n in names if not hasattr(F, n))
    assert missing == [], f"functional surface gaps: {missing}"


def test_activation_compositions():
    x = np.linspace(-3, 3, 7).astype("float32")
    np.testing.assert_allclose(
        F.log_sigmoid(_t(x)).numpy(), np.log(1 / (1 + np.exp(-x))),
        rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        F.softsign(_t(x)).numpy(), x / (1 + np.abs(x)), rtol=1e-6)
    np.testing.assert_allclose(
        F.soft_relu(_t(x), threshold=40.0).numpy(),
        np.log1p(np.exp(x)), rtol=1e-5)


def test_cosine_similarity_oracle():
    r = np.random.RandomState(0)
    a, b = r.rand(4, 8).astype("float32"), r.rand(4, 8).astype("float32")
    got = F.cosine_similarity(_t(a), _t(b), axis=1).numpy()
    want = (a * b).sum(1) / (np.linalg.norm(a, axis=1)
                             * np.linalg.norm(b, axis=1))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_losses():
    r = np.random.RandomState(1)
    probs = r.dirichlet(np.ones(3), size=(2, 5)).astype("float32")
    label = r.randint(0, 3, (2, 5, 1)).astype("int64")
    d = float(F.dice_loss(_t(probs), _t(label, "int64")).numpy())
    assert 0.0 <= d <= 1.0

    anchor = r.rand(4, 6).astype("float32")
    pos = r.rand(4, 6).astype("float32")
    labels = np.array([0, 1, 0, 2], "int64")
    n = float(F.npair_loss(_t(anchor), _t(pos),
                           _t(labels, "int64")).numpy())
    assert np.isfinite(n)

    x = r.rand(2, 3, 4, 4).astype("float32")
    y = r.rand(2, 5, 4, 4).astype("float32")
    fsp = F.fsp_matrix(_t(x), _t(y))
    assert list(fsp.shape) == [2, 3, 5]

    logit = r.rand(4, 1).astype("float32")
    lbl = r.rand(4, 1).astype("float32")
    assert np.isfinite(float(F.bpr_loss(
        _t(r.rand(4, 3)), _t(np.array([[0], [1], [2], [0]], "int64"))
    ).numpy().sum()))
    assert np.isfinite(float(F.teacher_student_sigmoid_loss(
        _t(logit), _t(lbl)).numpy().sum()))


def test_ctc_loss_wraps_warpctc():
    r = np.random.RandomState(2)
    T, B, C = 6, 2, 5
    logits = r.rand(T, B, C).astype("float32")
    labels = np.array([[1, 2], [2, 3]], "int32")
    loss = F.ctc_loss(_t(logits), _t(labels, "int32"),
                      _t(np.array([T, T], "int64"), "int64"),
                      _t(np.array([2, 2], "int64"), "int64"),
                      reduction="mean")
    assert np.isfinite(float(loss.numpy()))


def test_conv1d_matches_conv2d_squeeze():
    r = np.random.RandomState(3)
    x = r.rand(2, 3, 16).astype("float32")
    w = r.rand(5, 3, 4).astype("float32")
    out = F.conv1d(_t(x), _t(w), stride=2, padding=1).numpy()
    # oracle via conv2d on the unsqueezed layout
    out2 = F.conv2d(_t(x[:, :, None, :]), _t(w[:, :, None, :]),
                    stride=[1, 2], padding=[0, 1]).numpy()
    np.testing.assert_allclose(out, out2[:, :, 0, :], rtol=1e-5)
    # transpose variant round-trips shape
    wt = r.rand(3, 5, 4).astype("float32")
    y = F.conv1d_transpose(_t(x), _t(wt), stride=2)
    assert y.shape[1] == 5


def test_pool_1d_3d_and_adaptive():
    r = np.random.RandomState(4)
    x1 = r.rand(2, 3, 16).astype("float32")
    mp = F.max_pool1d(_t(x1), 2, stride=2).numpy()
    np.testing.assert_allclose(
        mp, x1.reshape(2, 3, 8, 2).max(-1), rtol=1e-6)
    ap = F.avg_pool1d(_t(x1), 2, stride=2).numpy()
    np.testing.assert_allclose(
        ap, x1.reshape(2, 3, 8, 2).mean(-1), rtol=1e-6)

    x3 = r.rand(2, 3, 4, 6, 8).astype("float32")
    m3 = F.max_pool3d(_t(x3), 2, stride=2).numpy()
    want = x3.reshape(2, 3, 2, 2, 3, 2, 4, 2).max((3, 5, 7))
    np.testing.assert_allclose(m3, want, rtol=1e-6)
    a3 = F.avg_pool3d(_t(x3), 2, stride=2).numpy()
    np.testing.assert_allclose(
        a3, x3.reshape(2, 3, 2, 2, 3, 2, 4, 2).mean((3, 5, 7)),
        rtol=1e-6)

    # adaptive: non-divisible output size uses exact region splits
    xa = r.rand(2, 3, 7).astype("float32")
    aa = F.adaptive_avg_pool1d(_t(xa), 3).numpy()
    want = np.stack([xa[:, :, 0:3].mean(-1), xa[:, :, 2:5].mean(-1),
                     xa[:, :, 4:7].mean(-1)], -1)
    np.testing.assert_allclose(aa, want, rtol=1e-6)
    am = F.adaptive_max_pool3d(_t(x3), 2)
    assert list(am.shape) == [2, 3, 2, 2, 2]


def test_vision_op_wrappers():
    r = np.random.RandomState(5)
    x = r.rand(2, 3, 8, 8).astype("float32")
    # grid_sample identity grid reproduces the input
    ys, xs = np.meshgrid(np.linspace(-1, 1, 8), np.linspace(-1, 1, 8),
                         indexing="ij")
    grid = np.stack([xs, ys], -1)[None].repeat(2, 0).astype("float32")
    out = F.grid_sample(_t(x), _t(grid)).numpy()
    np.testing.assert_allclose(out, x, atol=1e-4)

    x4 = r.rand(2, 4, 8, 8).astype("float32")  # C divisible by bs^2
    s2d = F.space_to_depth(_t(x4), 2)
    assert list(s2d.shape) == [2, 16, 4, 4]
    sc = F.shuffle_channel(_t(r.rand(2, 6, 4, 4).astype("float32")), 3)
    assert list(sc.shape) == [2, 6, 4, 4]

    x5 = r.rand(2, 3, 4, 4, 4).astype("float32")
    tri = F.resize_trilinear(_t(x5), out_shape=[8, 8, 8])
    assert list(tri.shape) == [2, 3, 8, 8, 8]

    short = F.image_resize_short(_t(x), 4)
    assert min(short.shape[2], short.shape[3]) == 4

    ape = F.add_position_encoding(_t(r.rand(2, 5, 8).astype("float32")),
                                  1.0, 1.0)
    assert list(ape.shape) == [2, 5, 8]


def test_roi_and_bilinear_wrappers():
    r = np.random.RandomState(6)
    x = r.rand(1, 4, 8, 8).astype("float32")
    rois = np.array([[0, 0, 0, 7, 7], [0, 2, 2, 6, 6]], "float32")
    out = F.roi_pool(_t(x), _t(rois), output_size=2)
    assert list(out.shape) == [2, 4, 2, 2]

    a = r.rand(3, 4).astype("float32")
    b = r.rand(3, 5).astype("float32")
    w = r.rand(6, 4, 5).astype("float32")
    btp = F.bilinear_tensor_product(_t(a), _t(b), _t(w)).numpy()
    want = np.einsum("bi,kij,bj->bk", a, w, b)
    np.testing.assert_allclose(btp, want, rtol=1e-4)
    assert F.bilinear is F.bilinear_tensor_product


def test_alpha_dropout_and_dropout3d():
    r = np.random.RandomState(7)
    x = r.randn(64, 64).astype("float32")
    out = F.alpha_dropout(_t(x), p=0.3, training=True).numpy()
    # mean/variance approximately preserved (the whole point)
    assert abs(out.mean() - x.mean()) < 0.15
    assert out.std() / x.std() < 1.5
    assert np.allclose(
        F.alpha_dropout(_t(x), p=0.3, training=False).numpy(), x)
    x5 = r.rand(2, 3, 4, 4, 4).astype("float32")
    d3 = F.dropout3d(_t(x5), p=0.5, training=True).numpy()
    assert d3.shape == x5.shape


def test_rnn_functional_drivers():
    import paddle_tpu.nn as nn

    r = np.random.RandomState(8)
    cell = nn.GRUCell(4, 6)
    x = _t(r.rand(2, 5, 4).astype("float32"))
    y, state = F.rnn(cell, x)
    assert list(y.shape) == [2, 5, 6]
    cell_bw = nn.GRUCell(4, 6)
    yb, states = F.birnn(cell, cell_bw, x)
    assert list(yb.shape) == [2, 5, 12]


def test_descope_guards_are_loud():
    for name in ("hash", "filter_by_instag", "merge_selected_rows",
                 "lod_append", "multi_box_head",
                 "roi_perspective_transform"):
        with pytest.raises(NotImplementedError, match="TPU-native"):
            getattr(F, name)()


def test_sequence_and_assign_wrappers():
    r = np.random.RandomState(9)
    # target_assign: X (N, M, K) gathered by match indices per column
    x = r.rand(2, 4, 3).astype("float32")
    match = np.array([[0, 2, -1], [1, -1, 3]], "int32")
    out, w = F.target_assign(_t(x), _t(match, "int32"),
                             mismatch_value=0)
    assert list(out.shape) == [2, 3, 3]
    assert list(w.shape) == [2, 3, 1]

    # per-sequence scatter-add: out[i, ids[i, j]] += updates[i, j]
    base = np.zeros((2, 6), "float32")
    ids = np.array([[0, 2], [1, 3]], "int64")
    ups = r.rand(2, 2).astype("float32")
    got = F.sequence_scatter(_t(base), _t(ids, "int64"),
                             _t(ups)).numpy()
    want = base.copy()
    for i in range(2):
        for j in range(2):
            want[i, ids[i, j]] += ups[i, j]
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_pixel_unshuffle_inverts_pixel_shuffle():
    r = np.random.RandomState(10)
    y = r.rand(2, 8, 3, 3).astype("float32")  # C=8 = 2*r^2
    shuffled = F.pixel_shuffle(_t(y), 2)
    back = F.pixel_unshuffle(shuffled, 2).numpy()
    np.testing.assert_allclose(back, y, rtol=1e-6)
    # works with C not divisible by r^2 (space_to_depth could not)
    out = F.pixel_unshuffle(_t(r.rand(1, 3, 4, 4).astype("float32")), 2)
    assert list(out.shape) == [1, 12, 2, 2]


def test_dropout3d_is_channel_wise():
    x = np.ones((2, 8, 4, 4, 4), "float32")
    out = F.dropout3d(_t(x), p=0.5, training=True).numpy()
    # every (n, c) channel is either fully zero or fully scaled
    for n in range(2):
        for c in range(8):
            ch = out[n, c]
            assert (ch == 0).all() or np.allclose(ch, 2.0)


def test_resize_trilinear_scale_only():
    x = np.random.RandomState(11).rand(1, 2, 4, 4, 4).astype("float32")
    out = F.resize_trilinear(_t(x), scale=2)
    assert list(out.shape) == [1, 2, 8, 8, 8]
    with pytest.raises(ValueError):
        F.resize_trilinear(_t(x))


def test_program_translator_gate():
    import paddle_tpu.jit as jit

    @jit.to_static
    def f(x):
        return x * 2

    jit.ProgramTranslator().enable(False)
    try:
        def g(x):
            return x * 3

        gg = jit.to_static(g)
        assert gg is g  # identity: conversion disabled
    finally:
        jit.ProgramTranslator().enable(True)


def test_beam_decoder_standalone_step():
    """The Decoder contract works without dynamic_decode driving it."""
    import paddle_tpu.nn as nn
    from tests.test_nn_tail import _ToyCell

    dec = nn.BeamSearchDecoder(_ToyCell(), start_token=0, end_token=5,
                               beam_size=2)
    init = _t(np.zeros((2, 1), "float32"))
    inputs, states, finished = dec.initialize(init)
    outputs, states, inputs, finished = dec.step(0, inputs, states)
    assert list(outputs["predicted_ids"].shape) == [2, 2]
