"""Executor tests: feed/fetch, persistable state commit, program cache,
backward correctness vs jax.grad oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu.fluid as fluid


def test_fill_and_fetch(fresh_programs):
    main, startup, scope = fresh_programs
    c = fluid.layers.fill_constant([2, 3], "float32", 7.0)
    exe = fluid.Executor()
    (out,) = exe.run(main, fetch_list=[c])
    np.testing.assert_allclose(out, np.full((2, 3), 7.0, "float32"))


def test_feed_fetch_matmul(fresh_programs):
    main, startup, scope = fresh_programs
    x = fluid.data("x", [-1, 4], "float32")
    y = fluid.data("y", [4, 5], "float32")
    z = fluid.layers.matmul(x, y)
    exe = fluid.Executor()
    a = np.random.rand(3, 4).astype("float32")
    b = np.random.rand(4, 5).astype("float32")
    (out,) = exe.run(main, feed={"x": a, "y": b}, fetch_list=[z])
    np.testing.assert_allclose(out, a @ b, rtol=1e-5)


def test_persistable_state_updates(fresh_programs):
    main, startup, scope = fresh_programs
    counter = fluid.layers.tensor.create_global_var(
        [1], 0.0, "float32", persistable=True, name="counter")
    fluid.layers.tensor.increment(counter, 1.0)
    exe = fluid.Executor()
    exe.run(startup)
    for i in range(3):
        (c,) = exe.run(main, fetch_list=[counter])
    np.testing.assert_allclose(c, [3.0])


def test_uninitialized_var_raises(fresh_programs):
    main, startup, scope = fresh_programs
    x = fluid.data("x", [-1, 4], "float32")
    y = fluid.layers.fc(x, 3)
    exe = fluid.Executor()
    with pytest.raises(RuntimeError, match="neither fed nor initialized"):
        exe.run(main, feed={"x": np.zeros((2, 4), "float32")},
                fetch_list=[y])


def test_backward_matches_jax_grad(fresh_programs):
    """d(mean(tanh(x@w)))/dw from append_backward == jax.grad oracle."""
    main, startup, scope = fresh_programs
    np.random.seed(0)
    w_init = np.random.rand(4, 3).astype("float32")
    x_val = np.random.rand(5, 4).astype("float32")

    x = fluid.data("x", [5, 4], "float32")
    w = fluid.layers.tensor.create_parameter(
        [4, 3], "float32", name="w_oracle",
        default_initializer=fluid.initializer.NumpyArray(w_init))
    y = fluid.layers.tanh(fluid.layers.matmul(x, w))
    loss = fluid.layers.reduce_mean(y)
    pgs = fluid.append_backward(loss)
    assert len(pgs) == 1
    p, g = pgs[0]

    exe = fluid.Executor()
    exe.run(startup)
    (got,) = exe.run(main, feed={"x": x_val}, fetch_list=[g])

    want = jax.grad(lambda w_: jnp.mean(jnp.tanh(x_val @ w_)))(w_init)
    np.testing.assert_allclose(got, np.asarray(want), rtol=1e-5, atol=1e-6)


def test_grad_accumulation_multi_consumer(fresh_programs):
    """x used by two branches -> grads summed via the emitted sum op."""
    main, startup, scope = fresh_programs
    w_init = np.ones((3, 3), "float32")
    w = fluid.layers.tensor.create_parameter(
        [3, 3], "float32", name="w_acc",
        default_initializer=fluid.initializer.NumpyArray(w_init))
    a = fluid.layers.reduce_sum(fluid.layers.square(w))
    b = fluid.layers.reduce_sum(w)
    loss = a + b
    pgs = fluid.append_backward(loss)
    exe = fluid.Executor()
    exe.run(startup)
    (g,) = exe.run(main, fetch_list=[pgs[0][1]])
    np.testing.assert_allclose(g, 2 * w_init + 1.0, rtol=1e-6)


def test_sgd_convergence(fresh_programs):
    """Linear regression converges (end-to-end fit_a_line analogue,
    reference tests/book/test_fit_a_line.py)."""
    main, startup, scope = fresh_programs
    rng = np.random.RandomState(42)
    true_w = rng.rand(4, 1).astype("float32")
    X = rng.rand(64, 4).astype("float32")
    Y = X @ true_w

    x = fluid.data("x", [-1, 4], "float32")
    yt = fluid.data("yt", [-1, 1], "float32")
    pred = fluid.layers.fc(x, 1, bias_attr=False)
    loss = fluid.layers.reduce_mean(
        fluid.layers.loss.square_error_cost(pred, yt))
    fluid.optimizer.SGD(0.5).minimize(loss)

    exe = fluid.Executor()
    exe.run(startup)
    losses = []
    for _ in range(50):
        (l,) = exe.run(main, feed={"x": X, "yt": Y}, fetch_list=[loss])
        losses.append(float(l))
    assert losses[-1] < 0.01 * max(losses[0], 1e-3), losses[-1]


def test_adam_state_advances(fresh_programs):
    main, startup, scope = fresh_programs
    x = fluid.data("x", [-1, 4], "float32")
    y = fluid.layers.fc(x, 2, bias_attr=False)
    loss = fluid.layers.reduce_mean(fluid.layers.square(y))
    opt = fluid.optimizer.Adam(0.01)
    opt.minimize(loss)
    exe = fluid.Executor()
    exe.run(startup)
    b1_name = next(n for n in scope.local_var_names()
                   if "beta1_pow_acc" in n)
    v0 = np.asarray(scope.get(b1_name)).copy()
    exe.run(main, feed={"x": np.ones((2, 4), "float32")}, fetch_list=[loss])
    v1 = np.asarray(scope.get(b1_name))
    np.testing.assert_allclose(v1, v0 * 0.9, rtol=1e-6)


def test_dropout_train_eval(fresh_programs):
    main, startup, scope = fresh_programs
    x = fluid.data("x", [100, 100], "float32")
    d = fluid.layers.dropout(x, 0.5, dropout_implementation="upscale_in_train")
    test_prog = main.clone(for_test=True)
    exe = fluid.Executor()
    X = np.ones((100, 100), "float32")
    (train_out,) = exe.run(main, feed={"x": X}, fetch_list=[d])
    (eval_out,) = exe.run(test_prog, feed={"x": X}, fetch_list=[d])
    assert (train_out == 0).mean() > 0.3  # roughly half dropped
    np.testing.assert_allclose(eval_out, X)  # identity at eval


def test_program_cache_is_bounded_lru(fresh_programs):
    """VERDICT r4 weak #7: a long-lived process cycling feed signatures
    must not grow the compile cache without bound, and the hot entry
    must survive eviction pressure (LRU, not FIFO)."""
    main, startup, scope = fresh_programs
    x = fluid.data("x", [-1, 4], "float32")
    y = fluid.layers.scale(x, 2.0)
    exe = fluid.Executor()
    cap = fluid.Executor.CACHE_CAPACITY

    hot = np.ones((1, 4), "float32")
    exe.run(main, feed={"x": hot}, fetch_list=[y])
    hot_key = next(iter(exe._cache))

    # churn: distinct batch sizes -> distinct cache keys, re-touching
    # the hot entry between insertions so LRU keeps it
    for n in range(2, cap + 10):
        exe.run(main, feed={"x": np.ones((n, 4), "float32")},
                fetch_list=[y])
        exe.run(main, feed={"x": hot}, fetch_list=[y])
    assert len(exe._cache) <= cap
    assert hot_key in exe._cache  # LRU retained the re-touched entry


def test_feed_rank_and_shape_mismatch_raise_crisply(fresh_programs):
    """Feed-boundary contract (reference executor feed checks): a wrong
    rank/shape must name the variable and both shapes, not surface as a
    raw jax broadcasting error mid-block."""
    main, startup, scope = fresh_programs
    x = fluid.data("x", [-1, 4], "float32")
    y = fluid.layers.scale(x, 2.0)
    exe = fluid.Executor()
    with pytest.raises(ValueError, match=r"rank mismatch.*'x'|'x'.*rank"):
        exe.run(main, feed={"x": np.ones((8,), "float32")},
                fetch_list=[y])
    with pytest.raises(ValueError, match="shape mismatch"):
        exe.run(main, feed={"x": np.ones((8, 5), "float32")},
                fetch_list=[y])
    # -1 dims accept anything
    (out,) = exe.run(main, feed={"x": np.ones((3, 4), "float32")},
                     fetch_list=[y])
    assert np.asarray(out).shape == (3, 4)
