"""WMT Transformer tests: training step + greedy/beam decode
(reference fixtures: dist_transformer.py and the machine_translation
book config with beam_search decode,
/root/reference/python/paddle/fluid/tests/book/test_machine_translation.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.fluid.dygraph import guard, to_variable
from paddle_tpu.models import transformer_wmt as tw


@pytest.fixture
def model():
    with guard():
        paddle.seed(0)
        yield tw.WMTTransformer(tw.TransformerConfig.tiny())


def _src(batch=2, t=7, seed=0):
    return to_variable(np.random.RandomState(seed)
                       .randint(2, 50, (batch, t)).astype("int64"))


class TestWMTDecode:
    def test_greedy_shapes(self, model):
        with guard():
            model.eval()
            out = model.greedy_decode(_src(), max_len=6)
            assert out.shape == [2, 6]

    def test_beam1_equals_greedy(self, model):
        """beam_size=1 must reproduce greedy exactly (same argmax)."""
        with guard():
            model.eval()
            g = model.greedy_decode(_src(), max_len=6)
            seqs, _ = model.beam_decode(_src(), beam_size=1, max_len=6)
            np.testing.assert_array_equal(g.numpy(),
                                          seqs.numpy()[:, 0])

    def test_beam4_at_least_as_good(self, model):
        """A wider beam can only improve the best cumulative log-prob."""
        with guard():
            model.eval()
            _, s1 = model.beam_decode(_src(), beam_size=1, max_len=6)
            seqs4, s4 = model.beam_decode(_src(), beam_size=4, max_len=6)
            assert (s4.numpy()[:, 0] >= s1.numpy()[:, 0] - 1e-5).all()
            # beams come back best-first
            assert (np.diff(s4.numpy(), axis=1) <= 1e-5).all()
            assert seqs4.shape == [2, 4, 6]


class TestWMTTrain:
    def test_loss_decreases(self):
        with guard():
            paddle.seed(0)
            import jax.numpy as jnp

            cfg = tw.TransformerConfig.tiny()
            model = tw.WMTTransformer(cfg)
            # short warmup: the default 4000-step Noam ramp leaves
            # lr ~ 1e-6 for a 10-step test
            step, state = tw.build_train_step(model, bf16=False,
                                              warmup_steps=10)
            rng = np.random.RandomState(0)
            batch = {
                "src": rng.randint(2, 50, (4, 8)).astype("int64"),
                "tgt_in": rng.randint(2, 50, (4, 8)).astype("int64"),
                "tgt_out": rng.randint(2, 50, (4, 8)).astype("int64"),
            }
            losses = []
            for _ in range(10):
                state, loss = step(state, batch)
                losses.append(float(loss))
            assert losses[-1] < losses[0]
