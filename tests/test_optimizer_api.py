"""Tests for paddle.optimizer-equivalent package: convergence, oracle
update math, schedulers, clipping, state_dict (SURVEY.md §4 strategy:
numeric oracles + loss-decrease assertions)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.fluid.dygraph import guard, to_variable
from paddle_tpu.optimizer import (SGD, Adam, AdamW, ClipGradByGlobalNorm,
                                  ClipGradByValue, Lamb, Momentum, lr)


@pytest.fixture(autouse=True)
def dygraph():
    with guard():
        yield


def _fit(opt_cls, steps=40, **kw):
    np.random.seed(0)
    model = nn.Linear(6, 1)
    opt = opt_cls(parameters=model.parameters(), **kw)
    x = to_variable(np.random.rand(32, 6).astype("float32"))
    w = np.random.rand(6, 1).astype("float32")
    y = to_variable(x.numpy() @ w)
    losses = []
    for _ in range(steps):
        loss = nn.MSELoss()(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    return losses


class TestConvergence:
    @pytest.mark.parametrize("opt_cls,kw", [
        (SGD, {"learning_rate": 0.1}),
        (Momentum, {"learning_rate": 0.05, "momentum": 0.9}),
        (Adam, {"learning_rate": 0.05}),
        (AdamW, {"learning_rate": 0.05, "weight_decay": 0.001}),
        (Lamb, {"learning_rate": 0.05}),
    ])
    def test_loss_decreases(self, opt_cls, kw):
        losses = _fit(opt_cls, **kw)
        assert losses[-1] < losses[0] * 0.3


class TestAdamOracle:
    def test_first_step_matches_formula(self):
        p0 = np.array([1.0, 2.0], dtype="float32")
        g = np.array([0.5, -0.5], dtype="float32")
        model = nn.Linear(1, 1)  # placeholder param container
        param = nn.Parameter(p0.copy())
        opt = Adam(learning_rate=0.1, parameters=[param])
        param._grad = __import__("jax.numpy", fromlist=["x"]).asarray(g)
        opt.step()
        # bias-corrected first step of adam: p - lr * mhat/(sqrt(vhat)+eps)
        m = 0.1 * g
        v = 0.001 * g * g
        mhat = m / (1 - 0.9)
        vhat = v / (1 - 0.999)
        ref = p0 - 0.1 * mhat / (np.sqrt(vhat) + 1e-8)
        np.testing.assert_allclose(param.numpy(), ref, rtol=1e-5)


class TestClipping:
    def test_global_norm_clip(self):
        param = nn.Parameter(np.zeros(4, "float32"))
        import jax.numpy as jnp

        param._grad = jnp.asarray(np.full(4, 10.0, "float32"))
        opt = SGD(learning_rate=1.0, parameters=[param],
                  grad_clip=ClipGradByGlobalNorm(1.0))
        opt.step()
        # update magnitude == clip_norm
        np.testing.assert_allclose(np.linalg.norm(param.numpy()), 1.0,
                                   rtol=1e-4)

    def test_value_clip(self):
        param = nn.Parameter(np.zeros(2, "float32"))
        import jax.numpy as jnp

        param._grad = jnp.asarray(np.array([5.0, -5.0], "float32"))
        opt = SGD(learning_rate=1.0, parameters=[param],
                  grad_clip=ClipGradByValue(0.5))
        opt.step()
        np.testing.assert_allclose(param.numpy(), [-0.5, 0.5], rtol=1e-5)


class TestSchedulers:
    def test_noam(self):
        s = lr.NoamDecay(d_model=512, warmup_steps=10, learning_rate=1.0)
        lrs = []
        for _ in range(20):
            s.step()
            lrs.append(s())
        peak = int(np.argmax(lrs)) + 1
        assert abs(peak - 10) <= 1  # peaks at warmup boundary

    def test_piecewise(self):
        s = lr.PiecewiseDecay([3, 6], [0.1, 0.01, 0.001])
        vals = []
        for _ in range(8):
            vals.append(s())
            s.step()
        assert vals[0] == 0.1 and vals[4] == 0.01 and vals[-1] == 0.001

    def test_cosine(self):
        s = lr.CosineAnnealingDecay(1.0, T_max=10)
        assert abs(s() - 1.0) < 1e-6
        s.step(10)
        assert abs(s() - 0.0) < 1e-6

    def test_linear_warmup_wraps_scheduler(self):
        inner = lr.ExponentialDecay(0.1, gamma=0.9)
        s = lr.LinearWarmup(inner, warmup_steps=5, start_lr=0.0, end_lr=0.1)
        first = s()
        for _ in range(5):
            s.step()
        assert s() <= 0.1 and first < s()

    def test_reduce_on_plateau(self):
        s = lr.ReduceOnPlateau(0.1, patience=1, factor=0.5)
        for m in [1.0, 1.0, 1.0, 1.0]:
            s.step(m)
        assert s() < 0.1

    def test_scheduler_drives_optimizer(self):
        sched = lr.StepDecay(0.1, step_size=1, gamma=0.1)
        param = nn.Parameter(np.zeros(1, "float32"))
        opt = SGD(learning_rate=sched, parameters=[param])
        assert abs(opt.get_lr() - 0.1) < 1e-9
        sched.step()
        assert abs(opt.get_lr() - 0.01) < 1e-9


class TestStateDict:
    def test_roundtrip_preserves_moments(self):
        losses = None
        model = nn.Linear(4, 1)
        opt = Adam(learning_rate=0.01, parameters=model.parameters())
        x = to_variable(np.random.rand(8, 4).astype("float32"))
        for _ in range(3):
            loss = model(x).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        sd = opt.state_dict()
        opt2 = Adam(learning_rate=0.01, parameters=model.parameters())
        opt2.set_state_dict(sd)
        assert opt2._step_count == 3
        p = model.parameters()[0]
        np.testing.assert_allclose(
            np.asarray(opt2._state[id(p)]["moment1"]),
            np.asarray(opt._state[id(p)]["moment1"]))


def test_proximal_ftrl_decayed_adagrad_train(fresh_programs):
    """The four long-tail fluid optimizers (reference optimizer.py:
    DecayedAdagrad/ProximalGD/ProximalAdagrad/Ftrl) drive a regression
    loss down through the Executor."""
    import numpy as np

    import paddle_tpu.fluid as fluid

    for opt_cls in ("DecayedAdagradOptimizer", "ProximalGDOptimizer",
                    "ProximalAdagradOptimizer", "FtrlOptimizer"):
        main, startup = fluid.Program(), fluid.Program()
        from paddle_tpu.fluid import framework, unique_name
        from paddle_tpu.fluid.executor import Scope, scope_guard

        with framework.program_guard(main, startup), unique_name.guard():
            x = fluid.data("x", [-1, 8], "float32")
            yt = fluid.data("yt", [-1, 1], "float32")
            pred = fluid.layers.fc(x, 1)
            loss = fluid.layers.reduce_mean(
                fluid.layers.loss.square_error_cost(pred, yt))
            getattr(fluid.optimizer, opt_cls)(0.1).minimize(loss)
            with scope_guard(Scope()):
                exe = fluid.Executor()
                exe.run(startup)
                rng = np.random.RandomState(0)
                W = rng.randn(8, 1).astype("float32")
                losses = []
                for _ in range(60):
                    X = rng.randn(16, 8).astype("float32")
                    l, = exe.run(main, feed={"x": X, "yt": X @ W},
                                 fetch_list=[loss.name])
                    losses.append(float(np.asarray(l)))
        assert losses[-1] < losses[0], (opt_cls, losses[::20])
