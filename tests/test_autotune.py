"""Self-tuning compile pipeline (paddle_tpu/tune, ISSUE 19).

Three layers of proof, mirroring the AOT-cache suite it rides beside:

* in-process unit tests — candidate-space content gating, the
  TunedConfig token discipline (flipping any tuned dimension changes
  the signature-join token), winner selection (a committed winner can
  never be slower than the measured default), record store/load with
  drift + corruption as counted misses, and `PADDLE_AUTOTUNE=off` as a
  byte-identical bypass (empty cache-key component, no overrides);
* in-process acceptance — a force-mode Executor run on the toy
  conv+bn trunk evaluates >= 3 distinct candidates, commits a winner
  whose measured step time is <= the default's, and a memo-reset
  replay resolves it from the record with zero new trials;
* cross-process acceptance — a FRESH process replays the persisted
  winner (`autotune_trials == 0`, `autotune_record_hits >= 1`) with
  outputs identical to the searching process, and a volatile-signature
  drift (quantized-collectives flip) forces a full re-tune.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu
import paddle_tpu.fluid as fluid
from paddle_tpu import profiler, tune
from paddle_tpu.fluid import flags, framework, unique_name
from paddle_tpu.fluid.executor import Scope, scope_guard
from paddle_tpu.tune import TunedConfig, record, space, tuner

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "fixtures", "autotune_worker.py")


def _stat(name):
    return profiler.get_int_stats().get(name, 0)


@pytest.fixture
def tuned_at(tmp_path):
    """Point the tuner at a test-local record dir in 'on' mode; drop
    the in-process memos and restore every flag after."""
    old = {k: flags.flag(k) for k in
           ("autotune", "autotune_dir", "autotune_trial_steps")}
    flags.set_flags({"FLAGS_autotune": "on",
                     "FLAGS_autotune_dir": str(tmp_path),
                     "FLAGS_autotune_trial_steps": 2})
    tune.reset_memo()
    try:
        yield str(tmp_path)
    finally:
        flags.set_flags({f"FLAGS_{k}": v for k, v in old.items()})
        tune.reset_memo()


def _conv_bn_eval_program():
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup), unique_name.guard():
        x = fluid.data("x", [4, 3, 12, 12], "float32")
        y = fluid.layers.conv2d(x, 8, 3, padding=1, bias_attr=True)
        y = fluid.layers.batch_norm(y, act="relu", is_test=True)
    return main, startup, y.name


# ---------------------------------------------------------------------------
# candidate space
# ---------------------------------------------------------------------------

class TestCandidateSpace:
    def test_conv_bn_program_yields_three_plus(self, tuned_at):
        main, _, _ = _conv_bn_eval_program()
        cands = space.program_candidates(main)
        assert len(cands) >= 3
        assert cands[0].is_default()
        tokens = [c.token() for c in cands]
        assert len(set(tokens)) == len(tokens)  # all distinct points
        labels = " ".join(c.label() for c in cands)
        assert "fold_bn=on" in labels
        assert "layout_optimize=off" in labels

    def test_glue_program_is_never_searched(self, tuned_at):
        main, startup = framework.Program(), framework.Program()
        with framework.program_guard(main, startup), \
                unique_name.guard():
            x = fluid.data("x", [4, 8], "float32")
            fluid.layers.relu(x)
        assert len(space.program_candidates(main)) == 1

    def test_grad_program_gets_no_fold_bn_candidate(self, tuned_at):
        main, startup = framework.Program(), framework.Program()
        with framework.program_guard(main, startup), \
                unique_name.guard():
            x = fluid.data("x", [4, 3, 12, 12], "float32")
            y = fluid.layers.conv2d(x, 8, 3, padding=1)
            y = fluid.layers.batch_norm(y, is_test=True)
            loss = fluid.layers.reduce_mean(y)
            fluid.append_backward(loss)
        labels = " ".join(c.label()
                          for c in space.program_candidates(main))
        assert "fold_bn" not in labels

    def test_candidate_cap_never_drops_default(self, tuned_at):
        flags.set_flags({"FLAGS_autotune_max_candidates": 1})
        try:
            main, _, _ = _conv_bn_eval_program()
            cands = space.program_candidates(main)
            assert len(cands) == 1 and cands[0].is_default()
        finally:
            flags.set_flags({"FLAGS_autotune_max_candidates": 6})

    def test_kernel_and_bucket_candidates(self, tuned_at):
        ks = space.kernel_candidates(["ffn"])
        assert [c.kernels.get("ffn") for c in ks] == \
            [None, "xla", "pallas"]
        bs = space.bucket_candidates(64)
        assert bs[0].is_default()
        assert [8, 16, 32, 64] in [c.buckets for c in bs[1:]]
        assert [64] in [c.buckets for c in bs[1:]]


# ---------------------------------------------------------------------------
# TunedConfig token discipline (the signature join)
# ---------------------------------------------------------------------------

class TestTokenDiscipline:
    def test_every_dimension_moves_the_token(self):
        base = TunedConfig()
        variants = [
            TunedConfig(passes={"fold_bn": True}),
            TunedConfig(passes={"fold_bn": False}),
            TunedConfig(kernels={"ffn": "pallas"}),
            TunedConfig(kernels={"ffn": "xla"}),
            TunedConfig(buckets=[8, 16]),
            TunedConfig(mesh_axes={"data": 4}),
        ]
        tokens = [base.token()] + [v.token() for v in variants]
        assert len(set(tokens)) == len(tokens)

    def test_roundtrip_through_record_dict(self):
        cfg = TunedConfig(passes={"layout_optimize": False},
                          kernels={"ffn": "pallas"}, buckets=[16, 64])
        back = TunedConfig.from_dict(
            json.loads(json.dumps(cfg.to_dict())))
        assert back.token() == cfg.token()
        assert not back.is_default()

    def test_cache_key_joins_effective_config(self, tuned_at):
        main, _, _ = _conv_bn_eval_program()
        assert tune.cache_token(main) == ()  # untuned: empty component
        cfg = TunedConfig(passes={"fold_bn": True})
        with tune.config_override(cfg):
            tok = tune.cache_token(main)
            assert tok == (f"autotune={cfg.token()}",)
            assert tune.aot_token_component(main) == tok[0]
            assert tune.pass_overrides(main) == {"fold_bn": True}
        assert tune.cache_token(main) == ()

    def test_off_mode_is_total_bypass(self, tuned_at):
        """With a committed NON-default record on disk, off-mode still
        reports the empty token/overrides — the compile-cache key and
        lowered graph are byte-identical to pre-autotune."""
        main, _, _ = _conv_bn_eval_program()
        stable = record.stable_for_program(main)
        assert record.try_store(
            stable, TunedConfig(passes={"fold_bn": True}).to_dict())
        flags.set_flags({"FLAGS_autotune": "off"})
        tune.reset_memo()
        c0 = _stat("autotune_record_hits")
        assert tune.cache_token(main) == ()
        assert tune.aot_token_component(main) is None
        assert tune.pass_overrides(main) is None
        assert tune.kernel_choice("ffn") is None
        assert tune.resolve(main) is None
        assert _stat("autotune_record_hits") == c0  # record never read


# ---------------------------------------------------------------------------
# record store: drift and corruption are counted misses
# ---------------------------------------------------------------------------

class TestRecordStore:
    def test_store_load_roundtrip(self, tuned_at):
        main, _, _ = _conv_bn_eval_program()
        stable = record.stable_for_program(main)
        cfg = TunedConfig(passes={"fold_bn": True})
        h0, s0 = _stat("autotune_record_hits"), \
            _stat("autotune_record_stores")
        assert record.try_store(stable, cfg.to_dict(),
                                extra={"objective": "median_step_ms"})
        assert _stat("autotune_record_stores") == s0 + 1
        rec = record.try_load(stable)
        assert rec is not None
        assert _stat("autotune_record_hits") == h0 + 1
        assert TunedConfig.from_dict(rec["config"]).token() == \
            cfg.token()
        # commit is atomic: one .json, no .tmp-* litter
        names = os.listdir(tuned_at)
        assert [n for n in names if n.startswith(".tmp-")] == []

    def test_volatile_drift_is_counted_hard_miss(self, tuned_at):
        main, _, _ = _conv_bn_eval_program()
        stable = record.stable_for_program(main)
        record.try_store(stable, TunedConfig().to_dict())
        old_q = flags.flag("quant_collectives")
        flags.set_flags({"FLAGS_quant_collectives": "int8"})
        try:
            d0, m0 = (_stat("autotune_record_drift"),
                      _stat("autotune_record_misses"))
            assert record.try_load(stable) is None
            assert _stat("autotune_record_drift") == d0 + 1
            assert _stat("autotune_record_misses") == m0 + 1
        finally:
            flags.set_flags({"FLAGS_quant_collectives": old_q})
        assert record.try_load(stable) is not None  # original hits

    def test_corrupted_record_is_counted_miss_never_crash(
            self, tuned_at):
        main, _, _ = _conv_bn_eval_program()
        stable = record.stable_for_program(main)
        record.try_store(stable, TunedConfig().to_dict())
        (name,) = os.listdir(tuned_at)
        with open(os.path.join(tuned_at, name), "w") as f:
            f.write('{"truncat')
        e0, m0 = (_stat("autotune_record_errors"),
                  _stat("autotune_record_misses"))
        assert record.try_load(stable) is None
        assert _stat("autotune_record_errors") == e0 + 1
        assert _stat("autotune_record_misses") == m0 + 1
        tune.reset_memo()
        assert tune.resolve(main) is None  # resolution degrades, only


# ---------------------------------------------------------------------------
# winner selection
# ---------------------------------------------------------------------------

def _trial(cfg, step_ms, badness=None):
    t = tuner.Trial(cfg)
    t.step_ms = step_ms
    t.badness = badness
    return t


class TestWinnerSelection:
    def test_fastest_wins_outside_band(self):
        trials = [_trial(TunedConfig(), 10.0),
                  _trial(TunedConfig(passes={"fold_bn": True}), 7.0)]
        assert tuner._pick_winner(trials) is trials[1]

    def test_tie_break_prefers_better_roofline(self):
        """Within the 2% band the roofline verdict decides — but only
        among candidates not slower than the measured default."""
        fold = TunedConfig(passes={"fold_bn": True})
        sink = TunedConfig(passes={"transpose_sink": True})
        trials = [_trial(TunedConfig(), 10.3, badness=5),
                  _trial(fold, 10.0, badness=5),
                  _trial(sink, 10.1, badness=1)]
        assert tuner._pick_winner(trials) is trials[2]

    def test_tie_break_prefers_fewer_overrides(self):
        one = TunedConfig(passes={"fold_bn": True})
        two = TunedConfig(passes={"fold_bn": True,
                                  "layout_optimize": False})
        trials = [_trial(two, 10.0, badness=1),
                  _trial(one, 10.1, badness=1)]
        # two is fastest but within the band `one` ranks higher
        trials = [_trial(TunedConfig(), 20.0, badness=1)] + trials
        assert tuner._pick_winner(trials).config is one

    def test_winner_never_slower_than_default(self):
        """The acceptance contract: a tie-break can never commit a
        config that measured slower than the default."""
        slow = TunedConfig(passes={"fold_bn": True})
        trials = [_trial(TunedConfig(), 10.0, badness=5),
                  _trial(slow, 10.15, badness=0)]
        w = tuner._pick_winner(trials)
        assert w.step_ms <= trials[0].step_ms
        assert w.config.is_default()

    def test_all_failed_falls_back_to_default(self):
        t0 = tuner.Trial(TunedConfig())
        t1 = tuner.Trial(TunedConfig(passes={"fold_bn": True}))
        t0.error = t1.error = "Boom"
        assert tuner._pick_winner([t0, t1]) is t0


# ---------------------------------------------------------------------------
# in-process acceptance: force-mode search on the Executor path
# ---------------------------------------------------------------------------

class TestForcedSearch:
    def test_search_commits_winner_and_replays_from_record(
            self, tuned_at):
        main, startup, yname = _conv_bn_eval_program()
        rng = np.random.RandomState(5)
        xv = rng.rand(4, 3, 12, 12).astype("float32")
        scope = Scope()
        flags.set_flags({"FLAGS_autotune": "force"})
        with scope_guard(scope):
            exe = fluid.Executor()
            exe.run(startup)
            s0, t0, c0 = (_stat("autotune_searches"),
                          _stat("autotune_trials"),
                          _stat("autotune_commits"))
            (ref,) = exe.run(main, feed={"x": xv}, fetch_list=[yname])
            assert _stat("autotune_searches") == s0 + 1
            assert _stat("autotune_commits") == c0 + 1
            trials_run = _stat("autotune_trials") - t0
            assert trials_run >= 3  # >= 3 candidates, >= 1 step each
            # the committed record names >= 3 distinct measured
            # candidates and the winner is not slower than default
            (name,) = [n for n in os.listdir(tuned_at)
                       if n.endswith(".json")]
            with open(os.path.join(tuned_at, name)) as f:
                rec = json.load(f)
            rows = rec["extra"]["trials"]
            assert len(rows) >= 3
            assert len({r["token"] for r in rows}) == len(rows)
            scored = [r for r in rows if r["step_ms"] is not None]
            default_ms = rows[0]["step_ms"]
            winner_tok = TunedConfig.from_dict(rec["config"]).token()
            (winner_row,) = [r for r in scored
                             if r["token"] == winner_tok]
            assert winner_row["step_ms"] <= default_ms
            # a second run is a pure cache hit: no new search/trials
            t1 = _stat("autotune_trials")
            (got,) = exe.run(main, feed={"x": xv}, fetch_list=[yname])
            assert _stat("autotune_trials") == t1
            assert _stat("autotune_searches") == s0 + 1
            np.testing.assert_allclose(np.asarray(got),
                                       np.asarray(ref),
                                       rtol=1e-4, atol=1e-5)
            # memo reset = fresh-process approximation: the winner
            # resolves from the record with zero trial dispatches
            tune.reset_memo()
            h0 = _stat("autotune_record_hits")
            (rep,) = exe.run(main, feed={"x": xv}, fetch_list=[yname])
            assert _stat("autotune_record_hits") >= h0 + 1
            assert _stat("autotune_trials") == t1
            np.testing.assert_allclose(np.asarray(rep),
                                       np.asarray(ref),
                                       rtol=1e-4, atol=1e-5)

    def test_glue_program_force_mode_never_searches(self, tuned_at):
        flags.set_flags({"FLAGS_autotune": "force"})
        main, startup = framework.Program(), framework.Program()
        with framework.program_guard(main, startup), \
                unique_name.guard():
            x = fluid.data("x", [4, 8], "float32")
            y = fluid.layers.relu(x)
        with scope_guard(Scope()):
            exe = fluid.Executor()
            exe.run(startup)
            s0 = _stat("autotune_searches")
            exe.run(main, feed={"x": np.ones((4, 8), "float32")},
                    fetch_list=[y.name])
            assert _stat("autotune_searches") == s0


# ---------------------------------------------------------------------------
# functional-path tuning: kernel choice + bucket ladders
# ---------------------------------------------------------------------------

class TestFunctionalPath:
    def test_kernel_choice_reads_thread_local_only(self, tuned_at):
        assert tune.kernel_choice("ffn") is None
        with tune.config_override(
                TunedConfig(kernels={"ffn": "pallas"})):
            assert tune.kernel_choice("ffn") == "pallas"
            assert tune.kernel_choice("other") is None
        assert tune.kernel_choice("ffn") is None

    def test_tune_callable_commits_and_resolves(self, tuned_at):
        import jax.numpy as jnp

        def fn(x):
            return jnp.tanh(x) * 2.0

        args = (jnp.ones((8, 8), jnp.float32),)
        cfg = tuner.tune_callable(fn, args, kernels=["ffn"],
                                  token="test-callable", steps=1)
        assert cfg is not None
        resolved = tune.resolve_callable("test-callable")
        assert resolved is not None
        assert resolved.token() == cfg.token()

    def test_tune_buckets_commits_ladder_runner_resolves(
            self, tuned_at):
        import jax.numpy as jnp

        from paddle_tpu.serving.bucketing import BucketedRunner

        def fn(x):
            return jnp.maximum(x, 0.0)

        ladder = tuner.tune_buckets(fn, sample_rows=[3, 9, 20],
                                    max_batch=32, token="test-model",
                                    trailing=(4,), steps=1)
        assert ladder and ladder == sorted(set(ladder))
        runner = BucketedRunner(fn, [8, 16, 32],
                                aot_token="test-model")
        assert runner.buckets == sorted(set(ladder))
        # a different token keeps the caller's ladder
        other = BucketedRunner(fn, [8, 16, 32], aot_token="other")
        assert other.buckets == [8, 16, 32]


# ---------------------------------------------------------------------------
# cross-process acceptance (the aot_worker subprocess idiom)
# ---------------------------------------------------------------------------

def _run_worker(out, tune_dir, mode="force", quant=None, steps=2):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env["PADDLE_AOT_CACHE"] = "off"
    env["PADDLE_AUTOTUNE"] = mode
    env["PADDLE_AUTOTUNE_DIR"] = str(tune_dir)
    env["PADDLE_AUTOTUNE_TRIAL_STEPS"] = "2"
    env["AT_STEPS"] = str(steps)
    env.pop("PADDLE_QUANT_COLLECTIVES", None)
    if quant is not None:
        env["PADDLE_QUANT_COLLECTIVES"] = quant
    proc = subprocess.run([sys.executable, WORKER, str(out)], env=env,
                          cwd=REPO, capture_output=True, text=True,
                          timeout=560)
    assert proc.returncode == 0, proc.stderr[-2000:]
    with open(out) as f:
        return json.load(f)


@pytest.fixture(scope="module")
def cold_and_warm(tmp_path_factory):
    """One cold force-mode search populating a record dir + one warm
    restart replaying against it (shared below — subprocesses are the
    expensive part)."""
    root = tmp_path_factory.mktemp("autotune_accept")
    tdir = root / "tuning"
    tdir.mkdir()
    cold = _run_worker(root / "cold.json", tdir)
    warm = _run_worker(root / "warm.json", tdir)
    return {"dir": tdir, "root": root, "cold": cold, "warm": warm}


@pytest.mark.slow
class TestCrossProcessAcceptance:
    def test_cold_searches_and_commits(self, cold_and_warm):
        cold = cold_and_warm["cold"]
        assert cold["stats"].get("autotune_searches", 0) == 1
        assert cold["stats"].get("autotune_commits", 0) == 1
        assert cold["stats"].get("autotune_trials", 0) >= 3
        recs = [n for n in os.listdir(cold_and_warm["dir"])
                if n.endswith(".json")]
        assert len(recs) == 1

    def test_warm_replays_with_zero_trials(self, cold_and_warm):
        # THE acceptance line: a fresh process resolves the persisted
        # winner on first compile with zero search cost
        warm = cold_and_warm["warm"]
        assert warm["stats"].get("autotune_trials", 0) == 0
        assert warm["stats"].get("autotune_searches", 0) == 0
        assert warm["stats"].get("autotune_record_hits", 0) >= 1

    def test_warm_outputs_match_cold(self, cold_and_warm):
        np.testing.assert_array_equal(
            np.asarray(cold_and_warm["cold"]["out"]),
            np.asarray(cold_and_warm["warm"]["out"]))

    def test_off_bypasses_and_matches_untuned_numerics(
            self, cold_and_warm, tmp_path):
        off = _run_worker(tmp_path / "off.json", cold_and_warm["dir"],
                          mode="off")
        assert off["stats"] == {}  # no autotune_* counter ever moved
        # the tuned config may fold/relayout (float reassociation):
        # tolerance-level parity, not byte equality, is the contract
        np.testing.assert_allclose(
            np.asarray(off["out"]),
            np.asarray(cold_and_warm["cold"]["out"]),
            rtol=1e-4, atol=1e-5)

    def test_volatile_drift_forces_retune(self, cold_and_warm,
                                          tmp_path):
        """PADDLE_QUANT_COLLECTIVES flipped between processes: the
        committed winner rides the OLD volatile signature — the new
        process must drift-miss and re-run the search."""
        drifted = _run_worker(tmp_path / "drift.json",
                              cold_and_warm["dir"], quant="int8")
        assert drifted["stats"].get("autotune_record_hits", 0) == 0
        assert drifted["stats"].get("autotune_record_drift", 0) >= 1
        assert drifted["stats"].get("autotune_searches", 0) == 1
