"""Real-model pipeline parallelism (VERDICT r3 task 9): BERT-tiny
through a 4-stage NON-UNIFORM pipeline — embedding stage, sharded
encoder-block stages, pooler+heads stage — must match the non-pipelined
model's loss trajectory (reference behavior: PipelineTrainer/
SectionWorker ran sectioned BERT programs,
/root/reference/paddle/fluid/framework/section_worker.cc:44)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.jit import functional_call, functional_state
from paddle_tpu.models import bert
from paddle_tpu.parallel.mesh import make_mesh


def _nodrop_cfg(layers=4):
    cfg = bert.BertConfig.tiny(num_hidden_layers=layers)
    cfg.hidden_dropout_prob = 0.0
    cfg.attention_probs_dropout_prob = 0.0
    return cfg


def test_bert_pipeline_matches_nonpipelined():
    cfg = _nodrop_cfg()
    paddle.seed(0)
    model = bert.BertForPretraining(cfg)
    b = bert.fake_batch(cfg, 8, 128, num_masked=10, seed=7)

    params0 = functional_state(model)
    crit = bert.BertPretrainingCriterion(cfg.vocab_size)

    def ref_loss(params, batch):
        am = (batch["attention_mask"] != 0)[:, None, None, :]
        (mlm, nsp), _ = functional_call(
            model, params, batch["input_ids"], batch["token_type_ids"],
            attention_mask=am,
            masked_positions=batch["masked_positions"])
        from paddle_tpu.nn.layer.layers import Tensor as T

        return crit(T(mlm), T(nsp), T(batch["masked_labels"]),
                    T(batch["nsp_labels"]))._value

    @jax.jit
    def ref_step(params, batch):
        loss, g = jax.value_and_grad(ref_loss)(params, batch)
        return {k: v - 1e-3 * g[k] for k, v in params.items()}, loss

    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    step, state = bert.build_pipeline_pretrain_step(
        model, mesh, num_microbatches=4)

    rp = {k: jnp.array(v) for k, v in params0.items()}
    ref_losses, pp_losses = [], []
    for _ in range(4):
        rp, rl = ref_step(rp, b)
        state, pl = step(state, b)
        ref_losses.append(float(rl))
        pp_losses.append(float(pl))
    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-4)


def test_block_params_are_stage_sharded():
    """The pipeline's memory win: encoder block params live sharded over
    the pp axis (each stage holds 1/n of the blocks), not replicated."""
    cfg = _nodrop_cfg()
    paddle.seed(0)
    model = bert.BertForPretraining(cfg)
    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    step, state = bert.build_pipeline_pretrain_step(
        model, mesh, num_microbatches=4)
    b = bert.fake_batch(cfg, 8, 128, num_masked=10, seed=7)
    state, _ = step(state, b)
    _, block_p, _ = state["params"]
    w = block_p["self_attn.q_proj.weight"]  # (n_stages, k, H, H)
    assert w.shape[0] == 4
    # after a jitted step with shard_map in_specs P(axis), the updated
    # stacked leaves come back partitioned across the 4 stage devices
    assert len(w.sharding.device_set) == 4


def test_microbatch_count_must_divide_batch():
    cfg = _nodrop_cfg()
    paddle.seed(0)
    model = bert.BertForPretraining(cfg)
    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    step, state = bert.build_pipeline_pretrain_step(
        model, mesh, num_microbatches=3)
    b = bert.fake_batch(cfg, 8, 128, num_masked=10, seed=7)
    with pytest.raises(AssertionError):
        step(state, b)
