"""Real-model pipeline parallelism (VERDICT r3 task 9): BERT-tiny
through a 4-stage NON-UNIFORM pipeline — embedding stage, sharded
encoder-block stages, pooler+heads stage — must match the non-pipelined
model's loss trajectory (reference behavior: PipelineTrainer/
SectionWorker ran sectioned BERT programs,
/root/reference/paddle/fluid/framework/section_worker.cc:44)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.jit import functional_call, functional_state
from paddle_tpu.models import bert
from paddle_tpu.parallel.mesh import make_mesh


def _nodrop_cfg(layers=4):
    cfg = bert.BertConfig.tiny(num_hidden_layers=layers)
    cfg.hidden_dropout_prob = 0.0
    cfg.attention_probs_dropout_prob = 0.0
    return cfg


def _ref_sgd_step(model, cfg, lr=1e-3):
    """Non-pipelined oracle: jitted full-model SGD step (the trajectory
    every pipeline variant must match)."""
    crit = bert.BertPretrainingCriterion(cfg.vocab_size)

    def ref_loss(params, batch):
        am = (batch["attention_mask"] != 0)[:, None, None, :]
        (mlm, nsp), _ = functional_call(
            model, params, batch["input_ids"], batch["token_type_ids"],
            attention_mask=am,
            masked_positions=batch["masked_positions"])
        from paddle_tpu.nn.layer.layers import Tensor as T

        return crit(T(mlm), T(nsp), T(batch["masked_labels"]),
                    T(batch["nsp_labels"]))._value

    @jax.jit
    def ref_step(params, batch):
        loss, g = jax.value_and_grad(ref_loss)(params, batch)
        return {k: v - lr * g[k] for k, v in params.items()}, loss

    return ref_step


def test_bert_pipeline_matches_nonpipelined():
    cfg = _nodrop_cfg()
    paddle.seed(0)
    model = bert.BertForPretraining(cfg)
    b = bert.fake_batch(cfg, 8, 128, num_masked=10, seed=7)

    params0 = functional_state(model)
    ref_step = _ref_sgd_step(model, cfg)

    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    step, state = bert.build_pipeline_pretrain_step(
        model, mesh, num_microbatches=4)

    rp = {k: jnp.array(v) for k, v in params0.items()}
    ref_losses, pp_losses = [], []
    for _ in range(4):
        rp, rl = ref_step(rp, b)
        state, pl = step(state, b)
        ref_losses.append(float(rl))
        pp_losses.append(float(pl))
    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-4)


def test_block_params_are_stage_sharded():
    """The pipeline's memory win: encoder block params live sharded over
    the pp axis (each stage holds 1/n of the blocks), not replicated."""
    cfg = _nodrop_cfg()
    paddle.seed(0)
    model = bert.BertForPretraining(cfg)
    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    step, state = bert.build_pipeline_pretrain_step(
        model, mesh, num_microbatches=4)
    b = bert.fake_batch(cfg, 8, 128, num_masked=10, seed=7)
    state, _ = step(state, b)
    _, block_p, _ = state["params"]
    w = block_p["self_attn.q_proj.weight"]  # (n_stages, k, H, H)
    assert w.shape[0] == 4
    # after a jitted step with shard_map in_specs P(axis), the updated
    # stacked leaves come back partitioned across the 4 stage devices
    assert len(w.sharding.device_set) == 4


def test_bert_pipeline_dp_pp_composition():
    """dp×pp (2×4 on the 8-device mesh): batch sharded over dp, the
    pipeline running per dp group, dp grad sync via shard_map AD's psum
    — losses must match the single-device non-pipelined trajectory
    (VERDICT r4 weak #5 / next #7)."""
    cfg = _nodrop_cfg()
    paddle.seed(0)
    model = bert.BertForPretraining(cfg)
    b = bert.fake_batch(cfg, 8, 128, num_masked=10, seed=7)

    params0 = functional_state(model)
    ref_step = _ref_sgd_step(model, cfg)

    mesh = make_mesh({"dp": 2, "pp": 4})
    step, state = bert.build_pipeline_pretrain_step(
        model, mesh, num_microbatches=2, dp_axis="dp")

    rp = {k: jnp.array(v) for k, v in params0.items()}
    ref_losses, pp_losses = [], []
    for _ in range(4):
        rp, rl = ref_step(rp, b)
        state, pl = step(state, b)
        ref_losses.append(float(rl))
        pp_losses.append(float(pl))
    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-4)


def _pp_step(vocab=None, remat=False, layers=4):
    cfg = _nodrop_cfg(layers)
    if vocab:
        cfg.vocab_size = vocab
    paddle.seed(0)
    model = bert.BertForPretraining(cfg)
    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    step, state = bert.build_pipeline_pretrain_step(
        model, mesh, num_microbatches=4, remat_stages=remat)
    b = bert.fake_batch(cfg, 8, 128, num_masked=10, seed=7)
    return cfg, model, step, state, b


def test_pipeline_block_params_arg_bytes_sharded():
    """Executable-boundary memory proof (VERDICT r4 next #8): the
    compiled step's per-device argument bytes must reflect 1/n-sharded
    encoder blocks, not replicated full params."""
    cfg, model, step, state, b = _pp_step()
    ma = step.lower(state, b).compile().memory_analysis()
    emb_p, block_p, last_p = state["params"]

    def nbytes(tree):
        return sum(np.asarray(v).nbytes
                   for v in jax.tree_util.tree_leaves(tree))

    full = nbytes(state["params"])
    # per-device: replicated emb/head + 1/4 of the blocks (+ the batch)
    expect = nbytes(emb_p) + nbytes(last_p) + nbytes(block_p) / 4
    batch_bytes = sum(np.asarray(v).nbytes for v in b.values())
    assert ma.argument_size_in_bytes < expect + batch_bytes + 2e5, \
        (ma.argument_size_in_bytes, expect, full)
    assert ma.argument_size_in_bytes < 0.8 * full


def test_pipeline_remat_reduces_stashed_activations():
    """remat_stages must measurably shrink peak temp bytes (the
    activation stash) while losses stay bit-identical."""
    _, _, step, state, b = _pp_step(remat=False)
    temp_plain = step.lower(state, b).compile() \
        .memory_analysis().temp_size_in_bytes
    _, l_plain = step(state, b)

    _, _, step_r, state_r, b_r = _pp_step(remat=True)
    temp_remat = step_r.lower(state_r, b_r).compile() \
        .memory_analysis().temp_size_in_bytes
    _, l_remat = step_r(state_r, b_r)

    assert temp_remat < 0.9 * temp_plain, (temp_remat, temp_plain)
    np.testing.assert_allclose(float(l_remat), float(l_plain), rtol=1e-6)


def test_pipeline_head_cost_not_per_tick():
    """Schedule-efficiency proof (VERDICT r4 weak #4): with a dominant
    MLM head (vocab 30k), the pipelined step's per-device flops must
    stay within a small factor of the non-pipelined step's — the head
    is hoisted out of the tick scan, NOT evaluated (m+n-1) times.  A
    compute-and-mask schedule fails this bound (head would cost ~7x)."""
    def flops_of(compiled):
        # cost_analysis() is a per-device LIST on the jax 0.4.x line,
        # a flat dict on current jax
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return cost["flops"]

    cfg, model, step, state, b = _pp_step(vocab=30522)
    pp_flops = flops_of(step.lower(state, b).compile())

    params0 = functional_state(model)
    ref_step = _ref_sgd_step(model, cfg)

    rp = {k: jnp.array(v) for k, v in params0.items()}
    ref_flops = flops_of(ref_step.lower(rp, b).compile())
    # per-device pipeline overhead vs the whole model on one device:
    # bubbles re-run blocks ((m+n-1)/m = 1.75x on the block share) and
    # every device runs the hoisted embedding+head batch — but never
    # per tick.  3x headroom stays far below the ~7x mask-schedule cost.
    assert pp_flops < 3.0 * ref_flops, (pp_flops, ref_flops)


def test_microbatch_count_must_divide_batch():
    cfg = _nodrop_cfg()
    paddle.seed(0)
    model = bert.BertForPretraining(cfg)
    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    step, state = bert.build_pipeline_pretrain_step(
        model, mesh, num_microbatches=3)
    b = bert.fake_batch(cfg, 8, 128, num_masked=10, seed=7)
    with pytest.raises(AssertionError):
        step(state, b)
