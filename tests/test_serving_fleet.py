"""Multi-tenant model fleet (serving/registry.py, ISSUE 17).

Co-tenancy proofs: N named models share one Engine; per-tenant quotas
reject without queue-squatting; priority aging un-starves low-priority
tenants; register/unregister/hot-swap are live; per-tenant compile
caches evict with byte release into the memprof ledger and never touch
a neighbour's entries; every tenant exports its own metric family.
"""

import threading
import time

import numpy as np
import pytest

from paddle_tpu import obs, profiler, serving
from paddle_tpu.serving import metrics as smetrics
from paddle_tpu.serving.batcher import DynamicBatcher, Request
from paddle_tpu.serving.registry import _TenantCache


def _stat(name):
    return profiler.get_int_stats().get(name, 0)


def _mk_registry(**cfg_kw):
    cfg = serving.EngineConfig(max_batch_size=8, max_queue_delay_ms=0.0,
                               max_queue=64, **cfg_kw)
    return serving.ModelRegistry(cfg)


X = np.ones((2, 4), np.float32)


class TestFleetBasics:
    def test_three_models_route_independently(self):
        with _mk_registry() as reg:
            reg.register("double", lambda x: [x * 2.0], quota=16)
            reg.register("inc", lambda x: [x + 1.0], quota=16)
            reg.register("neg", lambda x: [-x], quota=16)
            assert reg.model_names() == ["double", "inc", "neg"]
            np.testing.assert_array_equal(
                np.asarray(reg.infer("double", [X], timeout=120)[0]),
                X * 2.0)
            np.testing.assert_array_equal(
                np.asarray(reg.infer("inc", [X], timeout=120)[0]),
                X + 1.0)
            np.testing.assert_array_equal(
                np.asarray(reg.infer("neg", [X], timeout=120)[0]), -X)

    def test_per_tenant_series_exported(self):
        with _mk_registry() as reg:
            reg.register("telemetry_t", lambda x: [x], quota=16)
            reg.infer("telemetry_t", [X], timeout=120)
            s = profiler.get_int_stats()
            assert s.get(smetrics.tenant_stat(
                "telemetry_t", "requests_total"), 0) >= 1
            assert s.get(smetrics.tenant_stat(
                "telemetry_t", "completed_total"), 0) >= 1
            assert smetrics.latency_stats(smetrics.tenant_stat(
                "telemetry_t", "request_ms"))["count"] >= 1

    def test_tenant_series_reach_metrics_endpoint_series(self):
        """The telemetry Collector folds EVERY profiler int stat into a
        series — the per-tenant names ARE the /metrics surface."""
        from paddle_tpu.obs import telemetry

        with _mk_registry() as reg:
            reg.register("scrape_t", lambda x: [x], quota=16)
            reg.infer("scrape_t", [X], timeout=120)
            c = telemetry.Collector(sources=telemetry.default_sources(),
                                    sample_s=3600.0)
            c.sample_once()
            names = c.store.names()
            assert smetrics.tenant_stat("scrape_t",
                                        "requests_total") in names
            rendered = telemetry.prometheus_text(c)
            assert "serving_tenant_scrape_t_requests_total" in rendered
            # the per-tenant queue depth is a LEVEL, not a counter —
            # matched by shape since tenant names are dynamic
            qname = smetrics.tenant_stat("scrape_t", "queued")
            assert telemetry._is_gauge_stat(qname)
            if qname in names:
                assert c.store._series[qname].kind == telemetry.GAUGE

    def test_unknown_model_fails_fast(self):
        with _mk_registry() as reg:
            reg.register("known", lambda x: [x], quota=4)
            with pytest.raises(serving.EngineClosed):
                reg.submit("ghost", [X])

    def test_stats_view(self):
        with _mk_registry() as reg:
            reg.register("sv", lambda x: [x], quota=4)
            reg.infer("sv", [X], timeout=120)
            st = reg.stats("sv")
            assert st["requests_total"] >= 1
            assert st["completed_total"] >= 1
            assert st["rejected_total"] == 0
            assert "latency" in st

    def test_bundle_meta_carries_tenants(self):
        """Flight-recorder bundles must say WHICH tenants shared the
        device (serving/registry.active_tenants feeds obs bundle
        meta)."""
        from paddle_tpu.serving.registry import active_tenants

        with _mk_registry() as reg:
            reg.register("meta_a", lambda x: [x], quota=4)
            reg.register("meta_b", lambda x: [x * 2.0], quota=4)
            names = active_tenants()
            assert "meta_a" in names and "meta_b" in names
        assert "meta_a" not in active_tenants()


class TestQuotasAndPriority:
    def test_over_quota_tenant_rejected_without_queue_squatting(self):
        """quota=2 tenant: 3rd submit raises EngineOverloaded with the
        tenant counter bumped, while a sibling tenant still admits —
        the shared queue never filled."""
        eng = serving.Engine(
            config=serving.EngineConfig(max_queue=64), start=False)
        eng.add_model("greedy", lambda x: [x], quota=2)
        eng.add_model("polite", lambda x: [x], quota=2)
        eng.submit([X], model="greedy")
        eng.submit([X], model="greedy")
        r0 = _stat(smetrics.tenant_stat("greedy", "rejected_total"))
        with pytest.raises(serving.EngineOverloaded) as ei:
            eng.submit([X], model="greedy")
        assert ei.value.resource == "tenant:greedy"
        assert ei.value.bound == 2
        assert _stat(smetrics.tenant_stat(
            "greedy", "rejected_total")) == r0 + 1
        # the noisy neighbour consumed only ITS quota: the shared bound
        # has room and the polite tenant admits instantly
        eng.submit([X], model="polite")
        assert eng._batcher.tenant_depth("greedy") == 2
        assert eng._batcher.tenant_depth("polite") == 1

    def test_quota_slots_return_on_dequeue(self):
        b = DynamicBatcher(max_batch_size=8, max_queue_delay_ms=0.0)
        b.set_tenant("t", quota=1)
        b.submit(Request([X], tenant="t"))
        with pytest.raises(serving.EngineOverloaded):
            b.submit(Request([X], tenant="t"))
        batch = b.next_batch(timeout=1.0)
        assert [r.tenant for r in batch] == ["t"]
        b.submit(Request([X], tenant="t"))  # slot came back

    def test_batches_never_mix_tenants(self):
        b = DynamicBatcher(max_batch_size=8, max_queue_delay_ms=0.0)
        b.set_tenant("a", quota=None)
        b.set_tenant("b", quota=None)
        b.submit(Request([X], tenant="a"))
        b.submit(Request([X], tenant="b"))
        b.submit(Request([X], tenant="a"))
        batch = b.next_batch(timeout=1.0)
        assert len(set(r.tenant for r in batch)) == 1

    def test_priority_wins_fresh(self):
        b = DynamicBatcher(max_batch_size=8, max_queue_delay_ms=0.0,
                           aging_ms=10_000.0)
        b.set_tenant("lo", priority=0.0)
        b.set_tenant("hi", priority=5.0)
        b.submit(Request([X], tenant="lo"))
        b.submit(Request([X], tenant="hi"))
        batch = b.next_batch(timeout=1.0)
        assert batch[0].tenant == "hi"

    def test_aging_unstarves_low_priority(self):
        """A request that waited longer than priority_gap * aging_ms
        outbids a fresh high-priority one: starvation freedom."""
        b = DynamicBatcher(max_batch_size=8, max_queue_delay_ms=0.0,
                           aging_ms=5.0)
        b.set_tenant("lo", priority=0.0)
        b.set_tenant("hi", priority=5.0)
        b.submit(Request([X], tenant="lo"))
        time.sleep(0.06)  # 60ms / 5ms aging = +12 effective > 5
        b.submit(Request([X], tenant="hi"))
        batch = b.next_batch(timeout=1.0)
        assert batch[0].tenant == "lo"

    def test_aging_unstarves_under_continuous_flood(self):
        """Integration: a high-priority flood plus one low-priority
        request through a LIVE engine — the low request completes while
        the flood is still running (aged past the fixed priority)."""
        cfg = serving.EngineConfig(max_batch_size=4,
                                   max_queue_delay_ms=0.0,
                                   max_queue=256)
        with serving.ModelRegistry(cfg) as reg:
            reg.register("flood", lambda x: [x * 2.0], quota=None,
                         priority=50.0)
            reg.register("starved", lambda x: [x + 1.0], quota=None,
                         priority=0.0)
            reg.engine._batcher.aging_ms = 2.0
            # warm both models so the flood loop is pure dispatch
            reg.infer("flood", [X], timeout=120)
            reg.infer("starved", [X], timeout=120)

            stop = threading.Event()

            def flooder():
                while not stop.is_set():
                    try:
                        reg.submit("flood", [X])
                    except serving.EngineOverloaded:
                        time.sleep(0.001)

            threads = [threading.Thread(target=flooder)
                       for _ in range(2)]
            for t in threads:
                t.start()
            try:
                # the flood may hold the global queue at its bound;
                # admission itself is allowed to bounce — starvation
                # freedom is about what happens AFTER we're queued
                deadline = time.time() + 10.0
                resp = None
                while resp is None:
                    try:
                        resp = reg.submit("starved", [X])
                    except serving.EngineOverloaded:
                        if time.time() > deadline:
                            raise
                        time.sleep(0.002)
                out = resp.result(timeout=30.0)  # must NOT starve
                np.testing.assert_array_equal(np.asarray(out[0]),
                                              X + 1.0)
            finally:
                stop.set()
                for t in threads:
                    t.join()


class TestLiveMembership:
    def test_hot_swap_without_draining_sibling(self):
        with _mk_registry() as reg:
            reg.register("stable_t", lambda x: [x + 1.0], quota=16)
            reg.register("swapped", lambda x: [x * 2.0], quota=16)
            np.testing.assert_array_equal(
                np.asarray(reg.infer("swapped", [X], timeout=120)[0]),
                X * 2.0)
            reg.register("swapped", lambda x: [x * 10.0], quota=16)
            np.testing.assert_array_equal(
                np.asarray(reg.infer("swapped", [X], timeout=120)[0]),
                X * 10.0)
            # the sibling never paused
            np.testing.assert_array_equal(
                np.asarray(reg.infer("stable_t", [X], timeout=120)[0]),
                X + 1.0)

    def test_unregister_cancels_only_that_tenant(self):
        eng = serving.Engine(
            config=serving.EngineConfig(max_queue=64), start=False)
        eng.add_model("doomed", lambda x: [x], quota=8)
        eng.add_model("survivor", lambda x: [x], quota=8)
        doomed = [eng.submit([X], model="doomed") for _ in range(3)]
        alive = eng.submit([X], model="survivor")
        eng.remove_model("doomed")
        for resp in doomed:
            with pytest.raises(serving.RequestCancelled):
                resp.result(timeout=1.0)
        assert not alive.done()
        assert eng._batcher.tenant_depth("survivor") == 1

    def test_unregistered_tenant_requests_fail_not_hang(self):
        """Race window: requests queued when their model is removed
        with cancel_queued=False fail at dispatch resolution — the
        dispatch loop keeps serving everyone else."""
        with _mk_registry() as reg:
            reg.register("vanish", lambda x: [x], quota=8)
            reg.register("remain", lambda x: [x * 3.0], quota=8)
            reg.infer("remain", [X], timeout=120)  # warm
            reg.engine.remove_model("vanish", cancel_queued=False)
            with pytest.raises(serving.EngineClosed):
                reg.infer("vanish", [X], timeout=10.0)
            np.testing.assert_array_equal(
                np.asarray(reg.infer("remain", [X], timeout=120)[0]),
                X * 3.0)


class TestPerTenantCacheEviction:
    def test_eviction_releases_bytes_every_time(self):
        """capacity-1 tenant cache under signature pressure: every new
        signature evicts the previous entry, the memprof ledger entry
        shrinks (or vanishes) at EVERY eviction, and the shared +
        per-tenant eviction counters advance."""
        with _mk_registry() as reg:
            reg.register("churn", lambda x: [x * 2.0], quota=16,
                         cache_capacity=1)
            ledger_name = "serving.churn.compile_cache"

            def ledger_bytes():
                return obs.memory_ledger()["entries"].get(
                    ledger_name, 0)

            widths = (4, 6, 8, 10)
            evicted0 = _stat("compile_cache_evicted_bytes")
            tenant0 = _stat(smetrics.tenant_stat("churn",
                                                 "cache_evictions"))
            peak = 0
            for i, w in enumerate(widths):
                x = np.ones((2, w), np.float32)
                np.testing.assert_array_equal(
                    np.asarray(reg.infer("churn", [x],
                                         timeout=120)[0]), x * 2.0)
                now = ledger_bytes()
                assert now > 0
                # capacity 1: the ledger never accumulates signatures —
                # each eviction released the previous executable
                if i > 0:
                    assert now <= peak * 2
                peak = max(peak, now)
            assert _stat(smetrics.tenant_stat(
                "churn", "cache_evictions")) >= tenant0 + len(widths) - 1
            assert _stat("compile_cache_evicted_bytes") > evicted0

    def test_no_cross_tenant_eviction(self):
        """One tenant's churn can never evict a neighbour: per-tenant
        caches make it structural (the victim search space IS the
        tenant)."""
        with _mk_registry() as reg:
            reg.register("churner", lambda x: [x + 1.0], quota=16,
                         cache_capacity=1)
            reg.register("steady", lambda x: [x * 7.0], quota=16,
                         cache_capacity=4)
            xs = np.ones((2, 4), np.float32)
            reg.infer("steady", [xs], timeout=120)
            st0 = _stat(smetrics.tenant_stat("steady",
                                             "cache_evictions"))
            for w in (4, 6, 8, 10):
                reg.infer("churner",
                          [np.ones((2, w), np.float32)], timeout=120)
            # steady's single entry is still compiled & still hot —
            # and its eviction counter never moved
            assert reg.stats("steady")["cache_entries"] == 1
            assert _stat(smetrics.tenant_stat(
                "steady", "cache_evictions")) == st0
            np.testing.assert_array_equal(
                np.asarray(reg.infer("steady", [xs], timeout=120)[0]),
                xs * 7.0)

    def test_serving_never_blocks_under_eviction_pressure(self):
        """Concurrent churn on a capacity-1 cache: every request still
        completes within its timeout (the eviction path never wedges
        the dispatch/compiler loops)."""
        with _mk_registry() as reg:
            reg.register("pressure", lambda x: [x * 2.0], quota=None,
                         cache_capacity=1)
            errs = []

            def client(seed):
                r = np.random.RandomState(seed)
                for _ in range(6):
                    w = int(r.choice([4, 6, 8]))
                    x = np.ones((2, w), np.float32)
                    try:
                        out = reg.infer("pressure", [x], timeout=120)
                        np.testing.assert_array_equal(
                            np.asarray(out[0]), x * 2.0)
                    except Exception as e:  # noqa: BLE001
                        errs.append(e)

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errs

    def test_unregister_drains_cache_bytes(self):
        with _mk_registry() as reg:
            reg.register("drainee", lambda x: [x], quota=4,
                         cache_capacity=4)
            reg.infer("drainee", [X], timeout=120)
            ledger_name = "serving.drainee.compile_cache"
            assert obs.memory_ledger()["entries"].get(ledger_name,
                                                      0) > 0
            reg.unregister("drainee")
            assert obs.memory_ledger()["entries"].get(ledger_name,
                                                      0) == 0

    def test_tenant_cache_put_get_accounting(self):
        """_TenantCache unit: put charges the ledger, overflow evicts
        with exact release (what the integration tests observe through
        the registry)."""
        from paddle_tpu.obs import memprof

        class FakeExec:
            def memory_analysis(self):
                class MA:
                    temp_size_in_bytes = 1000
                    output_size_in_bytes = 24
                    generated_code_size_in_bytes = 0
                return MA()

        cache = _TenantCache(2, "unit_t")
        ledger = "serving.unit_t.compile_cache"
        try:
            cache.put("a", FakeExec())
            assert memprof.get_entry(ledger) == 1024
            cache.put("b", FakeExec())
            assert memprof.get_entry(ledger) == 2048
            e0 = _stat("compile_cache_evicted_bytes")
            cache.put("c", FakeExec())  # evicts "a"
            assert memprof.get_entry(ledger) == 2048
            assert _stat("compile_cache_evicted_bytes") == e0 + 1024
            assert _stat(smetrics.tenant_stat(
                "unit_t", "cache_evictions")) >= 1
            cache.drain()
            assert memprof.get_entry(ledger) == 0
            assert len(cache) == 0
        finally:
            cache.drain()
