"""Sequence (LoD-family) op tests — dense (data, lengths) re-design
(reference unittests: test_sequence_pool.py, test_sequence_softmax_op.py,
test_sequence_pad_op.py, test_sequence_unpad_op.py,
test_sequence_reverse.py, test_sequence_erase_op.py,
test_sequence_mask.py, test_sequence_conv.py, test_sequence_slice_op.py,
test_sequence_enumerate_op.py, test_sequence_expand_as.py,
test_sequence_concat.py).  Oracles computed per-row on the ragged view
(the semantics the reference defines over LoD), then re-padded."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, unique_name
from paddle_tpu.fluid.executor import Scope, scope_guard

from op_test import OpTest, randf, run_single_op

run_seq_op = run_single_op




X = randf(3, 5, 4, seed=201)          # (B=3, T=5, D=4)
LENS = np.array([5, 3, 0], "int32")   # incl. an empty row
MASK = np.arange(5)[None, :] < LENS[:, None]


class TestSequencePool:
    @pytest.mark.parametrize("ptype,fn", [
        ("SUM", lambda r: r.sum(0)),
        ("AVERAGE", lambda r: r.mean(0)),
        ("SQRT", lambda r: r.sum(0) / np.sqrt(len(r))),
        ("MAX", lambda r: r.max(0)),
        ("LAST", lambda r: r[-1]),
        ("FIRST", lambda r: r[0]),
    ])
    def test_pool(self, ptype, fn):
        out = run_seq_op("sequence_pool", {"X": X, "Length": LENS},
                         {"pooltype": ptype, "pad_value": -7.0},
                         ["Out"])["Out"]
        want = np.stack([fn(X[b, :LENS[b]]) if LENS[b] else
                         np.full(4, -7.0, "float32")
                         for b in range(3)])
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)


def test_sequence_softmax():
    x2 = randf(3, 5, seed=202)
    out = run_seq_op("sequence_softmax", {"X": x2, "Length": LENS}, {},
                     ["Out"])["Out"]
    for b in range(3):
        n = LENS[b]
        if n:
            e = np.exp(x2[b, :n] - x2[b, :n].max())
            np.testing.assert_allclose(out[b, :n], e / e.sum(), rtol=1e-5)
        assert np.all(out[b, n:] == 0)


def test_sequence_reverse():
    out = run_seq_op("sequence_reverse", {"X": X, "Length": LENS}, {},
                     ["Y"])["Y"]
    for b in range(3):
        n = LENS[b]
        np.testing.assert_allclose(out[b, :n], X[b, :n][::-1])
        np.testing.assert_allclose(out[b, n:], X[b, n:])  # padding in place


def test_sequence_mask():
    out = run_seq_op("sequence_mask", {"X": LENS},
                     {"maxlen": 6, "out_dtype": "float32"}, ["Y"])["Y"]
    want = (np.arange(6)[None, :] < LENS[:, None]).astype("float32")
    np.testing.assert_array_equal(out, want)


def test_sequence_expand_as():
    xr = randf(3, 4, seed=203)
    out = run_seq_op("sequence_expand_as",
                     {"X": xr, "Y": X, "Length": LENS}, {}, ["Out"])["Out"]
    for b in range(3):
        n = LENS[b]
        np.testing.assert_allclose(out[b, :n], np.tile(xr[b], (n, 1)))
        assert np.all(out[b, n:] == 0)


def test_sequence_pad_extends_and_fills():
    out, ln = (lambda d: (d["Out"], d["Length"]))(run_seq_op(
        "sequence_pad",
        {"X": X, "Length": LENS, "PadValue": np.float32(9.0)},
        {"padded_length": 7}, ["Out", "Length"],
        {"Length": "int64"}))
    assert out.shape == (3, 7, 4)
    for b in range(3):
        n = LENS[b]
        np.testing.assert_allclose(out[b, :n], X[b, :n])
        assert np.all(out[b, n:] == 9.0)
    np.testing.assert_array_equal(ln, LENS)


def test_sequence_unpad_front_packs():
    out = run_seq_op("sequence_unpad", {"X": X, "Length": LENS}, {},
                     ["Out"])["Out"]
    assert out.shape == (15, 4)
    want = np.concatenate([X[b, :LENS[b]] for b in range(3)])
    np.testing.assert_allclose(out[:len(want)], want)
    assert np.all(out[len(want):] == 0)


def test_sequence_concat():
    x2 = randf(3, 4, 4, seed=204)
    l2 = np.array([2, 4, 1], "int32")
    d = run_seq_op("sequence_concat",
                   {"X": [X, x2], "Length": [LENS, l2]}, {},
                   ["Out", "OutLength"], {"OutLength": "int64"})
    out, ln = d["Out"], d["OutLength"]
    assert out.shape == (3, 9, 4)
    np.testing.assert_array_equal(ln, LENS + l2)
    for b in range(3):
        want = np.concatenate([X[b, :LENS[b]], x2[b, :l2[b]]])
        np.testing.assert_allclose(out[b, :len(want)], want)
        assert np.all(out[b, len(want):] == 0)


def test_sequence_erase():
    ids = np.array([[2, 1, 2, 3, 5], [7, 2, 2, 0, 0], [1, 1, 1, 0, 0]],
                   "int32")
    lens = np.array([5, 3, 2], "int32")
    d = run_seq_op("sequence_erase", {"X": ids, "Length": lens},
                   {"tokens": [2, 1]}, ["Out", "OutLength"],
                   {"Out": "int32", "OutLength": "int64"})
    np.testing.assert_array_equal(d["OutLength"], [2, 1, 0])
    np.testing.assert_array_equal(d["Out"][0, :2], [3, 5])
    np.testing.assert_array_equal(d["Out"][1, :1], [7])
    assert np.all(d["Out"][2] == 0)


def test_sequence_slice():
    off = np.array([[1], [0], [2]], "int32")
    ln = np.array([[2], [3], [1]], "int32")
    out = run_seq_op("sequence_slice",
                     {"X": X, "Offset": off, "Length": ln}, {},
                     ["Out"])["Out"]
    for b in range(3):
        np.testing.assert_allclose(out[b, :ln[b, 0]],
                                   X[b, off[b, 0]:off[b, 0] + ln[b, 0]])
        assert np.all(out[b, ln[b, 0]:] == 0)


def test_sequence_enumerate():
    ids = np.array([[1, 2, 3, 4, 0], [9, 8, 0, 0, 0]], "int32")
    lens = np.array([4, 2], "int32")
    out = run_seq_op("sequence_enumerate", {"X": ids, "Length": lens},
                     {"win_size": 2, "pad_value": 0}, ["Out"],
                     {"Out": "int32"})["Out"]
    np.testing.assert_array_equal(
        out[0], [[1, 2], [2, 3], [3, 4], [4, 0], [0, 0]])
    np.testing.assert_array_equal(
        out[1], [[9, 8], [8, 0], [0, 0], [0, 0], [0, 0]])


def test_sequence_conv_matches_manual_window():
    x = randf(2, 4, 3, seed=205)
    lens = np.array([4, 2], "int32")
    w = randf(9, 5, seed=206)  # context 3 * D 3 -> 5
    out = run_seq_op("sequence_conv",
                     {"X": x, "Length": lens, "Filter": w},
                     {"contextLength": 3, "contextStart": -1}, ["Out"]
                     )["Out"]
    for b in range(2):
        n = lens[b]
        for t in range(4):
            if t >= n:
                assert np.all(np.abs(out[b, t]) < 1e-6)
                continue
            ctx = []
            for k in range(-1, 2):
                p = t + k
                ctx.append(x[b, p] if 0 <= p < n else np.zeros(3, "float32"))
            want = np.concatenate(ctx) @ w
            np.testing.assert_allclose(out[b, t], want, rtol=1e-4,
                                       atol=1e-5)


def test_sequence_layers_build_and_grad():
    """The layer wrappers wire into Programs and append_backward flows
    gradients through the masked ops."""
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup), unique_name.guard():
        x = fluid.data("x", [3, 5, 4], "float32")
        x.stop_gradient = False
        ln = fluid.data("ln", [3], "int32")
        import paddle_tpu.fluid.layers as layers

        sm = layers.sequence_softmax(layers.sequence_reverse(x, length=ln),
                                     length=ln)
        pooled = layers.sequence_pool(sm * x, "SUM", length=ln)
        loss = layers.reduce_sum(pooled)
        grads = fluid.append_backward(loss)
    with scope_guard(Scope()):
        exe = fluid.Executor()
        g = exe.run(main, feed={"x": X, "ln": LENS},
                    fetch_list=[framework.grad_var_name("x")])[0]
    g = np.asarray(g)
    assert g.shape == X.shape
    # padding positions receive no gradient
    for b in range(3):
        assert np.all(g[b, LENS[b]:] == 0)
    assert np.abs(g).max() > 0
