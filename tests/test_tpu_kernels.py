"""Real-hardware (non-interpret) Pallas kernel tests — the TPU lane.

Round 2 shipped a flash-attention kernel whose every test ran
`interpret=True` on CPU; the kernel then failed Mosaic lowering for every
input shape on the bench chip (VERDICT r2 weak #1, BENCH_r02).  This lane
exercises the kernels through the actual Mosaic compiler:

    PADDLE_TPU_TEST_LANE=1 python -m pytest tests/test_tpu_kernels.py -q

`bench.py` runs the same checks as a preflight before timing, so a
kernel regression can never reach the bench silently again.

Oracle: `_xla_attention` (tests/test_pallas_attention.py validates that
against NumPy in interpret mode; here it runs on the same chip).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.attention import (
    _xla_attention,
    flash_attention,
)

pytestmark = [
    pytest.mark.tpu,
    pytest.mark.skipif(jax.default_backend() != "tpu",
                       reason="needs a real TPU backend "
                              "(PADDLE_TPU_TEST_LANE=1)"),
]


def _rand(shape, seed, dtype=jnp.float32):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape), dtype)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_xla_on_tpu(causal):
    q, k, v = (_rand((2, 256, 4, 64), s) for s in (0, 1, 2))
    out = flash_attention(q, k, v, is_causal=causal)
    ref = _xla_attention(q, k, v, is_causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)


def test_key_padding_bias_on_tpu():
    q, k, v = (_rand((2, 256, 4, 64), s) for s in (3, 4, 5))
    kb = jnp.where(jnp.arange(256)[None, :] < 200, 0.0, -1e9)
    kb = jnp.broadcast_to(kb, (2, 256)).astype(jnp.float32)
    out = flash_attention(q, k, v, key_bias=kb)
    ref = _xla_attention(q, k, v, mask=kb[:, None, None, :])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)


def test_grads_match_xla_on_tpu():
    q, k, v = (_rand((2, 256, 4, 64), s) for s in (6, 7, 8))

    def loss(att):
        return lambda q, k, v: jnp.sum(att(q, k, v, is_causal=True) ** 2)

    g = jax.grad(loss(lambda q, k, v, **kw: flash_attention(q, k, v, **kw)),
                 argnums=(0, 1, 2))(q, k, v)
    r = jax.grad(loss(lambda q, k, v, **kw: _xla_attention(q, k, v, **kw)),
                 argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g, r):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-2, rtol=5e-2,
            err_msg=f"d{name} mismatch on TPU")


def test_bf16_dropout_lowers_and_runs():
    q, k, v = (_rand((2, 256, 4, 64), s, jnp.bfloat16) for s in (9, 10, 11))
    out = flash_attention(q, k, v, dropout_p=0.1, dropout_seed=3)
    assert out.dtype == jnp.bfloat16 and out.shape == q.shape
    g = jax.grad(lambda q: jnp.sum(flash_attention(
        q, k, v, dropout_p=0.1, dropout_seed=3).astype(jnp.float32)))(q)
    assert bool(jnp.all(jnp.isfinite(g.astype(jnp.float32))))


def test_odd_shapes_via_padding_shim():
    q = _rand((2, 300, 4, 64), 12)
    k = _rand((2, 333, 4, 64), 13)
    v = _rand((2, 333, 4, 64), 14)
    out = flash_attention(q, k, v)
    ref = _xla_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)


def test_bert_seq512_shape_regression():
    """The exact (B, S) = (·, 512) family that crashed in BENCH_r02."""
    q, k, v = (_rand((2, 512, 4, 64), s, jnp.bfloat16)
               for s in (15, 16, 17))
    kb = jnp.where(jnp.arange(512)[None, :] < 400, 0.0, -1e9)
    kb = jnp.broadcast_to(kb, (2, 512)).astype(jnp.float32)
    out = flash_attention(q, k, v, key_bias=kb, dropout_p=0.1,
                          dropout_seed=1)
    assert out.shape == (2, 512, 4, 64)
    assert bool(jnp.all(jnp.isfinite(out.astype(jnp.float32))))
