"""SPMD named-axis sharding (docs/spmd.md): data × fsdp × tp mesh
lowering on the 8-device virtual CPU mesh.

The contract under test: `BuildStrategy.mesh_axes = {"data":2, "fsdp":2,
"tp":2}` trains to the SAME losses as plain `{data: 8}` data parallelism
(XLA SPMD is semantics-preserving) while holding ~4x less optimizer
state per device (ZeRO via the PartitionSpec registry — Adam moments
inherit their parameter's layout through the name prefix), with the
SPMD-inserted collectives attributed in the profiler and the layout
recorded in checkpoint manifests."""

import os

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu.fluid as fluid
from paddle_tpu import profiler
from paddle_tpu.fluid import framework, unique_name
from paddle_tpu.fluid.executor import Scope, scope_guard
from paddle_tpu.parallel import mesh as mesh_lib
from paddle_tpu.parallel import spec_layout


@pytest.fixture(autouse=True)
def _clean_mesh_context():
    """Every test leaves the global mesh + spec registry as it found
    them — a leaked mesh flips checkpoint manifests repo-wide."""
    yield
    mesh_lib.set_current_mesh(None)
    spec_layout.clear_specs()


def spmd_mesh():
    return mesh_lib.make_mesh({"data": 2, "fsdp": 2, "tp": 2})


def dp_mesh():
    return mesh_lib.make_mesh({"data": 8})


# ---------------------------------------------------------------------------
# spec registry units
# ---------------------------------------------------------------------------

class TestSpecRegistry:
    def test_dense_weight_splits_fsdp_by_tp(self):
        mesh = spmd_mesh()
        assert spec_layout.spec_for("fc_0.w_0", (16, 64), mesh) \
            == P("fsdp", "tp")

    def test_moments_inherit_param_layout(self):
        # `fc_0.w_0_moment1_0` carries the param prefix — THE ZeRO
        # optimizer-state sharding
        mesh = spmd_mesh()
        assert spec_layout.spec_for("fc_0.w_0_moment1_0", (16, 64), mesh) \
            == P("fsdp", "tp")

    def test_bias_norm_scalars_replicated(self):
        mesh = spmd_mesh()
        for name, shape in [("fc_0.b_0", (64,)),
                            ("layer_norm_0.w_0", (64,)),
                            ("fc_0.w_0_beta1_pow_acc_0", (1,)),
                            ("learning_rate_0", (1,))]:
            assert spec_layout.spec_for(name, shape, mesh) == P(), name

    def test_embedding_vocab_over_fsdp_x_tp(self):
        mesh = spmd_mesh()
        assert spec_layout.spec_for("embedding_0.w_0", (32, 16), mesh) \
            == P(("fsdp", "tp"))

    def test_pure_data_mesh_is_all_replicated(self):
        # default {data: N}: byte-identical to the pre-SPMD compiler
        mesh = dp_mesh()
        for name, shape in [("fc_0.w_0", (16, 64)),
                            ("embedding_0.w_0", (32, 16)),
                            ("fc_0.w_0_moment1_0", (16, 64))]:
            assert spec_layout.spec_for(name, shape, mesh) == P(), name

    def test_misfit_rule_degrades_to_replicated(self):
        # neither dim divisible -> P(), never a crash
        mesh = spmd_mesh()
        assert spec_layout.spec_for("fc_9.w_0", (5, 7), mesh) == P()

    def test_override_wins_and_is_fitted(self):
        mesh = spmd_mesh()
        spec_layout.register_spec("custom.w", P("tp", "fsdp"))
        assert spec_layout.spec_for("custom.w", (16, 64), mesh) \
            == P("tp", "fsdp")
        # an override naming an absent axis is clamped (the verifier
        # flags it; the compiler must not crash)
        spec_layout.register_spec("custom.v", P("pipe"))
        assert spec_layout.spec_for("custom.v", (16,), mesh) == P()
        spec_layout.register_spec("custom.w", None)  # clear one
        assert "custom.w" not in spec_layout.registered_specs()

    def test_zero_annotation_first_fitting_axis(self):
        class Var:
            _sharding_axes = ("fsdp", "data")

        mesh = spmd_mesh()
        assert spec_layout.spec_for("g", (16, 4), mesh, var=Var()) \
            == P("fsdp")
        # on a pure data mesh the same annotation falls through to
        # "data" — ZeRO-1 over the data axis
        assert spec_layout.spec_for("g", (16, 4), dp_mesh(), var=Var()) \
            == P("data")

    def test_validate_spec_problem_strings(self):
        mesh = spmd_mesh()
        assert spec_layout.validate_spec(P("fsdp", "tp"), (16, 64),
                                         mesh) == []
        probs = spec_layout.validate_spec(P("pipe"), (16,), mesh)
        assert any("'pipe'" in p for p in probs)
        probs = spec_layout.validate_spec(P("fsdp"), (5,), mesh)
        assert any("not divisible" in p for p in probs)
        probs = spec_layout.validate_spec(P("fsdp", "tp"), (16,), mesh)
        assert any("entries" in p for p in probs)

    def test_batch_spec_composes_data_and_fsdp(self):
        mesh = spmd_mesh()
        assert mesh_lib.batch_spec(mesh, 16) == P(("data", "fsdp"))
        # 6 rows: data*fsdp=4 doesn't divide -> degrade to data alone
        assert mesh_lib.batch_spec(mesh, 6) == P("data")
        assert mesh_lib.batch_spec(mesh, 5) == P()
        assert mesh_lib.batch_spec(dp_mesh(), 16) == P("data")

    def test_spec_json_roundtrip(self):
        for spec in (P("fsdp", "tp"), P(("fsdp", "tp")), P(None, "tp"),
                     P()):
            doc = spec_layout.spec_to_json(spec)
            assert spec_layout.spec_from_json(doc) == spec


# ---------------------------------------------------------------------------
# the tentpole: dp vs dp*fsdp*tp loss parity + ZeRO memory reduction
# ---------------------------------------------------------------------------

def build_tiny_transformer():
    """Embedding -> FFN -> layer_norm -> classifier: exercises the
    vocab-split, row/col-split and replicated registry rules at once."""
    ids = fluid.data("ids", [-1, 1], "int64")
    label = fluid.data("label", [-1, 1], "int64")
    emb = fluid.layers.embedding(ids, size=[32, 16])
    h = fluid.layers.reshape(emb, [-1, 16])
    h = fluid.layers.fc(h, 64, act="relu")
    h = fluid.layers.layer_norm(h)
    pred = fluid.layers.fc(h, 8)
    loss = fluid.layers.reduce_mean(
        fluid.layers.loss.softmax_with_cross_entropy(pred, label))
    return loss


def _per_device_bytes(arr) -> int:
    by_dev = {}
    for s in arr.addressable_shards:
        by_dev[s.device] = by_dev.get(s.device, 0) + s.data.nbytes
    return max(by_dev.values())


def _optimizer_bytes_per_device(scope) -> int:
    total = 0
    for name, v in scope._vars.items():
        if ("_moment" in name or "pow_acc" in name) \
                and isinstance(v, jax.Array):
            total += _per_device_bytes(v)
    return total


def _train(axes, steps=4):
    rng = np.random.RandomState(0)
    IDS = rng.randint(0, 32, size=(16, 1)).astype("int64")
    L = rng.randint(0, 8, size=(16, 1)).astype("int64")
    main, startup = framework.Program(), framework.Program()
    scope = Scope()
    try:
        with framework.program_guard(main, startup), unique_name.guard(), \
                scope_guard(scope):
            loss = build_tiny_transformer()
            main.random_seed = 7
            startup.random_seed = 7
            fluid.optimizer.Adam(0.01).minimize(loss)
            exe = fluid.Executor()
            exe.run(startup)
            bs = fluid.BuildStrategy()
            bs.mesh_axes = axes
            compiled = fluid.CompiledProgram(main).with_data_parallel(
                loss_name=loss.name, build_strategy=bs)
            losses = []
            for _ in range(steps):
                (l,) = exe.run(compiled, feed={"ids": IDS, "label": L},
                               fetch_list=[loss])
                losses.append(float(np.asarray(l).reshape(-1)[0]))
            opt_bytes = _optimizer_bytes_per_device(scope)
            moment = scope.get("fc_0.w_0_moment1_0")
        return losses, opt_bytes, moment
    finally:
        mesh_lib.set_current_mesh(None)


def test_spmd_mesh_matches_dp_losses_with_sharded_optimizer_state():
    before = profiler.get_int_stats()
    dp_losses, dp_bytes, dp_moment = _train({"data": 8})
    spmd_losses, spmd_bytes, spmd_moment = _train(
        {"data": 2, "fsdp": 2, "tp": 2})

    # identical numerics: SPMD is a layout choice, not a program change
    assert dp_losses[0] > dp_losses[-1]  # it actually learns
    np.testing.assert_allclose(dp_losses, spmd_losses, rtol=2e-3,
                               atol=2e-4)

    # ZeRO: the fc weight's moment holds exactly 1/4 of its bytes per
    # device on the fsdp=2 x tp=2 mesh, and was fully replicated on dp
    assert _per_device_bytes(dp_moment) == dp_moment.nbytes
    assert _per_device_bytes(spmd_moment) * 4 == spmd_moment.nbytes
    shard_shapes = {tuple(s.data.shape)
                    for s in spmd_moment.addressable_shards}
    assert shard_shapes == {(8, 32)}  # (16, 64) / (fsdp=2, tp=2)

    # aggregate optimizer state (incl. replicated bias moments and
    # scalar pow accumulators) shrinks substantially
    assert spmd_bytes * 2.5 < dp_bytes

    # the SPMD-inserted collectives are attributed in the profiler
    after = profiler.get_int_stats()
    spmd_coll = {k: after[k] - before.get(k, 0) for k in after
                 if k.startswith("collective_bytes_spmd_")}
    assert any(v > 0 for v in spmd_coll.values()), after
    assert after.get("spmd_specs_applied", 0) \
        > before.get("spmd_specs_applied", 0)


# ---------------------------------------------------------------------------
# verifier: partition-spec WARNING pass
# ---------------------------------------------------------------------------

def test_partition_spec_pass_flags_misfits(fresh_programs):
    from paddle_tpu.analysis.verifier import verify_program

    main, startup, scope = fresh_programs
    x = fluid.data("x", [-1, 8], "float32")
    h = fluid.layers.fc(x, 5)       # fc_0.w_0: (8, 5)
    pred = fluid.layers.fc(h, 4)    # fc_1.w_0: (5, 4)

    mesh_lib.set_current_mesh(spmd_mesh())
    # dim 1 of 5 not divisible by tp=2
    spec_layout.register_spec("fc_0.w_0", P("fsdp", "tp"))
    # axis absent from the mesh
    spec_layout.register_spec("fc_0.b_0", P("pipe"))
    # ZeRO annotation naming only absent axes
    main.global_block().var("fc_1.w_0")._sharding_axes = ("pipe",)

    findings = verify_program(main, passes=["partition-spec"])
    msgs = [f.message for f in findings]
    assert any("fc_0.w_0" in m and "not divisible" in m for m in msgs)
    assert any("fc_0.b_0" in m and "'pipe'" in m for m in msgs)
    assert any("fc_1.w_0" in m and "absent from mesh axes" in m
               for m in msgs)
    assert all(f.severity == "warning" for f in findings)

    # outside any mesh context the pass is a no-op
    mesh_lib.set_current_mesh(None)
    assert verify_program(main, passes=["partition-spec"]) == []


# ---------------------------------------------------------------------------
# checkpoints: the layout is part of the artifact
# ---------------------------------------------------------------------------

class TestShardedCheckpoint:
    def test_manifest_records_layout_and_roundtrips(self, tmp_path):
        from paddle_tpu.ckpt import CheckpointError
        from paddle_tpu.ckpt.manager import CheckpointManager

        mesh = spmd_mesh()
        mesh_lib.set_current_mesh(mesh)
        W = np.arange(128, dtype="float32").reshape(16, 8)
        state = {
            "w": jax.device_put(W, NamedSharding(mesh, P("fsdp", "tp"))),
            "b": jax.device_put(np.ones(8, "float32"),
                                NamedSharding(mesh, P())),
        }
        m = CheckpointManager(str(tmp_path))
        path = m.save(state, step=1)
        manifest = m.read_meta(path)
        assert manifest["mesh_axes"] == {"data": 2, "fsdp": 2, "tp": 2}
        assert manifest["vars"]["w"]["spec"] == ["fsdp", "tp"]
        assert "spec" not in manifest["vars"]["b"]

        back, _ = m.restore(path)
        np.testing.assert_array_equal(np.asarray(back["w"]), W)

        # a different live mesh refuses, naming expected vs found axes
        mesh_lib.set_current_mesh(dp_mesh())
        with pytest.raises(CheckpointError, match="mesh axes"):
            m.restore(path)
        # weights-only escape hatch lets the compiler re-shard
        loose, _ = m.restore(path, strict_topology=False)
        assert set(loose) == {"w", "b"}

    def test_plain_dp_checkpoint_stays_legacy(self, tmp_path):
        # replicated state under an active mesh records NO mesh_axes:
        # old checkpoints and the merge-all restore path are untouched
        from paddle_tpu.ckpt.manager import CheckpointManager

        mesh_lib.set_current_mesh(dp_mesh())
        state = {"w": np.ones((4, 4), "float32")}
        m = CheckpointManager(str(tmp_path))
        path = m.save(state, step=1)
        assert "mesh_axes" not in m.read_meta(path)

    def test_owned_shards_only_restore(self, tmp_path):
        from paddle_tpu.ckpt.manager import CheckpointManager

        mesh = mesh_lib.make_mesh({"data": 4, "fsdp": 2})
        mesh_lib.set_current_mesh(mesh)
        sh = NamedSharding(mesh, P("fsdp"))
        state = {f"w{i}": jax.device_put(
            np.full((8, 4), i, "float32"), sh) for i in range(6)}
        for host in (1, 0):  # host 0 commits last (mocked pod)
            CheckpointManager(str(tmp_path), process_index=host,
                              process_count=2).save(state, step=3)
        m0 = CheckpointManager(str(tmp_path), process_index=0,
                               process_count=2)
        back, manifest = m0.restore()
        owned = {n for n, meta in manifest["vars"].items()
                 if meta["shard"] == 0}
        # each host loads ONLY its own shard — not the merged state
        assert owned and owned != set(state)
        assert set(back) == owned
        for n in owned:
            np.testing.assert_array_equal(np.asarray(back[n]),
                                          np.asarray(state[n]))


# ---------------------------------------------------------------------------
# hot-path lint coverage of the new entry points
# ---------------------------------------------------------------------------

def test_watchlist_covers_spmd_entry_points():
    from paddle_tpu.analysis.lint.hot_path_sync import (WATCHLIST,
                                                        check_repo)

    assert ("paddle_tpu/fluid/executor.py",
            "Executor._seat_state") in WATCHLIST
    assert ("paddle_tpu/dataset/feed_pipeline.py",
            "FeedPipeline._place_sharded") in WATCHLIST
    assert check_repo() == []
