"""Inference C ABI tests (VERDICT r4 weak #2): build libpaddle_tpu_c.so
fresh from c_api.cc, load it in a CLEAN subprocess via ctypes, and
round-trip LeNet through PT_NewPredictor/PT_PredictorRun against the
Python Predictor's own output.

Also compile-and-run tests the pure-C consumer example
(examples/c_inference/predictor_demo.c) — the counterpart of the
reference's Go binding (/root/reference/go/paddle/predictor.go:1,
config.go, tensor.go) over its C API
(/root/reference/paddle/fluid/inference/capi/c_api.cc:1); Go has no
toolchain in this image, so the demo host is C, which is the layer the
Go/R wrappers sit on anyway.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import core_native, inference, nn
from paddle_tpu.vision.models import LeNet

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the ctypes host subprocess: loads the fresh .so, runs one image
_CTYPES_HOST = r"""
import ctypes, json, os, sys
import numpy as np

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

so_path, prefix, inp_path, out_path = sys.argv[1:5]
lib = ctypes.CDLL(so_path)
lib.PT_GetLastError.restype = ctypes.c_char_p
lib.PT_Init.argtypes = [ctypes.c_char_p]
lib.PT_NewPredictor.restype = ctypes.c_void_p
lib.PT_NewPredictor.argtypes = [ctypes.c_char_p]
lib.PT_PredictorRun.argtypes = [
    ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
    ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
    ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ctypes.POINTER(ctypes.c_int)]
lib.PT_DeletePredictor.argtypes = [ctypes.c_void_p]

assert lib.PT_Init(b"") == 0, lib.PT_GetLastError()
h = lib.PT_NewPredictor(prefix.encode())
assert h, lib.PT_GetLastError()

x = np.load(inp_path)
shape = (ctypes.c_int64 * x.ndim)(*x.shape)
data = np.ascontiguousarray(x, np.float32)
out = np.zeros(1 << 16, np.float32)
count = ctypes.c_int64()
oshape = (ctypes.c_int64 * 8)()
ondim = ctypes.c_int()
rc = lib.PT_PredictorRun(
    h, data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), shape,
    x.ndim, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    out.size, ctypes.byref(count), oshape, ctypes.byref(ondim))
assert rc == 0, (rc, lib.PT_GetLastError())
res = out[:count.value].reshape([oshape[i] for i in range(ondim.value)])
np.save(out_path, res)

# error path: deleting and a bad prefix must not crash the process
lib.PT_DeletePredictor(h)
assert lib.PT_NewPredictor(b"/nonexistent/model") is None
assert b"" != lib.PT_GetLastError()
print("CTYPES_HOST_OK")
"""


@pytest.fixture(scope="module")
def lenet_model(tmp_path_factory):
    d = tmp_path_factory.mktemp("c_api_model")
    prefix = str(d / "lenet")
    net = LeNet(num_classes=10)
    inference.save_inference_model(prefix, net, [([1, 1, 28, 28],
                                                  "float32")])
    x = np.random.RandomState(0).uniform(
        -1, 1, (1, 1, 28, 28)).astype("float32")
    want = inference.Predictor(inference.Config(prefix)).run([x])[0]
    return prefix, x, want


@pytest.fixture(scope="module")
def fresh_so():
    """Force a from-source build (the point: the .so must not be a
    vendored binary)."""
    so = os.path.join(REPO, "paddle_tpu", "core_native",
                      "libpaddle_tpu_c.so")
    if os.path.exists(so):
        os.remove(so)
    built = core_native.build_c_api()
    assert os.path.exists(built)
    return built


class TestCAPI:
    def test_ctypes_roundtrip_clean_subprocess(self, lenet_model,
                                               fresh_so, tmp_path):
        prefix, x, want = lenet_model
        inp, out = str(tmp_path / "x.npy"), str(tmp_path / "y.npy")
        np.save(inp, x)
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        r = subprocess.run(
            [sys.executable, "-c", _CTYPES_HOST, fresh_so, prefix, inp,
             out], capture_output=True, text=True, timeout=300, env=env)
        assert r.returncode == 0, r.stderr[-2000:]
        assert "CTYPES_HOST_OK" in r.stdout
        got = np.load(out)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_small_output_buffer_reports_required_size(self, lenet_model,
                                                       fresh_so):
        # in-process ctypes load (host already runs Python): the -2
        # contract must set *out_count to the required element count
        import ctypes

        prefix, x, want = lenet_model
        lib = ctypes.CDLL(fresh_so)
        lib.PT_GetLastError.restype = ctypes.c_char_p
        lib.PT_Init.argtypes = [ctypes.c_char_p]
        lib.PT_NewPredictor.restype = ctypes.c_void_p
        lib.PT_NewPredictor.argtypes = [ctypes.c_char_p]
        lib.PT_PredictorRun.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int)]
        lib.PT_DeletePredictor.argtypes = [ctypes.c_void_p]
        assert lib.PT_Init(b"") == 0
        h = lib.PT_NewPredictor(prefix.encode())
        assert h, lib.PT_GetLastError()
        data = np.ascontiguousarray(x, np.float32)
        shape = (ctypes.c_int64 * x.ndim)(*x.shape)
        tiny = np.zeros(2, np.float32)
        count = ctypes.c_int64()
        oshape = (ctypes.c_int64 * 8)()
        ondim = ctypes.c_int()
        rc = lib.PT_PredictorRun(
            h, data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            shape, x.ndim,
            tiny.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            tiny.size, ctypes.byref(count), oshape, ctypes.byref(ondim))
        assert rc == -2
        assert count.value == int(np.prod(want.shape))
        lib.PT_DeletePredictor(h)


class TestGoConsumer:
    def test_go_binding_compiles_if_toolchain_present(self, lenet_model):
        """The committed Go binding (examples/go_inference/predictor.go,
        mirroring the reference's go/paddle wrapper) compile-checks when
        a Go toolchain exists; this image ships none, so the source is
        committed + documented (VERDICT r4 next #4)."""
        import shutil

        go = shutil.which("go")
        if go is None:
            pytest.skip("no Go toolchain in this image")
        prefix, _, _ = lenet_model
        so = core_native.build_c_api(embed=True)
        try:
            cfg = subprocess.run(["python3-config", "--embed",
                                  "--ldflags"],
                                 capture_output=True, text=True)
        except FileNotFoundError:
            pytest.skip("python3-config unavailable")
        if cfg.returncode != 0:
            pytest.skip("python3-config --embed failed")
        env = dict(
            os.environ,
            CGO_LDFLAGS=f"-L{os.path.dirname(so)} -lpaddle_tpu_c "
                        + cfg.stdout.strip())
        r = subprocess.run(
            [go, "build", "./..."], capture_output=True, text=True,
            cwd=os.path.join(REPO, "examples", "go_inference"), env=env,
            timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]


class TestCConsumer:
    def test_compile_and_run_c_demo(self, lenet_model, tmp_path):
        """gcc-compile the pure-C demo against the embed-linked ABI and
        run it as its own executable — no Python in the host source."""
        prefix, x, want = lenet_model
        demo = os.path.join(REPO, "examples", "c_inference",
                            "predictor_demo.c")
        so = core_native.build_c_api(embed=True)
        exe = str(tmp_path / "predictor_demo")
        cfg = subprocess.run(["python3-config", "--embed", "--ldflags"],
                             capture_output=True, text=True)
        if cfg.returncode != 0:
            pytest.skip("python3-config --embed unavailable")
        r = subprocess.run(
            ["gcc", "-O2", demo, "-o", exe,
             "-L" + os.path.dirname(so), "-lpaddle_tpu_c",
             "-Wl,-rpath," + os.path.dirname(so)] + cfg.stdout.split(),
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr[-2000:]
        inp = str(tmp_path / "x.f32")
        np.ascontiguousarray(x, np.float32).tofile(inp)
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        r = subprocess.run([exe, REPO, prefix, inp], capture_output=True,
                           text=True, timeout=300, env=env)
        assert r.returncode == 0, (r.stdout[-500:], r.stderr[-2000:])
        # demo prints "out[i] = v" lines; parse and compare
        got = [float(line.split("=")[1])
               for line in r.stdout.splitlines()
               if line.startswith("out[")]
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   want.reshape(-1), atol=1e-4)
