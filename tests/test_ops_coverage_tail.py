"""Tests for the last 15 registered-but-untested ops (VERDICT r4 weak #3):
the interp tail (linear/trilinear/bicubic) against torch oracles, the
dequantize family round-trips, random_crop shape/determinism,
average_accumulates window state math, and the small creation/predicate
ops (empty, fill, fill_zeros_like2, gaussian_random_batch_size_like,
grad_add, is_empty, seed).

Reference anchors: interpolate_v2_op.h, fake_dequantize_op.cc,
dequantize_log_op.cc, random_crop_op.h, average_accumulates_op.h,
fill_op.cc, empty_op.cc, seed_op.cc.
"""

import numpy as np
import torch
import torch.nn.functional as TF

from op_test import randf, run_single_op


def run_op(op_type, inputs, attrs, outs, dtypes=None):
    return run_single_op(op_type, inputs, attrs, outs, dtypes)


# ---------------------------------------------------------------------------
# interp tail: linear (3D), trilinear (5D), bicubic (4D)
# ---------------------------------------------------------------------------

class TestLinearInterp:
    def test_align_corners_true(self):
        x = randf(2, 3, 8, seed=10)
        want = TF.interpolate(torch.tensor(x), size=13, mode="linear",
                              align_corners=True).numpy()
        d = run_op("linear_interp", {"X": x},
                   {"out_w": 13, "align_corners": True}, ["Out"])
        np.testing.assert_allclose(d["Out"], want, atol=1e-5)
        d = run_op("linear_interp_v2", {"X": x},
                   {"out_w": 13, "align_corners": True}, ["Out"])
        np.testing.assert_allclose(d["Out"], want, atol=1e-5)

    def test_half_pixel(self):
        # align_corners=False + align_mode=0 is torch's half-pixel map
        x = randf(1, 2, 6, seed=11)
        d = run_op("linear_interp_v2", {"X": x},
                   {"out_w": 9, "align_corners": False, "align_mode": 0},
                   ["Out"])
        want = TF.interpolate(torch.tensor(x), size=9, mode="linear",
                              align_corners=False).numpy()
        np.testing.assert_allclose(d["Out"], want, atol=1e-5)

    def test_downsample(self):
        x = randf(2, 2, 12, seed=12)
        d = run_op("linear_interp_v2", {"X": x},
                   {"out_w": 5, "align_corners": True}, ["Out"])
        want = TF.interpolate(torch.tensor(x), size=5, mode="linear",
                              align_corners=True).numpy()
        np.testing.assert_allclose(d["Out"], want, atol=1e-5)


class TestTrilinearInterp:
    def test_align_corners_true(self):
        x = randf(1, 2, 3, 4, 5, seed=13)
        want = TF.interpolate(torch.tensor(x), size=(5, 7, 3),
                              mode="trilinear", align_corners=True).numpy()
        attrs = {"out_d": 5, "out_h": 7, "out_w": 3, "align_corners": True}
        d = run_op("trilinear_interp", {"X": x}, attrs, ["Out"])
        np.testing.assert_allclose(d["Out"], want, atol=1e-5)
        d = run_op("trilinear_interp_v2", {"X": x}, attrs, ["Out"])
        np.testing.assert_allclose(d["Out"], want, atol=1e-5)

    def test_half_pixel(self):
        x = randf(2, 1, 4, 4, 4, seed=14)
        d = run_op("trilinear_interp_v2", {"X": x},
                   {"out_d": 6, "out_h": 3, "out_w": 7,
                    "align_corners": False, "align_mode": 0}, ["Out"])
        want = TF.interpolate(torch.tensor(x), size=(6, 3, 7),
                              mode="trilinear",
                              align_corners=False).numpy()
        np.testing.assert_allclose(d["Out"], want, atol=1e-5)


class TestBicubicInterp:
    # torch's bicubic uses the same Keys kernel (a=-0.75) as the
    # reference (interpolate_v2_op.h cubic_interp)
    def test_align_corners_true(self):
        x = randf(2, 3, 6, 7, seed=15)
        want = TF.interpolate(torch.tensor(x), size=(11, 5),
                              mode="bicubic", align_corners=True).numpy()
        attrs = {"out_h": 11, "out_w": 5, "align_corners": True}
        d = run_op("bicubic_interp", {"X": x}, attrs, ["Out"])
        np.testing.assert_allclose(d["Out"], want, atol=1e-4)
        d = run_op("bicubic_interp_v2", {"X": x}, attrs, ["Out"])
        np.testing.assert_allclose(d["Out"], want, atol=1e-4)

    def test_half_pixel(self):
        x = randf(1, 1, 8, 8, seed=16)
        d = run_op("bicubic_interp_v2", {"X": x},
                   {"out_h": 13, "out_w": 3, "align_corners": False},
                   ["Out"])
        want = TF.interpolate(torch.tensor(x), size=(13, 3),
                              mode="bicubic", align_corners=False).numpy()
        np.testing.assert_allclose(d["Out"], want, atol=1e-4)


# ---------------------------------------------------------------------------
# dequantize family
# ---------------------------------------------------------------------------

class TestDequantize:
    def test_dequantize_abs_max(self):
        codes = np.random.RandomState(17).randint(
            -127, 128, size=(4, 6)).astype("int32")
        scale = np.asarray([0.37], "float32")
        d = run_op("dequantize_abs_max",
                   {"X": codes.astype("float32"), "Scale": scale},
                   {"max_range": 127.0}, ["Out"])
        want = codes.astype("float32") * 0.37 / 127.0
        np.testing.assert_allclose(d["Out"], want, rtol=1e-6)

    def test_dequantize_log(self):
        # codes in [-128, 127]; x<0 reads -table[x+128], else table[x]
        table = np.linspace(0.01, 1.0, 128).astype("float32")
        x = np.array([[-128, -1, 0, 5], [127, -64, 32, 100]], "int32")
        d = run_op("dequantize_log", {"X": x, "Dict": table}, {}, ["Out"])
        want = np.where(x < 0, -table[np.clip(x + 128, 0, 127)],
                        table[np.clip(x, 0, 127)])
        np.testing.assert_allclose(d["Out"], want, rtol=1e-6)

    def test_fake_channel_wise_dequantize_one_scale(self):
        x = randf(3, 4, 5, seed=18)
        s = randf(3, low=0.5, high=2.0, seed=19)
        d = run_op("fake_channel_wise_dequantize_max_abs",
                   {"X": x, "Scales": [s]},
                   {"max_range": 127.0, "quant_axis": 0}, ["Out"])
        want = x * s.reshape(3, 1, 1) / 127.0
        np.testing.assert_allclose(d["Out"], want, rtol=1e-5)

    def test_fake_channel_wise_dequantize_two_scales(self):
        # weight scale per channel (axis 1) x activation scalar scale
        x = randf(2, 4, 3, seed=20)
        s1 = randf(4, low=0.5, high=2.0, seed=21)
        s2 = np.asarray([3.0], "float32")
        d = run_op("fake_channel_wise_dequantize_max_abs",
                   {"X": x, "Scales": [s1, s2]},
                   {"max_range": 127.0 * 127.0}, ["Out"])
        want = x * s1.reshape(1, 4, 1) * 3.0 / (127.0 * 127.0)
        np.testing.assert_allclose(d["Out"], want, rtol=1e-5)


# ---------------------------------------------------------------------------
# creation / predicate ops
# ---------------------------------------------------------------------------

class TestCreationOps:
    def test_empty(self):
        d = run_op("empty", {}, {"shape": [2, 3], "dtype": "int32"},
                   ["Out"], {"Out": "int32"})
        assert d["Out"].shape == (2, 3)
        assert d["Out"].dtype == np.int32

    def test_fill(self):
        d = run_op("fill", {},
                   {"shape": [2, 3], "dtype": "float32",
                    "value": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]}, ["Out"])
        np.testing.assert_array_equal(
            d["Out"], np.arange(1.0, 7.0, dtype="float32").reshape(2, 3))

    def test_fill_zeros_like2(self):
        x = randf(3, 4, seed=22)
        d = run_op("fill_zeros_like2", {"X": x}, {"dtype": "float32"},
                   ["Out"])
        np.testing.assert_array_equal(d["Out"], np.zeros((3, 4), "float32"))

    def test_gaussian_random_batch_size_like(self):
        like = randf(7, 3, seed=23)
        d = run_op("gaussian_random_batch_size_like", {"Input": like},
                   {"shape": [999, 2048], "input_dim_idx": 0,
                    "output_dim_idx": 0, "mean": 2.0, "std": 3.0,
                    "dtype": "float32"}, ["Out"])
        out = d["Out"]
        assert out.shape == (7, 2048)
        assert abs(out.mean() - 2.0) < 0.1
        assert abs(out.std() - 3.0) < 0.1

    def test_grad_add(self):
        x, y = randf(2, 5, seed=24), randf(2, 5, seed=25)
        d = run_op("grad_add", {"X": x, "Y": y}, {}, ["Out"])
        np.testing.assert_allclose(d["Out"], x + y, rtol=1e-6)

    def test_is_empty(self):
        d = run_op("is_empty", {"X": np.zeros((0, 3), "float32")}, {},
                   ["Out"], {"Out": "bool"})
        assert bool(d["Out"])
        d = run_op("is_empty", {"X": randf(2, 2, seed=26)}, {},
                   ["Out"], {"Out": "bool"})
        assert not bool(d["Out"])

    def test_seed_fixed(self):
        d = run_op("seed", {}, {"seed": 1234}, ["Out"], {"Out": "int32"})
        np.testing.assert_array_equal(d["Out"], np.asarray([1234], "int32"))

    def test_seed_random(self):
        d = run_op("seed", {}, {"seed": 0}, ["Out"], {"Out": "int32"})
        v = int(d["Out"][0])
        assert 1 <= v < 2 ** 31


# ---------------------------------------------------------------------------
# random_crop
# ---------------------------------------------------------------------------

class TestRandomCrop:
    def test_shape_and_membership(self):
        # crop must be a contiguous window of x along the trailing dims
        x = np.arange(2 * 8 * 9, dtype="float32").reshape(2, 8, 9)
        seed = np.asarray([7], "int64")
        d = run_op("random_crop", {"X": x, "Seed": seed},
                   {"shape": [5, 4]}, ["Out", "SeedOut"],
                   {"SeedOut": "int64"})
        out = d["Out"]
        assert out.shape == (2, 5, 4)
        # locate the window via the first element (x values are unique)
        flat = int(out[0, 0, 0])
        r, c = flat // 9 % 8, flat % 9
        np.testing.assert_array_equal(out, x[:, r:r + 5, c:c + 4])

    def test_offsets_in_bounds_full_crop(self):
        # crop size == input size must be the identity
        x = randf(3, 4, 4, seed=27)
        d = run_op("random_crop", {"X": x, "Seed": np.asarray([1], "int64")},
                   {"shape": [4, 4]}, ["Out"])
        np.testing.assert_array_equal(d["Out"], x)


# ---------------------------------------------------------------------------
# average_accumulates (ModelAverage window state machine)
# ---------------------------------------------------------------------------

def _avg_acc_oracle(param, s1, s2, s3, num_acc, old_num, num_upd,
                    average_window, max_avg, min_avg):
    """Independent numpy re-derivation of average_accumulates_op.h."""
    k_max = 16384
    num_upd += 1
    num_acc += 1
    s1 = s1 + param
    if num_upd % k_max == 0:
        s2, s1 = s2 + s1, np.zeros_like(s1)
    window = min(max_avg, int(num_upd * average_window))
    if num_acc >= min_avg and num_acc >= window:
        s3 = s1 + s2
        s1, s2 = np.zeros_like(s1), np.zeros_like(s2)
        old_num, num_acc = num_acc, 0
    return s1, s2, s3, num_acc, old_num, num_upd


class TestAverageAccumulates:
    def _step(self, param, state, attrs):
        s1, s2, s3, num_acc, old_num, num_upd = state
        d = run_op(
            "average_accumulates",
            {"param": param, "in_sum_1": s1, "in_sum_2": s2,
             "in_sum_3": s3,
             "in_num_accumulates": np.asarray([num_acc], "int64"),
             "in_old_num_accumulates": np.asarray([old_num], "int64"),
             "in_num_updates": np.asarray([num_upd], "int64")},
            attrs,
            ["out_sum_1", "out_sum_2", "out_sum_3",
             "out_num_accumulates", "out_old_num_accumulates",
             "out_num_updates"],
            {"out_num_accumulates": "int64",
             "out_old_num_accumulates": "int64",
             "out_num_updates": "int64"})
        return (d["out_sum_1"], d["out_sum_2"], d["out_sum_3"],
                int(d["out_num_accumulates"][0]),
                int(d["out_old_num_accumulates"][0]),
                int(d["out_num_updates"][0]))

    def test_accumulate_then_roll(self):
        attrs = {"average_window": 1.0, "max_average_window": 100,
                 "min_average_window": 3}
        z = np.zeros((2, 3), "float32")
        state = (z, z, z, 0, 0, 0)
        oracle = (z, z, z, 0, 0, 0)
        rng = np.random.RandomState(28)
        for step in range(5):
            param = rng.uniform(-1, 1, (2, 3)).astype("float32")
            state = self._step(param, state, attrs)
            oracle = _avg_acc_oracle(param, *[np.asarray(o) if i < 3
                                              else o for i, o in
                                              enumerate(oracle[:3])]
                                     + list(oracle[3:]),
                                     1.0, 100, 3)
            for got, want in zip(state[:3], oracle[:3]):
                np.testing.assert_allclose(got, want, rtol=1e-5,
                                           err_msg=f"step {step}")
            assert state[3:] == tuple(oracle[3:]), f"step {step}"
        # with min_average_window=3 the window must have rolled at
        # step 3 (num_acc reached 3): old_num records it
        assert state[4] >= 3

    def test_no_roll_below_min_window(self):
        attrs = {"average_window": 1.0, "max_average_window": 100,
                 "min_average_window": 100}
        z = np.zeros((4,), "float32")
        state = (z, z, z, 0, 0, 0)
        p = np.ones((4,), "float32")
        for _ in range(3):
            state = self._step(p, state, attrs)
        # never rolled: sum_1 keeps accumulating, sum_3 untouched
        np.testing.assert_allclose(state[0], 3 * p)
        np.testing.assert_array_equal(state[2], z)
        assert state[3] == 3 and state[4] == 0 and state[5] == 3
