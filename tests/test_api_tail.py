"""Top-level paddle.* API tail (reference python/paddle/__init__.py
DEFINE_ALIAS set): every name the reference exports at top level must
exist here, and the round-5 additions must match numpy oracles."""

import re

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.fluid import dygraph


@pytest.fixture(autouse=True)
def _dygraph():
    with dygraph.guard():
        yield


def _t(a, dtype="float32"):
    return paddle.to_tensor(np.asarray(a, dtype=dtype))


def test_every_reference_top_level_name_exists():
    import os
    if not os.path.isdir("/root/reference"):
        pytest.skip("reference source tree not present in this environment")
    src = open("/root/reference/python/paddle/__init__.py").read()
    names = set(re.findall(r"from [\w.]+ import (\w+)\s+#DEFINE_ALIAS",
                           src))
    missing = sorted(n for n in names if not hasattr(paddle, n))
    assert missing == [], f"missing top-level API: {missing}"


def test_add_n_addcmul_mm():
    a, b, c = (np.random.RandomState(i).rand(3, 4).astype("float32")
               for i in range(3))
    np.testing.assert_allclose(
        paddle.add_n([_t(a), _t(b), _t(c)]).numpy(), a + b + c,
        rtol=1e-6)
    np.testing.assert_allclose(
        paddle.addcmul(_t(a), _t(b), _t(c), value=0.5).numpy(),
        a + 0.5 * b * c, rtol=1e-6)
    w = np.random.rand(4, 2).astype("float32")
    np.testing.assert_allclose(paddle.mm(_t(a), _t(w)).numpy(), a @ w,
                               rtol=1e-5)


def test_einsum_and_tensordot():
    a = np.random.RandomState(0).rand(2, 3, 4).astype("float32")
    b = np.random.RandomState(1).rand(4, 5).astype("float32")
    np.testing.assert_allclose(
        paddle.einsum("bij,jk->bik", _t(a), _t(b)).numpy(),
        np.einsum("bij,jk->bik", a, b), rtol=1e-5)
    np.testing.assert_allclose(
        paddle.tensordot(_t(a), _t(b), axes=1).numpy(),
        np.tensordot(a, b, axes=1), rtol=1e-5)


def test_scatter_nd_multiplex_unbind():
    idx = np.array([[1], [3], [1]], "int64")
    upd = np.array([9.0, 10.0, 11.0], "float32")
    out = paddle.scatter_nd(_t(idx, "int64"), _t(upd), [5]).numpy()
    want = np.zeros(5, "float32")
    np.add.at(want, idx[:, 0], upd)
    np.testing.assert_allclose(out, want)

    x1 = np.arange(6, dtype="float32").reshape(3, 2)
    x2 = x1 + 100
    ids = np.array([[0], [1], [0]], "int32")
    got = paddle.multiplex([_t(x1), _t(x2)], _t(ids, "int32")).numpy()
    np.testing.assert_allclose(got, np.stack([x1[0], x2[1], x1[2]]))

    parts = paddle.unbind(_t(x1), axis=0)
    assert len(parts) == 3
    np.testing.assert_allclose(parts[1].numpy(), x1[1])


def test_has_nan_inf_inverse_rank():
    x = np.array([1.0, np.nan], "float32")
    assert bool(paddle.has_nan(_t(x)).numpy())
    assert not bool(paddle.has_inf(_t(x)).numpy())
    m = np.array([[2.0, 0.0], [0.0, 4.0]], "float32")
    np.testing.assert_allclose(paddle.inverse(_t(m)).numpy(),
                               np.linalg.inv(m), rtol=1e-5)
    assert int(paddle.rank(_t(m)).numpy()) == 2
    assert paddle.is_tensor(_t(m)) and not paddle.is_tensor(m)


def test_default_dtype_and_broadcast_shape():
    assert paddle.get_default_dtype() == "float32"
    paddle.set_default_dtype("float64")
    try:
        assert paddle.get_default_dtype() == "float64"
        with pytest.raises(TypeError):
            paddle.set_default_dtype("int32")
    finally:
        paddle.set_default_dtype("float32")
    assert paddle.broadcast_shape([2, 1, 3], [4, 3]) == [2, 4, 3]


def test_lod_tensor_shim_feeds_executor():
    """fluid.LoDTensor().set(...) scripts keep working: the shim is
    array-like, so Executor feeds accept it."""
    import paddle_tpu.fluid as fluid

    t = fluid.LoDTensor()
    t.set(np.ones((2, 3), "float32"), fluid.CPUPlace())
    t.set_recursive_sequence_lengths([[1, 1]])
    assert t.recursive_sequence_lengths() == [[1, 1]]
    assert t.shape() == [2, 3]
    np.testing.assert_allclose(np.asarray(t), np.ones((2, 3)))
    assert isinstance(fluid.LoDTensorArray([1, 2]), list)


def test_cuda_compat_stubs():
    assert paddle.get_cuda_rng_state() == []
    paddle.set_cuda_rng_state([])
    with pytest.raises(ValueError):
        paddle.set_cuda_rng_state([b"state"])
    assert repr(paddle.CUDAPinnedPlace()) == "CUDAPinnedPlace"
    t = paddle.get_tensor_from_selected_rows(_t([1.0]))
    assert paddle.is_tensor(t)
    with pytest.raises(TypeError):
        paddle.get_tensor_from_selected_rows(np.ones(3))


def test_submodule_surfaces_complete():
    """Every uncommented DEFINE_ALIAS name in each reference submodule
    resolves on ours (the paddle.nn/nn.functional variants have their
    own dedicated tests)."""
    import importlib
    import os

    R = "/root/reference/python/paddle"

    def ref_names(path):
        names = set()
        for line in open(path):
            s = line.strip()
            if s.startswith("#"):
                continue
            m = re.match(r"from [\w.]+ import (\w+)\s+#DEFINE_ALIAS", s)
            if m:
                names.add(m.group(1))
        return names

    gaps = {}
    for sub in ["tensor", "optimizer", "static", "io", "metric",
                "distribution", "amp", "vision", "text", "jit",
                "distributed", "framework"]:
        path = f"{R}/{sub}/__init__.py"
        if not os.path.exists(path):
            path = f"{R}/{sub}.py"
        if not os.path.exists(path):
            continue
        names = ref_names(path)
        mod = importlib.import_module(f"paddle_tpu.{sub}")
        missing = sorted(n for n in names if not hasattr(mod, n))
        if missing:
            gaps[sub] = missing
    assert gaps == {}, f"submodule surface gaps: {gaps}"


def test_device_and_framework_modules():
    import paddle_tpu.device as device
    import paddle_tpu.framework as framework

    assert device.get_cudnn_version() is None
    assert not device.is_compiled_with_xpu()
    d = device.set_device("cpu")
    assert device.get_device() == "cpu"
    assert d is not None
    assert framework.seed(7) == 7
    assert framework.ComplexVariable is framework.VarBase


def test_static_print_and_parallel_executor():
    import paddle_tpu.fluid as fluid
    import paddle_tpu.static as static

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [-1, 4], "float32")
        y = static.Print(x, message="dbg")
        loss = fluid.layers.reduce_mean(y)
    exe = fluid.Executor()
    exe.run(startup)
    out = exe.run(main, feed={"x": np.ones((2, 4), "float32")},
                  fetch_list=[loss])
    np.testing.assert_allclose(out[0], 1.0, rtol=1e-6)
    assert hasattr(static, "ParallelExecutor")
    assert hasattr(static, "py_func")


def test_incubate_complex_and_reader():
    """paddle.incubate (reference incubate/__init__.py): the complex
    tensor API over NATIVE jax complex dtypes (the reference's
    ComplexVariable pair plumbing predates them) + the distributed
    reader shard."""
    import os

    import paddle_tpu as paddle

    C = paddle.incubate.complex
    a = np.array([[1 + 2j, 3 + 4j], [5 + 6j, 7 + 8j]], "complex64")
    b = np.array([[1 - 1j, 0], [0, 1 + 1j]], "complex64")
    np.testing.assert_allclose(C.matmul(a, b).numpy(), a @ b,
                               rtol=1e-6)
    np.testing.assert_allclose(C.elementwise_div(a, b + 1).numpy(),
                               a / (b + 1), rtol=1e-6)
    np.testing.assert_allclose(C.kron(a, b).numpy(), np.kron(a, b))
    np.testing.assert_allclose(
        C.transpose(a, [1, 0]).numpy(), a.T)
    np.testing.assert_allclose(C.sum(a).numpy(), a.sum())

    old = {k: os.environ.get(k)
           for k in ("PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM")}
    os.environ["PADDLE_TRAINER_ID"] = "1"
    os.environ["PADDLE_TRAINERS_NUM"] = "2"
    try:
        from paddle_tpu.fluid.contrib.reader import (
            distributed_batch_reader)

        r = distributed_batch_reader(lambda: iter(range(6)))
        assert list(r()) == [1, 3, 5]
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
