"""Observability tail (VERDICT r3 Missing #6): StatRegistry counters,
Executor FetchHandler, fleet distributed metrics."""

import time

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu import profiler
from paddle_tpu.distributed.fleet import metrics
from paddle_tpu.fluid import framework
from paddle_tpu.fluid.executor import (FetchHandler, Scope, scope_guard)


def _simple_program():
    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        x = fluid.data("x", [-1, 4], "float32")
        y = fluid.layers.fc(x, 2)
        loss = fluid.layers.reduce_mean(y)
    return main, startup, loss


def test_stat_registry_counters():
    profiler.stat_reset()
    profiler.stat_add("my_counter", 5)
    profiler.stat_add("my_counter", 2)
    assert profiler.get_int_stats()["my_counter"] == 7
    # the Executor bumps run/compile counters (monitor.h STAT_ADD role)
    main, startup, loss = _simple_program()
    with scope_guard(Scope()):
        exe = fluid.Executor()
        exe.run(startup)
        before = profiler.get_int_stats()
        exe.run(main, feed={"x": np.ones((2, 4), "float32")},
                fetch_list=[loss])
        exe.run(main, feed={"x": np.ones((2, 4), "float32")},
                fetch_list=[loss])
        after = profiler.get_int_stats()
    assert after["executor_run_count"] - before.get(
        "executor_run_count", 0) == 2
    assert after["executor_compile_count"] - before.get(
        "executor_compile_count", 0) == 1  # second run hits the cache
    profiler.stat_reset("my_counter")
    assert "my_counter" not in profiler.get_int_stats()


def test_fetch_handler_fires(tmp_path):
    """The async monitor snapshots scope vars during a dataset loop."""
    main, startup, loss = _simple_program()
    seen = []

    class H(FetchHandler):
        def handler(self, res_dict):
            seen.append(dict(res_dict))

    # one MultiSlot file with 64 single-slot rows of 4 floats
    path = str(tmp_path / "part-0.txt")
    rng = np.random.RandomState(0)
    with open(path, "w") as f:
        for _ in range(64):
            f.write("4 " + " ".join(
                f"{v:.6f}" for v in rng.randn(4)) + "\n")

    scope = Scope()
    with scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(4)
        ds.set_use_var([main.global_block().var("x")])
        ds.set_filelist([path])
        ds.load_into_memory()
        w_name = next(v.name for v in main.list_vars()
                      if v.persistable and v.name.endswith(".w_0"))
        handler = H(var_dict={"w": w_name}, period_secs=0.02)
        t0 = time.time()
        while time.time() - t0 < 0.5 and not seen:
            exe.train_from_dataset(main, ds, fetch_list=[loss],
                                   fetch_handler=handler)
    assert seen, "fetch handler never fired"
    assert any("w" in d and d["w"].shape == (4, 2) for d in seen)


def test_fleet_metrics_match_local():
    """Shard the data 8 ways, accumulate auc-op stats per shard, then
    fleet.metrics.auc over the shard stats must equal the single-shot
    auc over the full data (done-criterion of VERDICT r3 next #8)."""
    from op_test import run_single_op

    rng = np.random.RandomState(0)
    n = 256
    scores = rng.rand(n).astype("float32")
    labels = (rng.rand(n) < scores).astype("int64")  # informative preds
    pred2 = np.stack([1 - scores, scores], axis=1)
    nt = 255

    def stats(lo, hi):
        d = run_single_op(
            "auc",
            {"Predict": pred2[lo:hi], "Label": labels[lo:hi, None],
             "StatPos": np.zeros(nt + 1, "int64"),
             "StatNeg": np.zeros(nt + 1, "int64")},
            {"num_thresholds": nt, "slide_steps": 0},
            ["AUC", "StatPosOut", "StatNegOut"],
            {"StatPosOut": "int64", "StatNegOut": "int64"})
        return d["StatPosOut"], d["StatNegOut"], float(d["AUC"])

    # single shot over everything
    _, _, local_auc = stats(0, n)
    # 8 worker shards -> fleet reduction
    shard_pos, shard_neg = [], []
    for w in range(8):
        p, ng, _ = stats(w * 32, (w + 1) * 32)
        shard_pos.append(p)
        shard_neg.append(ng)
    fleet_auc = metrics.auc(shard_pos, shard_neg)
    np.testing.assert_allclose(fleet_auc, local_auc, rtol=1e-6)
    # sanity: the metric is informative, not degenerate
    assert 0.6 < fleet_auc < 1.0

    # the scalar helpers reduce across workers too
    assert metrics.acc([np.array([3.0]), np.array([1.0])],
                       [np.array([4.0]), np.array([4.0])]) == 0.5
    np.testing.assert_allclose(
        metrics.rmse([np.array([8.0]), np.array([10.0])],
                     [np.array([1.0]), np.array([1.0])]), 3.0)


class TestChromeTracingExport:
    def test_export_chrome_tracing(self, tmp_path):
        """RecordEvent host phases round-trip to a chrome://tracing
        JSON (the reference's tools/timeline.py conversion path)."""
        import json

        from paddle_tpu import profiler as prof

        prof.reset_profiler()
        prof.start_profiler()
        with prof.RecordEvent("forward"):
            with prof.RecordEvent("attention"):
                pass
        with prof.RecordEvent("forward"):
            pass
        prof.stop_profiler(profile_path=None)
        out = tmp_path / "trace.json"
        n = prof.export_chrome_tracing(str(out))
        assert n == 3
        doc = json.loads(out.read_text())
        evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert {e["name"] for e in evs} == {"forward", "attention"}
        assert all(e["dur"] >= 0 for e in evs)
        # nesting: attention lies within one forward span
        att = next(e for e in evs if e["name"] == "attention")
        fwd = [e for e in evs if e["name"] == "forward"]
        assert any(f["ts"] <= att["ts"] and
                   att["ts"] + att["dur"] <= f["ts"] + f["dur"] + 1e-3
                   for f in fwd)
        assert doc["otherData"]["dropped_events"] == 0

    def test_timeline_cap_counts_drops(self, tmp_path):
        """The bounded span buffer behind export_chrome_tracing counts
        overflow instead of losing it silently (the RecordEvent path
        now records into paddle_tpu.obs — ISSUE 6)."""
        import json

        from paddle_tpu import obs
        from paddle_tpu import profiler as prof

        prof.reset_profiler()
        old_cap = obs.TRACER.capacity
        obs.TRACER.capacity = 2
        try:
            prof.start_profiler()
            for _ in range(5):
                with prof.RecordEvent("e"):
                    pass
            prof.stop_profiler(profile_path=None)
            out = tmp_path / "capped.json"
            assert prof.export_chrome_tracing(str(out)) == 2
            doc = json.loads(out.read_text())
            assert doc["otherData"]["dropped_events"] == 3
        finally:
            obs.TRACER.capacity = old_cap
            prof.reset_profiler()
