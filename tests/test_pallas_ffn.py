"""Fused FFN Pallas kernel (ops/pallas/ffn.py): forward/backward parity
vs the XLA oracle in interpret mode, in-kernel hash dropout consistency
between forward and both backward passes, the dispatcher fallback, and
tpu-marked non-interpret variants for the hardware lane.

Reference counterpart: the CUDA fused_feedforward operator family
(/root/reference/paddle/fluid/operators/fused/fused_feedforward_op.cu:1).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.ffn import (_ffn_keep, fused_ffn)


def _params(T=256, H=128, F=256, seed=0, dtype=jnp.float32):
    r = np.random.RandomState(seed)
    return (jnp.asarray(r.randn(T, H), dtype),
            jnp.asarray(r.randn(H, F) * 0.05, dtype),
            jnp.asarray(r.randn(F) * 0.01, dtype),
            jnp.asarray(r.randn(F, H) * 0.05, dtype),
            jnp.asarray(r.randn(H) * 0.01, dtype))


def _ref(x, w1, b1, w2, b2, activation="gelu", keep=None, p=0.0):
    # "gelu" is the EXACT erf form (the repo's GELU()/F.gelu default)
    act = (lambda v: jax.nn.gelu(v, approximate=False)) \
        if activation == "gelu" else jax.nn.relu
    h = act(x @ w1 + b1)
    if keep is not None:
        h = jnp.where(keep, h / (1.0 - p), 0.0)
    return h @ w2 + b2


class TestFusedFFNInterpret:
    def test_forward_matches_oracle(self):
        x, w1, b1, w2, b2 = _params()
        out = fused_ffn(x, w1, b1, w2, b2, interpret=True)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_ref(x, w1, b1, w2, b2)),
                                   atol=2e-5)

    def test_relu_and_leading_dims(self):
        x, w1, b1, w2, b2 = _params()
        x3 = x.reshape(2, 128, 128)
        out = fused_ffn(x3, w1, b1, w2, b2, activation="relu",
                        interpret=True)
        want = _ref(x, w1, b1, w2, b2, activation="relu")
        np.testing.assert_allclose(np.asarray(out).reshape(256, 128),
                                   np.asarray(want), atol=2e-5)

    def test_gradients_match_oracle(self):
        x, w1, b1, w2, b2 = _params()

        def lk(a):
            return jnp.sum(fused_ffn(*a, interpret=True) ** 2)

        def lr(a):
            return jnp.sum(_ref(*a) ** 2)

        gk = jax.grad(lk)((x, w1, b1, w2, b2))
        gr = jax.grad(lr)((x, w1, b1, w2, b2))
        for name, a, b in zip(("dx", "dw1", "db1", "dw2", "db2"), gk,
                              gr):
            scale = max(1.0, float(jnp.max(jnp.abs(b))))
            np.testing.assert_allclose(
                np.asarray(a) / scale, np.asarray(b) / scale,
                atol=3e-6, err_msg=name)

    def test_dropout_forward_matches_hash_oracle(self):
        """The kernel's per-tile hash mask equals the full-array mask
        (absolute coordinates), so an oracle using _ffn_keep directly
        must agree exactly."""
        x, w1, b1, w2, b2 = _params(seed=1)
        seed = jnp.asarray([1234], jnp.int32)
        p = 0.3
        out = fused_ffn(x, w1, b1, w2, b2, dropout_p=p,
                        dropout_seed=seed, interpret=True)
        keep = _ffn_keep(seed.reshape(()), 0, 0, 256, 256, p)
        want = _ref(x, w1, b1, w2, b2, keep=keep, p=p)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   atol=3e-5)

    def test_dropout_gradients_consistent(self):
        """fwd and both bwd kernels must regenerate the SAME mask."""
        x, w1, b1, w2, b2 = _params(seed=2)
        seed = jnp.asarray([77], jnp.int32)
        p = 0.25

        def lk(a):
            return jnp.sum(fused_ffn(*a, dropout_p=p, dropout_seed=seed,
                                     interpret=True) ** 2)

        keep = _ffn_keep(seed.reshape(()), 0, 0, 256, 256, p)

        def lr(a):
            return jnp.sum(_ref(*a, keep=keep, p=p) ** 2)

        gk = jax.grad(lk)((x, w1, b1, w2, b2))
        gr = jax.grad(lr)((x, w1, b1, w2, b2))
        for name, a, b in zip(("dx", "dw1", "db1", "dw2", "db2"), gk,
                              gr):
            scale = max(1.0, float(jnp.max(jnp.abs(b))))
            np.testing.assert_allclose(
                np.asarray(a) / scale, np.asarray(b) / scale,
                atol=5e-6, err_msg=name)

    def test_bf16_path(self):
        x, w1, b1, w2, b2 = _params(dtype=jnp.bfloat16)
        out = fused_ffn(x, w1, b1, w2, b2, interpret=True)
        want = _ref(x.astype(jnp.float32), w1.astype(jnp.float32),
                    b1.astype(jnp.float32), w2.astype(jnp.float32),
                    b2.astype(jnp.float32))
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(want), atol=0.15)

    def test_untileable_shapes_fall_back(self):
        # T=100 not divisible by the 128-multiple block: XLA path, but
        # same hash dropout -> still deterministic
        r = np.random.RandomState(3)
        x = jnp.asarray(r.randn(100, 128), jnp.float32)
        _, w1, b1, w2, b2 = _params()
        out = fused_ffn(x, w1, b1, w2, b2)
        want = _ref(x, w1, b1, w2, b2)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   atol=2e-5)


class TestKernelChoiceSeam:
    """The re-armed FFN A/B (ISSUE 19): `tune.kernel_choice("ffn")`
    pins one dispatch arm at trace time.  Fresh CPU-interpret parity
    for BOTH arms here; the on-chip step-time verdict stays pending
    the hardware lane (artifacts/FFN_AB_r19.md — the 2026-07-31
    baseline was XLA 120.9 ms vs kernel 136.6 ms per step)."""

    def _stat(self, name):
        from paddle_tpu import profiler

        return profiler.get_int_stats().get(name, 0)

    def test_xla_choice_forces_fallback_even_in_interpret(self):
        from paddle_tpu import tune
        from paddle_tpu.tune import TunedConfig

        x, w1, b1, w2, b2 = _params(seed=7)
        k0 = self._stat("ffn_dispatch_kernel")
        x0 = self._stat("ffn_dispatch_xla")
        with tune.config_override(TunedConfig(kernels={"ffn": "xla"})):
            out = fused_ffn(x, w1, b1, w2, b2, interpret=True)
        assert self._stat("ffn_dispatch_xla") == x0 + 1
        assert self._stat("ffn_dispatch_kernel") == k0
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_ref(x, w1, b1, w2, b2)),
                                   atol=2e-5)

    def test_pallas_choice_takes_kernel_arm_and_matches(self):
        from paddle_tpu import tune
        from paddle_tpu.tune import TunedConfig

        x, w1, b1, w2, b2 = _params(seed=8)
        k0 = self._stat("ffn_dispatch_kernel")
        cfg = TunedConfig(kernels={"ffn": "pallas"})
        with tune.config_override(cfg):
            out = fused_ffn(x, w1, b1, w2, b2, interpret=True)
        assert self._stat("ffn_dispatch_kernel") == k0 + 1
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_ref(x, w1, b1, w2, b2)),
                                   atol=2e-5)
        # both arms agree with each other (the A/B is perf-only)
        with tune.config_override(TunedConfig(kernels={"ffn": "xla"})):
            xla_out = fused_ffn(x, w1, b1, w2, b2, interpret=True)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(xla_out), atol=2e-5)

    def test_untuned_dispatch_is_unchanged(self):
        from paddle_tpu import tune

        assert tune.kernel_choice("ffn") is None
        x, w1, b1, w2, b2 = _params(seed=9)
        k0 = self._stat("ffn_dispatch_kernel")
        out = fused_ffn(x, w1, b1, w2, b2, interpret=True)
        # interpret mode keeps taking the kernel arm with no override
        assert self._stat("ffn_dispatch_kernel") == k0 + 1
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_ref(x, w1, b1, w2, b2)),
                                   atol=2e-5)


@pytest.mark.tpu
class TestFusedFFNOnTPU:
    """Non-interpret Mosaic compilation + numerics on real hardware
    (PADDLE_TPU_TEST_LANE=1).  The kernel is opt-in by default (the
    2026-07-31 on-chip A/B showed the XLA FFN path faster for the
    bench config), so the lane enables it explicitly — the point here
    is that Mosaic still compiles it and its numerics still hold for
    whoever opts in."""

    def test_forward_backward_on_chip(self):
        import paddle_tpu.ops.pallas.ffn as ffn_mod

        prev = ffn_mod._FFN_DISABLED
        ffn_mod.enable_fused_ffn()
        try:
            self._run_kernel_vs_ref()
        finally:
            ffn_mod._FFN_DISABLED = prev

    def _run_kernel_vs_ref(self):
        x, w1, b1, w2, b2 = _params(T=512, H=256, F=512,
                                    dtype=jnp.bfloat16)

        def lk(a):
            return jnp.sum(fused_ffn(*a).astype(jnp.float32) ** 2)

        def lr(a):
            af = tuple(v.astype(jnp.float32) for v in a)
            return jnp.sum(_ref(*af) ** 2)

        lk_v = float(jax.jit(lk)((x, w1, b1, w2, b2)))
        lr_v = float(jax.jit(lr)((x, w1, b1, w2, b2)))
        assert abs(lk_v - lr_v) / max(1.0, abs(lr_v)) < 0.05
        gk = jax.grad(lk)((x, w1, b1, w2, b2))
        assert all(bool(jnp.all(jnp.isfinite(
            g.astype(jnp.float32)))) for g in gk)
