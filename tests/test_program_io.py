"""Program-level io contract: save/load/save_combine/load_combine ops
inside programs, and the load_inference_model fresh-process round-trip
(reference save_op.cc, load_op.cc, fluid/io.py)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework
from paddle_tpu.fluid.executor import Scope, scope_guard


def _run_program(main, feed, fetch):
    with scope_guard(Scope()):
        exe = fluid.Executor()
        return exe.run(main, feed=feed, fetch_list=fetch)


def test_save_load_op_roundtrip(tmp_path):
    path = str(tmp_path / "tensor.pk")
    x = np.random.RandomState(0).randn(3, 4).astype("float32")

    main = framework.Program()
    with framework.program_guard(main, framework.Program()):
        v = fluid.data("v", [3, 4], "float32")
        main.global_block().append_op(
            "save", inputs={"X": [v.name]}, outputs={},
            attrs={"file_path": path})
        w = fluid.layers.scale(v, 2.0)
    _run_program(main, {"v": x}, [w.name])
    assert os.path.exists(path)

    load_prog = framework.Program()
    with framework.program_guard(load_prog, framework.Program()):
        block = load_prog.global_block()
        out = block.create_var(name="loaded", shape=[3, 4],
                               dtype="float32")
        block.append_op("load", inputs={}, outputs={"Out": [out.name]},
                        attrs={"file_path": path})
        doubled = fluid.layers.scale(out, 2.0)
    (got,) = _run_program(load_prog, {}, [doubled.name])
    np.testing.assert_allclose(np.asarray(got), 2 * x, rtol=1e-6)


def test_save_combine_load_combine_roundtrip(tmp_path):
    path = str(tmp_path / "bundle")
    rng = np.random.RandomState(1)
    a = rng.randn(2, 3).astype("float32")
    b = rng.randn(4).astype("float32")

    main = framework.Program()
    with framework.program_guard(main, framework.Program()):
        va = fluid.data("a", [2, 3], "float32")
        vb = fluid.data("b", [4], "float32")
        main.global_block().append_op(
            "save_combine", inputs={"X": [va.name, vb.name]}, outputs={},
            attrs={"file_path": path})
        s = fluid.layers.reduce_sum(va)
    _run_program(main, {"a": a, "b": b}, [s.name])

    load_prog = framework.Program()
    with framework.program_guard(load_prog, framework.Program()):
        block = load_prog.global_block()
        oa = block.create_var(name="oa", shape=[2, 3], dtype="float32")
        ob = block.create_var(name="ob", shape=[4], dtype="float32")
        block.append_op("load_combine", inputs={},
                        outputs={"Out": [oa.name, ob.name]},
                        attrs={"file_path": path})
        sa = fluid.layers.scale(oa, 1.0)
        sb = fluid.layers.scale(ob, 1.0)
    ga, gb = _run_program(load_prog, {}, [sa.name, sb.name])
    np.testing.assert_allclose(np.asarray(ga), a, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(gb), b, rtol=1e-6)


def test_load_inference_model_fresh_process(tmp_path):
    """build -> save_inference_model -> NEW python process loads the
    Program JSON + params with no model code -> identical fetches."""
    dirname = str(tmp_path / "model")
    x = np.random.RandomState(2).randn(4, 8).astype("float32")

    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        inp = fluid.data("inp", [-1, 8], "float32")
        hidden = fluid.layers.fc(inp, 16, act="relu")
        out = fluid.layers.fc(hidden, 3, act="softmax")
    scope = Scope()
    with scope_guard(scope):
        exe = fluid.Executor()
        exe.run(startup)
        (want,) = exe.run(main, feed={"inp": x}, fetch_list=[out.name])
        fluid.io.save_inference_model(dirname, ["inp"], [out], exe, main)

    in_path = str(tmp_path / "in.npy")
    out_path = str(tmp_path / "out.npy")
    np.save(in_path, x)
    fixture = os.path.join(os.path.dirname(__file__), "fixtures",
                           "infer_loader.py")
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(fixture)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo_root)
    subprocess.run([sys.executable, fixture, dirname, in_path, out_path],
                   check=True, env=env, cwd=repo_root, timeout=300)
    got = np.load(out_path)
    np.testing.assert_allclose(got, np.asarray(want), atol=1e-5)


def test_onnx_export_descope_contract(tmp_path):
    """paddle.onnx.export: emits the StableHLO deployment artifact
    (explicit descope of ONNX protobufs — README); the artifact runs
    through the Predictor and matches eager outputs.  fmt='onnx' raises
    the documented error."""
    import paddle_tpu as paddle
    from paddle_tpu import onnx as ponnx
    from paddle_tpu.inference import Predictor
    from paddle_tpu.static import InputSpec
    from paddle_tpu.vision.models import LeNet

    paddle.disable_static()
    net = LeNet()
    net.eval()
    path = str(tmp_path / "lenet")
    out_path = ponnx.export(
        net, path, input_spec=[InputSpec([1, 1, 28, 28], "float32")])
    assert out_path.endswith(".stablehlo")
    x = np.random.RandomState(0).rand(1, 1, 28, 28).astype("float32")
    want = net(paddle.to_tensor(x)).numpy()
    from paddle_tpu.inference import Config
    pred = Predictor(Config(path))
    (got,) = pred.run([x])
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)

    with pytest.raises(NotImplementedError, match="StableHLO"):
        ponnx.export(net, path, fmt="onnx")
