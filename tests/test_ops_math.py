"""Op tests: math/elementwise/reduction/activation families
(mirrors reference unittests test_activation_op.py, test_elementwise_*_op.py,
test_reduce_op.py, test_matmul_op.py methodology)."""

import numpy as np
import pytest

from op_test import OpTest, randf


class TestRelu(OpTest):
    op_type = "relu"

    def setup(self):
        x = randf(4, 5, seed=1)
        x[np.abs(x) < 0.05] = 0.1  # keep away from the kink
        self.inputs = {"X": x}
        self.outputs = {"Out": np.maximum(x, 0)}

    def test(self):
        self.setup()
        self.check_output()
        self.check_grad(["X"], "Out")


class TestSigmoid(OpTest):
    op_type = "sigmoid"

    def test(self):
        x = randf(4, 5, seed=2)
        self.inputs = {"X": x}
        self.outputs = {"Out": 1 / (1 + np.exp(-x))}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestTanh(OpTest):
    op_type = "tanh"

    def test(self):
        x = randf(4, 5, seed=3)
        self.inputs = {"X": x}
        self.outputs = {"Out": np.tanh(x)}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestGelu(OpTest):
    op_type = "gelu"

    def test(self):
        from scipy.special import erf  # scipy is available via jax deps

        x = randf(4, 5, seed=4)
        self.inputs = {"X": x}
        self.outputs = {"Out": 0.5 * x * (1 + erf(x / np.sqrt(2)))}
        self.check_output(atol=1e-4)
        self.check_grad(["X"], "Out", max_relative_error=1e-2)


class TestExpLog(OpTest):
    op_type = "exp"

    def test(self):
        x = randf(3, 4, seed=5)
        self.inputs = {"X": x}
        self.outputs = {"Out": np.exp(x)}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestSqrtGrad(OpTest):
    op_type = "sqrt"

    def test(self):
        x = randf(3, 4, low=0.5, high=2.0, seed=6)
        self.inputs = {"X": x}
        self.outputs = {"Out": np.sqrt(x)}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestSoftplus(OpTest):
    op_type = "softplus"

    def test(self):
        x = randf(3, 4, seed=7)
        self.inputs = {"X": x}
        self.attrs = {"beta": 1.0, "threshold": 20.0}
        self.outputs = {"Out": np.log1p(np.exp(x))}
        self.check_output(atol=1e-5)
        self.check_grad(["X"], "Out")


class TestLeakyRelu(OpTest):
    op_type = "leaky_relu"

    def test(self):
        x = randf(3, 4, seed=8)
        x[np.abs(x) < 0.05] = 0.1
        self.inputs = {"X": x}
        self.attrs = {"alpha": 0.1}
        self.outputs = {"Out": np.where(x >= 0, x, 0.1 * x)}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestElementwiseAdd(OpTest):
    op_type = "elementwise_add"

    def test(self):
        x, y = randf(3, 4, seed=10), randf(3, 4, seed=11)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x + y}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestElementwiseAddBroadcastAxis(OpTest):
    op_type = "elementwise_add"

    def test(self):
        x, y = randf(2, 3, 4, seed=12), randf(3, seed=13)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": x + y.reshape(1, 3, 1)}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestElementwiseMulBroadcast(OpTest):
    op_type = "elementwise_mul"

    def test(self):
        x, y = randf(2, 3, 4, seed=14), randf(4, seed=15)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x * y}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestElementwiseDiv(OpTest):
    op_type = "elementwise_div"

    def test(self):
        x = randf(3, 4, seed=16)
        y = randf(3, 4, low=0.5, high=2.0, seed=17)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x / y}
        self.check_output()
        self.check_grad(["X", "Y"], "Out", max_relative_error=1e-2)


class TestElementwiseSubTrailingOnes(OpTest):
    op_type = "elementwise_sub"

    def test(self):
        x = randf(2, 3, 4, 5, seed=18)
        y = randf(3, 4, 1, 1, seed=19)  # paddle trailing-1 stripping
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": x - y.reshape(1, 3, 4, 1)}
        self.check_output()


class TestScale(OpTest):
    op_type = "scale"

    def test(self):
        x = randf(3, 4, seed=20)
        self.inputs = {"X": x}
        self.attrs = {"scale": 2.5, "bias": 0.5, "bias_after_scale": True}
        self.outputs = {"Out": 2.5 * x + 0.5}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestSumMulti(OpTest):
    op_type = "sum"

    def test(self):
        xs = [randf(3, 4, seed=21 + i) for i in range(3)]
        self.inputs = {"X": xs}
        self.outputs = {"Out": xs[0] + xs[1] + xs[2]}
        self.check_output()


class TestMatmul(OpTest):
    op_type = "matmul"

    def test(self):
        x, y = randf(3, 4, seed=30), randf(4, 5, seed=31)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"transpose_X": False, "transpose_Y": False,
                      "alpha": 1.0}
        self.outputs = {"Out": x @ y}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestMatmulTransposed(OpTest):
    op_type = "matmul"

    def test(self):
        x, y = randf(4, 3, seed=32), randf(5, 4, seed=33)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"transpose_X": True, "transpose_Y": True, "alpha": 2.0}
        self.outputs = {"Out": 2.0 * (x.T @ y.T)}
        self.check_output(atol=1e-4)
        self.check_grad(["X", "Y"], "Out")


class TestMatmulV2Batched(OpTest):
    op_type = "matmul_v2"

    def test(self):
        x, y = randf(2, 3, 4, seed=34), randf(2, 4, 5, seed=35)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": np.matmul(x, y)}
        self.check_output(atol=1e-4)
        self.check_grad(["X", "Y"], "Out")


class TestMul(OpTest):
    op_type = "mul"

    def test(self):
        x, y = randf(3, 2, 2, seed=36), randf(4, 5, seed=37)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"x_num_col_dims": 1, "y_num_col_dims": 1}
        self.outputs = {"Out": (x.reshape(3, 4) @ y).reshape(3, 5)}
        self.check_output(atol=1e-4)
        self.check_grad(["X", "Y"], "Out")


class TestReduceSum(OpTest):
    op_type = "reduce_sum"

    def test(self):
        x = randf(3, 4, 5, seed=40)
        self.inputs = {"X": x}
        self.attrs = {"dim": [1], "keep_dim": False, "reduce_all": False}
        self.outputs = {"Out": x.sum(axis=1)}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestReduceMeanAll(OpTest):
    op_type = "reduce_mean"

    def test(self):
        x = randf(3, 4, seed=41)
        self.inputs = {"X": x}
        self.attrs = {"dim": [0], "keep_dim": False, "reduce_all": True}
        self.outputs = {"Out": np.array(x.mean(), "float32")}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestReduceMaxKeepdim(OpTest):
    op_type = "reduce_max"

    def test(self):
        x = randf(3, 4, seed=42)
        self.inputs = {"X": x}
        self.attrs = {"dim": [-1], "keep_dim": True, "reduce_all": False}
        self.outputs = {"Out": x.max(axis=-1, keepdims=True)}
        self.check_output()


class TestSoftmaxOp(OpTest):
    op_type = "softmax"

    def test(self):
        x = randf(3, 7, seed=43)
        e = np.exp(x - x.max(-1, keepdims=True))
        self.inputs = {"X": x}
        self.attrs = {"axis": -1}
        self.outputs = {"Out": e / e.sum(-1, keepdims=True)}
        self.check_output()
        # d(sum softmax)/dx ≡ 0: both grads are float32 noise around zero,
        # so the relative tolerance is necessarily loose here
        self.check_grad(["X"], "Out", max_relative_error=5e-2)


class TestCast(OpTest):
    op_type = "cast"

    def test(self):
        x = randf(3, 4, seed=44)
        self.inputs = {"X": x}
        self.attrs = {"in_dtype": "float32", "out_dtype": "int32"}
        self.outputs = {"Out": x.astype("int32")}
        self.check_output()


class TestClip(OpTest):
    op_type = "clip"

    def test(self):
        x = randf(3, 4, seed=45)
        self.inputs = {"X": x}
        self.attrs = {"min": -0.3, "max": 0.3}
        self.outputs = {"Out": np.clip(x, -0.3, 0.3)}
        self.check_output()


class TestCumsum(OpTest):
    op_type = "cumsum"

    def test(self):
        x = randf(3, 4, seed=46)
        self.inputs = {"X": x}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": np.cumsum(x, axis=1)}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestCumsumExclusiveReverse(OpTest):
    op_type = "cumsum"

    def test(self):
        x = randf(3, 4, seed=47)
        rev = x[:, ::-1]
        want = np.cumsum(rev, 1) - rev
        self.inputs = {"X": x}
        self.attrs = {"axis": 1, "exclusive": True, "reverse": True}
        self.outputs = {"Out": want[:, ::-1]}
        self.check_output()


class TestCompare(OpTest):
    op_type = "less_than"

    def test(self):
        x, y = randf(3, 4, seed=48), randf(3, 4, seed=49)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x < y}
        self.check_output()


class TestLogicalAnd(OpTest):
    op_type = "logical_and"

    def test(self):
        x = randf(3, 4, seed=50) > 0
        y = randf(3, 4, seed=51) > 0
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x & y}
        self.check_output()


class TestSquaredL2Norm(OpTest):
    op_type = "squared_l2_norm"

    def test(self):
        x = randf(3, 4, seed=52)
        self.inputs = {"X": x}
        self.outputs = {"Out": np.array((x ** 2).sum(), "float32")}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestPowOp(OpTest):
    op_type = "pow"

    def test(self):
        x = randf(3, 4, low=0.5, high=2.0, seed=53)
        self.inputs = {"X": x}
        self.attrs = {"factor": 3.0}
        self.outputs = {"Out": x ** 3.0}
        self.check_output(atol=1e-4)
        self.check_grad(["X"], "Out", max_relative_error=1e-2)


class TestMatmulBf16AccumulatesFp32:
    """ISSUE 4 satellite: bf16 matmuls contract in fp32
    (preferred_element_type) and round once at the output."""

    def test_pref_and_numerics(self):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.ops import math_ops

        a = jnp.ones((4, 4096), jnp.bfloat16)
        b = jnp.full((4096, 2), 2.0 ** -10, jnp.bfloat16)
        jaxpr = str(jax.make_jaxpr(math_ops._mm)(a, b))
        assert "preferred_element_type=float32" in jaxpr
        out = math_ops._mm(a, b)
        assert out.dtype == jnp.bfloat16
        # 4096 * 2^-10 = 4.0 exactly; bf16 accumulation would lose the
        # small addends once the partial sum grows and land well short
        np.testing.assert_allclose(
            np.asarray(out, np.float32), 4.0, rtol=0.02)

    def test_fp32_matmul_untouched(self):
        import jax.numpy as jnp

        from paddle_tpu.ops import math_ops

        a = jnp.ones((3, 8), jnp.float32)
        b = jnp.ones((8, 3), jnp.float32)
        out = math_ops._mm(a, b)
        # no downcast sneaks in for full-precision operands
        assert out.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(out), 8.0)
