"""Tests for the op-parity closure batch: misc framework/math ops,
metric ops, roi pooling variants, retinanet assignment."""

import numpy as np
import pytest
import torch
import torch.nn.functional as TF

from op_test import OpTest, randf, run_single_op


def run_op(op_type, inputs, attrs, outs, dtypes=None):
    return run_single_op(op_type, inputs, attrs, outs, dtypes)


def test_add_position_encoding():
    x = randf(2, 5, 8, seed=1)
    d = run_op("add_position_encoding", {"X": x},
               {"alpha": 0.7, "beta": 1.3}, ["Out"])
    half = 4
    pos = np.arange(5)[:, None]
    # reference divisor: 10000^(k/(half-1)) (add_position_encoding_op.h:84)
    div = np.power(10000.0, np.arange(half) / (half - 1))
    pe = np.concatenate([np.sin(pos / div), np.cos(pos / div)], 1)
    np.testing.assert_allclose(d["Out"], 0.7 * x + 1.3 * pe[None],
                               atol=1e-5)


def test_allclose():
    x = np.array([1.0, 2.0], "float32")
    for y, want in ((np.array([1.0, 2.0 + 1e-9], "float32"), True),
                    (np.array([1.0, 3.0], "float32"), False)):
        d = run_op("allclose", {"Input": x, "Other": y},
                   {"rtol": 1e-5, "atol": 1e-8}, ["Out"], {"Out": "bool"})
        assert bool(d["Out"]) is want


def test_bilinear_tensor_product():
    x, y = randf(3, 4, seed=2), randf(3, 5, seed=3)
    w = randf(2, 4, 5, seed=4)
    b = randf(1, 2, seed=5)
    d = run_op("bilinear_tensor_product",
               {"X": x, "Y": y, "Weight": w, "Bias": b}, {}, ["Out"])
    want = np.einsum("bm,kmn,bn->bk", x, w, y) + b
    np.testing.assert_allclose(d["Out"], want, atol=1e-5)


def test_conv_shift():
    x = randf(2, 7, seed=6)
    y = randf(2, 3, seed=7)
    d = run_op("conv_shift", {"X": x, "Y": y}, {}, ["Out"])
    m, n = 7, 3
    half = (n - 1) // 2
    want = np.zeros_like(x)
    # reference kernel (conv_shift_op.cc:158)
    for i in range(m):
        for j in range(n):
            want[:, i] += x[:, (i + j - half) % m] * y[:, j]
    np.testing.assert_allclose(d["Out"], want, atol=1e-5)


def test_crf_decoding_brute_force():
    rng = np.random.RandomState(8)
    B, T, D = 2, 4, 3
    emission = rng.uniform(-1, 1, (B, T, D)).astype("float32")
    trans = rng.uniform(-0.5, 0.5, (D + 2, D)).astype("float32")
    lens = np.array([4, 2], "int64")
    d = run_op("crf_decoding",
               {"Emission": emission, "Transition": trans, "Length": lens},
               {}, ["ViterbiPath"], {"ViterbiPath": "int64"})
    import itertools
    for b in range(B):
        ln = int(lens[b])
        best, best_s = None, -1e30
        for path in itertools.product(range(D), repeat=ln):
            s = trans[0, path[0]] + emission[b, 0, path[0]] \
                + trans[1, path[-1]]
            for k in range(1, ln):
                s += emission[b, k, path[k]] \
                    + trans[path[k - 1] + 2, path[k]]
            if s > best_s:
                best, best_s = path, s
        np.testing.assert_array_equal(d["ViterbiPath"][b, :ln],
                                      np.asarray(best))
        assert (d["ViterbiPath"][b, ln:] == 0).all()


def test_crf_decoding_label_mode():
    rng = np.random.RandomState(9)
    emission = rng.uniform(-1, 1, (1, 3, 3)).astype("float32")
    trans = rng.uniform(-0.5, 0.5, (5, 3)).astype("float32")
    p = run_op("crf_decoding", {"Emission": emission, "Transition": trans},
               {}, ["ViterbiPath"], {"ViterbiPath": "int64"})
    lab = p["ViterbiPath"].copy()
    lab[0, 1] = (lab[0, 1] + 1) % 3  # corrupt one position
    d = run_op("crf_decoding",
               {"Emission": emission, "Transition": trans, "Label": lab},
               {}, ["ViterbiPath"], {"ViterbiPath": "int64"})
    np.testing.assert_array_equal(d["ViterbiPath"][0], [1, 0, 1])


def test_cvm():
    x = np.abs(randf(3, 6, seed=10)) + 0.1
    d = run_op("cvm", {"X": x, "CVM": x[:, :2]}, {"use_cvm": True}, ["Y"])
    show = np.log(x[:, :1] + 1)
    clk = np.log(x[:, 1:2] + 1) - show
    np.testing.assert_allclose(d["Y"],
                               np.concatenate([show, clk, x[:, 2:]], 1),
                               rtol=1e-5)
    d2 = run_op("cvm", {"X": x, "CVM": x[:, :2]}, {"use_cvm": False}, ["Y"])
    np.testing.assert_allclose(d2["Y"], x[:, 2:])


def test_diag_and_diag_embed():
    v = randf(4, seed=11)
    d = run_op("diag", {"Diagonal": v}, {}, ["Out"])
    np.testing.assert_allclose(d["Out"], np.diag(v))
    x = randf(2, 3, seed=12)
    d2 = run_op("diag_embed", {"Input": x},
                {"offset": 1, "dim1": -2, "dim2": -1}, ["Out"])
    want = torch.diag_embed(torch.tensor(x), offset=1).numpy()
    np.testing.assert_allclose(d2["Out"], want)


def test_fc_op():
    x = randf(3, 4, seed=13)
    w = randf(4, 5, seed=14)
    b = randf(5, seed=15)
    d = run_op("fc", {"Input": x, "W": w, "Bias": b},
               {"in_num_col_dims": 1, "activation_type": "relu"}, ["Out"])
    np.testing.assert_allclose(d["Out"], np.maximum(x @ w + b, 0),
                               atol=1e-5)


def test_mean_iou():
    pred = np.array([0, 1, 1, 2, 2, 2], "int32")
    lab = np.array([0, 1, 2, 2, 2, 1], "int32")
    d = run_op("mean_iou", {"Predictions": pred, "Labels": lab},
               {"num_classes": 3},
               ["OutMeanIou", "OutWrong", "OutCorrect"],
               {"OutWrong": "int32", "OutCorrect": "int32"})
    # class ious: 0: 1/1, 1: 1/3, 2: 2/4
    np.testing.assert_allclose(d["OutMeanIou"],
                               (1.0 + 1 / 3 + 0.5) / 3, rtol=1e-5)


def test_minus_l1_norm_squared_l2():
    x, y = randf(3, 4, seed=16), randf(3, 4, seed=17)
    d = run_op("minus", {"X": x, "Y": y}, {}, ["Out"])
    np.testing.assert_allclose(d["Out"], x - y)
    d = run_op("l1_norm", {"X": x}, {}, ["Out"])
    np.testing.assert_allclose(d["Out"].reshape(()), np.abs(x).sum(),
                               rtol=1e-5)
    d = run_op("squared_l2_distance", {"X": x, "Y": y}, {},
               ["Out", "sub_result"])
    np.testing.assert_allclose(d["Out"],
                               ((x - y) ** 2).sum(1, keepdims=True),
                               rtol=1e-5)


def test_modified_huber_loss():
    x = np.array([[-2.0], [-0.5], [0.5], [2.0]], "float32")
    y = np.array([[1.0], [1.0], [0.0], [1.0]], "float32")
    d = run_op("modified_huber_loss", {"X": x, "Y": y}, {},
               ["Out", "IntermediateVal"])
    z = 2 * y - 1
    xz = x * z
    want = np.where(xz < -1, -4 * xz,
                    np.where(xz < 1, (1 - xz) ** 2, 0.0))
    np.testing.assert_allclose(d["Out"], want, atol=1e-5)


def test_shard_index():
    x = np.array([[1], [6], [12], [19]], "int64")
    d = run_op("shard_index", {"X": x},
               {"index_num": 20, "nshards": 2, "shard_id": 0,
                "ignore_value": -1}, ["Out"], {"Out": "int64"})
    np.testing.assert_array_equal(d["Out"], [[1], [6], [-1], [-1]])


def test_teacher_student_sigmoid_loss():
    x = randf(4, 1, seed=18)
    lab = np.array([[-2.0], [-0.5], [0.3], [1.7]], "float32")
    d = run_op("teacher_student_sigmoid_loss", {"X": x, "Label": lab},
               {}, ["Y"])
    def bce(xv, z):
        return max(xv, 0) - xv * z + np.log1p(np.exp(-abs(xv)))
    want = np.array([[bce(x[0, 0], 0)],
                     [bce(x[1, 0], 1)],
                     [bce(x[2, 0], 0) + bce(x[2, 0], 0.3)],
                     [bce(x[3, 0], 1) + bce(x[3, 0], 0.7)]], "float32")
    np.testing.assert_allclose(d["Y"], want, atol=1e-5)


def test_partial_concat_and_sum():
    x1, x2 = randf(2, 6, seed=19), randf(2, 6, seed=20)
    d = run_op("partial_concat", {"X": [x1, x2]},
               {"start_index": 1, "length": 3}, ["Out"])
    np.testing.assert_allclose(d["Out"],
                               np.concatenate([x1[:, 1:4], x2[:, 1:4]], 1))
    d = run_op("partial_sum", {"X": [x1, x2]},
               {"start_index": 2, "length": 2}, ["Out"])
    np.testing.assert_allclose(d["Out"], x1[:, 2:4] + x2[:, 2:4])


def test_fsp():
    x = randf(2, 3, 4, 4, seed=21)
    y = randf(2, 5, 4, 4, seed=22)
    d = run_op("fsp", {"X": x, "Y": y}, {}, ["Out"])
    want = np.einsum("bchw,bdhw->bcd", x, y) / 16
    np.testing.assert_allclose(d["Out"], want, rtol=1e-4)


def test_sampling_id_distribution():
    probs = np.tile(np.array([[0.0, 1.0, 0.0]], "float32"), (8, 1))
    d = run_op("sampling_id", {"X": probs}, {}, ["Out"], {"Out": "int64"})
    np.testing.assert_array_equal(d["Out"], np.ones(8, "int64"))


def test_pool3d():
    x = randf(1, 2, 4, 4, 4, seed=23)
    d = run_op("pool3d", {"X": x},
               {"pooling_type": "max", "ksize": [2, 2, 2],
                "strides": [2, 2, 2], "paddings": [0, 0, 0]}, ["Out"])
    want = TF.max_pool3d(torch.tensor(x), 2, 2).numpy()
    np.testing.assert_allclose(d["Out"], want)


def test_pool3d_avg_global():
    x = randf(1, 2, 3, 3, 3, seed=24)
    d = run_op("pool3d", {"X": x},
               {"pooling_type": "avg", "global_pooling": True,
                "ksize": [1, 1, 1]}, ["Out"])
    np.testing.assert_allclose(d["Out"],
                               x.mean(axis=(2, 3, 4), keepdims=True),
                               rtol=1e-5)


# ---------------------------------------------------------------------------
# roi pooling variants
# ---------------------------------------------------------------------------

def test_psroi_pool():
    # 1 roi covering the whole 4x4 map, 2x2 bins, 2 output channels ->
    # input has 2*2*2=8 channels; bin (ph,pw) of out-chan c averages
    # input channel (c*2+ph)*2+pw over that spatial quadrant
    x = randf(1, 8, 4, 4, seed=25)
    rois = np.array([[0.0, 0.0, 3.0, 3.0]], "float32")
    d = run_op("psroi_pool",
               {"X": x, "ROIs": rois,
                "RoisNum": np.array([1], "int32")},
               {"pooled_height": 2, "pooled_width": 2,
                "output_channels": 2, "spatial_scale": 1.0}, ["Out"])
    for c in range(2):
        for ph in range(2):
            for pw in range(2):
                chan = (c * 2 + ph) * 2 + pw
                quad = x[0, chan, ph * 2:(ph + 1) * 2, pw * 2:(pw + 1) * 2]
                np.testing.assert_allclose(d["Out"][0, c, ph, pw],
                                           quad.mean(), rtol=1e-4)


def test_prroi_pool_integral():
    # integer-aligned roi: precise pooling == average pooling
    x = randf(1, 3, 6, 6, seed=26)
    rois = np.array([[0.0, 0.0, 6.0, 6.0]], "float32")
    d = run_op("prroi_pool",
               {"X": x, "ROIs": rois,
                "BatchRoINums": np.array([1], "int64")},
               {"pooled_height": 3, "pooled_width": 3,
                "spatial_scale": 1.0}, ["Out"])
    # The triangle kernel integrates the CONTINUOUS bilinear surface
    # (cell [i,i+1] integral = (v_i+v_{i+1})/2), which extends past the
    # grid with zeros (PrRoIPoolingGetData) — pad before building cells
    v = np.pad(x[0], [(0, 0), (0, 1), (0, 1)])
    col = 0.5 * (v[:, :-1] + v[:, 1:])                # integrate y
    cell = 0.5 * (col[:, :, :-1] + col[:, :, 1:])     # integrate x -> (3,6,6)
    for ph in range(3):
        for pw in range(3):
            acc = cell[:, ph * 2:ph * 2 + 2, pw * 2:pw * 2 + 2].sum((1, 2))
            np.testing.assert_allclose(d["Out"][0, :, ph, pw], acc / 4,
                                       rtol=1e-4)


def test_retinanet_target_assign():
    anchors = np.array([[0, 0, 9, 9], [20, 20, 29, 29],
                        [100, 100, 109, 109]], "float32")
    gt = np.array([[[0, 0, 9, 9], [21, 21, 30, 30]]], "float32")
    labs = np.array([[3, 7]], "int32")
    d = run_op("retinanet_target_assign",
               {"Anchor": anchors, "GtBoxes": gt, "GtLabels": labs,
                "ImInfo": np.array([[200, 200, 1]], "float32")},
               {"positive_overlap": 0.5, "negative_overlap": 0.4},
               ["ScoreTarget", "LocationTarget", "LocationWeight",
                "ScoreWeight", "ForegroundNumber"],
               {"ScoreTarget": "int32", "ForegroundNumber": "int32"})
    st = d["ScoreTarget"][0, :, 0]
    assert st[0] == 3          # IoU 1.0 with gt0 -> class 3
    assert st[1] == 7          # best anchor for gt1 -> class 7
    assert st[2] == 0          # background
    assert d["ForegroundNumber"][0, 0] == 3  # 2 fg + 1
    np.testing.assert_array_equal(d["LocationWeight"][0, :, 0], [1, 1, 0])
