"""Op tests: tensor creation/manipulation + RNG + optimizer-update ops
(mirrors reference test_reshape_op.py, test_concat_op.py, test_slice_op.py,
test_gather_op.py, test_top_k_v2_op.py, test_adam_op.py,
test_momentum_op.py methodology)."""

import numpy as np
import pytest

from op_test import OpTest, randf


class TestFillConstant(OpTest):
    op_type = "fill_constant"

    def test(self):
        self.inputs = {}
        self.attrs = {"shape": [3, 4], "dtype": "float32", "value": 2.5}
        self.outputs = {"Out": np.full((3, 4), 2.5, "float32")}
        self.check_output()


class TestReshape2(OpTest):
    op_type = "reshape2"

    def test(self):
        x = randf(2, 3, 4, seed=100)
        self.inputs = {"X": x}
        self.attrs = {"shape": [-1, 12]}
        self.outputs = {"Out": x.reshape(2, 12),
                        "XShape": np.zeros((0, 2, 3, 4), "float32")}
        self.check_output(no_check_set=("XShape",))
        self.check_grad(["X"], "Out")


class TestReshapeZeroCopyDim(OpTest):
    op_type = "reshape2"

    def test(self):
        x = randf(2, 3, 4, seed=101)
        self.inputs = {"X": x}
        self.attrs = {"shape": [0, -1]}  # 0 copies dim0
        self.outputs = {"Out": x.reshape(2, 12),
                        "XShape": np.zeros((0,), "float32")}
        self.check_output(no_check_set=("XShape",))


class TestTranspose(OpTest):
    op_type = "transpose2"

    def test(self):
        x = randf(2, 3, 4, seed=102)
        self.inputs = {"X": x}
        self.attrs = {"axis": [1, 2, 0]}
        self.outputs = {"Out": x.transpose(1, 2, 0)}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestConcatAxis1(OpTest):
    op_type = "concat"

    def test(self):
        xs = [randf(2, i + 2, seed=103 + i) for i in range(3)]
        self.inputs = {"X": xs}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": np.concatenate(xs, axis=1)}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestSplitSections(OpTest):
    op_type = "split"

    def test(self):
        x = randf(2, 9, seed=106)
        self.inputs = {"X": x}
        self.attrs = {"sections": [2, 3, -1], "num": 0, "axis": 1}
        self.outputs = {"Out": [x[:, :2], x[:, 2:5], x[:, 5:]]}
        self.check_output()


class TestStack(OpTest):
    op_type = "stack"

    def test(self):
        xs = [randf(3, 4, seed=107 + i) for i in range(3)]
        self.inputs = {"X": xs}
        self.attrs = {"axis": 1}
        self.outputs = {"Y": np.stack(xs, axis=1)}
        self.check_output()


class TestSliceDecrease(OpTest):
    op_type = "slice"

    def test(self):
        x = randf(3, 4, 5, seed=110)
        self.inputs = {"Input": x}
        self.attrs = {"axes": [0, 2], "starts": [1, 2], "ends": [2, 4],
                      "decrease_axis": [0]}
        self.outputs = {"Out": x[1, :, 2:4]}
        self.check_output()


class TestSliceNegative(OpTest):
    op_type = "slice"

    def test(self):
        x = randf(3, 6, seed=111)
        self.inputs = {"Input": x}
        self.attrs = {"axes": [1], "starts": [-3], "ends": [10000]}
        self.outputs = {"Out": x[:, -3:]}
        self.check_output()
        self.check_grad(["Input"], "Out")


class TestExpandV2(OpTest):
    op_type = "expand_v2"

    def test(self):
        x = randf(1, 3, seed=112)
        self.inputs = {"X": x}
        self.attrs = {"shape": [4, -1]}
        self.outputs = {"Out": np.broadcast_to(x, (4, 3))}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestTile(OpTest):
    op_type = "tile"

    def test(self):
        x = randf(2, 3, seed=113)
        self.inputs = {"X": x}
        self.attrs = {"repeat_times": [2, 2]}
        self.outputs = {"Out": np.tile(x, (2, 2))}
        self.check_output()


class TestGather(OpTest):
    op_type = "gather"

    def test(self):
        x = randf(8, 4, seed=114)
        idx = np.array([1, 5, 2], np.int32)
        self.inputs = {"X": x, "Index": idx}
        self.outputs = {"Out": x[idx]}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestGatherNd(OpTest):
    op_type = "gather_nd"

    def test(self):
        x = randf(3, 4, 5, seed=115)
        idx = np.array([[0, 1], [2, 3]], np.int32)
        self.inputs = {"X": x, "Index": idx}
        self.outputs = {"Out": x[[0, 2], [1, 3]]}
        self.check_output()


class TestScatterOverwrite(OpTest):
    op_type = "scatter"

    def test(self):
        x = randf(6, 3, seed=116)
        ids = np.array([1, 4], np.int32)
        upd = randf(2, 3, seed=117)
        want = x.copy()
        want[ids] = upd
        self.inputs = {"X": x, "Ids": ids, "Updates": upd}
        self.attrs = {"overwrite": True}
        self.outputs = {"Out": want}
        self.check_output()


class TestWhere(OpTest):
    op_type = "where"

    def test(self):
        c = randf(3, 4, seed=118) > 0
        x, y = randf(3, 4, seed=119), randf(3, 4, seed=120)
        self.inputs = {"Condition": c, "X": x, "Y": y}
        self.outputs = {"Out": np.where(c, x, y)}
        self.check_output()


class TestOneHot(OpTest):
    op_type = "one_hot_v2"

    def test(self):
        x = np.array([1, 0, 3], np.int32)
        self.inputs = {"X": x}
        self.attrs = {"depth": 4}
        self.outputs = {"Out": np.eye(4, dtype="float32")[x]}
        self.check_output()


class TestArgMax(OpTest):
    op_type = "arg_max"

    def test(self):
        x = randf(3, 5, seed=121)
        self.inputs = {"X": x}
        self.attrs = {"axis": 1, "keepdims": False, "dtype": "int64"}
        self.outputs = {"Out": x.argmax(1).astype("int64")}
        self.check_output()


class TestTopKV2(OpTest):
    op_type = "top_k_v2"

    def test(self):
        x = randf(3, 6, seed=122)
        k = 2
        idx = np.argsort(-x, axis=1)[:, :k]
        vals = np.take_along_axis(x, idx, axis=1)
        self.inputs = {"X": x}
        self.attrs = {"k": k, "axis": -1, "largest": True, "sorted": True}
        self.outputs = {"Out": vals, "Indices": idx.astype("int64")}
        self.check_output()


class TestArgsortDescending(OpTest):
    op_type = "argsort"

    def test(self):
        x = randf(3, 5, seed=123)
        idx = np.argsort(-x, axis=1)
        self.inputs = {"X": x}
        self.attrs = {"axis": 1, "descending": True}
        self.outputs = {"Out": np.take_along_axis(x, idx, 1),
                        "Indices": idx.astype("int64")}
        self.check_output()


class TestRange(OpTest):
    op_type = "range"

    def test(self):
        self.inputs = {}
        self.attrs = {"start": 2.0, "end": 10.0, "step": 2.0,
                      "dtype": "int64"}
        self.outputs = {"Out": np.arange(2, 10, 2).astype("int64")}
        self.check_output()


class TestTrilTriu(OpTest):
    op_type = "tril_triu"

    def test(self):
        x = randf(4, 4, seed=124)
        self.inputs = {"X": x}
        self.attrs = {"diagonal": 0, "lower": True}
        self.outputs = {"Out": np.tril(x)}
        self.check_output()


class TestPad(OpTest):
    op_type = "pad"

    def test(self):
        x = randf(2, 3, seed=125)
        self.inputs = {"X": x}
        self.attrs = {"paddings": [0, 1, 2, 0], "pad_value": 9.0}
        self.outputs = {"Out": np.pad(x, [(0, 1), (2, 0)],
                                      constant_values=9.0)}
        self.check_output()
        self.check_grad(["X"], "Out")


# -- RNG (statistical) ------------------------------------------------------

class TestGaussianStats(OpTest):
    op_type = "gaussian_random"

    def test(self):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid.executor import Scope, scope_guard

        self.inputs = {}
        self.attrs = {"shape": [500, 200], "dtype": "float32",
                      "mean": 1.0, "std": 2.0, "seed": 7}
        self.outputs = {"Out": np.zeros((500, 200), "float32")}
        main, startup, feed, fetch_names, _ = self._build()
        with scope_guard(Scope()):
            (out,) = fluid.Executor().run(
                main, fetch_list=[n for _, _, n in fetch_names])
        assert abs(out.mean() - 1.0) < 0.02
        assert abs(out.std() - 2.0) < 0.02
        # fixed seed => reproducible
        with scope_guard(Scope()):
            (out2,) = fluid.Executor().run(
                main, fetch_list=[n for _, _, n in fetch_names])
        np.testing.assert_array_equal(out, out2)


class TestUniformStats(OpTest):
    op_type = "uniform_random"

    def test(self):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid.executor import Scope, scope_guard

        self.inputs = {}
        self.attrs = {"shape": [1000, 100], "dtype": "float32",
                      "min": -2.0, "max": 4.0, "seed": 11}
        self.outputs = {"Out": np.zeros((1000, 100), "float32")}
        main, startup, feed, fetch_names, _ = self._build()
        with scope_guard(Scope()):
            (out,) = fluid.Executor().run(
                main, fetch_list=[n for _, _, n in fetch_names])
        assert out.min() >= -2.0 and out.max() < 4.0
        assert abs(out.mean() - 1.0) < 0.02


# -- optimizer update ops ---------------------------------------------------

class TestSGDOp(OpTest):
    op_type = "sgd"

    def test(self):
        p = randf(4, 3, seed=130)
        g = randf(4, 3, seed=131)
        lr = np.array([0.1], "float32")
        self.inputs = {"Param": p, "Grad": g, "LearningRate": lr}
        self.outputs = {"ParamOut": p - 0.1 * g}
        self.check_output()


class TestMomentumOp(OpTest):
    op_type = "momentum"

    def test(self):
        p, g, v = randf(4, 3, seed=132), randf(4, 3, seed=133), randf(4, 3, seed=134)
        lr = np.array([0.1], "float32")
        mu = 0.9
        v_out = mu * v + g
        self.inputs = {"Param": p, "Grad": g, "Velocity": v,
                       "LearningRate": lr}
        self.attrs = {"mu": mu, "use_nesterov": False}
        self.outputs = {"ParamOut": p - 0.1 * v_out, "VelocityOut": v_out}
        self.check_output(atol=1e-5)


class TestAdamOp(OpTest):
    op_type = "adam"

    def test(self):
        p, g = randf(4, 3, seed=135), randf(4, 3, seed=136)
        m1, m2 = randf(4, 3, seed=137), np.abs(randf(4, 3, seed=138))
        lr = np.array([0.01], "float32")
        b1, b2, eps = 0.9, 0.999, 1e-8
        b1p = np.array([0.9 ** 3], "float32")
        b2p = np.array([0.999 ** 3], "float32")
        m1o = b1 * m1 + (1 - b1) * g
        m2o = b2 * m2 + (1 - b2) * g * g
        lr_t = lr * np.sqrt(1 - b2p) / (1 - b1p)
        p_out = p - lr_t * m1o / (np.sqrt(m2o) + eps)
        self.inputs = {"Param": p, "Grad": g, "LearningRate": lr,
                       "Moment1": m1, "Moment2": m2,
                       "Beta1Pow": b1p, "Beta2Pow": b2p}
        self.attrs = {"beta1": b1, "beta2": b2, "epsilon": eps}
        self.outputs = {"ParamOut": p_out, "Moment1Out": m1o,
                        "Moment2Out": m2o,
                        "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2}
        self.check_output(atol=1e-5)


class TestLambOp(OpTest):
    op_type = "lamb"

    def test(self):
        p, g = randf(4, 3, seed=139), randf(4, 3, seed=140)
        m1, m2 = randf(4, 3, seed=141), np.abs(randf(4, 3, seed=142))
        lr = np.array([0.01], "float32")
        b1, b2, eps, wd = 0.9, 0.999, 1e-6, 0.01
        b1p = np.array([0.9], "float32")
        b2p = np.array([0.999], "float32")
        m1o = b1 * m1 + (1 - b1) * g
        m2o = b2 * m2 + (1 - b2) * g * g
        m1h = m1o / (1 - b1p)
        m2h = m2o / (1 - b2p)
        r = m1h / (np.sqrt(m2h) + eps) + wd * p
        trust = np.linalg.norm(p) / np.linalg.norm(r)
        p_out = p - lr * trust * r
        self.inputs = {"Param": p, "Grad": g, "LearningRate": lr,
                       "Moment1": m1, "Moment2": m2,
                       "Beta1Pow": b1p, "Beta2Pow": b2p}
        self.attrs = {"beta1": b1, "beta2": b2, "epsilon": eps,
                      "weight_decay": wd}
        self.outputs = {"ParamOut": p_out, "Moment1Out": m1o,
                        "Moment2Out": m2o,
                        "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2}
        self.check_output(atol=1e-4)


class TestCheckFiniteAndUnscale(OpTest):
    op_type = "check_finite_and_unscale"

    def test(self):
        xs = [randf(3, 3, seed=143), randf(2, 2, seed=144)]
        xs[1][0, 0] = np.inf
        scale = np.array([2.0], "float32")
        self.inputs = {"X": xs, "Scale": scale}
        self.outputs = {"Out": [x / 2.0 for x in xs],
                        "FoundInfinite": np.array([True])}
        self.check_output()


class TestUpdateLossScaling(OpTest):
    op_type = "update_loss_scaling"

    def test(self):
        xs = [randf(3, 3, seed=145)]
        found = np.array([False])
        prev = np.array([1024.0], "float32")
        good = np.array([999], "int32")
        bad = np.array([0], "int32")
        self.inputs = {"X": xs, "FoundInfinite": found,
                       "PrevLossScaling": prev, "InGoodSteps": good,
                       "InBadSteps": bad}
        self.attrs = {"incr_every_n_steps": 1000,
                      "decr_every_n_nan_or_inf": 2,
                      "incr_ratio": 2.0, "decr_ratio": 0.5}
        self.outputs = {"Out": xs, "LossScaling": prev * 2,
                        "OutGoodSteps": np.array([0], "int32"),
                        "OutBadSteps": np.array([0], "int32")}
        self.check_output()
