"""Direct op-level tests for every collective variant on the 8-device
virtual CPU mesh (reference unittests collective_allreduce_op.py /
collective_*_api.py wrappers around test_collective_base.py), plus the
remaining alias / no-op / observer op types so the op-coverage gate
(tools/op_coverage.py) reflects real exercise.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid


def run_collective(fresh, op_type, x_np, attrs=None, out_shape=None,
                   extra=None):
    """Append one collective op on a (8, ...) sharded input and run it
    under the data-parallel compiler; returns the fetched output
    (gathered back replicated)."""
    main, startup, scope = fresh
    x = fluid.data("x", list(x_np.shape), "float32")
    block = main.global_block()
    out = block.create_var(dtype="float32",
                           shape=list(out_shape or x_np.shape))
    block.append_op(op_type, inputs={"X": [x]}, outputs={"Out": [out]},
                    attrs={"ring_id": 0, **(attrs or {})},
                    infer_shape=False)
    if extra:
        extra(block, out)
    compiled = fluid.CompiledProgram(main).with_data_parallel()
    exe = fluid.Executor()
    (o,) = exe.run(compiled, feed={"x": x_np}, fetch_list=[out])
    return np.asarray(o)


X8 = (np.arange(8, dtype="float32") + 1).reshape(8, 1) \
    * np.ones((1, 4), "float32")  # row i == i+1


@pytest.mark.parametrize("op_type,want_row", [
    ("c_allreduce_sum", np.full(4, 36.0)),
    ("c_allreduce_max", np.full(4, 8.0)),
    ("c_allreduce_min", np.full(4, 1.0)),
    ("mp_allreduce_sum", np.full(4, 36.0)),
    ("c_reduce_sum", np.full(4, 36.0)),
])
def test_allreduce_family(fresh_programs, op_type, want_row):
    o = run_collective(fresh_programs, op_type, X8)
    # per-shard shape is (1, 4); the replicated fetch returns one shard's
    # copy of the reduction
    assert o.shape == (1, 4)
    np.testing.assert_allclose(o[0], want_row, rtol=1e-6)


def test_c_allreduce_prod(fresh_programs):
    x = np.full((8, 2), 2.0, "float32")
    o = run_collective(fresh_programs, "c_allreduce_prod", x)
    np.testing.assert_allclose(o, np.full((1, 2), 2.0 ** 8), rtol=1e-4)


def test_c_broadcast(fresh_programs):
    o = run_collective(fresh_programs, "c_broadcast", X8,
                       attrs={"root": 3})
    np.testing.assert_allclose(o, np.full((1, 4), 4.0), rtol=1e-6)


def test_c_reducescatter(fresh_programs):
    # per-shard input must have leading dim divisible by nranks: feed
    # (64, 1) -> per-shard (8, 1); the scatter sums across shards and
    # keeps each shard's 1-row slice (all 8.0 for an all-ones input)
    x = np.ones((64, 1), "float32")
    o = run_collective(fresh_programs, "c_reducescatter", x,
                       out_shape=[1, 1])
    np.testing.assert_allclose(o, np.full((1, 1), 8.0), rtol=1e-6)


def test_c_allgather(fresh_programs):
    o = run_collective(fresh_programs, "c_allgather", X8,
                       attrs={"nranks": 8}, out_shape=[64, 4])
    want = (np.arange(8, dtype="float32") + 1).reshape(8, 1) \
        * np.ones((1, 4), "float32")
    np.testing.assert_allclose(o[:8], want, rtol=1e-6)


def test_c_concat(fresh_programs):
    # concat along the LAST axis across ranks (model-parallel gather)
    o = run_collective(fresh_programs, "c_concat", X8, out_shape=[8, 32])
    # every rank's row becomes [row0 | row1 | ... | row7] per-position
    want = np.concatenate([np.full(4, r + 1.0) for r in range(8)])
    np.testing.assert_allclose(o[0], want, rtol=1e-6)


def test_c_split(fresh_programs):
    # rank i keeps column slice i; allgather the per-rank slices back to
    # observe all of them through the replicated fetch
    main, startup, scope = fresh_programs
    x = fluid.data("x", [8, 8], "float32")
    block = main.global_block()
    out = block.create_var(dtype="float32", shape=[1, 1])
    gathered = block.create_var(dtype="float32", shape=[8, 1])
    block.append_op("c_split", inputs={"X": [x]}, outputs={"Out": [out]},
                    attrs={"ring_id": 0}, infer_shape=False)
    block.append_op("c_allgather", inputs={"X": [out]},
                    outputs={"Out": [gathered]},
                    attrs={"ring_id": 0, "nranks": 8}, infer_shape=False)
    compiled = fluid.CompiledProgram(main).with_data_parallel()
    exe = fluid.Executor()
    xv = np.tile(np.arange(8, dtype="float32"), (8, 1))
    (o,) = exe.run(compiled, feed={"x": xv}, fetch_list=[gathered])
    np.testing.assert_allclose(np.asarray(o)[:, 0],
                               np.arange(8, dtype="float32"), rtol=1e-6)


def test_c_identity_and_fences(fresh_programs):
    main, startup, scope = fresh_programs
    x = fluid.data("x", [8, 4], "float32")
    block = main.global_block()
    v1 = block.create_var(dtype="float32", shape=[8, 4])
    v2 = block.create_var(dtype="float32", shape=[8, 4])
    v3 = block.create_var(dtype="float32", shape=[8, 4])
    block.append_op("c_identity", inputs={"X": [x]},
                    outputs={"Out": [v1]}, attrs={"ring_id": 0},
                    infer_shape=False)
    block.append_op("c_sync_calc_stream", inputs={"X": [v1]},
                    outputs={"Out": [v2]}, attrs={}, infer_shape=False)
    block.append_op("c_sync_comm_stream", inputs={"X": [v2]},
                    outputs={"Out": [v3]}, attrs={"ring_id": 0},
                    infer_shape=False)
    # bootstrap no-ops execute without outputs
    block.append_op("c_comm_init_all", inputs={}, outputs={}, attrs={},
                    infer_shape=False)
    block.append_op("c_gen_nccl_id", inputs={}, outputs={}, attrs={},
                    infer_shape=False)
    block.append_op("c_comm_init", inputs={}, outputs={}, attrs={},
                    infer_shape=False)
    block.append_op("c_wait_calc_stream", inputs={}, outputs={}, attrs={},
                    infer_shape=False)
    block.append_op("c_wait_comm_stream", inputs={}, outputs={}, attrs={},
                    infer_shape=False)
    exe = fluid.Executor()
    X = np.random.RandomState(0).randn(8, 4).astype("float32")
    (o,) = exe.run(main, feed={"x": X}, fetch_list=[v3])
    np.testing.assert_allclose(np.asarray(o), X, rtol=1e-6)


def test_barrier_passthrough(fresh_programs):
    o = run_collective(fresh_programs, "barrier", X8)
    np.testing.assert_allclose(o, X8[:1], rtol=1e-6)


def test_alltoall(fresh_programs):
    # per-shard (8, 1) where shard r holds rows all = r; alltoall sends
    # block k of rank r to block r of rank k, so every rank ends with
    # [0, 1, ..., 7]
    x = np.repeat(np.arange(8, dtype="float32"), 8)[:, None]  # (64, 1)
    main, startup, scope = fresh_programs
    xv = fluid.data("x", [64, 1], "float32")
    block = main.global_block()
    out = block.create_var(dtype="float32", shape=[8, 1])
    gathered = block.create_var(dtype="float32", shape=[64, 1])
    block.append_op("alltoall", inputs={"X": [xv]}, outputs={"Out": [out]},
                    attrs={"ring_id": 0}, infer_shape=False)
    block.append_op("c_allgather", inputs={"X": [out]},
                    outputs={"Out": [gathered]},
                    attrs={"ring_id": 0, "nranks": 8}, infer_shape=False)
    compiled = fluid.CompiledProgram(main).with_data_parallel()
    exe = fluid.Executor()
    (o,) = exe.run(compiled, feed={"x": x}, fetch_list=[gathered])
    o = np.asarray(o).reshape(8, 8)  # (rank, its 8 received blocks)
    for r in range(8):
        np.testing.assert_allclose(o[r], np.arange(8), rtol=1e-6)


# -- alias / shape-variant op types ----------------------------------------

def _one_op(op_type, inputs, attrs, outputs_spec):
    from paddle_tpu.fluid import framework, unique_name
    from paddle_tpu.fluid.executor import Scope, scope_guard

    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup), unique_name.guard():
        block = main.global_block()
        feed = {}
        in_map = {}
        for slot, arrs in inputs.items():
            arrs = arrs if isinstance(arrs, list) else [arrs]
            names = []
            for i, arr in enumerate(arrs):
                name = f"i_{slot}_{i}"
                block.create_var(name=name, shape=list(np.shape(arr)),
                                 dtype=str(np.asarray(arr).dtype),
                                 is_data=True)
                feed[name] = np.asarray(arr)
                names.append(name)
            in_map[slot] = names
        out_map = {}
        for slot in outputs_spec:
            v = block.create_var(dtype="float32")
            out_map[slot] = [v.name]
        block.append_op(op_type, inputs=in_map, outputs=out_map,
                        attrs=attrs, infer_shape=False)
        with scope_guard(Scope()):
            exe = fluid.Executor()
            outs = exe.run(main, feed=feed,
                           fetch_list=[out_map[s][0] for s in outputs_spec])
    return {s: np.asarray(o) for s, o in zip(outputs_spec, outs)}


def test_shape_variant_aliases():
    x = np.arange(6, dtype="float32").reshape(1, 2, 3)
    d = _one_op("flatten2", {"X": x}, {"axis": 1}, ["Out", "XShape"])
    assert d["Out"].shape == (1, 6)
    d = _one_op("squeeze2", {"X": x}, {"axes": [0]}, ["Out", "XShape"])
    assert d["Out"].shape == (2, 3)
    d = _one_op("unsqueeze2", {"X": x}, {"axes": [0]}, ["Out", "XShape"])
    assert d["Out"].shape == (1, 1, 2, 3)


def test_multiclass_nms_aliases():
    boxes = np.array([[[0, 0, 1, 1], [5, 5, 6, 6]]], "float32")
    scores = np.array([[[0.0, 0.0], [0.9, 0.8]]], "float32")
    attrs = {"background_label": 0, "score_threshold": 0.1,
             "nms_top_k": 2, "keep_top_k": 2, "nms_threshold": 0.5}
    d = _one_op("multiclass_nms", {"BBoxes": boxes, "Scores": scores},
                attrs, ["Out"])
    assert d["Out"].shape == (1, 2, 6)
    np.testing.assert_allclose(d["Out"][0, 0, 1], 0.9, rtol=1e-5)
    d = _one_op("multiclass_nms2", {"BBoxes": boxes, "Scores": scores},
                attrs, ["Out"])
    np.testing.assert_allclose(d["Out"][0, 0, 1], 0.9, rtol=1e-5)


def test_select_input_output_print_assert():
    mask = np.array([1], "int32")
    a = np.zeros((2, 2), "float32")
    b = np.ones((2, 2), "float32")
    d = _one_op("select_input", {"X": [a, b], "Mask": mask}, {}, ["Out"])
    np.testing.assert_allclose(d["Out"], b)
    d = _one_op("select_output", {"X": a, "Mask": mask}, {}, ["Out"])
    np.testing.assert_allclose(d["Out"], a)
    d = _one_op("print", {"In": a}, {"message": "dbg"}, ["Out"])
    np.testing.assert_allclose(d["Out"], a)
    _one_op("assert", {"Cond": np.array([True])}, {}, [])


def test_tensor_array_to_tensor_op():
    """Exercised through the layers API (array_write + array_to_tensor)."""
    from paddle_tpu.fluid import framework, unique_name
    from paddle_tpu.fluid.executor import Scope, scope_guard

    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup), unique_name.guard():
        import paddle_tpu.fluid.layers as layers

        x = fluid.data("x", [2, 3], "float32")
        i0 = layers.fill_constant([1], "int64", 0)
        i1 = layers.fill_constant([1], "int64", 1)
        arr = layers.array_write(x, i0)
        arr = layers.array_write(x + 1.0, i1, array=arr)
        helper = layers.tensor_array_to_tensor if hasattr(
            layers, "tensor_array_to_tensor") else None
        block = main.global_block()
        out = block.create_var(name="stacked", dtype="float32")
        oi = block.create_var(name="stacked_idx", dtype="int64")
        block.append_op("tensor_array_to_tensor",
                        inputs={"X": [arr.name]},
                        outputs={"Out": [out.name], "OutIndex": [oi.name]},
                        attrs={"use_stack": True, "axis": 0},
                        infer_shape=False)
    with scope_guard(Scope()):
        exe = fluid.Executor()
        X = np.arange(6, dtype="float32").reshape(2, 3)
        (o,) = exe.run(main, feed={"x": X}, fetch_list=[out])
    np.testing.assert_allclose(np.asarray(o),
                               np.stack([X, X + 1.0]))


def test_sequence_expand_alias():
    x = np.arange(6, dtype="float32").reshape(2, 3)
    y = np.zeros((2, 4, 3), "float32")
    d = _one_op("sequence_expand", {"X": x, "Y": y}, {}, ["Out"])
    assert d["Out"].shape == (2, 4, 3)
    np.testing.assert_allclose(d["Out"][0, 2], x[0])


def test_quant_observer_variants():
    x = (np.random.RandomState(3).randn(4, 4) * 2).astype("float32")
    s = np.array([1.0], "float32")
    d = _one_op("fake_quantize_moving_average_abs_max",
                {"X": x, "InScale": s, "InAccum": s, "InState": s},
                {"bit_length": 8, "moving_rate": 0.9, "is_test": False},
                ["Out", "OutScale", "OutAccum", "OutState"])
    assert np.all(np.abs(d["Out"]) <= 127)
    d = _one_op("fake_quantize_range_abs_max", {"X": x, "InScale": s},
                {"bit_length": 8, "is_test": False}, ["Out", "OutScale"])
    np.testing.assert_allclose(d["OutScale"],
                               [max(np.abs(x).max(), 1.0)], rtol=1e-5)
    d = _one_op("moving_average_abs_max_scale",
                {"X": x, "InAccum": s, "InState": s},
                {"moving_rate": 0.9},
                ["OutScale", "OutAccum", "OutState"])
    np.testing.assert_allclose(
        d["OutAccum"], [0.9 + np.abs(x).max()], rtol=1e-5)
    d = _one_op("fake_channel_wise_quantize_dequantize_abs_max", {"X": x},
                {"bit_length": 8, "quant_axis": 0}, ["Out", "OutScale"])
    assert np.abs(d["Out"] - x).max() < np.abs(x).max() / 100
