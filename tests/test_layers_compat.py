"""fluid.layers legacy-name tail (paddle_tpu/fluid/layers/compat.py):
full-surface sweep vs the reference's per-module __all__ sets, plus
executor-backed oracles for a sample of the static wrappers."""

import ast
import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid


@pytest.fixture
def prog():
    from paddle_tpu.fluid import framework, unique_name
    from paddle_tpu.fluid.executor import Scope, scope_guard

    main, startup = framework.Program(), framework.Program()
    with framework.program_guard(main, startup):
        with unique_name.guard():
            with scope_guard(Scope()):
                yield main, startup


def _run(main, startup, feed, fetch):
    exe = fluid.Executor()
    exe.run(startup)
    return exe.run(main, feed=feed, fetch_list=fetch)


def test_fluid_layers_surface_complete():
    import os
    if not os.path.isdir("/root/reference"):
        pytest.skip("reference source tree not present in this environment")
    R = "/root/reference/python/paddle/fluid/layers"
    names = set()
    for f in os.listdir(R):
        if not f.endswith(".py"):
            continue
        try:
            tree = ast.parse(open(f"{R}/{f}").read())
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if getattr(t, "id", "") == "__all__":
                        try:
                            names |= set(ast.literal_eval(node.value))
                        except Exception:
                            pass
    L = fluid.layers
    missing = sorted(n for n in names if not hasattr(L, n))
    assert missing == [], f"fluid.layers gaps: {missing}"


def test_static_wrapper_oracles(prog):
    main, startup = prog
    L = fluid.layers
    x = fluid.data("x", [-1, 4], "float32")
    y = fluid.data("y", [-1, 4], "float32")
    cs = L.cos_sim(x, y)
    hi = L.has_inf(x)
    hn = L.has_nan(x)
    sr = L.soft_relu(x, threshold=40.0)
    br = L.brelu(x, t_min=0.0, t_max=2.0)
    xv = np.array([[1, 2, 3, 4], [0, 1, 0, 1]], "float32")
    yv = np.array([[1, 2, 3, 4], [1, 0, 1, 0]], "float32")
    out = _run(main, startup, {"x": xv, "y": yv},
               [cs, hi, hn, sr, br])
    csv, hiv, hnv, srv, brv = out
    want_cs = (xv * yv).sum(1) / (
        np.linalg.norm(xv, axis=1) * np.linalg.norm(yv, axis=1))
    np.testing.assert_allclose(csv.reshape(-1), want_cs, rtol=1e-5)
    assert not bool(hiv) and not bool(hnv)
    np.testing.assert_allclose(srv, np.log1p(np.exp(xv)), rtol=1e-5)
    np.testing.assert_allclose(brv, np.clip(xv, 0, 2), rtol=1e-6)


def test_scatter_nd_and_unique_with_counts(prog):
    main, startup = prog
    L = fluid.layers
    idx = fluid.data("i", [-1, 1], "int64")
    upd = fluid.data("u", [-1], "float32")
    out = L.scatter_nd(idx, upd, [6])
    xs = fluid.data("xs", [-1], "int64")
    uq, uidx, ucnt = L.unique_with_counts(xs)
    iv = np.array([[1], [3], [1]], "int64")
    uv = np.array([2.0, 5.0, 7.0], "float32")
    xv = np.array([3, 1, 3, 3, 2], "int64")
    o, q, qi, qc = _run(main, startup,
                        {"i": iv, "u": uv, "xs": xv},
                        [out, uq, uidx, ucnt])
    want = np.zeros(6, "float32")
    np.add.at(want, iv[:, 0], uv)
    np.testing.assert_allclose(o, want)
    # padded static-shape unique: first 3 entries are the uniques
    np.testing.assert_array_equal(q[:3], [1, 2, 3])
    np.testing.assert_array_equal(qc[:3], [1, 1, 3])
    # inverse map reconstructs x
    np.testing.assert_array_equal(np.asarray(q)[qi], xv)


def test_mean_iou_and_sum(prog):
    main, startup = prog
    L = fluid.layers
    pred = fluid.data("p", [-1], "int64")
    lab = fluid.data("l", [-1], "int64")
    miou, _, _ = L.mean_iou(pred, lab, num_classes=3)
    a = fluid.data("a", [-1, 2], "float32")
    b = fluid.data("b", [-1, 2], "float32")
    s = L.sum([a, b])
    pv = np.array([0, 1, 2, 1], "int64")
    lv = np.array([0, 1, 1, 1], "int64")
    av = np.ones((2, 2), "float32")
    m, sv = _run(main, startup,
                 {"p": pv, "l": lv, "a": av, "b": av * 2}, [miou, s])
    assert 0.0 < float(np.asarray(m).reshape(-1)[0]) <= 1.0
    np.testing.assert_allclose(sv, av * 3)


def test_legacy_aliases_and_guards():
    L = fluid.layers
    from paddle_tpu.nn.decode import BeamSearchDecoder as BSD

    assert L.dynamic_decode is not None
    cell = L.GRUCell(4, 6)  # lazy class alias -> nn.layer.rnn.GRUCell
    from paddle_tpu.nn.layer.rnn import GRUCell as RealGRUCell

    assert isinstance(cell, RealGRUCell)
    with pytest.raises(NotImplementedError, match="DataLoader"):
        L.py_reader()
    with pytest.raises(NotImplementedError, match="cond"):
        L.IfElse()
    with pytest.raises(NotImplementedError, match="chunk"):
        L.chunk_eval()


def test_positional_attrs_and_fixed_semantics(prog):
    main, startup = prog
    L = fluid.layers
    x = fluid.data("x", [-1, 4, 4, 4], "float32")
    ps = L.pixel_shuffle(x, 2)          # positional upscale_factor
    st = L.space_to_depth(x, 2)         # C=4 divisible by bs^2
    xv = np.random.RandomState(9).rand(1, 4, 4, 4).astype("float32")
    p, s = _run(main, startup, {"x": xv}, [ps, st])
    assert p.shape == (1, 1, 8, 8) and s.shape == (1, 16, 2, 2)
    with pytest.raises(TypeError, match="positionally"):
        L.cos_sim(fluid.data("a", [-1, 2], "float32"),
                  fluid.data("b", [-1, 2], "float32"), 3)


def test_dice_loss_matches_dygraph_formula(prog):
    main, startup = prog
    L = fluid.layers
    probs = fluid.data("p", [-1, 5, 3], "float32")
    lab = fluid.data("l", [-1, 5, 1], "int64")
    loss = L.dice_loss(probs, lab)
    r = np.random.RandomState(10)
    pv = r.dirichlet(np.ones(3), size=(2, 5)).astype("float32")
    lv = r.randint(0, 3, (2, 5, 1)).astype("int64")
    (sv,) = _run(main, startup, {"p": pv, "l": lv}, [loss])

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.fluid import dygraph

    with dygraph.guard():
        dv = float(F.dice_loss(paddle.to_tensor(pv),
                               paddle.to_tensor(lv)).numpy())
    np.testing.assert_allclose(float(np.asarray(sv).reshape(-1)[0]),
                               dv, rtol=1e-5)


def test_switch_case_list_default_is_max_index(prog):
    main, startup = prog
    L = fluid.layers
    idx = fluid.data("i", [1], "int64")
    f0 = lambda: L.fill_constant([1], "float32", 10.0)
    f3 = lambda: L.fill_constant([1], "float32", 30.0)
    sw = L.switch_case(idx, [(3, f3), (0, f0)])
    (v,) = _run(main, startup, {"i": np.array([9], "int64")}, [sw])
    assert float(v) == 30.0  # out-of-range -> max-index fn, not f0


def test_multivariate_normal_diag_std():
    import paddle_tpu.fluid.layers as L

    d = L.MultivariateNormalDiag(np.zeros(2, "float32"),
                                 np.diag([4.0, 9.0]).astype("float32"))
    # std must be sqrt of the covariance diagonal
    s = d.sample([10000])
    arr = np.asarray(s.numpy() if hasattr(s, "numpy") else s)
    assert abs(arr[:, 0].std() - 2.0) < 0.2
    assert abs(arr[:, 1].std() - 3.0) < 0.3
