"""Op version registry / model-compat (reference
paddle/fluid/framework/op_version_registry.h + OpVersionMap,
framework.proto:185)."""

import warnings

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import op_version_registry as ovr


def _make_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [-1, 4], "float32")
        fluid.layers.fc(x, 2)
    return main


class TestRegistry:
    def test_default_and_bumped_versions(self):
        assert ovr.op_version("elementwise_add") == 1  # never bumped
        assert ovr.op_version("recv_v2") == 2          # bumped in r3

    def test_monotonic_enforced(self):
        with pytest.raises(ValueError):
            ovr.register_op_version("recv_v2", 1, "going backwards")

    def test_program_roundtrip_carries_map(self, fresh_programs):
        main = _make_program()
        d = main.to_dict()
        assert "mul" in d["op_version_map"] or "matmul_v2" in \
            d["op_version_map"] or len(d["op_version_map"]) > 0
        back = fluid.Program.from_json(main.to_json())
        assert back.to_dict()["op_version_map"] == d["op_version_map"]

    def test_newer_writer_raises(self, fresh_programs):
        main = _make_program()
        d = main.to_dict()
        some_op = next(iter(d["op_version_map"]))
        d["op_version_map"][some_op] = 999
        with pytest.raises(RuntimeError, match="NEWER framework"):
            fluid.Program.from_dict(d)

    def test_older_writer_warns(self, fresh_programs):
        main = _make_program()
        d = main.to_dict()
        d["op_version_map"]["recv_v2"] = 1  # pre-r3 semantics
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            fluid.Program.from_dict(d)
        assert any("older op semantics" in str(x.message) for x in w)
