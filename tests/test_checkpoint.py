"""Fault-tolerant training (ISSUE 8): the `paddle_tpu.ckpt` subsystem.

Covers the atomic multi-file commit protocol (manifest written last,
half-written/partial/topology-mismatched checkpoints refused), the
async writer pool's overlap + backpressure + error surfacing, the
legacy io.checkpoint shims, deterministic mid-epoch resume through
`Executor.train_from_dataset` (in-process AND SIGKILL crash-injection
subprocess parity against an uninterrupted golden run), and the
serving Engine's live weight hot-swap (docs/fault_tolerance.md)."""

import json
import os
import random
import shutil
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import profiler
from paddle_tpu.ckpt import (CheckpointError, CheckpointManager,
                             MANIFEST_FILE, WriterPool, latest_checkpoint,
                             list_checkpoints, read_state,
                             shard_assignment, write_state)
from paddle_tpu.fluid import framework, unique_name
from paddle_tpu.fluid.executor import Scope, scope_guard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "fixtures", "ckpt_worker.py")


def _stat(name):
    return profiler.get_int_stats().get(name, 0)


def _time_stat(name):
    return profiler.get_time_stats().get(name, 0.0)


def _state(seed=0, n=6):
    rng = np.random.RandomState(seed)
    out = {f"w_{i}": rng.randn(8, 4).astype("float32") for i in range(n)}
    out["scoped/name"] = rng.randn(3).astype("float32")
    out["step_count"] = np.int64(41)
    return out


# ---------------------------------------------------------------------------
# commit protocol / manifest
# ---------------------------------------------------------------------------

class TestCommitProtocol:
    def test_roundtrip_and_layout(self, tmp_path):
        import jax.numpy as jnp

        m = CheckpointManager(str(tmp_path), keep=3)
        state = dict(_state(), bf=jnp.ones((4,), jnp.bfloat16))
        path = m.save(state, step=5, meta={"feed_epoch": 1})
        assert sorted(os.listdir(path)) == [MANIFEST_FILE,
                                            "shard_00000.npz"]
        # no tmp dir survives a clean commit
        assert not [d for d in os.listdir(tmp_path)
                    if d.startswith(".tmp-")]
        back, manifest = m.restore()
        assert manifest["meta"]["feed_epoch"] == 1
        assert manifest["process_count"] == 1
        for k, v in _state().items():
            np.testing.assert_array_equal(back[k], v)
        assert str(back["bf"].dtype) == "bfloat16"  # dtype survives npz
        assert int(back["step_count"]) == 41

    def test_half_written_dir_skipped_and_refused(self, tmp_path):
        m = CheckpointManager(str(tmp_path))
        good = m.save(_state(), step=1)
        # a dir with shards but NO manifest = never committed
        half = tmp_path / "ckpt-00000002"
        half.mkdir()
        (half / "shard_00000.npz").write_bytes(b"torn")
        assert latest_checkpoint(str(tmp_path)) == good
        with pytest.raises(CheckpointError, match="not a committed"):
            m.restore(str(half))

    def test_partial_checkpoint_refused(self, tmp_path):
        m = CheckpointManager(str(tmp_path))
        path = m.save(_state(), step=3)
        os.remove(os.path.join(path, "shard_00000.npz"))
        assert latest_checkpoint(str(tmp_path)) is None  # skipped
        with pytest.raises(CheckpointError, match="partial"):
            m.restore(path)

    def test_corrupt_manifest_skipped(self, tmp_path):
        m = CheckpointManager(str(tmp_path))
        old = m.save(_state(), step=1)
        newer = m.save(_state(seed=1), step=2)
        with open(os.path.join(newer, MANIFEST_FILE), "w") as f:
            f.write("{ torn json")
        assert latest_checkpoint(str(tmp_path)) == old

    def test_topology_mismatch_refused(self, tmp_path):
        state = _state()
        for host in (1, 0):  # host 0 commits last (mocked pod)
            CheckpointManager(str(tmp_path), process_index=host,
                              process_count=2).save(state, step=1)
        two = CheckpointManager(str(tmp_path), process_index=0,
                                process_count=2)
        back, _ = two.restore()
        assert set(back) == set(state)  # all shards merge back
        one = CheckpointManager(str(tmp_path))
        with pytest.raises(CheckpointError, match="topology mismatch"):
            one.restore()
        # weights-only escape hatch for serving reload
        loose, _ = one.restore(strict_topology=False)
        assert set(loose) == set(state)

    def test_shard_map_disjoint_exhaustive(self, tmp_path):
        names = [f"v{i}" for i in range(17)] + ["a/b", "z"]
        for count in (1, 2, 3, 5, 32):
            asg = shard_assignment(names, count)
            assert set(asg) == set(names)
            assert set(asg.values()) <= set(range(count))
        # mocked 3-host write: union of shards is the full state
        state = _state(n=7)
        for host in (2, 1, 0):
            CheckpointManager(str(tmp_path), process_index=host,
                              process_count=3).save(state, step=4)
        back, manifest = CheckpointManager(
            str(tmp_path), process_index=0, process_count=3).restore()
        assert set(back) == set(state)
        shards = {manifest["vars"][n]["shard"] for n in state}
        assert shards == {0, 1, 2}  # every host owns part of the state

    def test_retention_and_tmp_gc(self, tmp_path):
        # a half-written tmp dir from a "killed" writer
        stale = tmp_path / ".tmp-ckpt-00000001"
        stale.mkdir()
        (stale / "shard_00000.npz").write_bytes(b"dead")
        m = CheckpointManager(str(tmp_path), keep=2)
        for step in (2, 3, 4, 5):
            m.save(_state(), step=step)
        names = sorted(os.listdir(tmp_path))
        assert names == ["ckpt-00000004", "ckpt-00000005"]  # keep=2, GC'd


# ---------------------------------------------------------------------------
# async writer: overlap, backpressure, error surfacing
# ---------------------------------------------------------------------------

class TestAsyncWriter:
    def test_save_async_overlaps_write(self, tmp_path, monkeypatch):
        orig = CheckpointManager._write_job

        def slow(self, *a, **kw):
            time.sleep(0.3)
            return orig(self, *a, **kw)

        monkeypatch.setattr(CheckpointManager, "_write_job", slow)
        stall0 = _time_stat("ckpt_stall_ms")
        m = CheckpointManager(str(tmp_path), max_in_flight=2)
        t0 = time.perf_counter()
        m.save_async(_state(), step=1)
        returned = time.perf_counter() - t0
        assert returned < 0.15, \
            f"save_async blocked for the write ({returned:.3f}s)"
        assert m.in_flight >= 1  # snapshot pending while we keep running
        m.wait()
        assert latest_checkpoint(str(tmp_path)) is not None
        stall = _time_stat("ckpt_stall_ms") - stall0
        assert stall < 150, f"stall {stall}ms should be snapshot-only"

    def test_backpressure_bounds_in_flight(self, tmp_path, monkeypatch):
        orig = CheckpointManager._write_job

        def slow(self, *a, **kw):
            time.sleep(0.25)
            return orig(self, *a, **kw)

        monkeypatch.setattr(CheckpointManager, "_write_job", slow)
        m = CheckpointManager(str(tmp_path), max_in_flight=1)
        m.save_async(_state(), step=1)
        t0 = time.perf_counter()
        m.save_async(_state(), step=2)  # must wait for the slot
        waited = time.perf_counter() - t0
        assert waited > 0.1, "second save_async should backpressure"
        assert m.in_flight <= 1
        m.wait()
        assert len(list_checkpoints(str(tmp_path))) == 2

    def test_writer_error_surfaces_on_wait(self, tmp_path, monkeypatch):
        def boom(self, *a, **kw):
            raise OSError("disk on fire")

        monkeypatch.setattr(CheckpointManager, "_write_job", boom)
        m = CheckpointManager(str(tmp_path))
        m.save_async(_state(), step=1)
        with pytest.raises(OSError, match="disk on fire"):
            m.wait()
        # error cleared after surfacing; next wait is clean
        m.wait()

    def test_writer_error_surfaces_on_next_save(self, tmp_path,
                                                monkeypatch):
        calls = []

        def boom(self, *a, **kw):
            calls.append(1)
            raise OSError("disk on fire")

        monkeypatch.setattr(CheckpointManager, "_write_job", boom)
        m = CheckpointManager(str(tmp_path))
        m.save_async(_state(), step=1)
        while m.in_flight:
            time.sleep(0.01)
        with pytest.raises(OSError, match="disk on fire"):
            m.save_async(_state(), step=2)

    def test_pool_inflight_gauges(self, tmp_path):
        max0 = _stat("ckpt_inflight_max")
        pool = WriterPool(max_in_flight=2)
        gate = []

        def job():
            while not gate:
                time.sleep(0.005)

        pool.submit(job)
        pool.submit(job)
        assert pool.in_flight == 2
        gate.append(1)
        pool.close()
        assert _stat("ckpt_inflight_max") >= max(2, max0)


# ---------------------------------------------------------------------------
# legacy io.checkpoint shims
# ---------------------------------------------------------------------------

class TestLegacyShims:
    def test_save_state_is_atomic_new_format(self, tmp_path):
        from paddle_tpu.io.checkpoint import load_state, save_state

        p = str(tmp_path / "state")
        save_state({"a/b": np.ones((2, 2)), "c": np.float32(3)}, p)
        assert os.path.isfile(os.path.join(p, MANIFEST_FILE))
        # no tmp remnant: commit was rename-atomic
        assert not [d for d in os.listdir(tmp_path)
                    if d.startswith(".tmp-")]
        back = load_state(p)
        np.testing.assert_array_equal(back["a/b"], np.ones((2, 2)))
        assert float(back["c"]) == 3.0

    def test_save_state_empty_raises(self, tmp_path):
        from paddle_tpu.io.checkpoint import save_state

        with pytest.raises(ValueError, match="empty state"):
            save_state({"a": None}, str(tmp_path / "s"))

    def test_async_saver_surfaces_writer_exception(self, tmp_path):
        from paddle_tpu.io.checkpoint import AsyncSaver

        blocker = tmp_path / "file"
        blocker.write_text("not a dir")
        saver = AsyncSaver()
        # parent of the target path is a FILE: the writer must fail
        saver.save({"a": np.ones(3)}, str(blocker / "child" / "state"))
        with pytest.raises(Exception):
            saver.wait()
        saver.wait()  # cleared after surfacing

    def test_async_saver_snapshot_survives_donation(self, tmp_path):
        """save() snapshots device arrays before returning: mutating /
        rebinding the caller's state afterwards must not change what
        lands on disk."""
        import jax.numpy as jnp

        from paddle_tpu.io.checkpoint import AsyncSaver, load_state

        state = {"w": jnp.arange(4.0)}
        saver = AsyncSaver()
        saver.save(state, str(tmp_path / "ck"))
        state["w"] = jnp.zeros(4)
        saver.wait()
        np.testing.assert_array_equal(
            np.asarray(load_state(str(tmp_path / "ck"))["w"]),
            np.arange(4.0))


# ---------------------------------------------------------------------------
# deterministic mid-epoch re-deal (pure functions)
# ---------------------------------------------------------------------------

class TestDeterministicRedeal:
    @pytest.mark.parametrize("hosts,host,epoch", [(1, 0, 0), (4, 2, 3),
                                                  (3, 0, 1)])
    def test_resume_tail_matches_uninterrupted(self, hosts, host, epoch):
        """Kill after k batches, re-deal the same (seed, epoch) via
        shard_plan, skip k: the remaining order is EXACTLY the
        uninterrupted run's tail — the property the crash-injection
        subprocess test exercises end to end."""
        from paddle_tpu.dataset.feed_pipeline import shard_plan

        full = shard_plan(103, host, hosts, epoch=epoch, seed=11)
        for k in (0, 1, len(full) // 2, len(full)):
            redeal = shard_plan(103, host, hosts, epoch=epoch, seed=11)
            assert redeal[k:] == full[k:]
            assert redeal[:k] == full[:k]

    def test_feed_pipeline_skip_batches(self):
        from paddle_tpu.dataset.feed_pipeline import FeedPipeline

        src = [{"x": np.full((2,), i, "float32")} for i in range(8)]
        pipe = FeedPipeline(lambda f: f, iter(src), depth=2,
                            skip_batches=3)
        got = [int(b["x"][0]) for b in pipe]
        assert got == [3, 4, 5, 6, 7]


# ---------------------------------------------------------------------------
# executor auto-checkpoint loop (in-process)
# ---------------------------------------------------------------------------

def _write_slot_files(d, files=3, rows=20, seed=0):
    os.makedirs(d, exist_ok=True)
    rng = np.random.RandomState(seed)
    W = np.arange(1, 9, dtype="float32").reshape(8, 1) / 10.0
    out = []
    for i in range(files):
        p = os.path.join(d, f"part-{i}.txt")
        with open(p, "w") as f:
            for _ in range(rows):
                x = rng.randn(8).astype("float32")
                f.write("8 " + " ".join(f"{v:.6f}" for v in x)
                        + f" 1 {float((x @ W)[0]):.6f}\n")
        out.append(p)
    return out


def _train_run(files, ckpt_dir, epochs, every_steps=2, batch=10):
    """One fresh 'process': new program/scope/executor, auto-ckpt into
    `ckpt_dir`; returns {executor_step: (loss, xmean)}."""
    steps = {}
    main, startup = framework.Program(), framework.Program()
    main.random_seed = 123
    scope = Scope()
    with framework.program_guard(main, startup), unique_name.guard(), \
            scope_guard(scope):
        x = fluid.data("x", [-1, 8], "float32")
        y = fluid.data("y", [-1, 1], "float32")
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.loss.square_error_cost(pred, y))
        xmean = fluid.layers.reduce_mean(x)
        fluid.optimizer.SGD(0.1).minimize(loss)
        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(batch)
        ds.set_use_var([x, y])
        ds.set_filelist(files)
        ds.load_into_memory()
        exe = fluid.Executor()
        exe.run(startup)

        def cb(step, sie, outs):
            steps[step] = (float(outs[0].numpy().ravel()[0]),
                           float(outs[1].numpy().ravel()[0]))

        for _ in range(epochs):
            exe.train_from_dataset(main, ds, fetch_list=[loss, xmean],
                                   checkpoint_dir=ckpt_dir,
                                   checkpoint_every_steps=every_steps,
                                   step_callback=cb)
    return steps


class TestExecutorAutoCheckpoint:
    def test_mid_job_resume_matches_golden(self, tmp_path):
        """Golden 2-epoch run vs (1-epoch run; fresh process resumes
        for the full 2 epochs): identical per-step loss AND batch-mean
        trajectories — state, step counter, and remaining data order
        all restore exactly."""
        files = _write_slot_files(str(tmp_path / "data"))
        golden = _train_run(files, str(tmp_path / "ck_g"), epochs=2)
        part = _train_run(files, str(tmp_path / "ck_r"), epochs=1)
        resumed = _train_run(files, str(tmp_path / "ck_r"), epochs=2)
        assert resumed, "resumed run re-ran nothing"
        assert min(resumed) == max(part) + 1  # continues, not replays
        merged = dict(part)
        merged.update(resumed)
        assert sorted(merged) == sorted(golden)
        for step in golden:
            np.testing.assert_allclose(merged[step], golden[step],
                                       rtol=1e-6,
                                       err_msg=f"step {step} diverged")

    def test_manifest_records_resume_coordinates(self, tmp_path):
        files = _write_slot_files(str(tmp_path / "data"))
        _train_run(files, str(tmp_path / "ck"), epochs=1)
        newest = latest_checkpoint(str(tmp_path / "ck"))
        with open(os.path.join(newest, MANIFEST_FILE)) as f:
            manifest = json.load(f)
        meta = manifest["meta"]
        assert meta["feed_epoch"] == 0
        assert meta["step_in_epoch"] == 6  # 60 rows / batch 10
        assert meta["executor_step"] >= 6
        assert "feed_seed" in meta
        assert manifest["process_count"] == 1
        # state includes the optimizer-updated parameters
        names = set(manifest["vars"])
        assert any(".w_" in n for n in names), names

    def test_resume_skips_consumed_batches(self, tmp_path):
        files = _write_slot_files(str(tmp_path / "data"))
        _train_run(files, str(tmp_path / "ck"), epochs=1)
        skipped0 = _stat("feed_skipped_batches")
        resumed = _train_run(files, str(tmp_path / "ck"), epochs=2)
        # epoch 0 fully consumed pre-restore: all 6 batches skipped
        assert _stat("feed_skipped_batches") - skipped0 == 6
        assert len(resumed) == 6  # only epoch 1 steps ran

    def test_checkpoint_overhead_is_snapshot_only(self, tmp_path,
                                                  monkeypatch):
        """Acceptance: with a writer ~100x slower than a step, training
        still only stalls for the snapshot + bounded backpressure —
        ckpt_stall_ms stays a fraction of ckpt_save_ms, and >=2
        snapshots were in flight while steps kept dispatching."""
        orig = CheckpointManager._write_job

        def slow(self, *a, **kw):
            time.sleep(0.25)
            return orig(self, *a, **kw)

        monkeypatch.setattr(CheckpointManager, "_write_job", slow)
        files = _write_slot_files(str(tmp_path / "data"))
        stall0 = _time_stat("ckpt_stall_ms")
        save0 = _time_stat("ckpt_save_ms")
        _train_run(files, str(tmp_path / "ck"), epochs=1, every_steps=2)
        stall = _time_stat("ckpt_stall_ms") - stall0
        save = _time_stat("ckpt_save_ms") - save0
        assert save > 700  # 3 saves x 250ms writer
        assert stall < 0.6 * save, \
            f"stall {stall:.0f}ms vs save {save:.0f}ms: writes are " \
            f"not overlapping training"
        assert _stat("ckpt_inflight_max") >= 2

    def test_resume_refuses_topology_mismatch(self, tmp_path):
        files = _write_slot_files(str(tmp_path / "data"))
        ck = str(tmp_path / "ck")
        _train_run(files, ck, epochs=1)
        # rewrite the newest manifest as if 4 hosts had written it
        newest = latest_checkpoint(ck)
        mf_path = os.path.join(newest, MANIFEST_FILE)
        with open(mf_path) as f:
            manifest = json.load(f)
        manifest["process_count"] = 4
        manifest["shards"] = ["shard_00000.npz"]
        with open(mf_path, "w") as f:
            json.dump(manifest, f)
        with pytest.raises(CheckpointError, match="topology mismatch"):
            _train_run(files, ck, epochs=2)

    def test_resume_falls_back_past_corrupted_newest(self, tmp_path):
        files = _write_slot_files(str(tmp_path / "data"))
        ck = str(tmp_path / "ck")
        golden = _train_run(files, str(tmp_path / "ck_g"), epochs=2)
        _train_run(files, ck, epochs=1)
        # corrupt the NEWEST checkpoint (end-of-epoch save): resume
        # must fall back to the previous complete one and replay
        done = list_checkpoints(ck)
        assert len(done) >= 2
        shutil.rmtree(os.path.join(done[-1][1]))
        resumed = _train_run(files, ck, epochs=2)
        assert resumed, "nothing re-ran after the fallback restore"
        for step, vals in resumed.items():
            np.testing.assert_allclose(vals, golden[step], rtol=1e-6,
                                       err_msg=f"step {step} diverged")


# ---------------------------------------------------------------------------
# crash injection: SIGKILL at a step boundary, resume, compare
# ---------------------------------------------------------------------------

def _run_worker(out, data_dir, ckpt_dir, epochs=1, kill_at=None,
                every_steps=2):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env["DATA_DIR"] = data_dir
    env["EPOCHS"] = str(epochs)
    env["PADDLE_CKPT_DIR"] = ckpt_dir
    env["PADDLE_CKPT_EVERY_STEPS"] = str(every_steps)
    env["KILL_AT_STEP"] = str(-1 if kill_at is None else kill_at)
    return subprocess.run([sys.executable, WORKER, str(out)], env=env,
                          cwd=REPO, capture_output=True, text=True,
                          timeout=300)


def _read_trajectory(path):
    out = {}
    with open(path) as f:
        for line in f:
            step, loss, xmean = line.split()
            out[int(step)] = (float(loss), float(xmean))  # replays overwrite
    return out


class TestCrashInjection:
    def test_kill_resume_smoke(self, tmp_path):
        """Fast CI smoke (tools/ci.sh): SIGKILL mid-epoch, restart,
        job completes with a contiguous step trajectory."""
        data = str(tmp_path / "data")
        _write_slot_files(data, files=2, rows=20, seed=3)
        out = tmp_path / "t.txt"
        ck = str(tmp_path / "ck")
        # save every step, kill near the end of epoch 2: several async
        # commits are guaranteed durable before the SIGKILL lands
        rc1 = _run_worker(out, data, ck, epochs=2, kill_at=8,
                          every_steps=1)
        assert rc1.returncode == -signal.SIGKILL, rc1.stderr
        assert latest_checkpoint(ck) is not None
        rc2 = _run_worker(out, data, ck, epochs=2, every_steps=1)
        assert rc2.returncode == 0, rc2.stdout + rc2.stderr
        steps = sorted(_read_trajectory(out))
        assert steps == list(range(steps[0], steps[0] + 8)), steps

    def test_sigkill_random_boundary_parity(self, tmp_path):
        """The acceptance run: golden uninterrupted 2-epoch job vs a
        job SIGKILLed at a RANDOM step boundary and resumed — loss AND
        batch-content trajectories must match step for step (same
        state, same remaining data order)."""
        data = str(tmp_path / "data")
        _write_slot_files(data, files=3, rows=20, seed=5)
        golden_out = tmp_path / "golden.txt"
        rc = _run_worker(golden_out, data, str(tmp_path / "ck_g"),
                         epochs=2)
        assert rc.returncode == 0, rc.stdout + rc.stderr
        golden = _read_trajectory(golden_out)
        steps = sorted(golden)
        assert len(steps) == 12  # 2 epochs x 6 batches

        kill_at = random.Random().choice(steps[1:-1])
        out = tmp_path / "t.txt"
        ck = str(tmp_path / "ck")
        rc1 = _run_worker(out, data, ck, epochs=2, kill_at=kill_at)
        assert rc1.returncode == -signal.SIGKILL, \
            f"kill_at={kill_at}: {rc1.stderr}"
        rc2 = _run_worker(out, data, ck, epochs=2)
        assert rc2.returncode == 0, \
            f"kill_at={kill_at}: {rc2.stdout}{rc2.stderr}"
        got = _read_trajectory(out)
        assert sorted(got) == steps, f"kill_at={kill_at}"
        for s in steps:
            np.testing.assert_allclose(
                got[s], golden[s], rtol=1e-6,
                err_msg=f"step {s} diverged (kill_at={kill_at})")


# ---------------------------------------------------------------------------
# serving hot swap
# ---------------------------------------------------------------------------

class TestServingReload:
    def test_reload_weights_live_engine(self, fresh_programs, tmp_path):
        from paddle_tpu import serving
        from paddle_tpu.serving.engine import ProgramModel

        main, startup, scope = fresh_programs
        x = fluid.data("x", [-1, 4], "float32")
        pred = fluid.layers.fc(x, 2, bias_attr=False)
        exe = fluid.Executor()
        exe.run(startup)
        w_name = next(v.name for v in main.list_vars()
                      if v.persistable and ".w_" in v.name)
        w0 = np.asarray(scope.get(w_name)).copy()

        model = ProgramModel(exe, main, ["x"], [pred], scope=scope)
        eng = serving.Engine(model, serving.EngineConfig(
            max_batch_size=4, max_queue_delay_ms=0.0))
        try:
            xin = np.ones((2, 4), "float32")
            (before,) = eng.infer([xin], timeout=60)
            np.testing.assert_allclose(before, xin @ w0, rtol=1e-5)
            # publish a checkpoint with doubled weights, swap it in
            # WITHOUT shutting the engine down
            write_state(str(tmp_path / "ck"), {w_name: w0 * 2.0})
            swapped = eng.reload_weights(str(tmp_path / "ck"))
            assert swapped == 1
            (after,) = eng.infer([xin], timeout=60)
            np.testing.assert_allclose(after, xin @ (w0 * 2.0),
                                       rtol=1e-5)
            assert _stat("ckpt_reload_count") >= 1
        finally:
            eng.shutdown(drain=True)

    def test_reload_resolves_checkpoint_root(self, fresh_programs,
                                             tmp_path):
        """A checkpoint ROOT (step-numbered children) resolves to the
        newest complete checkpoint."""
        main, startup, scope = fresh_programs
        x = fluid.data("x", [-1, 4], "float32")
        pred = fluid.layers.fc(x, 2, bias_attr=False)
        exe = fluid.Executor()
        exe.run(startup)
        w_name = next(v.name for v in main.list_vars()
                      if v.persistable and ".w_" in v.name)
        m = CheckpointManager(str(tmp_path))
        m.save({w_name: np.zeros((4, 2), "float32")}, step=1)
        m.save({w_name: np.full((4, 2), 7.0, "float32")}, step=2)
        from paddle_tpu.serving.engine import ProgramModel

        model = ProgramModel(exe, main, ["x"], [pred], scope=scope)
        assert model.reload_weights(str(tmp_path)) == 1
        np.testing.assert_allclose(np.asarray(scope.get(w_name)), 7.0)

    def test_reload_rejects_closure_models(self):
        import jax.numpy as jnp

        from paddle_tpu import serving

        eng = serving.Engine(lambda a: jnp.tanh(a), start=False)
        with pytest.raises(TypeError, match="ProgramModel"):
            eng.reload_weights("/nonexistent")


# ---------------------------------------------------------------------------
# lint wiring + flags
# ---------------------------------------------------------------------------

class TestLintAndFlags:
    def test_ckpt_writers_on_hot_path_watchlist(self):
        tools = os.path.join(REPO, "tools")
        if tools not in sys.path:
            sys.path.insert(0, tools)
        from tpulint import load_lint

        lint = load_lint()
        watched = set(lint.hot_path_sync.WATCHLIST)
        for entry in (("paddle_tpu/ckpt/manager.py",
                       "CheckpointManager.save_async"),
                      ("paddle_tpu/ckpt/manager.py",
                       "CheckpointManager._snapshot"),
                      ("paddle_tpu/ckpt/writer.py", "WriterPool.submit")):
            assert entry in watched, entry
        assert "paddle_tpu/ckpt" in lint.span_leak.WATCHED

    def test_ckpt_flags_registered(self):
        import paddle_tpu

        flags = paddle_tpu.get_flags(
            ["FLAGS_ckpt_dir", "FLAGS_ckpt_every_steps",
             "FLAGS_ckpt_every_secs", "FLAGS_ckpt_keep",
             "FLAGS_ckpt_max_in_flight", "FLAGS_ckpt_resume"])
        assert flags["FLAGS_ckpt_keep"] == 3
        assert flags["FLAGS_ckpt_resume"] is True

    def test_ckpt_spans_flow_linked(self, tmp_path):
        """One save emits a ckpt.snapshot span on the training thread
        and a flow-linked ckpt.write span on the writer thread."""
        from paddle_tpu import obs

        obs.enable(reset=True)
        try:
            m = CheckpointManager(str(tmp_path))
            m.save(_state(), step=1)
        finally:
            trace = str(tmp_path / "trace.json")
            obs.export_trace(trace)
            obs.disable()
        with open(trace) as f:
            events = json.load(f)["traceEvents"]
        by_name = {}
        for e in events:
            if e.get("ph") == "X":
                by_name.setdefault(e["name"], []).append(e)
        assert "ckpt.snapshot" in by_name
        assert "ckpt.write" in by_name
        assert by_name["ckpt.snapshot"][0]["tid"] != \
            by_name["ckpt.write"][0]["tid"]  # crossed the thread boundary
        flows = [e for e in events if e.get("ph") in ("s", "t", "f")]
        assert flows, "no flow events linking snapshot -> write"
