"""Loss-trajectory equivalence for every parallel mode (VERDICT r3 task
5): same seed, N-way sharded vs 1-device, losses must match — the
reference's `check_with_place` standard
(/root/reference/python/paddle/fluid/tests/unittests/test_dist_base.py:1119
asserts dist losses ~= local losses for each strategy's model fixture).

Modes covered on the 8-device virtual CPU mesh:
  dp        batch sharding (BERT-tiny full train step)
  mp        tensor/model parallel param sharding (BERT-tiny)
  dp x mp   combined 4x2 mesh (BERT-tiny)
  sp        ring-attention sequence parallelism (BERT-tiny, dropout=0)
  sharding  ZeRO-style param+optimizer-state sharding (BERT-tiny)
  pp        GPipe pipeline (MLP stages; BERT pipeline lands with the
            non-uniform-stage generalization)
  dygraph   eager DataParallel
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import bert
from paddle_tpu.parallel.mesh import make_mesh

STEPS = 4
TOL = dict(rtol=2e-3, atol=2e-4)


def _bert_losses(mesh=None, steps=STEPS, dropout=True, **mesh_kw):
    import paddle_tpu as paddle

    cfg = bert.BertConfig.tiny()
    if not dropout:
        cfg.hidden_dropout_prob = 0.0
        cfg.attention_probs_dropout_prob = 0.0
    paddle.seed(0)  # init draws from the global generator
    model = bert.BertForPretraining(cfg)
    step, state = bert.build_pretrain_step(model, bf16=False, mesh=mesh,
                                           **mesh_kw)
    b = bert.fake_batch(cfg, 8, 128, num_masked=10, seed=7)
    losses = []
    for _ in range(steps):
        state, loss = step(state, b, jnp.float32(1e-3))
        losses.append(float(loss))
    return losses


@pytest.fixture(scope="module")
def single_device_losses():
    return _bert_losses(mesh=None)


@pytest.fixture(scope="module")
def single_device_losses_nodrop():
    return _bert_losses(mesh=None, dropout=False)


class TestShardedEqualsSingle:
    def test_dp(self, single_device_losses):
        mesh = make_mesh({"dp": 8})
        got = _bert_losses(mesh=mesh, dp_axis="dp")
        np.testing.assert_allclose(got, single_device_losses, **TOL)

    def test_mp(self, single_device_losses):
        mesh = make_mesh({"dp": 1, "mp": 8})
        got = _bert_losses(mesh=mesh, dp_axis="dp", mp_axis="mp")
        np.testing.assert_allclose(got, single_device_losses, **TOL)

    def test_dp_x_mp(self, single_device_losses):
        mesh = make_mesh({"dp": 4, "mp": 2})
        got = _bert_losses(mesh=mesh, dp_axis="dp", mp_axis="mp")
        np.testing.assert_allclose(got, single_device_losses, **TOL)

    def test_sp_ring_attention(self, single_device_losses_nodrop):
        mesh = make_mesh({"dp": 2, "sp": 4})
        got = _bert_losses(mesh=mesh, dp_axis="dp", sp_axis="sp",
                           use_ring_attention=True, dropout=False)
        np.testing.assert_allclose(got, single_device_losses_nodrop,
                                   **TOL)

    def test_sp_ulysses_attention(self, single_device_losses_nodrop):
        """The OTHER sequence-parallel path (all-to-all head
        re-sharding, parallel/ulysses.py) on the same dp x sp mesh —
        and unlike ring, the key-padding attention_mask stays active
        (Ulysses supports it), so this exercises the masked path too."""
        mesh = make_mesh({"dp": 2, "sp": 4})
        got = _bert_losses(mesh=mesh, dp_axis="dp", sp_axis="sp",
                           use_ulysses=True, dropout=False)
        np.testing.assert_allclose(got, single_device_losses_nodrop,
                                   **TOL)

    def test_zero_sharding(self, single_device_losses):
        """ZeRO: params + adam moments sharded over the data axis.
        Numerics must be identical — sharding only changes layout."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        import paddle_tpu as paddle

        mesh = make_mesh({"dp": 8})
        cfg = bert.BertConfig.tiny()
        paddle.seed(0)
        model = bert.BertForPretraining(cfg)
        step, state = bert.build_pretrain_step(model, bf16=False)
        # re-place the state ZeRO-style: shard each tensor's first
        # axis that divides the mesh (stage-3 partitioning)
        def zero_spec(v):
            for i, d in enumerate(v.shape):
                if d % 8 == 0:
                    return P(*([None] * i + ["dp"]))
            return P()

        shardings = {
            grp: {k: NamedSharding(mesh, zero_spec(v))
                  for k, v in state[grp].items()}
            for grp in ("params", "m", "v")}
        shardings["t"] = NamedSharding(mesh, P())
        state = jax.device_put(state, shardings)
        b = bert.fake_batch(cfg, 8, 128, num_masked=10, seed=7)
        losses = []
        for _ in range(STEPS):
            state, loss = step(state, b, jnp.float32(1e-3))
            losses.append(float(loss))
        np.testing.assert_allclose(losses, single_device_losses, **TOL)

    def test_pp_gpipe(self):
        """4-stage GPipe MLP == non-pipelined (uniform stages; the
        real-model pipeline test lives in test_pipeline_bert.py)."""
        from paddle_tpu.parallel.pipeline import gpipe, stack_stage_params

        mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
        rng = np.random.RandomState(0)
        ws = [jnp.asarray(rng.randn(16, 16) * 0.3, jnp.float32)
              for _ in range(4)]

        def stage(p, x):
            return jnp.tanh(x @ p["w"])

        run = gpipe(mesh, stage, num_microbatches=4, axis="pp")
        x = jnp.asarray(rng.randn(8, 16), jnp.float32)

        def loss_pp(params, x):
            return jnp.mean(run(params, x) ** 2)

        def loss_ref(params_list, x):
            h = x
            for p in params_list:
                h = stage({"w": p}, h)
            return jnp.mean(h ** 2)

        stacked = stack_stage_params([{"w": w} for w in ws])
        lp, gp = jax.value_and_grad(loss_pp)(stacked, x)
        lr, gr = jax.value_and_grad(
            lambda ws, x: loss_ref(list(ws), x))(tuple(ws), x)
        np.testing.assert_allclose(float(lp), float(lr), rtol=1e-5)
        for i in range(4):
            np.testing.assert_allclose(np.asarray(gp["w"][i]),
                                       np.asarray(gr[i]), rtol=1e-4,
                                       atol=1e-5)


class TestDygraphDataParallel:
    def test_dp_matches_single(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        import paddle_tpu.nn.functional as F
        from paddle_tpu.fluid.dygraph import (DataParallel, guard,
                                              to_variable)
        from paddle_tpu.optimizer import SGD

        def run(parallel):
            with guard():
                paddle.seed(0)
                net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                                    nn.Linear(32, 10))
                model = DataParallel(net) if parallel else net
                opt = SGD(learning_rate=0.1,
                          parameters=net.parameters())
                rng = np.random.RandomState(1)
                losses = []
                for _ in range(6):
                    x = to_variable(rng.randn(32, 16).astype("float32"))
                    y = to_variable(
                        rng.randint(0, 10, (32,)).astype("int64"))
                    loss = F.cross_entropy(model(x), y)
                    loss = (model.scale_loss(loss) if parallel else loss)
                    loss.backward()
                    if parallel:
                        model.apply_collective_grads()
                    opt.minimize(loss)
                    for p in net.parameters():
                        p.clear_gradient()
                    losses.append(float(loss.numpy()))
                return losses

        np.testing.assert_allclose(run(True), run(False), rtol=2e-4)

    def test_params_replicated_and_inputs_sharded(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.fluid.dygraph import DataParallel, guard, to_variable

        with guard():
            net = nn.Linear(8, 4)
            model = DataParallel(net)
            assert model._nranks == 8
            x = to_variable(np.ones((16, 8), "float32"))
            out = model(x)
            # input got the data sharding; params stayed replicated
            assert len(set(x._value.sharding.device_set)) == 8
            assert net.weight._value.sharding.is_fully_replicated
            assert out.shape == [16, 4]
