"""Flash-attention kernel tests (interpret mode on the CPU mesh).

Covers the round-2 kernel upgrades: in-kernel key-padding bias,
in-kernel counter-based dropout (bit-exact fwd/bwd agreement), the
padding shim for non-block-multiple shapes, and the Pallas backward
kernels vs autodiff-through-XLA oracle gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas import attention as A


def _rand_qkv(rng, b=2, sq=128, sk=128, h=2, d=64):
    mk = lambda s: jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    return mk(sq), mk(sk), mk(sk)


class TestFlashForward:
    def test_causal_oracle(self):
        rng = np.random.RandomState(0)
        q, k, v = _rand_qkv(rng)
        ref = A._xla_attention(q, k, v, is_causal=True)
        out = A.flash_attention(q, k, v, is_causal=True, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-3, atol=1e-3)

    def test_unaligned_lengths_padding_shim(self):
        """ADVICE round-1 #1: non-block-multiple seq lens must not read
        garbage K/V columns."""
        rng = np.random.RandomState(1)
        q, k, v = _rand_qkv(rng, sq=100, sk=75, d=48)
        ref = A._xla_attention(q, k, v)
        out = A.flash_attention(q, k, v, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-3, atol=1e-3)

    def test_cross_attention_causal_offset(self):
        rng = np.random.RandomState(2)
        q, k, v = _rand_qkv(rng, sq=64, sk=160)
        ref = A._xla_attention(q, k, v, is_causal=True)
        out = A.flash_attention(q, k, v, is_causal=True, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-3, atol=1e-3)

    def test_key_padding_bias_in_kernel(self):
        rng = np.random.RandomState(3)
        b, sk = 2, 128
        q, k, v = _rand_qkv(rng, b=b, sk=sk)
        lens = np.array([100, 57])
        bool_mask = jnp.asarray(np.arange(sk)[None, :] < lens[:, None])
        bias = jnp.where(bool_mask, 0.0, A.DEFAULT_MASK_VALUE)
        ref = A._xla_attention(q, k, v,
                               mask=bool_mask[:, None, None, :])
        out = A.flash_attention(q, k, v, key_bias=bias, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-3, atol=1e-3)

    def test_dispatcher_mask_reduction(self):
        m4 = jnp.zeros((2, 1, 1, 128), jnp.float32)
        assert A._mask_as_key_bias(m4, 2, 128) is not None
        m_bool = jnp.ones((2, 128), jnp.bool_)
        kb = A._mask_as_key_bias(m_bool, 2, 128)
        assert kb is not None and kb.dtype == jnp.float32
        # per-query masks are NOT expressible as key bias
        dense = jnp.zeros((2, 1, 128, 128), jnp.float32)
        assert A._mask_as_key_bias(dense, 2, 128) is None
        per_head = jnp.zeros((2, 4, 1, 128), jnp.float32)
        assert A._mask_as_key_bias(per_head, 2, 128) is None


class TestFlashBackward:
    def _grads(self, fn, q, k, v):
        def loss(q, k, v):
            out = fn(q, k, v)
            # non-uniform cotangent exercises all grad paths
            w = jnp.arange(out.size, dtype=jnp.float32).reshape(out.shape)
            return jnp.sum(out * w) / out.size
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_vs_oracle(self, causal):
        rng = np.random.RandomState(4)
        q, k, v = _rand_qkv(rng, b=1, sq=128, sk=128, h=2, d=64)
        g_ref = self._grads(
            lambda q, k, v: A._xla_attention(q, k, v, is_causal=causal),
            q, k, v)
        g_out = self._grads(
            lambda q, k, v: A.flash_attention(q, k, v, is_causal=causal,
                                              interpret=True),
            q, k, v)
        for a, b in zip(g_out, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=2e-3)

    def test_grads_unaligned_with_bias(self):
        rng = np.random.RandomState(5)
        b, sk = 2, 90
        q, k, v = _rand_qkv(rng, b=b, sq=70, sk=sk, d=32)
        lens = np.array([88, 41])
        bool_mask = jnp.asarray(np.arange(sk)[None, :] < lens[:, None])
        bias = jnp.where(bool_mask, 0.0, A.DEFAULT_MASK_VALUE)
        g_ref = self._grads(
            lambda q, k, v: A._xla_attention(
                q, k, v, mask=bool_mask[:, None, None, :]), q, k, v)
        g_out = self._grads(
            lambda q, k, v: A.flash_attention(q, k, v, key_bias=bias,
                                              interpret=True), q, k, v)
        for a, b_ in zip(g_out, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=2e-3, atol=2e-3)


class TestFlashDropout:
    """The in-kernel RNG is a pure function of absolute coordinates, so
    an XLA oracle applying the *same* keep mask must match bit-for-bit
    in expectation AND gradient."""

    def _oracle_with_keep(self, q, k, v, keep, p_drop):
        d = q.shape[-1]
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (d ** 0.5)
        probs = jax.nn.softmax(logits, axis=-1)
        probs = jnp.where(keep, probs / (1.0 - p_drop), 0.0)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, v)

    def _keep_for(self, seed, b, h, sq, sk, p_drop):
        """Reconstruct the kernel's keep mask with the same hash."""
        seed_arr = jnp.asarray([seed], jnp.int32)
        rows = []
        for bh in range(b * h):
            rows.append(A._keep_mask(seed_arr[0], bh, 0, 0, sq, sk, p_drop))
        m = jnp.stack(rows).reshape(b, h, sq, sk)
        return m

    def test_dropout_matches_masked_oracle(self):
        rng = np.random.RandomState(6)
        p_drop = 0.3
        b, sq, sk, h, d = 1, 128, 128, 2, 64
        q, k, v = _rand_qkv(rng, b=b, sq=sq, sk=sk, h=h, d=d)
        keep = self._keep_for(7, b, h, sq, sk, p_drop)

        out = A.flash_attention(q, k, v, dropout_p=p_drop, dropout_seed=7,
                                interpret=True)
        ref = self._oracle_with_keep(q, k, v, keep, p_drop)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-3, atol=1e-3)

    def test_dropout_grads_match_masked_oracle(self):
        rng = np.random.RandomState(7)
        p_drop = 0.25
        b, sq, sk, h, d = 1, 128, 128, 1, 32
        q, k, v = _rand_qkv(rng, b=b, sq=sq, sk=sk, h=h, d=d)
        keep = self._keep_for(11, b, h, sq, sk, p_drop)

        def l_kernel(q, k, v):
            return jnp.sum(A.flash_attention(
                q, k, v, dropout_p=p_drop, dropout_seed=11,
                interpret=True) ** 2)

        def l_oracle(q, k, v):
            return jnp.sum(self._oracle_with_keep(q, k, v, keep,
                                                  p_drop) ** 2)

        g_k = jax.grad(l_kernel, argnums=(0, 1, 2))(q, k, v)
        g_o = jax.grad(l_oracle, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_k, g_o):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=2e-3, atol=2e-3)

    def test_dropout_rate_and_determinism(self):
        keep = np.asarray(A._keep_mask(jnp.int32(3), 0, 0, 0, 256, 256, 0.4))
        assert abs(keep.mean() - 0.6) < 0.02
        keep2 = np.asarray(A._keep_mask(jnp.int32(3), 0, 0, 0, 256, 256, 0.4))
        np.testing.assert_array_equal(keep, keep2)
        keep3 = np.asarray(A._keep_mask(jnp.int32(4), 0, 0, 0, 256, 256, 0.4))
        assert (keep != keep3).any()
        # block-layout independence: bits at offset == slice of full mask
        sub = np.asarray(A._keep_mask(jnp.int32(3), 0, 128, 64, 128, 128, 0.4))
        np.testing.assert_array_equal(sub, keep[128:, 64:192])
