"""CTC family tests: warpctc vs torch.nn.functional.ctc_loss,
ctc_align greedy collapse, edit_distance vs a numpy Levenshtein oracle
(reference unittests: test_warpctc_op.py, test_ctc_align.py,
test_edit_distance_op.py), plus hinge_loss / data_norm / masked_select.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, unique_name
from paddle_tpu.fluid.executor import Scope, scope_guard

from op_test import OpTest, randf, run_single_op

run_op = run_single_op




class TestWarpCTC:
    def test_matches_torch_ctc_loss(self):
        import torch

        rng = np.random.RandomState(0)
        T, B, C, L = 12, 3, 6, 4
        logits = rng.randn(T, B, C).astype("float32")
        labels = rng.randint(1, C, (B, L)).astype("int32")
        logit_len = np.array([12, 9, 7], "int32")
        label_len = np.array([4, 3, 2], "int32")
        d = run_op("warpctc",
                   {"Logits": logits, "Label": labels,
                    "LogitsLength": logit_len, "LabelLength": label_len},
                   {"blank": 0}, ["Loss"])
        ref = torch.nn.functional.ctc_loss(
            torch.log_softmax(torch.tensor(logits), -1),
            torch.tensor(labels.astype("int64")),
            torch.tensor(logit_len.astype("int64")),
            torch.tensor(label_len.astype("int64")),
            blank=0, reduction="none").numpy()
        np.testing.assert_allclose(d["Loss"].reshape(-1), ref,
                                   rtol=1e-4, atol=1e-4)

    def test_grad_flows(self, fresh_programs):
        main, startup, scope = fresh_programs
        rng = np.random.RandomState(1)
        lg = fluid.data("lg", [8, 2, 5], "float32")
        lg.stop_gradient = False
        lb = fluid.data("lb", [2, 3], "int32")
        loss_var = main.global_block().create_var(name="ctcl",
                                                  dtype="float32")
        main.global_block().append_op(
            "warpctc", inputs={"Logits": [lg], "Label": [lb]},
            outputs={"Loss": [loss_var]}, attrs={"blank": 0},
            infer_shape=False)
        total = fluid.layers.reduce_sum(main.global_block().var("ctcl"))
        fluid.append_backward(total)
        exe = fluid.Executor()
        g = exe.run(main,
                    feed={"lg": rng.randn(8, 2, 5).astype("float32"),
                          "lb": rng.randint(1, 5, (2, 3)).astype("int32")},
                    fetch_list=[framework.grad_var_name("lg")])[0]
        g = np.asarray(g)
        assert g.shape == (8, 2, 5)
        assert np.isfinite(g).all() and np.abs(g).max() > 0


def test_ctc_align_respects_input_length():
    ids = np.array([[1, 0, 2, 9, 9, 9]], "int32")
    d = run_op("ctc_align",
               {"Input": ids, "InputLength": np.array([[3]], "int32")},
               {"blank": 0, "padding_value": -1},
               ["Output", "OutputLength"],
               {"Output": "int32", "OutputLength": "int32"})
    # steps >= 3 are padding and must not decode
    np.testing.assert_array_equal(d["Output"][0, :2], [1, 2])
    assert np.all(d["Output"][0, 2:] == -1)
    np.testing.assert_array_equal(d["OutputLength"].reshape(-1), [2])


def test_ctc_align_collapse():
    ids = np.array([[1, 1, 0, 2, 2, 0, 3],
                    [0, 0, 4, 4, 4, 0, 0]], "int32")
    d = run_op("ctc_align", {"Input": ids},
               {"blank": 0, "padding_value": -1}, ["Output", "OutputLength"],
               {"Output": "int32", "OutputLength": "int32"})
    np.testing.assert_array_equal(d["Output"][0, :3], [1, 2, 3])
    assert np.all(d["Output"][0, 3:] == -1)
    np.testing.assert_array_equal(d["Output"][1, :1], [4])
    np.testing.assert_array_equal(d["OutputLength"].reshape(-1), [3, 1])


def _lev(a, b):
    dp = np.zeros((len(a) + 1, len(b) + 1), int)
    dp[:, 0] = np.arange(len(a) + 1)
    dp[0, :] = np.arange(len(b) + 1)
    for i in range(1, len(a) + 1):
        for j in range(1, len(b) + 1):
            dp[i, j] = min(dp[i - 1, j] + 1, dp[i, j - 1] + 1,
                           dp[i - 1, j - 1] + (a[i - 1] != b[j - 1]))
    return dp[len(a), len(b)]


def test_edit_distance_matches_numpy():
    rng = np.random.RandomState(2)
    hyp = rng.randint(0, 5, (4, 6)).astype("int32")
    ref = rng.randint(0, 5, (4, 7)).astype("int32")
    hl = np.array([6, 4, 5, 2], "int32")
    rl = np.array([7, 5, 3, 2], "int32")
    d = run_op("edit_distance",
               {"Hyps": hyp, "Refs": ref, "HypsLength": hl,
                "RefsLength": rl},
               {"normalized": False}, ["Out", "SequenceNum"],
               {"SequenceNum": "int64"})
    want = [_lev(list(hyp[i, :hl[i]]), list(ref[i, :rl[i]]))
            for i in range(4)]
    np.testing.assert_allclose(d["Out"].reshape(-1), want)
    assert int(d["SequenceNum"]) == 4


def test_hinge_loss():
    logits = np.array([[0.5], [-2.0], [1.5]], "float32")
    labels = np.array([[1.0], [0.0], [0.0]], "float32")
    d = run_op("hinge_loss", {"Logits": logits, "Labels": labels}, {},
               ["Loss"])
    np.testing.assert_allclose(
        d["Loss"], np.maximum(1 - (2 * labels - 1) * logits, 0),
        rtol=1e-6)


def test_data_norm():
    rng = np.random.RandomState(3)
    x = rng.randn(6, 4).astype("float32") * 3 + 1
    bsize = np.full((4,), 100.0, "float32")
    bsum = np.full((4,), 200.0, "float32")   # mean 2
    bsq = np.full((4,), 500.0, "float32")
    d = run_op("data_norm",
               {"X": x, "BatchSize": bsize, "BatchSum": bsum,
                "BatchSquareSum": bsq},
               {"epsilon": 1e-4}, ["Y", "Means", "Scales"])
    # reference formula: scale = sqrt(N / sum_sq) (sum_sq accumulated
    # centered by the update path)
    means = 200.0 / 100.0
    scales = np.sqrt(100.0 / 500.0)
    np.testing.assert_allclose(d["Means"], np.full(4, means), rtol=1e-5)
    np.testing.assert_allclose(d["Y"], (x - means) * scales, rtol=1e-4)


def test_masked_select_front_packs():
    x = np.arange(12, dtype="float32").reshape(3, 4)
    mask = x % 2 == 0
    d = run_op("masked_select", {"X": x, "Mask": mask}, {}, ["Y"])
    np.testing.assert_allclose(d["Y"][:6],
                               np.array([0, 2, 4, 6, 8, 10], "float32"))
    assert np.all(d["Y"][6:] == 0)


def test_ctc_layers_api(fresh_programs):
    main, startup, scope = fresh_programs
    probs = fluid.data("probs", [2, 7, 5], "float32")
    decoded, dlen = fluid.layers.ctc_greedy_decoder(probs, blank=0)
    exe = fluid.Executor()
    rng = np.random.RandomState(4)
    P = rng.rand(2, 7, 5).astype("float32")
    P[0, :, :] = 0
    P[0, :3, 2] = 5.0  # -> [2,2,2, argmax rest 0...] collapses to [2]
    o, ln = exe.run(main, feed={"probs": P}, fetch_list=[decoded, dlen])
    assert np.asarray(o)[0, 0] == 2
    assert np.asarray(ln).reshape(-1)[0] >= 1


def test_spp_concats_pyramid():
    x = np.random.RandomState(5).randn(2, 3, 8, 8).astype("float32")
    d = run_op("spp", {"X": x},
               {"pyramid_height": 3, "pooling_type": "max"}, ["Out"])
    # 1 + 4 + 16 bins per channel
    assert d["Out"].shape == (2, 3 * 21)
    np.testing.assert_allclose(d["Out"][:, :3],
                               x.max(axis=(2, 3)), rtol=1e-5)


def test_hsigmoid_binary_tree_loss_positive():
    rng = np.random.RandomState(6)
    x = rng.randn(4, 5).astype("float32")
    num_classes = 8
    w = rng.randn(num_classes - 1, 5).astype("float32") * 0.1
    label = rng.randint(0, num_classes, (4, 1)).astype("int32")
    d = run_op("hierarchical_sigmoid",
               {"X": x, "W": w, "Label": label},
               {"num_classes": num_classes}, ["Out", "PreOut"])
    assert d["Out"].shape == (4, 1)
    assert (d["Out"] > 0).all() and np.isfinite(d["Out"]).all()


def test_nce_cost_shape_and_finite():
    rng = np.random.RandomState(7)
    x = rng.randn(4, 6).astype("float32")
    w = rng.randn(20, 6).astype("float32") * 0.1
    label = rng.randint(0, 20, (4, 1)).astype("int32")
    d = run_op("nce", {"Input": x, "Label": label, "Weight": w},
               {"num_total_classes": 20, "num_neg_samples": 5},
               ["Cost", "SampleLogits", "SampleLabels"],
               {"SampleLabels": "int32"})
    assert d["Cost"].shape == (4, 1)
    assert np.isfinite(d["Cost"]).all() and (d["Cost"] > 0).all()
    assert d["SampleLabels"].shape == (4, 6)
