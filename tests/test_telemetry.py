"""Live production telemetry (ISSUE 10): paddle_tpu.obs.telemetry.

Covers the collector's delta/level folding and bounded memory, the
Prometheus + JSON export and the /metrics + /healthz + /snapshot +
/debug/trace endpoint, the anomaly watchdog (rule pos/neg: an injected
step-time spike and an injected NaN both flip /healthz with a reason
and publish a COMPLETE flight-record bundle; a healthy run publishes
none), the flight recorder's rate limit + retention GC, the
PADDLE_OBS_HTTP_PORT auto-attach on train_from_dataset and
serving.Engine, and the zero-sync contract: the sampler adds zero
device->host transfers to the dispatch hot path
(executor_sync_count-asserted, like the async-executor suite).  Also
the serving/metrics.py stat-table sync satellite: every stat name the
module writes must appear in its docstring table.
"""

import json
import os
import re
import threading
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import obs, profiler
from paddle_tpu.obs import telemetry
from paddle_tpu.obs.telemetry import (Collector, MetricStore, Watchdog,
                                      prometheus_text, replay_rules,
                                      series_stats)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _scripted(counters=None, timers=None, gauges=None):
    """A sources() callable over mutable dicts the test scripts."""
    counters = counters if counters is not None else {}
    timers = timers if timers is not None else {}
    gauges = gauges if gauges is not None else {}

    def sources():
        return {"counters": dict(counters), "timers_ms": dict(timers),
                "gauges": dict(gauges)}

    return sources, counters, timers, gauges


def _collector(tmp_path=None, sample_s=1.0, capacity=600, **wd_kw):
    """Collector + watchdog over scripted sources and a scripted
    clock; returns (collector, watchdog, counters, timers, gauges,
    tick)."""
    sources, counters, timers, gauges = _scripted()
    clock = {"t": 1000.0}
    wd = Watchdog(artifacts_dir=str(tmp_path) if tmp_path else None,
                  clock=lambda: clock["t"], **wd_kw)
    col = Collector(sources=sources, sample_s=sample_s,
                    capacity=capacity, watchdog=wd,
                    clock=lambda: clock["t"])

    def tick(n=1, dt=1.0):
        fired = []
        for _ in range(n):
            clock["t"] += dt
            fired = col.sample_once()
        return fired

    return col, wd, counters, timers, gauges, tick


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.status, r.read().decode()


def _get_allow_error(port, path):
    try:
        return _get(port, path)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ---------------------------------------------------------------------------
# collector: delta/level folding + bounded memory
# ---------------------------------------------------------------------------

class TestCollector:
    def test_counters_fold_as_deltas(self):
        col, _, counters, _, _, tick = _collector()
        counters["steps_total"] = 100
        tick()
        counters["steps_total"] = 130
        tick()
        counters["steps_total"] = 130
        tick()
        # first sample is the baseline (delta 0), then per-sample deltas
        assert col.store.vals("steps_total") == [0.0, 30.0, 0.0]
        # the raw cumulative value survives for the Prometheus renderer
        assert col.store.get("steps_total").cum == 130.0

    def test_counter_reset_restarts_at_raw(self):
        col, _, counters, _, _, tick = _collector()
        counters["c"] = 50
        tick()
        counters["c"] = 7  # registry reset mid-run
        tick()
        assert col.store.vals("c") == [0.0, 7.0]

    def test_gauges_and_gauge_stats_fold_as_levels(self):
        col, _, counters, timers, gauges, tick = _collector()
        counters["serving_queue_depth"] = 4   # GAUGE_STATS member
        timers["shard_skew_ms"] = 12.5        # GAUGE_TIMERS member
        gauges["mfu_pct"] = 37.0
        tick(2)
        assert col.store.vals("serving_queue_depth") == [4.0, 4.0]
        assert col.store.vals("shard_skew_ms") == [12.5, 12.5]
        assert col.store.last("mfu_pct") == 37.0
        for name in ("serving_queue_depth", "shard_skew_ms", "mfu_pct"):
            assert col.store.get(name).kind == telemetry.GAUGE

    def test_accumulator_timers_fold_as_deltas(self):
        col, _, _, timers, _, tick = _collector()
        timers["dispatch_ms"] = 10.0
        tick()
        timers["dispatch_ms"] = 25.0
        tick()
        assert col.store.vals("dispatch_ms") == [0.0, 15.0]
        assert col.store.get("dispatch_ms").kind == telemetry.COUNTER

    def test_bounded_points_with_counted_drops(self):
        col, _, _, _, gauges, tick = _collector(capacity=4)
        for i in range(10):
            gauges["g"] = float(i)
            tick()
        s = col.store.get("g")
        assert len(s.points) == 4
        assert s.dropped == 6
        assert col.store.vals("g") == [6.0, 7.0, 8.0, 9.0]
        assert col.drops() == 6

    def test_bounded_series_count(self):
        sources, _, _, gauges = _scripted()
        col = Collector(sources=sources, sample_s=1.0, max_series=3)
        for i in range(8):
            gauges[f"g{i}"] = 1.0
        col.sample_once()
        assert len(col.store.names()) == 3
        assert col.store.series_dropped == 5
        assert col.drops() == 5

    def test_non_finite_values_sanitized(self):
        col, _, _, _, gauges, tick = _collector()
        gauges["g"] = float("nan")
        tick()
        gauges["g"] = float("inf")
        tick()
        assert col.store.vals("g") == [0.0, 0.0]

    def test_broken_source_counted_not_fatal(self):
        def sources():
            raise RuntimeError("boom")

        col = Collector(sources=sources, sample_s=1.0)
        assert col.sample_once() == []
        assert col.source_errors == 1 and col.samples == 0

    def test_sampler_thread_and_overhead_timer(self):
        sources, _, _, gauges = _scripted()
        gauges["g"] = 1.0
        col = Collector(sources=sources, sample_s=0.01)
        seen = []
        col.overhead_cb = seen.append
        col.start()
        deadline = time.time() + 5.0
        while col.samples < 3 and time.time() < deadline:
            time.sleep(0.01)
        col.stop()
        assert col.samples >= 3
        assert col.sampler_overhead_ms > 0.0
        assert len(seen) == col.samples  # the gateable overhead seam

    def test_to_json_and_series_stats(self):
        col, _, counters, _, gauges, tick = _collector()
        counters["c"] = 0
        for i in range(4):
            counters["c"] += 10
            gauges["g"] = float(i)
            tick()
        doc = col.to_json()
        assert doc["samples"] == 4 and "health" in doc
        rows = {r["metric"]: r for r in series_stats(doc)}
        assert rows["g"]["min"] == 0.0 and rows["g"]["max"] == 3.0
        assert rows["g"]["last"] == 3.0 and rows["g"]["count"] == 4
        assert rows["c"]["mean"] == 7.5  # 0 baseline + three 10s


# ---------------------------------------------------------------------------
# watchdog rules: pos/neg per rule over scripted series
# ---------------------------------------------------------------------------

def _store(**series):
    st = MetricStore()
    for name, vals in series.items():
        kind = telemetry.GAUGE if name in ("step_ms", "mfu_pct",
                                           "serving_queue_depth") \
            else telemetry.COUNTER
        for i, v in enumerate(vals):
            st.record(float(i), name, kind, v)
    return st


class TestWatchdogRules:
    CFG = dict(telemetry.DEFAULT_THRESHOLDS)

    def test_step_spike_pos_neg(self):
        pos = telemetry.rule_step_time_spike(
            _store(step_ms=[10, 10, 11, 10, 90]), self.CFG)
        assert pos and "step_ms" in pos
        assert telemetry.rule_step_time_spike(
            _store(step_ms=[10, 10, 11, 10, 12]), self.CFG) is None
        # too few points: not armed
        assert telemetry.rule_step_time_spike(
            _store(step_ms=[10, 90]), self.CFG) is None

    def test_mfu_drop_pos_neg(self):
        assert telemetry.rule_mfu_drop(
            _store(mfu_pct=[40, 41, 40, 39, 5]), self.CFG)
        assert telemetry.rule_mfu_drop(
            _store(mfu_pct=[40, 41, 40, 39, 38]), self.CFG) is None
        # below the noise floor: never fires
        assert telemetry.rule_mfu_drop(
            _store(mfu_pct=[0.1, 0.1, 0.1, 0.1, 0.01]),
            self.CFG) is None

    def test_non_finite_loss_pos_neg(self):
        assert telemetry.rule_non_finite_loss(
            _store(nan_inf_hits_total=[0, 2]), self.CFG)
        assert telemetry.rule_non_finite_loss(
            _store(nan_inf_hits_total=[0, 0]), self.CFG) is None

    def test_rejection_spike_pos_neg(self):
        assert telemetry.rule_serving_rejection_spike(
            _store(serving_rejected_total=[0, 20],
                   serving_requests_total=[0, 3]), self.CFG)
        # high traffic, few rejects: rate below threshold
        assert telemetry.rule_serving_rejection_spike(
            _store(serving_rejected_total=[0, 6],
                   serving_requests_total=[0, 100]), self.CFG) is None
        # trickle of rejects below the arm count
        assert telemetry.rule_serving_rejection_spike(
            _store(serving_rejected_total=[0, 2],
                   serving_requests_total=[0, 0]), self.CFG) is None

    def test_tenant_rejection_spike_pos_neg(self):
        # one tenant hammered past its quota: fires and NAMES it
        msg = telemetry.rule_tenant_rejection_spike(
            _store(serving_tenant_ranker_rejected_total=[0, 20],
                   serving_tenant_ranker_requests_total=[0, 3]),
            self.CFG)
        assert msg and "'ranker'" in msg
        # healthy tenant: plenty of traffic, rejects below the rate bar
        assert telemetry.rule_tenant_rejection_spike(
            _store(serving_tenant_ranker_rejected_total=[0, 6],
                   serving_tenant_ranker_requests_total=[0, 100]),
            self.CFG) is None
        # trickle of rejects below the arm count never fires
        assert telemetry.rule_tenant_rejection_spike(
            _store(serving_tenant_ranker_rejected_total=[0, 3],
                   serving_tenant_ranker_requests_total=[0, 0]),
            self.CFG) is None
        # no tenant series at all (single-model serving): rule is inert
        assert telemetry.rule_tenant_rejection_spike(
            _store(serving_rejected_total=[0, 50]), self.CFG) is None

    def test_tenant_rule_fires_while_global_rule_stays_green(self):
        # the fleet-wide rate averages the noisy neighbour away: 30
        # rejects vs 1000 admitted is globally fine, but ALL 30 hit
        # tenant "abuser" — the per-tenant rule must still name it,
        # and of two spiking tenants it reports the WORST
        st = _store(
            serving_rejected_total=[0, 30],
            serving_requests_total=[0, 1000],
            serving_tenant_abuser_rejected_total=[0, 25],
            serving_tenant_abuser_requests_total=[0, 2],
            serving_tenant_bursty_rejected_total=[0, 5],
            serving_tenant_bursty_requests_total=[0, 4],
            serving_tenant_good_rejected_total=[0, 0],
            serving_tenant_good_requests_total=[0, 994])
        assert telemetry.rule_serving_rejection_spike(
            st, self.CFG) is None
        msg = telemetry.rule_tenant_rejection_spike(st, self.CFG)
        assert msg and "'abuser'" in msg and "'bursty'" not in msg
        assert ("tenant_rejection_spike",
                telemetry.rule_tenant_rejection_spike) \
            in telemetry.RULES

    def test_queue_saturation_pos_neg(self):
        assert telemetry.rule_serving_queue_saturation(
            _store(serving_queue_depth=[2, 3, 2, 3, 40]), self.CFG)
        assert telemetry.rule_serving_queue_saturation(
            _store(serving_queue_depth=[2, 3, 2, 3, 4]),
            self.CFG) is None
        # a spike that stays shallow (< queue_min) is not saturation
        assert telemetry.rule_serving_queue_saturation(
            _store(serving_queue_depth=[1, 1, 1, 1, 5]),
            self.CFG) is None

    def test_kv_pressure_pos_neg(self):
        # 60 of 63 pages handed out: past the 90% threshold
        msg = telemetry.rule_kv_pressure(
            _store(serving_kv_pages_in_use=[10, 60],
                   serving_kv_pages_capacity=[63, 63]), self.CFG)
        assert msg and "serving_kv_pages_in_use" in msg
        # healthy pool: below threshold
        assert telemetry.rule_kv_pressure(
            _store(serving_kv_pages_in_use=[10, 20],
                   serving_kv_pages_capacity=[63, 63]),
            self.CFG) is None
        # no serving engine on this host: series absent, rule silent
        assert telemetry.rule_kv_pressure(
            _store(step_ms=[10, 10]), self.CFG) is None
        assert ("kv_pressure", telemetry.rule_kv_pressure) \
            in telemetry.RULES

    def test_ckpt_stall_pos_neg(self):
        assert telemetry.rule_ckpt_stall(
            _store(ckpt_stall_ms=[0, 900]), self.CFG)
        assert telemetry.rule_ckpt_stall(
            _store(ckpt_stall_ms=[0, 100]), self.CFG) is None

    def test_feed_starvation_pos_neg(self):
        assert telemetry.rule_feed_starvation(
            _store(ring_empty_wait_ms=[0, 800]), self.CFG)
        assert telemetry.rule_feed_starvation(
            _store(ring_empty_wait_ms=[0, 100]), self.CFG) is None

    def test_collective_bytes_jump_pos_neg(self):
        assert telemetry.rule_collective_bytes_jump(
            _store(collective_bytes_c_allreduce_sum=[4096, 4096, 4096,
                                                     40960]), self.CFG)
        assert telemetry.rule_collective_bytes_jump(
            _store(collective_bytes_c_allreduce_sum=[4096, 4096, 4096,
                                                     4096]),
            self.CFG) is None

    def test_host_lost_pos_neg(self):
        def st(hosts, ages=()):
            s = MetricStore()
            for i, v in enumerate(hosts):
                s.record(float(i), "hosts_reporting",
                         telemetry.GAUGE, float(v))
            for i, v in enumerate(ages):
                s.record(float(i), "merged_age_s",
                         telemetry.GAUGE, float(v))
            return s

        # a host drops out of the pod-merged snapshot
        pos = telemetry.rule_host_lost(st([2, 2, 1]), self.CFG)
        assert pos and "1 host(s) missing" in pos
        # full pod reporting: silent
        assert telemetry.rule_host_lost(st([2, 2, 2]), self.CFG) is None
        # single-host run: nothing to lose, never fires
        assert telemetry.rule_host_lost(st([1, 1]), self.CFG) is None
        assert telemetry.rule_host_lost(
            st([1, 1], ages=[9999]), self.CFG) is None
        # pod intact but the merged snapshot went stale: the gather
        # stopped reaching this host
        stale = telemetry.rule_host_lost(
            st([2, 2, 2], ages=[1, 2, 400]), self.CFG)
        assert stale and "stale" in stale
        assert telemetry.rule_host_lost(
            st([2, 2, 2], ages=[1, 2, 30]), self.CFG) is None

    def test_broken_rule_is_contained(self):
        wd = Watchdog(rules=[("boom", lambda v, c: 1 / 0),
                             ("ok", lambda v, c: "fired")])
        assert wd.evaluate(_store()) == [("ok", "fired")]


class TestHostLostFeed:
    """refresh_merged / sample_once feed the series rule_host_lost
    reads, so losing a pod host actually pages."""

    def test_collector_records_hosts_and_merge_age(self, tmp_path):
        col, wd, counters, timers, gauges, tick = _collector(tmp_path)
        col.refresh_merged(lambda: {"hosts": {"0": {}, "1": {}}})
        assert col.store.last("hosts_reporting") == 2.0
        tick(3)
        age = col.store.last("merged_age_s")
        assert age is not None and age >= 3.0
        # a failing gather leaves the last good snapshot (and its
        # growing age) in place instead of recording a phantom count
        col.refresh_merged(lambda: 1 / 0)
        assert col.store.last("hosts_reporting") == 2.0


# ---------------------------------------------------------------------------
# watchdog + flight recorder end to end
# ---------------------------------------------------------------------------

BUNDLE_FILES = ("reason.json", "series.json", "snapshot.json",
                "op_profile.json", "trace.json")


def _bundles(d):
    return sorted(n for n in os.listdir(str(d))
                  if n.startswith(telemetry.BUNDLE_PREFIX))


def _full_callbacks(kw):
    kw.setdefault("snapshot_cb", lambda: {"host": 0})
    kw.setdefault("op_profile_cb", lambda: {"tables": {}})
    kw.setdefault("trace_cb",
                  lambda p: json.dump({"traceEvents": []}, open(p, "w")))
    return kw


class TestFlightRecorder:
    def _spike(self, tmp_path, **wd_kw):
        col, wd, _, _, gauges, tick = _collector(
            tmp_path=tmp_path, **_full_callbacks(wd_kw))
        gauges["step_ms"] = 10.0
        tick(6)
        gauges["step_ms"] = 500.0
        return col, wd, gauges, tick

    def test_healthy_run_produces_nothing(self, tmp_path):
        col, wd, _, _, gauges, tick = _collector(
            tmp_path=tmp_path, **_full_callbacks({}))
        gauges["step_ms"] = 10.0
        gauges["mfu_pct"] = 40.0
        tick(20)
        assert wd.healthy and wd.reason is None
        assert not os.listdir(str(tmp_path))
        status = wd.health()
        assert status["healthy"] and status["fired"] == []

    def test_step_spike_flips_health_and_dumps_complete_bundle(
            self, tmp_path):
        col, wd, gauges, tick = self._spike(tmp_path)
        fired = tick()
        assert {f["rule"] for f in fired} == {"step_time_spike"}
        assert not wd.healthy
        assert "step_ms" in wd.reason
        (bundle,) = _bundles(tmp_path)
        assert "step_time_spike" in bundle
        bdir = tmp_path / bundle
        for fname in BUNDLE_FILES:
            assert (bdir / fname).exists(), f"bundle missing {fname}"
        reason = json.loads((bdir / "reason.json").read_text())
        assert reason["fired"][0]["rule"] == "step_time_spike"
        assert reason["errors"] == {}
        series = json.loads((bdir / "series.json").read_text())
        assert series["series"]["step_ms"]["points"][-1][1] == 500.0
        # the dump replays through the tracetool surface
        assert any(f["rule"] == "step_time_spike"
                   for f in replay_rules(series))

    def test_nan_flips_health_and_dumps_bundle(self, tmp_path):
        col, wd, counters, _, _, tick = _collector(
            tmp_path=tmp_path, **_full_callbacks({}))
        counters["nan_inf_hits_total"] = 0
        tick(3)
        counters["nan_inf_hits_total"] = 2
        fired = tick()
        assert {f["rule"] for f in fired} == {"non_finite_loss"}
        assert not wd.healthy and "non-finite" in wd.reason
        (bundle,) = _bundles(tmp_path)
        for fname in BUNDLE_FILES:
            assert (tmp_path / bundle / fname).exists()

    def test_rate_limit_then_gc(self, tmp_path):
        col, wd, gauges, tick = self._spike(tmp_path, keep=2,
                                            min_interval_s=30.0)
        tick()
        assert wd.bundles_written == 1
        # still anomalous next sample: no second bundle inside the window
        tick()
        assert wd.bundles_written == 1 and wd.dumps_rate_limited >= 1
        assert len(_bundles(tmp_path)) == 1
        # past the window, repeatedly: retention keeps the newest `keep`
        for _ in range(3):
            tick(dt=31.0)
        assert wd.bundles_written == 4
        assert len(_bundles(tmp_path)) == 2

    def test_gc_sweeps_tmp_dirs(self, tmp_path):
        leftover = tmp_path / (telemetry.TMP_PREFIX + "crashed")
        leftover.mkdir()
        col, wd, gauges, tick = self._spike(tmp_path)
        tick()
        assert not leftover.exists()
        assert len(_bundles(tmp_path)) == 1

    def test_broken_export_cb_recorded_not_fatal(self, tmp_path):
        col, wd, gauges, tick = self._spike(
            tmp_path, snapshot_cb=lambda: 1 / 0)
        tick()
        (bundle,) = _bundles(tmp_path)
        reason = json.loads(
            (tmp_path / bundle / "reason.json").read_text())
        assert "snapshot.json" in reason["errors"]
        assert (tmp_path / bundle / "series.json").exists()

    def test_reset_restores_health(self, tmp_path):
        col, wd, gauges, tick = self._spike(tmp_path)
        tick()
        assert not wd.healthy
        wd.reset()
        assert wd.healthy and wd.reason is None
        assert wd.health()["fired"]  # history survives the ack


# ---------------------------------------------------------------------------
# export: Prometheus text + HTTP endpoint
# ---------------------------------------------------------------------------

PROM_LINE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]* -?[0-9.e+-]+$")


class TestExport:
    def test_prometheus_text_parses(self):
        col, wd, counters, _, gauges, tick = _collector()
        counters["steps_total"] = 42
        gauges["mfu_pct"] = 37.5
        tick(2)
        text = prometheus_text(col)
        for line in text.strip().splitlines():
            assert line.startswith("# TYPE ") or PROM_LINE.match(line), \
                f"unparseable exposition line: {line!r}"
        assert "# TYPE paddle_tpu_steps_total counter" in text
        assert "paddle_tpu_steps_total 42" in text  # cum, not delta
        assert "# TYPE paddle_tpu_mfu_pct gauge" in text
        assert "paddle_tpu_mfu_pct 37.5" in text
        assert "paddle_tpu_healthy 1" in text
        assert "paddle_tpu_telemetry_samples_total 2" in text

    def test_metric_name_sanitized(self):
        col, _, counters, _, _, tick = _collector()
        counters["weird.name-1/x"] = 3
        tick()
        assert "paddle_tpu_weird_name_1_x" in prometheus_text(col)

    @pytest.fixture
    def served(self):
        col, wd, counters, timers, gauges, tick = _collector()
        col.snapshot_cb = lambda: {"host": 0, "local": True}
        col.trace_json_cb = lambda: {"traceEvents": [1, 2]}
        srv = telemetry.TelemetryServer(col, port=0).start()
        try:
            yield col, wd, counters, gauges, tick, srv.port
        finally:
            srv.stop()

    def test_http_metrics_and_json(self, served):
        col, wd, counters, gauges, tick, port = served
        counters["steps_total"] = 5
        tick()
        status, body = _get(port, "/metrics")
        assert status == 200 and "paddle_tpu_steps_total 5" in body
        status, body = _get(port, "/metrics?format=json")
        doc = json.loads(body)
        assert doc["samples"] == 1 and "steps_total" in doc["series"]

    def test_http_healthz_flips_with_reason(self, served):
        col, wd, counters, gauges, tick, port = served
        gauges["step_ms"] = 10.0
        tick(6)
        status, body = _get(port, "/healthz")
        assert status == 200 and json.loads(body)["healthy"]
        gauges["step_ms"] = 400.0
        tick()
        status, body = _get_allow_error(port, "/healthz")
        doc = json.loads(body)
        assert status == 503 and not doc["healthy"]
        assert "step_ms" in doc["reason"]

    def test_http_snapshot_local_and_merged(self, served):
        col, wd, counters, gauges, tick, port = served
        status, body = _get(port, "/snapshot")
        assert status == 200 and json.loads(body)["local"]
        # no merged view yet: all_hosts falls back to the local one
        status, body = _get(port, "/snapshot?all_hosts=1")
        assert status == 200 and json.loads(body)["local"]
        col.refresh_merged(lambda: {"hosts": {"0": {}, "1": {}}})
        status, body = _get(port, "/snapshot?all_hosts=1")
        assert status == 200
        assert set(json.loads(body)["hosts"]) == {"0", "1"}

    def test_http_trace_and_404(self, served):
        col, wd, counters, gauges, tick, port = served
        status, body = _get(port, "/debug/trace")
        assert status == 200
        assert json.loads(body)["traceEvents"] == [1, 2]
        status, body = _get_allow_error(port, "/nope")
        assert status == 404 and "endpoints" in body


# ---------------------------------------------------------------------------
# in-process wiring: executor + serving auto-attach, epoch refresh
# ---------------------------------------------------------------------------

def _write_slot_files(d, files=2, rows=20, seed=0):
    os.makedirs(d, exist_ok=True)
    rng = np.random.RandomState(seed)
    W = np.arange(1, 9, dtype="float32").reshape(8, 1) / 10.0
    out = []
    for i in range(files):
        p = os.path.join(d, f"part-{i}.txt")
        with open(p, "w") as f:
            for _ in range(rows):
                x = rng.randn(8).astype("float32")
                f.write("8 " + " ".join(f"{v:.6f}" for v in x)
                        + f" 1 {float((x @ W)[0]):.6f}\n")
        out.append(p)
    return out


class TestTrainingAttach:
    def test_train_from_dataset_serves_metrics_and_detaches(
            self, tmp_path, monkeypatch, fresh_programs):
        """Acceptance: a training run with PADDLE_OBS_HTTP_PORT set
        exposes live /metrics (Prometheus-parseable, gauges present)
        and /healthz mid-run, and the session detaches when the pass
        ends."""
        monkeypatch.setenv("PADDLE_OBS_HTTP_PORT", "0")
        monkeypatch.setenv("PADDLE_OBS_SAMPLE_S", "0.02")
        monkeypatch.setenv("PADDLE_OBS_FLIGHT_DIR",
                           str(tmp_path / "flight"))
        files = _write_slot_files(str(tmp_path / "data"))
        main, startup, scope = fresh_programs
        x = fluid.data("x", [-1, 8], "float32")
        y = fluid.data("y", [-1, 1], "float32")
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.loss.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(10)
        ds.set_use_var([x, y])
        ds.set_filelist(files)
        ds.load_into_memory()
        exe = fluid.Executor()
        exe.run(startup)
        scrapes = {}

        def cb(step, sie, outs):
            handle = obs.telemetry_handle()
            assert handle is not None and handle.port is not None
            if "metrics" not in scrapes:
                handle.collector.sample_once()
                scrapes["metrics"] = _get(handle.port, "/metrics")[1]
                scrapes["healthz"] = _get(handle.port, "/healthz")

        exe.train_from_dataset(main, ds, fetch_list=[loss],
                               step_callback=cb)
        assert "paddle_tpu_executor_run_calls" in scrapes["metrics"] \
            or "paddle_tpu_" in scrapes["metrics"]
        status, body = scrapes["healthz"]
        assert status == 200 and json.loads(body)["healthy"]
        # the pass released its reference: the session is gone
        assert obs.telemetry_handle() is None

    def test_no_env_no_telemetry(self, tmp_path, monkeypatch,
                                 fresh_programs):
        monkeypatch.delenv("PADDLE_OBS_HTTP_PORT", raising=False)
        assert obs.maybe_start_telemetry() is None
        assert obs.telemetry_handle() is None

    def test_refcounted_sharing(self, monkeypatch):
        """A trainer and a server in one process share ONE session;
        it tears down on the LAST release."""
        h1 = obs.start_telemetry(port=-1, sample_s=60.0)
        h2 = obs.start_telemetry(port=0)
        assert h1 is h2
        obs.stop_telemetry()
        assert obs.telemetry_handle() is h1
        obs.stop_telemetry()
        assert obs.telemetry_handle() is None

    def test_bundle_meta_names_live_tenants(self, tmp_path):
        """reason.json meta must list which tenants shared the device
        at dump time — otherwise an incident bundle can't distinguish
        noisy-neighbour from self-inflicted (serving/registry.py)."""
        from paddle_tpu import serving

        h = obs.start_telemetry(port=-1, sample_s=60.0,
                                flight_dir=str(tmp_path))
        try:
            meta = h.watchdog.meta_cb()
            assert "tenants" not in meta  # no fleet: key absent
            cfg = serving.EngineConfig(max_batch_size=4,
                                       max_queue_delay_ms=0.0)
            with serving.ModelRegistry(cfg) as reg:
                reg.register("ranker", lambda x: [x * 2.0], quota=8)
                reg.register("embedder", lambda x: [x + 1.0], quota=8)
                meta = h.watchdog.meta_cb()
                assert meta["tenants"] == ["embedder", "ranker"]
                assert "quant_collectives" in meta
            assert "tenants" not in h.watchdog.meta_cb()
        finally:
            obs.stop_telemetry()

    def test_epoch_refresh_caches_merged_view(self):
        h = obs.start_telemetry(port=-1, sample_s=60.0)
        try:
            assert h.collector.merged() is None
            obs.telemetry_epoch_refresh()
            merged = h.collector.merged()
            assert merged is not None and "cost" in merged
        finally:
            obs.stop_telemetry()


class TestServingAttach:
    def test_engine_serves_metrics_and_detaches(self, monkeypatch):
        from paddle_tpu import serving
        from paddle_tpu.serving import EngineConfig

        monkeypatch.setenv("PADDLE_OBS_HTTP_PORT", "0")
        monkeypatch.setenv("PADDLE_OBS_SAMPLE_S", "0.02")

        def double(xs):
            return [xs[0] * 2.0]

        eng = serving.Engine(double,
                             EngineConfig(max_batch_size=4,
                                          max_queue_delay_ms=1.0))
        try:
            handle = obs.telemetry_handle()
            assert handle is not None and handle.port is not None
            for i in range(6):
                out = eng.infer([np.full((1, 2), float(i), "float32")],
                                timeout=30)
                np.testing.assert_allclose(out[0], 2.0 * i)
            handle.collector.sample_once()
            _, body = _get(handle.port, "/metrics")
            assert "paddle_tpu_serving_requests_total" in body
            assert "paddle_tpu_serving_queue_depth" in body
            status, _ = _get(handle.port, "/healthz")
            assert status == 200
        finally:
            eng.shutdown(drain=False)
        assert obs.telemetry_handle() is None


# ---------------------------------------------------------------------------
# the NaN seam: async check_nan_inf -> nan_inf_hits_total
# ---------------------------------------------------------------------------

class TestNanSeam:
    def test_nan_monitor_feeds_watchdog_counter(self):
        import jax.numpy as jnp

        from paddle_tpu.fluid.executor import _NanMonitor

        profiler.stat_reset("nan_inf_hits_total")
        mon = _NanMonitor()
        mon.submit(jnp.asarray([False, True, True]), ["a", "b", "c"])
        deadline = time.time() + 5.0
        while time.time() < deadline:
            if profiler.get_int_stats().get("nan_inf_hits_total", 0):
                break
            time.sleep(0.01)
        assert profiler.get_int_stats()["nan_inf_hits_total"] == 2
        with pytest.raises(RuntimeError, match="NaN/Inf"):
            mon.drain()
        # and the watchdog rule fires on exactly this counter's delta
        col, wd, counters, _, _, tick = _collector()
        counters["nan_inf_hits_total"] = 0
        tick()
        counters["nan_inf_hits_total"] = 2
        assert {f["rule"] for f in tick()} == {"non_finite_loss"}


# ---------------------------------------------------------------------------
# zero-sync contract: sampling never touches the dispatch hot path
# ---------------------------------------------------------------------------

class TestZeroSync:
    def test_sampler_adds_zero_syncs_to_async_steps(self,
                                                    fresh_programs):
        """Acceptance: ten async executor steps with the live sampler
        + watchdog + Prometheus render interleaved after every one of
        them — executor_sync_count stays ZERO until the caller's own
        sanctioned materialization."""
        main, startup, scope = fresh_programs
        x = fluid.data("x", [-1, 4], "float32")
        yt = fluid.data("yt", [-1, 1], "float32")
        pred = fluid.layers.fc(x, 1, bias_attr=False)
        loss = fluid.layers.reduce_mean(
            fluid.layers.loss.square_error_cost(pred, yt))
        fluid.optimizer.SGD(0.01).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        X = rng.rand(8, 4).astype("float32")
        Y = rng.rand(8, 1).astype("float32")
        exe.run(main, feed={"x": X, "yt": Y}, fetch_list=[loss],
                return_numpy=False)  # warm the compile cache
        wd = Watchdog(artifacts_dir=None)
        col = Collector(sources=telemetry.default_sources(),
                        sample_s=60.0, watchdog=wd)
        profiler.stat_reset("executor_sync_count")
        handles = None
        for _ in range(10):
            handles = exe.run(main, feed={"x": X, "yt": Y},
                              fetch_list=[loss], return_numpy=False)
            col.sample_once()
            prometheus_text(col)
        assert profiler.get_int_stats().get("executor_sync_count",
                                            0) == 0
        assert col.samples == 10
        # sanity: the counter still works at the sanctioned boundary
        assert np.isfinite(float(handles[0]))
        assert profiler.get_int_stats()["executor_sync_count"] == 1


# ---------------------------------------------------------------------------
# serving/metrics.py stat-table sync (ISSUE 10 satellite)
# ---------------------------------------------------------------------------

class TestServingMetricsDocs:
    def test_every_written_stat_is_documented(self):
        """Every stat name serving/metrics.py writes (stat_add /
        stat_set string literals) appears in its docstring table — the
        drift that hid serving_batch_requests_total cannot recur."""
        from paddle_tpu.serving import metrics as m

        path = os.path.join(REPO_ROOT, "paddle_tpu", "serving",
                            "metrics.py")
        with open(path) as f:
            src = f.read()
        written = set(re.findall(
            r"stat_(?:add|set|max)\(\s*[\"']([a-z0-9_]+)[\"']", src))
        assert written, "no stats written? parser drifted"
        for name in written:
            assert name in (m.__doc__ or ""), \
                f"{name} written by serving/metrics.py but missing " \
                f"from its docstring stat table"

    def test_batch_requests_total_in_table_and_recorded(self):
        from paddle_tpu.serving import metrics as m

        assert "serving_batch_requests_total" in m.__doc__
        profiler.stat_reset("serving_batch_requests_total")
        m.observe_batch(3, 8, 1)
        assert profiler.get_int_stats()[
            "serving_batch_requests_total"] == 3

    def test_latency_stats_values_unchanged_by_lock_fix(self):
        from paddle_tpu.serving import metrics as m

        m.reset_latency("t_lockfix_ms")
        for v in (5.0, 1.0, 9.0, 3.0):
            m.record_latency("t_lockfix_ms", v)
        s = m.latency_stats("t_lockfix_ms")
        assert s["count"] == 4 and s["max_ms"] == 9.0
        assert s["p50_ms"] == 5.0  # index round(0.5*3)=2 of sorted
        assert m.latency_stats("t_never_recorded_ms") is None
