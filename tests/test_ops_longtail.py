"""Long-tail op tests: losses, normalization tail, tensor manipulation,
RNN family, CRF, sequence utilities (VERDICT r3 Missing #1 closure)."""

import numpy as np
import pytest
import torch
import torch.nn.functional as TF

from op_test import OpTest, randf, run_single_op


def run_op(op_type, inputs, attrs, outs, dtypes=None):
    return run_single_op(op_type, inputs, attrs, outs, dtypes)


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

def test_nll_loss_reductions():
    x = np.log(TF.softmax(torch.tensor(randf(5, 4, seed=1)), -1).numpy())
    lab = np.array([0, 3, 2, 1, 2], "int64")
    w = randf(4, low=0.5, high=1.5, seed=2)
    for red in ("none", "sum", "mean"):
        d = run_op("nll_loss",
                   {"X": x, "Label": lab, "Weight": w},
                   {"reduction": red, "ignore_index": -100},
                   ["Out", "Total_weight"])
        want = TF.nll_loss(torch.tensor(x), torch.tensor(lab),
                           torch.tensor(w), reduction=red).numpy()
        np.testing.assert_allclose(d["Out"].reshape(want.shape), want,
                                   atol=1e-5)


def test_nll_loss_ignore_index_2d():
    x = np.log(TF.softmax(torch.tensor(randf(2, 3, 4, 4, seed=3)),
                          1).numpy())
    lab = np.random.RandomState(4).randint(0, 3, (2, 4, 4)).astype("int64")
    lab[0, 0, 0] = 1  # then ignore tag value 1
    d = run_op("nll_loss", {"X": x, "Label": lab},
               {"reduction": "mean", "ignore_index": 1},
               ["Out", "Total_weight"])
    want = TF.nll_loss(torch.tensor(x), torch.tensor(lab),
                       ignore_index=1).numpy()
    np.testing.assert_allclose(d["Out"].reshape(()), want, atol=1e-5)


def test_log_loss():
    p = randf(6, 1, low=0.05, high=0.95, seed=5)
    l = (randf(6, 1, seed=6) > 0).astype("float32")
    d = run_op("log_loss", {"Predicted": p, "Labels": l},
               {"epsilon": 1e-4}, ["Loss"])
    want = -(l * np.log(p + 1e-4) + (1 - l) * np.log(1 - p + 1e-4))
    np.testing.assert_allclose(d["Loss"], want, atol=1e-6)


def test_rank_loss_and_grad():
    t = OpTest()
    t.op_type = "rank_loss"
    left, right = randf(5, 1, seed=7), randf(5, 1, seed=8)
    lab = (randf(5, 1, seed=9) > 0).astype("float32")
    t.inputs = {"Label": lab, "Left": left, "Right": right}
    o = left - right
    t.outputs = {"Out": np.log1p(np.exp(o)) - lab * o}
    t.check_output(atol=1e-5)
    t.check_grad(["Left", "Right"], "Out")


def test_margin_rank_loss():
    x1, x2 = randf(6, 1, seed=10), randf(6, 1, seed=11)
    lab = np.sign(randf(6, 1, seed=12)).astype("float32")
    d = run_op("margin_rank_loss", {"Label": lab, "X1": x1, "X2": x2},
               {"margin": 0.1}, ["Out", "Activated"])
    raw = -lab * (x1 - x2) + 0.1
    np.testing.assert_allclose(d["Out"], np.maximum(raw, 0), atol=1e-6)
    np.testing.assert_allclose(d["Activated"], (raw > 0).astype("float32"))


def test_bpr_loss():
    x = randf(4, 5, seed=13)
    lab = np.array([[1], [0], [4], [2]], "int64")
    d = run_op("bpr_loss", {"X": x, "Label": lab}, {}, ["Y"])
    want = np.zeros((4, 1), "float64")
    for i in range(4):
        p = lab[i, 0]
        s = 0.0
        for j in range(5):
            if j == p:
                continue
            s += np.log1p(np.exp(x[i, j] - x[i, p]))
        want[i, 0] = s / 4
    np.testing.assert_allclose(d["Y"], want, rtol=1e-5)


def test_center_loss_updates_centers():
    x = randf(4, 3, seed=14)
    lab = np.array([0, 1, 0, 2], "int64")
    centers = randf(5, 3, seed=15)
    rate = np.array([0.5], "float32")
    d = run_op("center_loss",
               {"X": x, "Label": lab, "Centers": centers,
                "CenterUpdateRate": rate},
               {"need_update": True},
               ["Loss", "SampleCenterDiff", "CentersOut"])
    diff = x - centers[lab]
    np.testing.assert_allclose(d["SampleCenterDiff"], diff, atol=1e-6)
    np.testing.assert_allclose(d["Loss"],
                               0.5 * (diff ** 2).sum(1, keepdims=True),
                               rtol=1e-5)
    want = centers.copy()
    for c in range(5):
        sel = lab == c
        if sel.any():
            want[c] += 0.5 * diff[sel].sum(0) / (1 + sel.sum())
    np.testing.assert_allclose(d["CentersOut"], want, atol=1e-5)


def test_cos_sim_broadcast():
    x = randf(4, 6, seed=16)
    y = randf(1, 6, seed=17)
    d = run_op("cos_sim", {"X": x, "Y": y}, {}, ["Out", "XNorm", "YNorm"])
    want = TF.cosine_similarity(torch.tensor(x),
                                torch.tensor(y)).numpy()[:, None]
    np.testing.assert_allclose(d["Out"], want, atol=1e-5)


def test_sample_logits_customized():
    logits = randf(3, 8, seed=18)
    labels = np.array([[2], [5], [0]], "int64")
    samples = np.array([[2, 1, 4], [5, 1, 4], [0, 1, 4]], "int64")
    probs = np.full((3, 3), 0.25, "float32")
    d = run_op("sample_logits",
               {"Logits": logits, "Labels": labels,
                "CustomizedSamples": samples,
                "CustomizedProbabilities": probs},
               {"use_customized_samples": True,
                "remove_accidental_hits": False, "num_samples": 2},
               ["Samples", "Probabilities", "SampledLogits",
                "SampledLabels"],
               {"Samples": "int64", "SampledLabels": "int64"})
    want = np.take_along_axis(logits, samples, axis=1) - np.log(0.25)
    np.testing.assert_allclose(d["SampledLogits"], want, atol=1e-5)
    np.testing.assert_array_equal(d["SampledLabels"],
                                  np.zeros((3, 1), "int64"))


def test_sample_logits_sampled_negatives():
    logits = randf(2, 20, seed=19)
    labels = np.array([[3], [7]], "int64")
    d = run_op("sample_logits", {"Logits": logits, "Labels": labels},
               {"num_samples": 5, "remove_accidental_hits": True,
                "use_customized_samples": False},
               ["Samples", "Probabilities", "SampledLogits"],
               {"Samples": "int64"})
    assert d["Samples"].shape == (2, 6)
    np.testing.assert_array_equal(d["Samples"][:, 0], [3, 7])
    assert (d["Samples"] >= 0).all() and (d["Samples"] < 20).all()
    # accidental hit (negative == true label) must be heavily suppressed
    for i in range(2):
        for j in range(1, 6):
            if d["Samples"][i, j] == labels[i, 0]:
                assert d["SampledLogits"][i, j] < -1e19


# ---------------------------------------------------------------------------
# normalization/activation tail
# ---------------------------------------------------------------------------

def test_lrn_vs_torch():
    x = randf(2, 7, 4, 4, seed=20)
    n, alpha, beta, k = 5, 1e-3, 0.75, 2.0
    d = run_op("lrn", {"X": x},
               {"n": n, "alpha": alpha, "beta": beta, "k": k},
               ["Out", "MidOut"])
    # torch divides alpha by n; paddle multiplies the raw sum by alpha
    want = TF.local_response_norm(torch.tensor(x), n, alpha=alpha * n,
                                  beta=beta, k=k).numpy()
    np.testing.assert_allclose(d["Out"], want, atol=1e-5)


def test_norm_l2():
    x = randf(3, 5, 2, seed=21)
    d = run_op("norm", {"X": x}, {"axis": 1, "epsilon": 1e-10},
               ["Out", "Norm"])
    nrm = np.sqrt((x ** 2).sum(1, keepdims=True) + 1e-10)
    np.testing.assert_allclose(d["Norm"], nrm, atol=1e-6)
    np.testing.assert_allclose(d["Out"], x / nrm, atol=1e-6)


def test_selu_vs_torch():
    x = randf(4, 7, seed=22)
    d = run_op("selu", {"X": x}, {}, ["Out"])
    np.testing.assert_allclose(d["Out"], TF.selu(torch.tensor(x)).numpy(),
                               atol=1e-5)


def test_spectral_norm():
    w = randf(4, 6, seed=23)
    u = randf(4, seed=24)
    v = randf(6, seed=25)
    d = run_op("spectral_norm", {"Weight": w, "U": u, "V": v},
               {"dim": 0, "power_iters": 20, "eps": 1e-12}, ["Out"])
    # after enough iterations sigma converges to the top singular value
    sigma = np.linalg.svd(w, compute_uv=False)[0]
    np.testing.assert_allclose(d["Out"], w / sigma, atol=1e-4)


# ---------------------------------------------------------------------------
# tensor manipulation
# ---------------------------------------------------------------------------

def test_multiplex():
    x1, x2, x3 = (randf(4, 3, seed=s) for s in (26, 27, 28))
    ids = np.array([[2], [0], [1], [0]], "int32")
    d = run_op("multiplex", {"X": [x1, x2, x3], "Ids": ids}, {}, ["Out"])
    want = np.stack([x3[0], x1[1], x2[2], x1[3]])
    np.testing.assert_allclose(d["Out"], want)


def test_unbind():
    x = randf(3, 4, 5, seed=29)
    t = OpTest()
    t.op_type = "unbind"
    t.inputs = {"X": x}
    t.attrs = {"axis": 1}
    t.outputs = {"Out": [x[:, i] for i in range(4)]}
    t.check_output(atol=1e-6)


def test_reverse():
    x = randf(3, 4, seed=30)
    d = run_op("reverse", {"X": x}, {"axis": [0, 1]}, ["Out"])
    np.testing.assert_allclose(d["Out"], x[::-1, ::-1])


def test_inverse():
    x = randf(2, 3, 3, seed=31) + 3 * np.eye(3, dtype="float32")
    d = run_op("inverse", {"Input": x}, {}, ["Output"])
    np.testing.assert_allclose(d["Output"], np.linalg.inv(x), atol=1e-4)


def test_shuffle_batch_is_permutation():
    x = randf(8, 3, seed=32)
    d = run_op("shuffle_batch", {"X": x, "Seed": np.array([1], "int64")},
               {}, ["Out", "ShuffleIdx", "SeedOut"],
               {"ShuffleIdx": "int64", "SeedOut": "int64"})
    perm = d["ShuffleIdx"].astype(int)
    assert sorted(perm.tolist()) == list(range(8))
    np.testing.assert_allclose(d["Out"], x[perm])


def test_segment_pool_modes():
    x = randf(6, 3, seed=33)
    ids = np.array([0, 0, 1, 1, 1, 3], "int32")
    for mode, red in (("SUM", np.sum), ("MEAN", np.mean),
                      ("MAX", np.max), ("MIN", np.min)):
        d = run_op("segment_pool", {"X": x, "SegmentIds": ids},
                   {"pooltype": mode}, ["Out"])
        for s in (0, 1, 3):
            np.testing.assert_allclose(d["Out"][s], red(x[ids == s], axis=0),
                                       rtol=1e-5,
                                       err_msg=f"{mode} segment {s}")
        np.testing.assert_allclose(d["Out"][2], 0.0)


def test_expand_as_grad():
    t = OpTest()
    t.op_type = "expand_as"
    x = randf(2, 1, seed=34)
    t.inputs = {"X": x, "target_tensor": np.zeros((4, 3), "float32")}
    t.outputs = {"Out": np.tile(x, (2, 3))}
    t.check_output(atol=1e-6)
    t.check_grad(["X"], "Out")


# ---------------------------------------------------------------------------
# RNN family
# ---------------------------------------------------------------------------

def _torch_lstm_weights(L, D, I, H, seed):
    """Build a torch LSTM and return (module, WeightList in paddle rnn-op
    raw order [FWih,FWhh,BWih,BWhh]*L + biases)."""
    torch.manual_seed(seed)
    m = torch.nn.LSTM(I, H, L, bidirectional=(D == 2))
    ws, bs = [], []
    for li in range(L):
        for d in range(D):
            sfx = f"_l{li}" + ("_reverse" if d else "")
            ws += [getattr(m, f"weight_ih{sfx}").detach().numpy(),
                   getattr(m, f"weight_hh{sfx}").detach().numpy()]
            bs += [getattr(m, f"bias_ih{sfx}").detach().numpy(),
                   getattr(m, f"bias_hh{sfx}").detach().numpy()]
    return m, [w.copy() for w in ws + bs]


@pytest.mark.parametrize("bidi", [False, True])
def test_rnn_lstm_vs_torch(bidi):
    T, B, I, H, L = 5, 3, 4, 6, 2
    D = 2 if bidi else 1
    m, wl = _torch_lstm_weights(L, D, I, H, seed=35)
    x = randf(T, B, I, seed=36)
    h0 = randf(L * D, B, H, seed=37)
    c0 = randf(L * D, B, H, seed=38)
    d = run_op("rnn",
               {"Input": x, "PreState": [h0, c0], "WeightList": wl},
               {"mode": "LSTM", "num_layers": L, "is_bidirec": bidi,
                "hidden_size": H, "is_test": True, "dropout_prob": 0.0},
               ["Out", "State"])
    out, (hn, cn) = m(torch.tensor(x), (torch.tensor(h0), torch.tensor(c0)))
    np.testing.assert_allclose(d["Out"], out.detach().numpy(), atol=1e-4)
    np.testing.assert_allclose(d["State"], hn.detach().numpy(), atol=1e-4)


def test_rnn_gru_vs_torch():
    T, B, I, H = 4, 2, 3, 5
    torch.manual_seed(39)
    m = torch.nn.GRU(I, H, 1)
    wl = [m.weight_ih_l0.detach().numpy(), m.weight_hh_l0.detach().numpy(),
          m.bias_ih_l0.detach().numpy(), m.bias_hh_l0.detach().numpy()]
    x = randf(T, B, I, seed=40)
    h0 = randf(1, B, H, seed=41)
    d = run_op("rnn", {"Input": x, "PreState": [h0], "WeightList": wl},
               {"mode": "GRU", "num_layers": 1, "is_bidirec": False,
                "hidden_size": H, "is_test": True}, ["Out", "State"])
    out, hn = m(torch.tensor(x), torch.tensor(h0))
    np.testing.assert_allclose(d["Out"], out.detach().numpy(), atol=1e-4)
    np.testing.assert_allclose(d["State"], hn.detach().numpy(), atol=1e-4)


def test_rnn_sequence_length_masks():
    T, B, I, H = 5, 3, 4, 4
    torch.manual_seed(42)
    m = torch.nn.RNN(I, H, 1)
    wl = [m.weight_ih_l0.detach().numpy(), m.weight_hh_l0.detach().numpy(),
          m.bias_ih_l0.detach().numpy(), m.bias_hh_l0.detach().numpy()]
    x = randf(T, B, I, seed=43)
    h0 = np.zeros((1, B, H), "float32")
    lens = np.array([5, 3, 1], "int32")
    d = run_op("rnn",
               {"Input": x, "PreState": [h0], "WeightList": wl,
                "SequenceLength": lens},
               {"mode": "RNN_TANH", "num_layers": 1, "hidden_size": H,
                "is_test": True}, ["Out", "State"])
    packed = torch.nn.utils.rnn.pack_padded_sequence(
        torch.tensor(x), torch.tensor(lens, dtype=torch.int64),
        enforce_sorted=True)
    out_p, hn = m(packed, torch.tensor(h0))
    out_pad, _ = torch.nn.utils.rnn.pad_packed_sequence(out_p, total_length=T)
    np.testing.assert_allclose(d["Out"], out_pad.detach().numpy(), atol=1e-4)
    np.testing.assert_allclose(d["State"], hn.detach().numpy(), atol=1e-4)


def test_gru_unit_step():
    B, H = 3, 4
    x = randf(B, 3 * H, seed=44)
    hp = randf(B, H, seed=45)
    w = randf(H, 3 * H, seed=46)
    d = run_op("gru_unit", {"Input": x, "HiddenPrev": hp, "Weight": w},
               {"gate_activation": 1, "activation": 2,
                "origin_mode": False},
               ["Gate", "ResetHiddenPrev", "Hidden"])
    g = x.copy()
    g[:, :2 * H] += hp @ w[:, :2 * H]
    u = 1 / (1 + np.exp(-g[:, :H]))
    r = 1 / (1 + np.exp(-g[:, H:2 * H]))
    c = np.tanh(g[:, 2 * H:] + (r * hp) @ w[:, 2 * H:])
    np.testing.assert_allclose(d["Hidden"], u * c + (1 - u) * hp, atol=1e-5)


def test_lstm_unit_step():
    B, D = 2, 3
    x = randf(B, 4 * D, seed=47)
    c_prev = randf(B, D, seed=48)
    d = run_op("lstm_unit", {"X": x, "C_prev": c_prev},
               {"forget_bias": 0.5}, ["C", "H"])
    sig = lambda v: 1 / (1 + np.exp(-v))
    i, f = sig(x[:, :D]), sig(x[:, D:2 * D] + 0.5)
    o, g = sig(x[:, 2 * D:3 * D]), np.tanh(x[:, 3 * D:])
    c = f * c_prev + i * g
    np.testing.assert_allclose(d["C"], c, atol=1e-5)
    np.testing.assert_allclose(d["H"], o * np.tanh(c), atol=1e-5)


def test_lstmp_projection_shapes_and_recurrence():
    B, T, H, P = 2, 4, 5, 3
    x = randf(B, T, 4 * H, seed=49)
    w = randf(P, 4 * H, seed=50)
    wp = randf(H, P, seed=51)
    bias = randf(1, 4 * H, seed=52)
    d = run_op("lstmp",
               {"Input": x, "Weight": w, "ProjWeight": wp, "Bias": bias},
               {"gate_activation": "sigmoid", "cell_activation": "tanh",
                "candidate_activation": "tanh", "proj_activation": "tanh"},
               ["Projection", "Cell"])
    assert d["Projection"].shape == (B, T, P)
    assert d["Cell"].shape == (B, T, H)
    # manual recurrence for step 0
    sig = lambda v: 1 / (1 + np.exp(-v))
    g0 = x[:, 0] + bias
    i, f = sig(g0[:, :H]), sig(g0[:, H:2 * H])
    cand, o = np.tanh(g0[:, 2 * H:3 * H]), sig(g0[:, 3 * H:])
    c0 = f * 0 + i * cand
    r0 = np.tanh((o * np.tanh(c0)) @ wp)
    np.testing.assert_allclose(d["Cell"][:, 0], c0, atol=1e-5)
    np.testing.assert_allclose(d["Projection"][:, 0], r0, atol=1e-5)


def test_gather_tree():
    ids = np.array([[[2, 2]], [[3, 4]], [[5, 6]]], "int64")  # (3,1,2)
    parents = np.array([[[0, 0]], [[1, 1]], [[1, 0]]], "int64")
    d = run_op("gather_tree", {"Ids": ids, "Parents": parents}, {},
               ["Out"], {"Out": "int64"})
    # reference backtrack oracle
    want = np.zeros_like(ids)
    T, B, W = ids.shape
    for b in range(B):
        for w in range(W):
            want[T - 1, b, w] = ids[T - 1, b, w]
            parent = parents[T - 1, b, w]
            for t in range(T - 2, -1, -1):
                want[t, b, w] = ids[t, b, parent]
                parent = parents[t, b, parent]
    np.testing.assert_array_equal(d["Out"], want)


def test_row_conv():
    B, T, D, FC = 2, 6, 3, 3
    x = randf(B, T, D, seed=53)
    f = randf(FC, D, seed=54)
    d = run_op("row_conv", {"X": x, "Filter": f}, {}, ["Out"])
    want = np.zeros_like(x)
    for t in range(T):
        for w in range(FC):
            if t + w < T:
                want[:, t] += x[:, t + w] * f[w]
    np.testing.assert_allclose(d["Out"], want, atol=1e-5)


def test_linear_chain_crf_brute_force():
    B, T, D = 2, 4, 3
    rng = np.random.RandomState(55)
    emission = rng.uniform(-1, 1, (B, T, D)).astype("float32")
    trans = rng.uniform(-0.5, 0.5, (D + 2, D)).astype("float32")
    label = rng.randint(0, D, (B, T)).astype("int64")
    lens = np.array([4, 2], "int64")
    d = run_op("linear_chain_crf",
               {"Emission": emission, "Transition": trans, "Label": label,
                "Length": lens},
               {}, ["LogLikelihood", "Alpha", "EmissionExps",
                    "TransitionExps"])
    import itertools
    for b in range(B):
        ln = lens[b]
        x = emission[b, :ln].astype("float64")
        # logZ by brute-force path enumeration
        zsum = 0.0
        for path in itertools.product(range(D), repeat=int(ln)):
            s = trans[0, path[0]] + x[0, path[0]] + trans[1, path[-1]]
            for k in range(1, ln):
                s += x[k, path[k]] + trans[path[k - 1] + 2, path[k]]
            zsum += np.exp(s)
        gold = trans[0, label[b, 0]] + x[0, label[b, 0]] \
            + trans[1, label[b, ln - 1]]
        for k in range(1, ln):
            gold += x[k, label[b, k]] \
                + trans[label[b, k - 1] + 2, label[b, k]]
        want_nll = np.log(zsum) - gold
        np.testing.assert_allclose(d["LogLikelihood"][b, 0], want_nll,
                                   rtol=1e-4)


def test_linear_chain_crf_grad():
    t = OpTest()
    t.op_type = "linear_chain_crf"
    rng = np.random.RandomState(56)
    t.inputs = {"Emission": rng.uniform(-1, 1, (2, 3, 3)).astype("float32"),
                "Transition": rng.uniform(-0.3, 0.3, (5, 3)).astype("float32"),
                "Label": rng.randint(0, 3, (2, 3)).astype("int64")}
    t.outputs = {"LogLikelihood": np.zeros((2, 1), "float32")}
    t.check_grad(["Emission", "Transition"], "LogLikelihood",
                 max_relative_error=1e-2)


# ---------------------------------------------------------------------------
# sequence utilities
# ---------------------------------------------------------------------------

def test_im2sequence():
    x = randf(2, 3, 4, 4, seed=57)
    d = run_op("im2sequence", {"X": x},
               {"kernels": [2, 2], "strides": [2, 2],
                "paddings": [0, 0, 0, 0]}, ["Out"])
    assert d["Out"].shape == (2, 4, 12)
    # first patch of first image = x[0,:,0:2,0:2] flattened (C,kh,kw)
    np.testing.assert_allclose(d["Out"][0, 0],
                               x[0, :, 0:2, 0:2].reshape(-1), atol=1e-6)
    # patch row order is row-major over (oh, ow)
    np.testing.assert_allclose(d["Out"][0, 1],
                               x[0, :, 0:2, 2:4].reshape(-1), atol=1e-6)


def test_sequence_reshape():
    x = randf(2, 4, 6, seed=58)
    d = run_op("sequence_reshape", {"X": x}, {"new_dim": 8}, ["Out"])
    np.testing.assert_allclose(d["Out"], x.reshape(2, 3, 8))


def test_sequence_scatter():
    x = randf(2, 6, seed=59)
    ids = np.array([[0, 3, -1], [5, 5, 1]], "int32")
    upd = randf(2, 3, seed=60)
    d = run_op("sequence_scatter", {"X": x, "Ids": ids, "Updates": upd},
               {}, ["Out"])
    want = x.copy()
    want[0, 0] += upd[0, 0]
    want[0, 3] += upd[0, 1]
    want[1, 5] += upd[1, 0] + upd[1, 1]
    want[1, 1] += upd[1, 2]
    np.testing.assert_allclose(d["Out"], want, atol=1e-5)


def test_lod_reset_passthrough():
    x = randf(3, 4, seed=61)
    d = run_op("lod_reset", {"X": x}, {"target_lod": [0, 2, 3]}, ["Out"])
    np.testing.assert_allclose(d["Out"], x)
