"""Ulysses all-to-all sequence parallelism (parallel/ulysses.py):
exact parity with single-device full attention (forward AND gradients),
causal + key-padding masks, and the sequence-sharding memory layout.

Complements tests for ring attention (the other long-context path);
the reference has neither (SURVEY.md §5.7).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.ulysses import (_full_attention,
                                         ulysses_attention)


def _mk(B=2, S=32, H=8, D=16, seed=0):
    r = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(r.randn(B, S, H, D), jnp.float32)
    return mk(), mk(), mk()


class TestUlysses:
    def test_matches_full_attention(self):
        mesh = make_mesh({"sp": 8})
        q, k, v = _mk()
        attn = ulysses_attention(mesh, axis="sp")
        got = attn(q, k, v)
        want = _full_attention(q, k, v, 1.0 / np.sqrt(16))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5)

    def test_causal_and_padding_mask(self):
        mesh = make_mesh({"sp": 8})
        q, k, v = _mk(seed=1)
        mask = jnp.asarray(
            np.arange(32)[None, :] < np.array([[20], [32]]))
        attn = ulysses_attention(mesh, axis="sp")
        got = attn(q, k, v, mask=mask, is_causal=True)
        want = _full_attention(q, k, v, 1.0 / np.sqrt(16), mask=mask,
                               is_causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5)

    def test_gradients_match(self):
        mesh = make_mesh({"sp": 8})
        q, k, v = _mk(B=1, S=16, H=8, D=8, seed=2)
        attn = ulysses_attention(mesh, axis="sp")

        def loss_sharded(qkv):
            return jnp.sum(attn(*qkv) ** 2)

        def loss_ref(qkv):
            return jnp.sum(
                _full_attention(*qkv, 1.0 / np.sqrt(8)) ** 2)

        gs = jax.grad(loss_sharded)((q, k, v))
        gr = jax.grad(loss_ref)((q, k, v))
        for a, b in zip(gs, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-4)

    def test_head_divisibility_guard(self):
        mesh = make_mesh({"sp": 8})
        q, k, v = _mk(H=6)
        attn = ulysses_attention(mesh, axis="sp")
        with pytest.raises(AssertionError, match="ring attention"):
            attn(q, k, v)

    def test_activations_stay_sequence_sharded(self):
        """The memory property: in/out of the shard_map are S-sharded
        (each device holds S/8 of the sequence)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = make_mesh({"sp": 8})
        q, k, v = _mk(S=64)
        spec = NamedSharding(mesh, P(None, "sp"))
        q = jax.device_put(q, spec)
        attn = ulysses_attention(mesh, axis="sp")
        out = jax.jit(lambda q, k, v: attn(q, k, v))(q, k, v)
        shard_seq = {s.data.shape[1] for s in out.addressable_shards}
        assert shard_seq == {64 // 8}, shard_seq

    def test_fully_masked_row_yields_zeros_not_nan(self):
        mesh = make_mesh({"sp": 8})
        q, k, v = _mk(seed=3)
        mask = jnp.asarray(
            np.arange(32)[None, :] < np.array([[0], [32]]))  # row 0: none
        attn = ulysses_attention(mesh, axis="sp")
        got = np.asarray(attn(q, k, v, mask=mask))
        assert np.isfinite(got).all()
        np.testing.assert_array_equal(got[0], np.zeros_like(got[0]))
        assert np.abs(got[1]).sum() > 0
