"""Tests for the paddle.nn-equivalent Layer library (the dygraph module
system).  Mirrors the reference's test strategy (SURVEY.md §4): numeric
oracles are numpy; dygraph-vs-oracle equivalence per layer."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.fluid.dygraph import guard, to_variable


@pytest.fixture(autouse=True)
def dygraph():
    with guard():
        yield


def _t(a):
    return to_variable(np.asarray(a, dtype="float32"))


class TestLayerBase:
    def test_parameter_registration(self):
        lin = nn.Linear(4, 3)
        names = [n for n, _ in lin.named_parameters()]
        assert set(names) == {"weight", "bias"}
        assert lin.weight.shape == [4, 3]
        assert lin.bias.shape == [3]

    def test_sublayer_traversal(self):
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        assert len(net.sublayers()) == 3
        assert len(net.parameters()) == 4

    def test_state_dict_roundtrip(self):
        net = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8))
        sd = net.state_dict()
        # params + BN buffers
        assert len(sd) == 4 + 2
        net2 = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8))
        missing, unexpected = net2.set_state_dict(sd)
        assert not missing and not unexpected
        for (k1, v1), (k2, v2) in zip(sorted(net.state_dict().items()),
                                      sorted(net2.state_dict().items())):
            np.testing.assert_allclose(v1.numpy(), v2.numpy())

    def test_train_eval_mode(self):
        net = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        assert net.training
        net.eval()
        assert not net[1].training
        x = _t(np.ones((4, 2)))
        y1, y2 = net(x), net(x)
        np.testing.assert_allclose(y1.numpy(), y2.numpy())  # no dropout

    def test_forward_hooks(self):
        lin = nn.Linear(2, 2)
        calls = []
        h = lin.register_forward_post_hook(
            lambda layer, ins, out: calls.append(1))
        lin(_t(np.ones((1, 2))))
        assert calls == [1]
        h.remove()
        lin(_t(np.ones((1, 2))))
        assert calls == [1]

    def test_apply_and_astype(self):
        net = nn.Linear(2, 2)
        net.astype("bfloat16")
        assert net.weight.dtype == "bfloat16"


class TestLayers:
    def test_linear_oracle(self):
        lin = nn.Linear(5, 3)
        x = np.random.rand(2, 5).astype("float32")
        ref = x @ lin.weight.numpy() + lin.bias.numpy()
        np.testing.assert_allclose(lin(_t(x)).numpy(), ref, rtol=1e-5)

    def test_conv2d_shapes(self):
        conv = nn.Conv2D(3, 8, 3, stride=2, padding=1)
        y = conv(_t(np.random.rand(2, 3, 16, 16)))
        assert y.shape == [2, 8, 8, 8]

    def test_embedding(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        ids = to_variable(np.array([[1, 0, 3]], dtype="int64"))
        out = emb(ids)
        assert out.shape == [1, 3, 4]
        np.testing.assert_allclose(out.numpy()[0, 1], np.zeros(4))

    def test_layernorm_oracle(self):
        ln = nn.LayerNorm(6)
        x = np.random.rand(3, 6).astype("float32")
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        ref = (x - mu) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(ln(_t(x)).numpy(), ref, rtol=1e-4,
                                   atol=1e-5)

    def test_batchnorm_running_stats(self):
        bn = nn.BatchNorm2D(4, momentum=0.9)
        x = np.random.rand(8, 4, 5, 5).astype("float32") * 3 + 1
        bn(_t(x))
        # running mean moved toward batch mean
        assert not np.allclose(bn._mean.numpy(), 0.0)
        bn.eval()
        y = bn(_t(x))
        assert y.shape == [8, 4, 5, 5]

    def test_losses(self):
        logits = np.random.rand(4, 10).astype("float32")
        labels = np.random.randint(0, 10, (4,)).astype("int64")
        loss = nn.CrossEntropyLoss()(_t(logits), to_variable(labels))
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        ref = -np.log(p[np.arange(4), labels]).mean()
        np.testing.assert_allclose(float(loss.numpy()), ref, rtol=1e-4)

        a, b = np.random.rand(3, 2), np.random.rand(3, 2)
        np.testing.assert_allclose(
            float(nn.MSELoss()(_t(a), _t(b)).numpy()),
            ((a - b) ** 2).mean(), rtol=1e-5)
        np.testing.assert_allclose(
            float(nn.L1Loss()(_t(a), _t(b)).numpy()),
            np.abs(a - b).mean(), rtol=1e-5)

    def test_activations(self):
        x = np.linspace(-3, 3, 13).astype("float32")
        np.testing.assert_allclose(nn.ReLU()(_t(x)).numpy(),
                                   np.maximum(x, 0))
        np.testing.assert_allclose(
            nn.Sigmoid()(_t(x)).numpy(), 1 / (1 + np.exp(-x)), rtol=1e-5)
        sm = nn.Softmax()(_t(x)).numpy()
        np.testing.assert_allclose(sm.sum(), 1.0, rtol=1e-5)

    def test_backward_through_stack(self):
        net = nn.Sequential(nn.Linear(4, 8), nn.GELU(), nn.Linear(8, 1))
        loss = net(_t(np.random.rand(4, 4))).mean()
        loss.backward()
        for p in net.parameters():
            assert p.grad is not None


class TestRNN:
    def test_lstm_shapes_and_grad(self):
        lstm = nn.LSTM(8, 16, num_layers=2)
        x = _t(np.random.rand(4, 6, 8))
        y, (h, c) = lstm(x)
        assert y.shape == [4, 6, 16]
        assert h.shape == [2, 4, 16] and c.shape == [2, 4, 16]

    def test_bidirectional(self):
        gru = nn.GRU(8, 16, direction="bidirect")
        y, h = gru(_t(np.random.rand(2, 5, 8)))
        assert y.shape == [2, 5, 32]

    def test_gru_cell_oracle(self):
        cell = nn.GRUCell(4, 6)
        x = np.random.rand(3, 4).astype("float32")
        h0 = np.zeros((3, 6), "float32")
        out, h = cell(_t(x), _t(h0))
        # oracle
        wi, wh = cell.weight_ih.numpy(), cell.weight_hh.numpy()
        bi, bh = cell.bias_ih.numpy(), cell.bias_hh.numpy()
        gi, gh = x @ wi.T + bi, h0 @ wh.T + bh
        ir, iz, ic = np.split(gi, 3, -1)
        hr, hz, hc = np.split(gh, 3, -1)
        s = lambda v: 1 / (1 + np.exp(-v))
        r, z = s(ir + hr), s(iz + hz)
        n = np.tanh(ic + r * hc)
        ref = (1 - z) * n + z * h0
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


class TestTransformer:
    def test_encoder_forward_backward(self):
        enc = nn.TransformerEncoder(
            nn.TransformerEncoderLayer(32, 4, 64, dropout=0.0), 2)
        x = _t(np.random.rand(2, 10, 32))
        y = enc(x)
        assert y.shape == [2, 10, 32]
        y.mean().backward()
        assert enc.parameters()[0].grad is not None

    def test_mha_self_attention_oracle(self):
        mha = nn.MultiHeadAttention(16, 2, dropout=0.0)
        x = np.random.rand(1, 4, 16).astype("float32")
        out = mha(_t(x))
        assert out.shape == [1, 4, 16]
        # oracle: project, attend, project back
        q = x @ mha.q_proj.weight.numpy() + mha.q_proj.bias.numpy()
        k = x @ mha.k_proj.weight.numpy() + mha.k_proj.bias.numpy()
        v = x @ mha.v_proj.weight.numpy() + mha.v_proj.bias.numpy()
        q = q.reshape(1, 4, 2, 8).transpose(0, 2, 1, 3)
        k = k.reshape(1, 4, 2, 8).transpose(0, 2, 1, 3)
        v = v.reshape(1, 4, 2, 8).transpose(0, 2, 1, 3)
        s = (q @ k.transpose(0, 1, 3, 2)) / np.sqrt(8)
        e = np.exp(s - s.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        o = (p @ v).transpose(0, 2, 1, 3).reshape(1, 4, 16)
        ref = o @ mha.out_proj.weight.numpy() + mha.out_proj.bias.numpy()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-3, atol=1e-4)

    def test_decoder_cache(self):
        dec_layer = nn.TransformerDecoderLayer(16, 2, 32, dropout=0.0)
        dec = nn.TransformerDecoder(dec_layer, 1)
        memory = _t(np.random.rand(1, 6, 16))
        cache = dec.gen_cache(memory)
        tgt = _t(np.random.rand(1, 1, 16))
        out, new_cache = dec(tgt, memory, cache=cache)
        assert out.shape == [1, 1, 16]


class TestFunctional:
    def test_flash_attention_oracle(self):
        """Pallas flash-attention kernel (interpret mode) vs XLA oracle."""
        from paddle_tpu.ops.pallas import attention as A
        import jax.numpy as jnp

        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(2, 128, 2, 64), jnp.float32)
        k = jnp.asarray(rng.randn(2, 128, 2, 64), jnp.float32)
        v = jnp.asarray(rng.randn(2, 128, 2, 64), jnp.float32)
        ref = A._xla_attention(q, k, v, is_causal=True)
        out = A.flash_attention(q, k, v, is_causal=True, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-3, atol=1e-3)

    def test_pad_and_interpolate(self):
        x = _t(np.random.rand(1, 2, 4, 4))
        y = F.pad(x, [1, 1, 1, 1])
        assert y.shape == [1, 2, 6, 6]
        z = F.interpolate(x, scale_factor=2, mode="nearest")
        assert z.shape == [1, 2, 8, 8]


class TestWeightedLosses:
    """Reference semantics for class-weighted / ignore_index losses
    (VERDICT r2 weak #4 / round-1 ADVICE #3): weighted mean divides by
    the sum of applied weights, not the element count."""

    def _np_ce(self, logits, label, weight=None, ignore_index=-100,
               reduction="mean"):
        x = logits - logits.max(-1, keepdims=True)
        logp = x - np.log(np.exp(x).sum(-1, keepdims=True))
        n = logits.shape[0]
        li = -logp[np.arange(n), np.clip(label, 0, logits.shape[-1] - 1)]
        keep = label != ignore_index
        w = (weight[np.clip(label, 0, len(weight) - 1)]
             if weight is not None else np.ones(n, "float32"))
        w = np.where(keep, w, 0.0)
        if reduction == "mean":
            return (li * w).sum() / w.sum()
        if reduction == "sum":
            return (li * w).sum()
        return li * w

    def test_cross_entropy_weighted_mean(self):
        rng = np.random.RandomState(0)
        logits = rng.randn(8, 5).astype("float32")
        label = rng.randint(0, 5, (8,)).astype("int64")
        w = np.array([0.2, 1.0, 2.0, 0.5, 3.0], "float32")
        out = F.cross_entropy(_t(logits), to_variable(label),
                              weight=_t(w))
        ref = self._np_ce(logits, label, weight=w)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)

    def test_cross_entropy_ignore_index_mean(self):
        rng = np.random.RandomState(1)
        logits = rng.randn(6, 4).astype("float32")
        label = np.array([0, 1, -100, 2, -100, 3], "int64")
        out = F.cross_entropy(_t(logits), to_variable(label))
        ref = self._np_ce(logits, label)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)

    def test_cross_entropy_weighted_sum_and_none(self):
        rng = np.random.RandomState(2)
        logits = rng.randn(5, 3).astype("float32")
        label = rng.randint(0, 3, (5,)).astype("int64")
        w = np.array([1.0, 0.3, 2.5], "float32")
        s = F.cross_entropy(_t(logits), to_variable(label), weight=_t(w),
                            reduction="sum")
        np.testing.assert_allclose(
            s.numpy(), self._np_ce(logits, label, weight=w,
                                   reduction="sum"), rtol=1e-5)
        e = F.cross_entropy(_t(logits), to_variable(label), weight=_t(w),
                            reduction="none")
        np.testing.assert_allclose(
            e.numpy().reshape(-1),
            self._np_ce(logits, label, weight=w, reduction="none"),
            rtol=1e-5)

    def test_nll_loss_weight_ignore(self):
        rng = np.random.RandomState(3)
        logits = rng.randn(7, 4).astype("float32")
        logp = (logits - np.log(np.exp(logits).sum(-1, keepdims=True)))
        label = np.array([0, 1, 2, -100, 3, 1, -100], "int64")
        w = np.array([0.5, 1.5, 1.0, 2.0], "float32")
        out = F.nll_loss(_t(logp), to_variable(label), weight=_t(w))
        keep = label != -100
        safe = np.clip(label, 0, 3)
        li = -logp[np.arange(7), safe] * w[safe] * keep
        ref = li.sum() / (w[safe] * keep).sum()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)

    def test_nll_loss_grad_flows(self):
        rng = np.random.RandomState(4)
        logp = _t(rng.randn(4, 3))
        logp.stop_gradient = False
        label = to_variable(np.array([0, 1, 2, 1], "int64"))
        w = _t(np.array([1.0, 2.0, 0.5], "float32"))
        loss = F.nll_loss(logp, label, weight=w)
        loss.backward()
        assert np.isfinite(logp.grad.numpy()).all()
        assert np.abs(logp.grad.numpy()).sum() > 0
